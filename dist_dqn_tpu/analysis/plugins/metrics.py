"""Check ``metrics``: no NEW JSON-line metric emission bypassing the
telemetry registry, and no ``dqn_*`` family undocumented in
docs/observability.md.

Migrated from scripts/check_metrics.py (ISSUE 13) with the logic and
both allowlists intact; the history and rationale live in the original
docstrings below. ISSUE 1 unified metrics behind ``dist_dqn_tpu/
telemetry`` — new code records through the registry, not more ad-hoc
``print(json.dumps(...))`` / ``log_fn(json.dumps(...))`` call sites
scrapers can't see; ISSUE 5 added the docs-drift half (every registered
``dqn_*`` family must appear in docs/observability.md or carry a
DOCS_ALLOWLIST rationale).
"""
from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, Set

from dist_dqn_tpu.analysis.core import (AnalysisContext, Check, Finding,
                                        count_matches)
from dist_dqn_tpu.analysis.registry import register

PATTERN = re.compile(r"(?:print|log_fn)\(json\.dumps")

#: Registry registration with a literal family name. ``\s`` spans
#: newlines, so multi-line calls are covered.
REGISTRATION = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\"'](dqn_[a-z0-9_]+)[\"']")
#: Canonical name constants in telemetry/collectors.py (including the
#: ``NAME = \`` + next-line-string spelling).
CONSTANT = re.compile(
    r"^[A-Z0-9_]+\s*=\s*(?:\\\s*)?[\"'](dqn_[a-z0-9_]+)[\"']", re.M)

#: dqn_* families allowed to be absent from docs/observability.md,
#: each with the reason it stays undocumented.
DOCS_ALLOWLIST = {
    # Internal plumbing of the span tracer: a scratch gauge the
    # MetricLogger uses to mirror counter-style extras; not a scrape
    # surface anyone should alert on (utils/trace.py).
    "dqn_trace_counter",
}

#: file (repo-relative, posix) -> call sites grandfathered at ISSUE 1.
ALLOWLIST = {
    "bench.py": 1,
    "benchmarks/ale_learning.py": 2,
    "benchmarks/apex_feeder_bench.py": 1,
    "benchmarks/apex_split_bench.py": 2,
    "benchmarks/bench_sweep.py": 4,
    "benchmarks/cli_e2e.py": 3,
    "benchmarks/host_replay_bench.py": 1,
    "benchmarks/learner_bench.py": 3,
    "benchmarks/pong_learning.py": 4,
    "benchmarks/r2d2_pixel_learning.py": 1,
    "benchmarks/roofline_inscan.py": 1,
    # +1 at ISSUE 18: the sharded arm's per-grid BENCH row line — a CLI
    # output contract like the per-impl rows; the device-sampling
    # runtime metrics go through the registry
    # (dqn_replay_device_sample_seconds / _writeback_rows_total).
    "benchmarks/sampler_bench.py": 3,
    # ISSUE 7: the per-arm BENCH row line (the contract line goes
    # through bench.ContractEmitter, counted under bench.py) — CLI
    # output contracts; the serving metrics themselves go through the
    # registry (dqn_serving_*).
    "benchmarks/serving_bench.py": 1,
    "benchmarks/tpu_battery.py": 5,
    "dist_dqn_tpu/actors/remote.py": 1,
    # +2 at ISSUE 8: the ingest_degraded alarm transitions (one line
    # per episode edge, state changes — the continuous signal is the
    # dqn_ingest_degraded gauge).
    "dist_dqn_tpu/actors/service.py": 5,
    # ISSUE 8: the one-per-episode transport shedding alarm (the
    # per-record stream is dqn_transport_tcp_shed_total).
    "dist_dqn_tpu/actors/transport.py": 1,
    "dist_dqn_tpu/atari57.py": 7,
    # +1 at ISSUE 4: the telemetry_port announcement line (a CLI output
    # contract like train.py's, not a metric — the metrics themselves go
    # through the registry the flag exposes).
    "dist_dqn_tpu/evaluate.py": 2,
    # +2 at ISSUE 8: the resumed_at_frames and per-save checkpoint
    # announcement lines (run-lifecycle output contracts, mirroring
    # train.py's resume line; the chaos/crash metrics go through the
    # registry). +1 at ISSUE 19: the one-shot profile_trace
    # announcement after the --profile-dir first-chunk capture lands
    # (a path, not a metric; chip-time metrics go through the
    # registry's dqn_program_*/dqn_chip_* families).
    "dist_dqn_tpu/host_replay_loop.py": 4,
    # ISSUE 7: the serving CLI's startup announcements (serving_port +
    # optional telemetry_port) — output contracts like train.py's; act
    # metrics go through the registry. +1 at ISSUE 8: the shutdown
    # serving_drained line (graceful-drain outcome contract).
    "dist_dqn_tpu/serving/__main__.py": 3,
    # +1 at ISSUE 4: the one-per-run {"manifest": ...} provenance line
    # (telemetry/manifest.py) — run identity, not a metric stream.
    # +4 at ISSUE 20: the population loop's telemetry_port /
    # resumed_at_frames / profile_trace announcements and its per-chunk
    # metric row — the same output contracts as the solo loop's sites;
    # the population metrics themselves go through the registry
    # (dqn_population_*).
    "dist_dqn_tpu/train.py": 15,
    "dist_dqn_tpu/utils/metrics.py": 1,  # MetricLogger.flush itself
}

SCAN_ROOTS = ("dist_dqn_tpu", "benchmarks", "bench.py", "__graft_entry__.py")


def scan(repo_root: Path, ctx: AnalysisContext = None) -> Dict[str, int]:
    """{relpath: direct-emission call-site count} over the scan roots
    (the telemetry package itself is the sanctioned emitter). Pass the
    run's shared ``ctx`` to reuse its parse cache."""
    if ctx is None:
        ctx = AnalysisContext(Path(repo_root))
    counts: Dict[str, int] = {}
    for rel in ctx.iter_py_files(SCAN_ROOTS):
        if rel.startswith("dist_dqn_tpu/telemetry/"):
            continue  # the registry itself is the sanctioned emitter
        if rel.startswith("dist_dqn_tpu/analysis/"):
            continue  # the lint layer DEFINES the pattern it hunts
        n = count_matches(PATTERN, ctx.source(rel))
        if n:
            counts[rel] = n
    return counts


def scan_metric_names(repo_root: Path,
                      ctx: AnalysisContext = None) -> Set[str]:
    """Every dqn_* family name the package registers or canonicalizes."""
    if ctx is None:
        ctx = AnalysisContext(Path(repo_root))
    names: Set[str] = set()
    for rel in ctx.iter_py_files(("dist_dqn_tpu",)):
        names.update(REGISTRATION.findall(ctx.source(rel)))
    names.update(CONSTANT.findall(
        ctx.source("dist_dqn_tpu/telemetry/collectors.py")))
    return names


def check_docs(repo_root: Path, ctx: AnalysisContext = None) -> List[str]:
    """Names registered in code but absent from docs/observability.md
    (minus the rationale'd allowlist). Whole-name match: a family that
    is merely a prefix of a documented longer name (dqn_foo vs
    dqn_foo_seconds) still counts as undocumented."""
    doc = (Path(repo_root) / "docs" / "observability.md").read_text()
    return sorted(
        n for n in scan_metric_names(repo_root, ctx=ctx)
        if not re.search(rf"{re.escape(n)}(?![a-z0-9_])", doc)
        and n not in DOCS_ALLOWLIST)


class MetricsCheck(Check):
    name = "metrics"
    description = ("metric emission goes through the telemetry registry "
                   "(no new print(json.dumps) call sites) and every "
                   "registered dqn_* family is documented in "
                   "docs/observability.md")
    rationale_tag = None  # suppression = the in-module allowlists

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings = []
        for rel, n in sorted(scan(ctx.root, ctx=ctx).items()):
            allowed = ALLOWLIST.get(rel, 0)
            if n > allowed:
                findings.append(self.finding(
                    rel, 0,
                    f"{n} direct JSON-metric emission call sites "
                    f"(allowlist: {allowed}). New metrics must go "
                    f"through dist_dqn_tpu/telemetry (registry counters/"
                    f"gauges/histograms); see docs/observability.md.",
                    key=f"emission:{rel}"))
        for name in check_docs(ctx.root, ctx=ctx):
            findings.append(self.finding(
                "", 0,
                f"{name}: registered in dist_dqn_tpu/ but missing from "
                f"the docs/observability.md naming table. Document the "
                f"family (or add it to DOCS_ALLOWLIST with a rationale).",
                key=f"undocumented:{name}"))
        return findings


register(MetricsCheck())
