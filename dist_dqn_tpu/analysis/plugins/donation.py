"""Check ``donation``: every jitted train/collect entry point must
declare explicit ``donate_argnums`` — or carry a ``donation:``
rationale comment.

Migrated from scripts/check_donation.py (ISSUE 13). ISSUE 6's aliasing
audit (utils/donation.py) verified the chunk programs donate their
GB-sized carries completely; what the runtime audit cannot do is stop
the NEXT train/collect jit from silently omitting the donation — the
failure mode is an HBM working set doubled on a chip that used to fit,
discovered as an OOM months later. This is the static half of the
guard.

AST-based: any ``jax.jit(...)`` call (or ``partial(jax.jit, ...)``)
whose jitted expression mentions ``train``/``collect``/``chunk``/
``shard`` is a learner/collector entry point and must either pass
``donate_argnums=`` explicitly, or be preceded (within two lines, or on
the same line) by a comment containing ``donation:`` stating why
nothing is donated. Functions named act/eval/sample are out of scope by
construction (their params ARE reused across calls — donating would be
the bug).
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Tuple

from dist_dqn_tpu.analysis.core import AnalysisContext, Check, Finding
from dist_dqn_tpu.analysis.registry import register

SCAN_ROOTS = ("dist_dqn_tpu", "benchmarks", "bench.py")

#: What makes a jitted expression a train/collect entry point.
#: ``shard`` joined in ISSUE 10: the data-parallel learners wrap their
#: train steps in closures named ``sharded`` (parallel/learner.py
#: make_sharded_train_step), which the train/collect/chunk patterns
#: would silently stop seeing. ``snapshot``/``lane`` joined in
#: ISSUE 15: the sharded-collect runtime's per-chunk param-snapshot
#: program (host_replay_loop.py snapshot_collect_params) and any
#: lane-block split dispatch are collect-side entry points whose
#: buffers are chunk-sized — a rename away from "collect" must not
#: drop them out of scope. ``population`` joined in ISSUE 20: the
#: stacked-member entry points (population.py run_population_chunk /
#: init_population) carry M whole fused carries — the costliest
#: working set in the repo; a rename away from "chunk" must keep them
#: in scope.
TARGET = re.compile(r"train|collect|chunk|shard|snapshot|lane|population")
#: Rationale escape hatch: a nearby comment owning the decision.
RATIONALE = re.compile(r"#.*donation:")


def _is_jit_call(node: ast.Call) -> bool:
    """True for ``jax.jit(...)`` / ``jit(...)`` and the
    ``partial(jax.jit, ...)`` spelling."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "partial" and node.args:
        inner = node.args[0]
        return (isinstance(inner, ast.Attribute) and inner.attr == "jit") \
            or (isinstance(inner, ast.Name) and inner.id == "jit")
    return False


def _jitted_expr_text(node: ast.Call) -> str:
    """Source text of what is being jitted (first non-jax.jit arg)."""
    args = node.args
    if args and isinstance(args[0], (ast.Attribute, ast.Name)) \
            and getattr(args[0], "attr", getattr(args[0], "id", "")) \
            == "jit":
        args = args[1:]  # partial(jax.jit, ...) positional tail
    try:
        return " ".join(ast.unparse(a) for a in args)
    except Exception:
        return ""


def _has_rationale(lines, lineno: int) -> bool:
    """A ``donation:`` comment on the call line or the two above it."""
    lo = max(lineno - 3, 0)
    return any(RATIONALE.search(ln) for ln in lines[lo:lineno])


def scan(repo_root: Path, ctx: AnalysisContext = None
         ) -> List[Tuple[str, int, str]]:
    """[(relpath, lineno, jitted expr), ...] for violating sites.
    Pass the run's shared ``ctx`` to reuse its parse cache."""
    if ctx is None:
        ctx = AnalysisContext(Path(repo_root))
    failures: List[Tuple[str, int, str]] = []
    for rel in ctx.iter_py_files(SCAN_ROOTS):
        try:
            tree = ctx.tree(rel)
        except SyntaxError as e:
            failures.append((rel, e.lineno or 0, "<unparseable>"))
            continue
        src = ctx.source(rel)
        lines = src.splitlines()
        decorator_calls = set()
        # Decorator spellings: @jax.jit / @partial(jax.jit, ...) on
        # a def — the jitted expression is the function's own name.
        for node in ast.walk(tree):
            if not isinstance(node, (ast.FunctionDef,
                                     ast.AsyncFunctionDef)):
                continue
            for dec in node.decorator_list:
                is_call = isinstance(dec, ast.Call)
                if is_call and _is_jit_call(dec):
                    decorator_calls.add(id(dec))
                    kw = {k.arg for k in dec.keywords}
                elif isinstance(dec, ast.Attribute) \
                        and dec.attr == "jit":
                    kw = set()
                else:
                    continue
                if not TARGET.search(node.name):
                    continue
                if "donate_argnums" in kw:
                    continue
                if _has_rationale(lines, dec.lineno):
                    continue
                failures.append((rel, dec.lineno, node.name))
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _is_jit_call(node)) \
                    or id(node) in decorator_calls:
                continue
            expr = _jitted_expr_text(node)
            if not TARGET.search(expr):
                continue
            kw = {k.arg for k in node.keywords}
            if "donate_argnums" in kw:
                continue
            if _has_rationale(lines, node.lineno):
                continue
            failures.append((rel, node.lineno, expr.split("\n")[0]))
    return failures


class DonationCheck(Check):
    name = "donation"
    description = ("every jitted train/collect entry point declares "
                   "donate_argnums or a '# donation:' rationale (HBM "
                   "working-set guard)")
    rationale_tag = "donation:"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings = []
        for rel, lineno, expr in scan(ctx.root, ctx=ctx):
            findings.append(self.finding(
                rel, lineno,
                f"jax.jit({expr!r}) is a train/collect entry point "
                "without explicit donate_argnums — donate the carry/"
                "state (in-place HBM update) or add a '# donation: "
                "<why not>' rationale comment (docs/performance.md, "
                "learner utilization)",
                key=f"jit:{rel}:{expr[:60]}"))
        return findings


register(DonationCheck())
