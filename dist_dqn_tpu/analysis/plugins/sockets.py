"""Check ``sockets``: every socket acquisition site in dist_dqn_tpu/
must bound its blocking behavior — set a timeout nearby or carry a
rationale comment.

Migrated from scripts/check_sockets.py (ISSUE 13). ISSUE 8: the chaos
harness's whole disconnect/partition fault class turns into a silent
process wedge the moment one socket blocks forever (the round-1 tunnel
incident was exactly an unbounded wait nobody knew existed). Wherever a
socket is CREATED or ACCEPTED (``socket.socket(``,
``socket.create_connection(``, ``.accept()``), one of the following
must hold within ``CONTEXT_LINES`` lines of the call: a ``settimeout(``
/ ``timeout=`` (the socket is bounded), or a ``# socket:`` rationale
comment explaining why unbounded blocking is safe here.

REQUIRED_SUBPACKAGES makes the coverage explicit: the check FAILS if a
listed tree goes missing rather than silently scanning nothing (real
repo only — synthetic test trees legitimately lack subpackages).
"""
from __future__ import annotations

import re
from pathlib import Path
from typing import List

from dist_dqn_tpu.analysis.core import AnalysisContext, Check, Finding
from dist_dqn_tpu.analysis.registry import register

#: How far (in lines, both directions) evidence may sit from the call.
CONTEXT_LINES = 6

ACQUIRE = re.compile(
    r"socket\.socket\(|socket\.create_connection\(|\.accept\(\)")
EVIDENCE = re.compile(r"settimeout\(|timeout\s*=|#\s*socket:")

#: Subtrees the scan must actually see (guards against a refactor
#: moving socket code out from under the rglob): the transport-bearing
#: packages today.
REQUIRED_SUBPACKAGES = ("actors", "ingest", "serving", "telemetry")


def scan(repo_root: Path, ctx: AnalysisContext = None) -> List[str]:
    repo_root = Path(repo_root)
    if ctx is None:
        ctx = AnalysisContext(repo_root)
    failures: List[str] = []
    pkg = repo_root / "dist_dqn_tpu"
    # Coverage guard only for the real repo (the lint tests scan
    # synthetic single-file trees, which legitimately lack subpackages).
    if (repo_root / "scripts" / "check_sockets.py").exists():
        for sub in REQUIRED_SUBPACKAGES:
            if pkg.is_dir() and not (pkg / sub).is_dir():
                failures.append(
                    f"dist_dqn_tpu/{sub}/: expected subpackage missing "
                    f"— update REQUIRED_SUBPACKAGES if it moved")
    for rel in ctx.iter_py_files(("dist_dqn_tpu",)):
        if rel.startswith("dist_dqn_tpu/analysis/"):
            continue  # the lint layer DEFINES the patterns it hunts
        lines = ctx.lines(rel)
        for i, line in enumerate(lines):
            if not ACQUIRE.search(line):
                continue
            lo = max(0, i - CONTEXT_LINES)
            hi = min(len(lines), i + CONTEXT_LINES + 1)
            window = "\n".join(lines[lo:hi])
            if not EVIDENCE.search(window):
                failures.append(
                    f"{rel}:{i + 1}: socket acquired without a nearby "
                    f"timeout or '# socket:' rationale comment: "
                    f"{line.strip()}")
    return failures


class SocketsCheck(Check):
    name = "sockets"
    description = ("every socket acquisition bounds its blocking "
                   "(timeout nearby) or carries a '# socket:' rationale")
    rationale_tag = "socket:"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings = []
        for msg in scan(ctx.root, ctx=ctx):
            loc, _, detail = msg.partition(": ")
            rel, _, lineno = loc.partition(":")
            n = int(lineno) if lineno.isdigit() else 0
            # Key on the acquisition line's TEXT: line-stable (the
            # baseline contract) and distinct per site — a path-only
            # key would let one entry blanket every future unbounded
            # socket in the file.
            site = ctx.lines(rel)[n - 1].strip()[:80] if n else ""
            findings.append(self.finding(
                rel, n,
                detail + f" Bound the socket (settimeout) or add a "
                f"'# socket: <why unbounded blocking is safe>' comment "
                f"within {CONTEXT_LINES} lines.",
                key=f"socket:{rel}:{site}" if n else f"socket:{loc}"))
        return findings


register(SocketsCheck())
