"""Check ``threads``: every ``threading.Thread(...)`` in
``dist_dqn_tpu/`` must pass explicit ``name=`` AND ``daemon=``.

Migrated from scripts/check_threads.py (ISSUE 13). ISSUE 4 added
all-thread stack dumps to the forensics bundles and ``/debug/stacks``
(telemetry/watchdog.py ``format_stacks``): the stacks are labeled by
THREAD NAME, so an unnamed thread prints as ``Thread-7`` and the one
dump you get from a wedged production run points nowhere. Explicit
``daemon=`` is required for the same post-mortem reason — shutdown
behavior must be a decision visible at the call site, not an inherited
default someone has to go look up.

AST-based (no regex false positives on comments/strings): flags any
``threading.Thread(...)`` or bare ``Thread(...)`` call whose keywords
do not include both ``name`` and ``daemon``. ``threading.Timer`` is out
of scope — its constructor takes neither.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import List, Tuple

from dist_dqn_tpu.analysis.core import (AnalysisContext, Check, Finding,
                                        unparseable)
from dist_dqn_tpu.analysis.registry import register

SCAN_ROOTS = ("dist_dqn_tpu",)
REQUIRED_KEYWORDS = ("name", "daemon")


def _is_thread_call(func: ast.expr) -> bool:
    if isinstance(func, ast.Attribute) and func.attr == "Thread":
        return isinstance(func.value, ast.Name) \
            and func.value.id == "threading"
    # ``from threading import Thread`` style — not current repo idiom,
    # but the lint must bite if it appears.
    return isinstance(func, ast.Name) and func.id == "Thread"


def scan(repo_root: Path, ctx: AnalysisContext = None
         ) -> List[Tuple[str, int, List[str]]]:
    """[(relpath, lineno, missing keywords), ...] for violating sites.
    Pass the run's shared ``ctx`` to reuse its parse cache."""
    if ctx is None:
        ctx = AnalysisContext(Path(repo_root))
    failures: List[Tuple[str, int, List[str]]] = []
    for rel in ctx.iter_py_files(SCAN_ROOTS):
        try:
            tree = ctx.tree(rel)
        except SyntaxError as e:
            failures.append((rel, e.lineno or 0, ["<unparseable>"]))
            continue
        for node in ast.walk(tree):
            if not (isinstance(node, ast.Call)
                    and _is_thread_call(node.func)):
                continue
            kw = {k.arg for k in node.keywords}
            missing = [r for r in REQUIRED_KEYWORDS if r not in kw]
            if missing:
                failures.append((rel, node.lineno, missing))
    return failures


class ThreadsCheck(Check):
    name = "threads"
    description = ("every threading.Thread call site passes explicit "
                   "name= and daemon= (forensics stack dumps are "
                   "labeled by thread name)")
    rationale_tag = None

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings = []
        for rel, lineno, missing in scan(ctx.root, ctx=ctx):
            if missing == ["<unparseable>"]:
                findings.append(unparseable(
                    self, rel, SyntaxError("invalid syntax",
                                           ("", lineno, 0, ""))))
                continue
            wanted = ", ".join(f"{m}=" for m in missing)
            # Key on the call line's TEXT, not its number: unrelated
            # edits above the site must not invalidate a baseline entry.
            site = ctx.lines(rel)[lineno - 1].strip()[:80] \
                if lineno else ""
            findings.append(self.finding(
                rel, lineno,
                f"threading.Thread(...) without explicit {wanted} — "
                "unnamed/implicit threads make forensics stack dumps "
                "unreadable (docs/observability.md)",
                key=f"thread:{rel}:{site}"))
        return findings


register(ThreadsCheck())
