"""Check ``lock-discipline``: per-class guarded-field race inference.

The repo's riskiest bugs have all been concurrency bugs found by hand
in review — the DoubleBufferedStager aliasing race (ISSUE 5), the
batcher drain/admission windows and the checkpoint stamp-thread
teardown (ISSUE 7/8 review rounds). This analyzer makes the review
mechanical for the lock-using classes (ISSUE 13 tentpole):

For every class that owns a lock attribute (``self._lock =
threading.Lock()`` / ``RLock()`` / ``Condition()``), infer the class's
GUARDED FIELD SET: every ``self.<attr>`` that any non-constructor
method writes while holding one of the class's locks (``with
self._lock: ...``) — plain assignment, augmented assignment,
``self.x[k] = v`` subscript stores, and mutating container calls
(``self.q.append(...)`` etc.). The discipline the guarded set implies:
a field the class protects with a lock SOMEWHERE must be protected
EVERYWHERE. Any read or write of a guarded field outside a lexical lock
hold is a finding, unless a ``# lock: <reason>`` rationale comment owns
the decision at the access site (within 3 lines above) or at the
enclosing method's ``def`` line (covering helpers that are only ever
called with the lock already held — lexical analysis cannot see
cross-function holds).

Known limits, by design (each is a rationale comment away):

  * hold tracking is lexical and per-function — ``.acquire()``/
    ``.release()`` pairs and helpers called under a caller's hold read
    as unlocked;
  * fields NEVER written under a hold are invisible (a fully
    lock-free racy class produces no findings — this check finds
    inconsistent discipline, not missing discipline);
  * nested functions/lambdas defined under a hold are analyzed as NOT
    held (closures usually outlive the hold that created them);
  * ANY of the class's locks counts as a hold — in a class with
    several locks partitioning its fields, a read under the WRONG lock
    is a false negative (no such class in the target set today; the
    guarded-set inference would need per-lock partitions to see it).

Constructor writes (``__init__``/``__post_init__``/``__del__``) neither
contribute to the guarded set nor get flagged: construction
happens-before any other thread can hold a reference.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Sequence, Set, Tuple

from dist_dqn_tpu.analysis.core import (AnalysisContext, Check, Finding,
                                        dedupe, has_rationale)
from dist_dqn_tpu.analysis.registry import register

#: The concurrency-heavy modules the analyzer polices (ISSUE 13 list;
#: grow it as threads spread — a listed file that stops existing fails
#: the check rather than silently scanning nothing).
TARGET_FILES: Tuple[str, ...] = (
    "dist_dqn_tpu/replay/staging.py",
    "dist_dqn_tpu/serving/batcher.py",
    "dist_dqn_tpu/serving/model_store.py",
    "dist_dqn_tpu/actors/transport.py",
    "dist_dqn_tpu/actors/service.py",
    "dist_dqn_tpu/telemetry/watchdog.py",
    "dist_dqn_tpu/utils/checkpoint.py",
    "dist_dqn_tpu/utils/metrics.py",
)

#: ``self.x = threading.<factory>()`` registers x as a lock attribute.
LOCK_FACTORIES = frozenset({"Lock", "RLock", "Condition"})

#: Method calls that mutate the receiver in place — writes for the
#: purposes of guarded-set inference (``self.q.append(...)`` under a
#: hold marks ``q`` guarded exactly like ``self.q = ...`` would).
MUTATOR_METHODS = frozenset({
    "append", "appendleft", "extend", "insert", "add", "update",
    "setdefault", "pop", "popleft", "popitem", "remove", "discard",
    "clear", "sort", "reverse",
})

CONSTRUCTORS = frozenset({"__init__", "__post_init__", "__del__",
                          "__new__"})

RATIONALE_TAG = "lock:"


class _Access:
    __slots__ = ("method", "method_lineno", "attr", "lineno", "is_write",
                 "held")

    def __init__(self, method: str, method_lineno: int, attr: str,
                 lineno: int, is_write: bool, held: bool):
        self.method = method
        self.method_lineno = method_lineno
        self.attr = attr
        self.lineno = lineno
        self.is_write = is_write
        self.held = held


def _self_attr(node: ast.AST) -> Optional[str]:
    """'attr' when node is ``self.attr``, else None."""
    if isinstance(node, ast.Attribute) \
            and isinstance(node.value, ast.Name) \
            and node.value.id == "self":
        return node.attr
    return None


def _find_lock_attrs(cls: ast.ClassDef) -> Set[str]:
    """Attributes assigned a threading.Lock/RLock/Condition anywhere in
    the class body (constructor included — that is where they live)."""
    locks: Set[str] = set()
    for node in ast.walk(cls):
        if not isinstance(node, ast.Assign):
            continue
        value = node.value
        if not isinstance(value, ast.Call):
            continue
        f = value.func
        factory = None
        if isinstance(f, ast.Attribute) and isinstance(f.value, ast.Name) \
                and f.value.id == "threading":
            factory = f.attr
        elif isinstance(f, ast.Name):
            factory = f.id
        if factory not in LOCK_FACTORIES:
            continue
        for target in node.targets:
            attr = _self_attr(target)
            if attr is not None:
                locks.add(attr)
    return locks


def _is_lock_hold(item: ast.withitem, lock_attrs: Set[str]) -> bool:
    """True for ``with self._lock:`` / ``with self._cond:`` items."""
    attr = _self_attr(item.context_expr)
    return attr is not None and attr in lock_attrs


def _collect_accesses(method, lock_attrs: Set[str]) -> List[_Access]:
    """Every ``self.<attr>`` touch in ``method`` with its (lexical)
    hold state and read/write classification."""
    accesses: List[_Access] = []
    name = method.name
    m_lineno = method.lineno

    def record(attr: str, lineno: int, is_write: bool, held: bool):
        accesses.append(_Access(name, m_lineno, attr, lineno, is_write,
                                held))

    def visit(node: ast.AST, held: bool) -> None:
        if isinstance(node, (ast.With, ast.AsyncWith)):
            inner = held or any(_is_lock_hold(i, lock_attrs)
                                for i in node.items)
            for item in node.items:
                visit(item.context_expr, held)
                if item.optional_vars is not None:
                    visit(item.optional_vars, inner)
            for child in node.body:
                visit(child, inner)
            return
        if isinstance(node, (ast.FunctionDef, ast.AsyncFunctionDef)):
            # A nested def usually runs AFTER the enclosing hold is
            # released (worker targets, callbacks) — analyze unheld.
            for child in node.body:
                visit(child, False)
            return
        if isinstance(node, ast.Lambda):
            visit(node.body, False)
            return
        if isinstance(node, ast.Call):
            # Mutating container call: self.x.append(...) writes x.
            f = node.func
            if isinstance(f, ast.Attribute) \
                    and f.attr in MUTATOR_METHODS:
                attr = _self_attr(f.value)
                if attr is not None:
                    record(attr, node.lineno, True, held)
        if isinstance(node, ast.Subscript) \
                and isinstance(node.ctx, (ast.Store, ast.Del)):
            # self.x[k] = v / del self.x[k] writes x.
            attr = _self_attr(node.value)
            if attr is not None:
                record(attr, node.lineno, True, held)
        attr = _self_attr(node)
        if attr is not None:
            record(attr, node.lineno,
                   isinstance(node.ctx, (ast.Store, ast.Del)), held)
        for child in ast.iter_child_nodes(node):
            visit(child, held)

    for stmt in method.body:
        visit(stmt, False)
    return accesses


def scan_source(rel: str, src: str,
                lines: Optional[Sequence[str]] = None,
                tree: Optional[ast.AST] = None
                ) -> List[Tuple[str, str, str, int, str]]:
    """[(class, method, attr, lineno, kind), ...] unguarded accesses of
    guarded fields in ``src`` (kind: "read"/"write"), rationale-filtered.
    Pass the run's cached ``tree`` to avoid a second parse.
    """
    if lines is None:
        lines = src.splitlines()
    if tree is None:
        tree = ast.parse(src)
    out: List[Tuple[str, str, str, int, str]] = []
    for cls in [n for n in ast.walk(tree)
                if isinstance(n, ast.ClassDef)]:
        lock_attrs = _find_lock_attrs(cls)
        if not lock_attrs:
            continue
        methods = [n for n in cls.body
                   if isinstance(n, (ast.FunctionDef,
                                     ast.AsyncFunctionDef))]
        accesses: List[_Access] = []
        for m in methods:
            accesses.extend(_collect_accesses(m, lock_attrs))
        guarded = {a.attr for a in accesses
                   if a.is_write and a.held
                   and a.method not in CONSTRUCTORS}
        guarded -= lock_attrs
        rows: dict = {}
        for a in accesses:
            if a.attr not in guarded or a.held \
                    or a.method in CONSTRUCTORS:
                continue
            if has_rationale(lines, a.lineno, RATIONALE_TAG,
                             def_lineno=a.method_lineno):
                continue
            # One row per (method, attr): a mutator call records both
            # the call-write and the attribute-read — keep the earliest
            # site, preferring "write" (the stronger claim).
            ident = (cls.name, a.method, a.attr)
            prev = rows.get(ident)
            if prev is None:
                rows[ident] = (a.lineno, a.is_write)
            else:
                lineno, was_write = prev
                rows[ident] = (min(lineno, a.lineno),
                               was_write or a.is_write)
        out.extend((c, m, attr, lineno, "write" if w else "read")
                   for (c, m, attr), (lineno, w) in sorted(
                       rows.items(), key=lambda kv: kv[1][0]))
    return out


def scan(repo_root: Path, files: Optional[Sequence[str]] = None,
         ctx: Optional[AnalysisContext] = None
         ) -> List[Tuple[str, str, str, str, int, str]]:
    """[(relpath, class, method, attr, lineno, kind), ...] over
    ``files`` (default: TARGET_FILES when any exist under the root,
    else every .py under dist_dqn_tpu/ — the synthetic-tree test mode).
    A <missing> row marks a listed target file that disappeared."""
    root = Path(repo_root)
    if ctx is None:
        ctx = AnalysisContext(root)
    failures: List[Tuple[str, str, str, str, int, str]] = []
    if files is None:
        present = [f for f in TARGET_FILES if (root / f).is_file()]
        if present:
            files = list(present)
            failures.extend(
                (f, "<missing>", "", "", 0, "missing")
                for f in TARGET_FILES if f not in present)
        else:
            files = list(ctx.iter_py_files(("dist_dqn_tpu",)))
    for rel in files:
        try:
            rows = scan_source(rel, ctx.source(rel), ctx.lines(rel),
                               tree=ctx.tree(rel))
        except SyntaxError as e:
            failures.append((rel, "<unparseable>", "", "",
                             e.lineno or 0, "error"))
            continue
        failures.extend((rel, *row) for row in rows)
    return failures


class LockDisciplineCheck(Check):
    name = "lock-discipline"
    description = ("fields a class writes under a lock hold must be "
                   "read/written under the lock everywhere, or carry a "
                   "'# lock:' rationale / reasoned baseline entry")
    rationale_tag = RATIONALE_TAG

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings = []
        for rel, cls, meth, attr, lineno, kind in scan(ctx.root,
                                                       ctx=ctx):
            if cls == "<missing>":
                findings.append(self.finding(
                    rel, 0,
                    "listed in lock_discipline.TARGET_FILES but absent "
                    "— update the target list if the module moved",
                    key=f"missing:{rel}"))
                continue
            if cls == "<unparseable>":
                findings.append(self.finding(
                    rel, lineno, "unparseable Python — lock analysis "
                    "skipped", key=f"unparseable:{rel}"))
                continue
            findings.append(self.finding(
                rel, lineno,
                f"{cls}.{meth} {kind}s self.{attr} outside any lock "
                f"hold, but {cls} writes {attr} under a 'with "
                f"self.<lock>' hold elsewhere — take the lock, add a "
                f"'# lock: <why safe>' rationale at the site (or the "
                f"method's def line), or baseline it with a reason",
                key=f"{cls}.{meth}:{attr}"))
        return dedupe(findings)


register(LockDisciplineCheck())
