"""Check ``chaos-seams``: the seam registry and the real code paths
must not drift apart.

The chaos harness (ISSUE 8) only proves anything while every registered
seam still has (a) an injection point — a ``chaos.fire("seam")`` call
threaded through the real code path — and (b) a recovery proof — a
``chaos.mark_recovered("seam")`` anchor the surviving path hits. A
refactor that drops either leaves the seam registered, the game-day
scenarios green, and the fault class silently untested: the harness
hollows out without a single test failing. This check (ISSUE 13
tentpole) cross-references the three surfaces statically:

  * every seam in ``chaos/plan.py``'s ``SEAMS`` registry has >= 1
    ``fire()`` site in package code (outside ``dist_dqn_tpu/chaos/``
    itself);
  * every seam has >= 1 ``mark_recovered()`` anchor — EXCEPT seams
    whose every fault is terminal (``crash``-only seams kill the
    process; recovery is proved by the next process's resume, which a
    dead process cannot mark);
  * every ``fire()``/``mark_recovered()`` call site names a seam the
    registry knows (an unknown name would fail at arm time — but only
    on the game day that exercises it, which is too late);
  * seam names at call sites are string literals (a computed name is
    invisible to this check AND to the registry validation).

AST-based, so the ``chaos.fire("transport.recv")`` examples in
docstrings never count as injection points.
"""
from __future__ import annotations

import ast
from pathlib import Path
from typing import Dict, List, Optional, Tuple

from dist_dqn_tpu.analysis.core import AnalysisContext, Check, Finding
from dist_dqn_tpu.analysis.registry import register

PLAN_PATH = "dist_dqn_tpu/chaos/plan.py"
CHAOS_PKG_PREFIX = "dist_dqn_tpu/chaos/"
SCAN_ROOTS = ("dist_dqn_tpu",)

#: Faults that end the process at the seam: a seam interpreting ONLY
#: these cannot carry an in-process recovery anchor (the proof is the
#: next process's resume, pinned by the game-day scenarios instead).
TERMINAL_FAULTS = frozenset({"crash"})


def extract_seams(plan_src: str) -> Tuple[Dict[str, Tuple[str, ...]],
                                          Dict[str, int]]:
    """(seam -> faults, seam -> registry lineno) parsed statically from
    chaos/plan.py's ``SEAMS`` dict literal — static on purpose, so a
    synthetic test tree needs no importable package and the check reads
    exactly what is committed, not what an interposed import produced.
    """
    tree = ast.parse(plan_src)
    for node in ast.walk(tree):
        target = None
        if isinstance(node, ast.Assign) and len(node.targets) == 1:
            target = node.targets[0]
        elif isinstance(node, ast.AnnAssign):
            target = node.target
        if not (isinstance(target, ast.Name) and target.id == "SEAMS"):
            continue
        value = node.value
        if not isinstance(value, ast.Dict):
            break
        seams: Dict[str, Tuple[str, ...]] = {}
        linenos: Dict[str, int] = {}
        for key, val in zip(value.keys, value.values):
            try:
                seam = ast.literal_eval(key)
                faults = tuple(ast.literal_eval(val))
            except (ValueError, TypeError):
                continue
            seams[seam] = faults
            linenos[seam] = key.lineno
        return seams, linenos
    return {}, {}


def _literal_seam_arg(node: ast.Call) -> Optional[str]:
    if node.args and isinstance(node.args[0], ast.Constant) \
            and isinstance(node.args[0].value, str):
        return node.args[0].value
    return None


def _call_target(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def scan_sites(repo_root: Path, ctx: Optional[AnalysisContext] = None
               ) -> Tuple[Dict[str, List[Tuple[str, int]]],
                          Dict[str, List[Tuple[str, int]]],
                          List[Tuple[str, int, str]]]:
    """(fire sites, recovery sites, non-literal sites) over the package,
    excluding the chaos package itself (it defines the surface)."""
    if ctx is None:
        ctx = AnalysisContext(Path(repo_root))
    fires: Dict[str, List[Tuple[str, int]]] = {}
    recoveries: Dict[str, List[Tuple[str, int]]] = {}
    nonliteral: List[Tuple[str, int, str]] = []
    for rel in ctx.iter_py_files(SCAN_ROOTS):
        if rel.startswith(CHAOS_PKG_PREFIX):
            continue
        try:
            tree = ctx.tree(rel)
        except SyntaxError:
            continue  # the unparseable file is another check's finding
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            target = _call_target(node)
            if target not in ("fire", "mark_recovered"):
                continue
            seam = _literal_seam_arg(node)
            if seam is None:
                nonliteral.append((rel, node.lineno, target))
                continue
            sink = fires if target == "fire" else recoveries
            sink.setdefault(seam, []).append((rel, node.lineno))
    return fires, recoveries, nonliteral


class ChaosSeamsCheck(Check):
    name = "chaos-seams"
    description = ("every registered chaos seam keeps a live fire() "
                   "injection point and (non-crash-only seams) a "
                   "mark_recovered() anchor; every call site names a "
                   "registered seam")
    rationale_tag = None  # the registry IS the intent record

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        try:
            plan_src = ctx.source(PLAN_PATH)
        except OSError:
            return [self.finding(
                PLAN_PATH, 0,
                "chaos/plan.py not found — the seam registry the whole "
                "game-day harness hangs off is gone",
                key="no-plan")]
        seams, linenos = extract_seams(plan_src)
        if not seams:
            findings.append(self.finding(
                PLAN_PATH, 0,
                "no SEAMS dict literal found in chaos/plan.py — the "
                "registry moved or became dynamic; update chaos_seams."
                "extract_seams", key="no-registry"))
            return findings
        fires, recoveries, nonliteral = scan_sites(ctx.root, ctx=ctx)
        for seam, faults in seams.items():
            if seam not in fires:
                findings.append(self.finding(
                    PLAN_PATH, linenos.get(seam, 0),
                    f"seam {seam!r} is registered but has no "
                    f"chaos.fire({seam!r}) call site in package code — "
                    "it lost its injection point; every game-day "
                    "scenario naming it now passes vacuously. Re-thread "
                    "the seam or delete the registry entry.",
                    key=f"no-fire:{seam}"))
            if seam not in recoveries \
                    and not set(faults) <= TERMINAL_FAULTS:
                findings.append(self.finding(
                    PLAN_PATH, linenos.get(seam, 0),
                    f"seam {seam!r} interprets recoverable faults "
                    f"{sorted(set(faults) - TERMINAL_FAULTS)} but has "
                    f"no chaos.mark_recovered({seam!r}) anchor — "
                    "dqn_recovery_seconds can never close its trip and "
                    "the open-trips end-of-scenario invariant is "
                    "vacuous for it. Anchor the surviving path or make "
                    "the seam crash-only.",
                    key=f"no-recovery:{seam}"))
        for seam, sites in fires.items():
            if seam not in seams:
                rel, lineno = sites[0]
                findings.append(self.finding(
                    rel, lineno,
                    f"chaos.fire({seam!r}) names a seam the registry "
                    "does not know — a plan can never schedule it, so "
                    "the injection point is dead code (register the "
                    "seam in chaos/plan.py SEAMS with its fault set).",
                    key=f"unregistered-fire:{seam}"))
        for seam, sites in recoveries.items():
            if seam not in seams:
                rel, lineno = sites[0]
                findings.append(self.finding(
                    rel, lineno,
                    f"chaos.mark_recovered({seam!r}) names a seam the "
                    "registry does not know — dead recovery anchor "
                    "(register the seam or fix the name).",
                    key=f"unregistered-recovery:{seam}"))
        for rel, lineno, target in nonliteral:
            # Line-text key, not line number: baseline entries must
            # survive unrelated edits above the site.
            site = ctx.lines(rel)[lineno - 1].strip()[:80] \
                if lineno else ""
            findings.append(self.finding(
                rel, lineno,
                f"chaos.{target}(...) with a non-literal seam name — "
                "the drift check (and arm-time validation) can only "
                "protect literal seams; inline the name.",
                key=f"nonliteral:{rel}:{site}"))
        return findings


register(ChaosSeamsCheck())
