"""dqnlint plugins: one module per check, discovered by
``dist_dqn_tpu.analysis.registry.discover()`` (a pkgutil walk — adding
a check is adding a file here that calls ``register(SomeCheck())`` at
import time; no central list to edit)."""
