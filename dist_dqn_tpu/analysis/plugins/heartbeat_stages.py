"""Check ``heartbeat-stages``: the watchdog stage table cannot drift.

ISSUE 16 satellite. The hang runbook (docs/observability.md) triages by
STAGE NAME: a forensics bundle or ``/healthz`` 503 names the wedged
heartbeat, and the reader looks it up in the "Heartbeat stage names"
table to learn what beats it and what stale means. A stage registered
in code but missing from the table sends that reader grepping; a table
row whose stage no longer exists sends them chasing a ghost. This check
cross-references both directions:

  * every ``tm_watchdog.heartbeat(...)`` registration in the runtime
    layers must be covered by a table row — literal names match rows
    exactly, f-string names (``f"host_replay.collect.s{s}"``,
    ``f"evac.{name}"``) match rows as a wildcard over their ``{...}``
    holes, and a bare-identifier argument resolves through a same-file
    ``NAME = "literal"`` constant (serving/batcher.py's
    ``BATCHER_STAGE``);
  * every table row must still be producible by some registration.

The telemetry package (which DEFINES the heartbeat API) and the
analysis layer (which hunts it) are excluded from the scan, same as the
metrics check's emitter exclusion.
"""
from __future__ import annotations

import re
from pathlib import Path
from typing import Dict, List, Tuple

from dist_dqn_tpu.analysis.core import AnalysisContext, Check, Finding
from dist_dqn_tpu.analysis.registry import register

#: A heartbeat registration's first argument: string literal, f-string,
#: or a bare identifier (resolved against same-file constants).
CALL = re.compile(
    r"\bheartbeat\(\s*(?:(f?)([\"'])((?:[^\"'\\]|\\.)*?)\2|"
    r"([A-Za-z_][A-Za-z0-9_]*))")

#: Same-file ``NAME = "stage.literal"`` constant assignments.
ASSIGN_TMPL = r"^\s*{name}\s*=\s*[\"']([^\"']+)[\"']"

#: The docs table rows: ``| `stage.name` | ... |`` under the
#: "### Heartbeat stage names" heading.
DOC_ROW = re.compile(r"^\|\s*`([^`]+)`\s*\|", re.M)

DOC_SECTION = "### Heartbeat stage names"

#: Runtime layers that register heartbeats. telemetry/ defines the API
#: (its docstrings quote example names), analysis/ hunts it — excluded.
SCAN_ROOTS = ("dist_dqn_tpu",)
SKIP_PREFIXES = ("dist_dqn_tpu/telemetry/", "dist_dqn_tpu/analysis/")


def _hole_pattern(text: str) -> str:
    """An f-string (or ``{N}``-templated docs) stage name as a regex:
    each ``{...}`` hole matches any non-empty run of name characters."""
    out, depth, hole = [], 0, False
    for ch in text:
        if ch == "{":
            depth += 1
            hole = True
            continue
        if ch == "}":
            depth = max(depth - 1, 0)
            if depth == 0 and hole:
                out.append(r"[A-Za-z0-9_.\-]+")
                hole = False
            continue
        if depth == 0:
            out.append(re.escape(ch))
    return "".join(out)


def scan_stages(repo_root: Path, ctx: AnalysisContext = None
                ) -> List[Tuple[str, str, int, bool]]:
    """Every heartbeat registration: (stage_text, relpath, line,
    is_pattern). ``is_pattern`` marks f-strings with holes. Bare
    identifiers resolve through same-file constants; unresolvable ones
    are skipped (a dynamic name the table documents as a pattern has
    its f-string site scanned where it is built)."""
    if ctx is None:
        ctx = AnalysisContext(Path(repo_root))
    out: List[Tuple[str, str, int, bool]] = []
    for rel in ctx.iter_py_files(SCAN_ROOTS):
        if any(rel.startswith(p) for p in SKIP_PREFIXES):
            continue
        src = ctx.source(rel)
        for m in CALL.finditer(src):
            line = src.count("\n", 0, m.start()) + 1
            if m.group(4):  # bare identifier: resolve the constant
                am = re.search(ASSIGN_TMPL.format(name=m.group(4)),
                               src, re.M)
                if am:
                    out.append((am.group(1), rel, line, False))
                continue
            text = m.group(3)
            is_fstr = bool(m.group(1)) and "{" in text
            out.append((text, rel, line, is_fstr))
    return out


def doc_stages(repo_root: Path) -> Dict[str, int]:
    """{stage row -> line} from the docs table (empty dict when the
    section is missing — the check reports that as its own finding)."""
    path = Path(repo_root) / "docs" / "observability.md"
    text = path.read_text()
    at = text.find(DOC_SECTION)
    if at < 0:
        return {}
    # The section runs to the next heading (or EOF).
    end = text.find("\n#", at + len(DOC_SECTION))
    section = text[at:end if end > 0 else len(text)]
    base_line = text.count("\n", 0, at) + 1
    rows: Dict[str, int] = {}
    for m in DOC_ROW.finditer(section):
        if m.group(1) == "stage":
            continue  # the header row
        rows[m.group(1)] = base_line + section.count("\n", 0, m.start())
    return rows


class HeartbeatStagesCheck(Check):
    name = "heartbeat-stages"
    description = ("every registered watchdog heartbeat stage appears "
                   "in the docs/observability.md stage table, and every "
                   "table row is still producible by code")
    rationale_tag = None

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings: List[Finding] = []
        rows = doc_stages(ctx.root)
        if not rows:
            findings.append(self.finding(
                "docs/observability.md", 0,
                f"missing the {DOC_SECTION!r} table the hang runbook "
                f"keys on", key="no-stage-table"))
            return findings
        stages = scan_stages(ctx.root, ctx=ctx)
        # Docs rows as match targets: {N}-style holes instantiated to a
        # representative name so code wildcards can hit them.
        row_regexes = {row: re.compile(_hole_pattern(row) + r"\Z")
                       for row in rows}
        row_instances = {row: re.sub(r"\{[^}]*\}", "0", row)
                         for row in rows}
        for text, rel, line, is_pattern in stages:
            if is_pattern:
                pat = re.compile(_hole_pattern(text) + r"\Z")
                covered = any(pat.match(inst)
                              for inst in row_instances.values())
            else:
                covered = any(rx.match(text)
                              for rx in row_regexes.values())
            if not covered:
                findings.append(self.finding(
                    rel, line,
                    f"heartbeat stage {text!r} is not in the "
                    f"'Heartbeat stage names' table in docs/"
                    f"observability.md — the hang runbook cannot "
                    f"triage a stage the table does not name",
                    key=f"undocumented-stage:{text}"))
        code_regexes = [re.compile(_hole_pattern(t) + r"\Z")
                        if p else None
                        for t, _, _, p in stages]
        code_literals = {t for (t, _, _, p), rx
                         in zip(stages, code_regexes) if not p}
        for row, row_line in sorted(rows.items()):
            inst = row_instances[row]
            produced = (
                row in code_literals or inst in code_literals
                or any(rx is not None and rx.match(inst)
                       for rx in code_regexes)
                or any(row_regexes[row].match(lit)
                       for lit in code_literals))
            if not produced:
                findings.append(self.finding(
                    "docs/observability.md", row_line,
                    f"stage table row {row!r} matches no heartbeat "
                    f"registration in dist_dqn_tpu/ — a renamed or "
                    f"removed stage must update the table",
                    key=f"ghost-stage:{row}"))
        return findings


register(HeartbeatStagesCheck())
