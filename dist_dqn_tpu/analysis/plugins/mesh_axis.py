"""Check ``mesh-axis``: mesh-parallel call sites resolve through
utils/compat.py and name their mesh axis (or carry a rationale).

Migrated from scripts/check_mesh_axis.py (ISSUE 13). Two rules, both
born from the ISSUE 10 scale-out:

1. No direct ``jax.shard_map`` / ``jax.experimental.shard_map`` outside
   ``dist_dqn_tpu/utils/compat.py`` — JAX moved the API between 0.4.x
   and 0.5 (and renamed ``check_rep`` to ``check_vma``), and a direct
   spelling import-errors on the other side. The compat resolver is the
   one place allowed to touch either spelling.
2. Every ``shard_map``/``pjit`` call site names its axis: a literal
   ``P("dp")``-style spec or an ``axis``/``axis_name`` keyword in the
   call text, or a ``# mesh-axis:`` comment within three lines above
   stating where the axis lives — so a reader at the call site can
   always answer "which leaves live on which axis".
"""
from __future__ import annotations

import ast
import re
from pathlib import Path
from typing import List, Tuple

from dist_dqn_tpu.analysis.core import AnalysisContext, Check, Finding
from dist_dqn_tpu.analysis.registry import register

SCAN_ROOTS = ("dist_dqn_tpu", "benchmarks", "bench.py", "__graft_entry__.py")
COMPAT_MODULE = "dist_dqn_tpu/utils/compat.py"

#: Direct spellings rule 1 forbids outside the compat module.
DIRECT = re.compile(
    r"jax\.shard_map|jax\.experimental\.shard_map|"
    r"from\s+jax\.experimental\.shard_map\s+import")
#: What satisfies rule 2 inside the call text.
AXIS_IN_CALL = re.compile(r"""P\(\s*['"]|axis_name|axis\s*=""")
#: Rationale escape hatch for spec-variable call sites.
RATIONALE = re.compile(r"#.*mesh-axis:")


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _has_rationale(lines, lineno: int) -> bool:
    lo = max(lineno - 4, 0)
    return any(RATIONALE.search(ln) for ln in lines[lo:lineno])


def scan(repo_root: Path, ctx: AnalysisContext = None
         ) -> List[Tuple[str, int, str]]:
    """[(relpath, lineno, message), ...] for violating sites.
    Pass the run's shared ``ctx`` to reuse its parse cache."""
    if ctx is None:
        ctx = AnalysisContext(Path(repo_root))
    failures: List[Tuple[str, int, str]] = []
    for rel in ctx.iter_py_files(SCAN_ROOTS):
        if rel.startswith("dist_dqn_tpu/analysis/"):
            continue  # the lint layer DEFINES the patterns it hunts
        src = ctx.source(rel)
        lines = src.splitlines()
        if rel == COMPAT_MODULE:
            # The resolver itself forwards to whichever spelling
            # exists; its axis comes from the caller's specs —
            # rule 2 applies at call sites, not here.
            continue
        for i, ln in enumerate(lines, 1):
            if DIRECT.search(ln):
                failures.append(
                    (rel, i,
                     "direct jax.shard_map spelling — resolve "
                     "through dist_dqn_tpu.utils.compat."
                     "shard_map (version-adaptive)"))
        try:
            tree = ctx.tree(rel)
        except SyntaxError as e:
            failures.append((rel, e.lineno or 0, "<unparseable>"))
            continue
        for node in ast.walk(tree):
            if not isinstance(node, ast.Call):
                continue
            if _call_name(node) not in ("shard_map", "pjit"):
                continue
            try:
                call_text = ast.get_source_segment(src, node) or ""
            except Exception:
                call_text = ""
            if AXIS_IN_CALL.search(call_text):
                continue
            if _has_rationale(lines, node.lineno):
                continue
            failures.append(
                (rel, node.lineno,
                 f"{_call_name(node)}(...) names no mesh axis — "
                 "put a literal axis spec in the call or a "
                 "'# mesh-axis: <where the specs name it>' comment "
                 "above it"))
    return failures


class MeshAxisCheck(Check):
    name = "mesh-axis"
    description = ("shard_map resolves through utils/compat.py and "
                   "every shard_map/pjit call site names its mesh axis "
                   "or carries a '# mesh-axis:' rationale")
    rationale_tag = "mesh-axis:"

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        findings = []
        for rel, lineno, msg in scan(ctx.root, ctx=ctx):
            # Line-text key: stable across unrelated edits (the
            # baseline contract), distinct per site.
            site = ctx.lines(rel)[lineno - 1].strip()[:80] \
                if lineno else ""
            findings.append(self.finding(rel, lineno, msg,
                                         key=f"mesh:{rel}:{site}"))
        return findings


register(MeshAxisCheck())
