"""Check ``ckpt-schema``: the checkpoint-sidecar schema is pinned to
its version.

Migrated from scripts/check_ckpt_schema.py (ISSUE 13). ISSUE 12:
host-replay's whole-state resume deserializes an npz sidecar by FIELD
NAME — a renamed/dropped/added field without a version bump would
surface at restore time (3am, on the production fleet) as a
silently-wrong or crashing resume, not in CI. The mechanics mirror the
wire check: fingerprint the field registry of
``dist_dqn_tpu/utils/ckpt_schema.py``; the digest must equal
``SIDECAR_HISTORY[SIDECAR_VERSION]``; history is append-only with the
live version leading it; and the schema's validator must accept its own
canonical minimal sidecar.
"""
from __future__ import annotations

from typing import List

from dist_dqn_tpu.analysis.core import AnalysisContext, Check, Finding
from dist_dqn_tpu.analysis.registry import register


def check() -> List[str]:
    from dist_dqn_tpu.utils import ckpt_schema as cs

    failures = []
    digest = cs.sidecar_digest()
    if cs.SIDECAR_VERSION not in cs.SIDECAR_HISTORY:
        failures.append(
            f"SIDECAR_VERSION {cs.SIDECAR_VERSION} has no SIDECAR_HISTORY "
            f"entry — record it as {cs.SIDECAR_VERSION}: \"{digest}\"")
    elif cs.SIDECAR_HISTORY[cs.SIDECAR_VERSION] != digest:
        failures.append(
            f"sidecar-schema fingerprint {digest} does not match "
            f"SIDECAR_HISTORY[{cs.SIDECAR_VERSION}] = "
            f"{cs.SIDECAR_HISTORY[cs.SIDECAR_VERSION]!r}: the field set "
            f"changed — bump SIDECAR_VERSION "
            f"(dist_dqn_tpu/utils/ckpt_schema.py) and append the new "
            f"(version, digest) pair to SIDECAR_HISTORY; resumes then "
            f"refuse a mismatched sidecar loudly at restore instead of "
            f"deserializing silence")
    if cs.SIDECAR_HISTORY and max(cs.SIDECAR_HISTORY) != cs.SIDECAR_VERSION:
        failures.append(
            f"SIDECAR_HISTORY records version {max(cs.SIDECAR_HISTORY)} "
            f"but SIDECAR_VERSION is {cs.SIDECAR_VERSION} — history is "
            "append-only and the constant must lead it")
    digests = list(cs.SIDECAR_HISTORY.values())
    if len(set(digests)) != len(digests):
        failures.append(
            "SIDECAR_HISTORY maps two versions to the same digest — a "
            "version bump without a schema change (or a rewritten entry)")
    # The validator itself must accept a canonical minimal sidecar —
    # a schema whose own patterns reject its scalar fields would pass
    # the digest check while failing every real save.
    try:
        cs.validate_sidecar(list(cs.SIDECAR_SCALAR_FIELDS))
    except ValueError as e:
        failures.append(f"validate_sidecar rejects the schema's own "
                        f"scalar field set: {e}")
    return failures


class CkptSchemaCheck(Check):
    name = "ckpt-schema"
    description = ("the checkpoint-sidecar field-set fingerprint "
                   "matches SIDECAR_HISTORY[SIDECAR_VERSION] (schema "
                   "drift must bump the version)")
    rationale_tag = None

    def run(self, ctx: AnalysisContext) -> List[Finding]:
        return [self.finding("dist_dqn_tpu/utils/ckpt_schema.py", 0, msg,
                             key=f"ckpt:{i}")
                for i, msg in enumerate(check())]


register(CkptSchemaCheck())
