"""dqnlint run orchestration: checks x context x baseline -> results.

One :class:`~dist_dqn_tpu.analysis.core.AnalysisContext` is shared by
every check in a run (files parse once), the baseline is applied per
finding, and stale baseline entries surface as findings of a synthetic
``baseline`` check — so `scripts/dqnlint.py`, the tier-1 in-process
test and the legacy ``scripts/check_*.py`` shims all run the exact
same code path and can only agree.
"""
from __future__ import annotations

from pathlib import Path
from typing import Dict, List, Optional, Sequence

from dist_dqn_tpu.analysis import baseline as baseline_mod
from dist_dqn_tpu.analysis import registry
from dist_dqn_tpu.analysis.core import AnalysisContext, Check, Finding
from dist_dqn_tpu.analysis.report import CheckResult


class _BaselineCheck(Check):
    """Synthetic owner of stale-baseline findings (never registered —
    it has no ``run``; the runner materializes its findings)."""

    name = "baseline"
    description = ("baseline hygiene: every entry must still match a "
                   "finding of a check that ran")


def run_checks(root: Path, names: Optional[Sequence[str]] = None,
               baseline_path: Optional[Path] = None) -> List[CheckResult]:
    """Run the named checks (default: all registered) over ``root``.

    Raises :class:`~dist_dqn_tpu.analysis.baseline.BaselineError` on an
    invalid baseline file — bad suppression data fails the run, it does
    not get skipped.
    """
    root = Path(root).resolve()
    checks = registry.get_checks(names)
    if baseline_path is None:
        baseline_path = root / baseline_mod.DEFAULT_BASELINE
    entries = baseline_mod.load_baseline(baseline_path)
    ctx = AnalysisContext(root)

    raw: Dict[str, List[Finding]] = {}
    for check in checks:
        raw[check.name] = list(check.run(ctx))

    all_findings = [f for fs in raw.values() for f in fs]
    ran = [c.name for c in checks]
    active, suppressed, stale = baseline_mod.apply_baseline(
        all_findings, entries, checks_run=ran)

    active_by = _group(active)
    supp_by: Dict[str, List] = {}
    for f, reason in suppressed:
        supp_by.setdefault(f.check, []).append((f, reason))

    results = [CheckResult(check=c,
                           findings=active_by.get(c.name, []),
                           suppressed=supp_by.get(c.name, []))
               for c in checks]
    if stale:
        results.append(CheckResult(check=_BaselineCheck(),
                                   findings=stale, suppressed=[]))
    return results


def _group(findings: Sequence[Finding]) -> Dict[str, List[Finding]]:
    out: Dict[str, List[Finding]] = {}
    for f in findings:
        out.setdefault(f.check, []).append(f)
    return out


def legacy_main(check_name: str, legacy_label: str,
                root: Optional[Path] = None) -> int:
    """Back-compat driver for the seven ``scripts/check_*.py`` shims:
    same verdict line (``check_X: OK`` / ``check_X: FAIL`` + per-finding
    stderr detail), same exit code, logic now shared with dqnlint."""
    import sys

    if root is None:
        root = Path(__file__).resolve().parents[2]
    results = run_checks(root, names=[check_name])
    failures = [f for r in results for f in r.findings]
    if failures:
        print(f"{legacy_label}: FAIL", file=sys.stderr)
        for f in failures:
            print(f"  {f.location()}: {f.message}", file=sys.stderr)
        return 1
    print(f"{legacy_label}: OK")
    return 0
