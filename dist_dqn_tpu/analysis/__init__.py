"""dqnlint: the unified static-analysis framework (ISSUE 13).

One substrate (``core``: repo-file discovery, cached AST walks, the
Finding dataclass, rationale-comment parsing), one suppression story
(``baseline``: reasoned entries only, stale entries fail), two
reporters (``report``: text + versioned JSON), one registry
(``registry``: a plugin per file under ``plugins/``) and one runner
(``scripts/dqnlint.py`` -> ``runner.run_checks``).

The nine checks registered today: the seven lints migrated from their
``scripts/check_*.py`` one-offs (metrics, threads, donation, sockets,
wire, mesh-axis, ckpt-schema) plus the two analyzers the one-off
pattern could never support — ``lock-discipline`` (per-class guarded-
field race inference) and ``chaos-seams`` (seam registry vs. fire/
recovery call-site drift). Catalog: docs/static_analysis.md.
"""
from dist_dqn_tpu.analysis.baseline import (BaselineError,  # noqa: F401
                                            DEFAULT_BASELINE,
                                            apply_baseline, load_baseline,
                                            save_baseline)
from dist_dqn_tpu.analysis.core import (AnalysisContext,  # noqa: F401
                                        Check, Finding, has_rationale)
from dist_dqn_tpu.analysis.registry import (check_names,  # noqa: F401
                                            discover, get_checks,
                                            register)
from dist_dqn_tpu.analysis.report import (CheckResult,  # noqa: F401
                                          render_json, render_text)
from dist_dqn_tpu.analysis.runner import (legacy_main,  # noqa: F401
                                          run_checks)
