"""dqnlint core: the shared substrate every static check builds on.

ISSUE 13: correctness tooling had accreted as seven disconnected
``scripts/check_*.py`` one-offs — each with its own repo-file walk, its
own AST parse of the same files, its own allowlist convention and its
own test wiring. This module is the shared half the one-offs never had:

  * :class:`Finding` — one defect, with a repo-relative ``file:line``
    anchor, a human message and a STABLE ``key`` (line-number-free) that
    the baseline file suppresses on;
  * :class:`AnalysisContext` — repo-file discovery (one rglob, one
    ``__pycache__``/generated-file skip rule for every check) with
    cached source text, split lines and parsed ASTs, so nine checks in
    one process parse each file once, not nine times;
  * :func:`has_rationale` — the one rationale-comment parser behind
    every ``# lock:`` / ``# donation:`` / ``# socket:`` / ``# mesh-axis:``
    escape hatch (a nearby comment owning the decision, with a reason).

Checks subclass :class:`Check` and register through
``dist_dqn_tpu.analysis.registry``; ``scripts/dqnlint.py`` is the one
runner. Stdlib only: the lint layer must import without jax.
"""
from __future__ import annotations

import ast
import dataclasses
import re
from pathlib import Path
from typing import Dict, Iterable, Iterator, List, Optional, Sequence

#: Directories never scanned, whatever the check: bytecode caches,
#: VCS internals, build/venv output trees. One skip rule for all nine
#: checks — the "skips __pycache__/generated files" satellite is a
#: property of the substrate, not of each plugin's diligence.
SKIP_DIR_NAMES = frozenset({
    "__pycache__", ".git", ".pytest_cache", ".mypy_cache", ".ruff_cache",
    "node_modules", ".eggs", "build", "dist", ".venv", "venv",
})

#: File suffixes that mark generated artifacts which may carry a .py
#: name (protobuf output is the classic).
GENERATED_SUFFIXES = ("_pb2.py", "_pb2_grpc.py")


@dataclasses.dataclass(frozen=True)
class Finding:
    """One defect one check found.

    ``path`` is repo-relative posix ("" for repo-level findings like an
    undocumented metric family); ``line`` is 1-based (0 = file/repo
    level). ``key`` is the line-number-free fingerprint baseline
    entries match on — stable across unrelated edits to the file, so a
    baselined finding does not resurface every time code above it
    moves.
    """

    check: str
    path: str
    line: int
    message: str
    key: str = ""

    def location(self) -> str:
        if not self.path:
            return "<repo>"
        return f"{self.path}:{self.line}" if self.line else self.path

    def to_dict(self) -> Dict:
        return {"check": self.check, "path": self.path, "line": self.line,
                "message": self.message, "key": self.key}


class Check:
    """One registered analyzer. Subclasses set the class attributes and
    implement :meth:`run`; ``rationale_tag`` documents the in-source
    suppression comment the check honors (None = none — suppressions go
    through the baseline file only)."""

    name: str = ""
    description: str = ""
    rationale_tag: Optional[str] = None

    def run(self, ctx: "AnalysisContext") -> List[Finding]:
        raise NotImplementedError

    def finding(self, path: str, line: int, message: str,
                key: str = "") -> Finding:
        return Finding(check=self.name, path=path, line=line,
                       message=message, key=key or f"{path}:{line}")


class AnalysisContext:
    """Shared repo-file discovery + per-file parse cache for one run.

    Every check receives the SAME context, so the source text, split
    lines and AST of a file touched by several checks are read/parsed
    once per run. Paths in and out are repo-relative posix strings —
    the same spelling Finding.path, the baseline file and the legacy
    allowlists use.
    """

    def __init__(self, root: Path):
        self.root = Path(root).resolve()
        self._source: Dict[str, str] = {}
        self._lines: Dict[str, List[str]] = {}
        self._trees: Dict[str, ast.AST] = {}

    # -- discovery -----------------------------------------------------------
    def iter_py_files(self, roots: Sequence[str]) -> Iterator[str]:
        """Repo-relative posix paths of every non-generated .py file
        under ``roots`` (each a repo-relative file or directory),
        sorted per root; missing roots yield nothing (the caller guards
        required trees explicitly, like the sockets check does)."""
        for root in roots:
            base = self.root / root
            if base.is_file():
                if self._wanted(base):
                    yield base.relative_to(self.root).as_posix()
                continue
            if not base.is_dir():
                continue
            for f in sorted(base.rglob("*.py")):
                if self._wanted(f):
                    yield f.relative_to(self.root).as_posix()

    def _wanted(self, path: Path) -> bool:
        if path.name.endswith(GENERATED_SUFFIXES):
            return False
        rel = path.relative_to(self.root)
        return not any(part in SKIP_DIR_NAMES for part in rel.parts[:-1])

    # -- cached reads --------------------------------------------------------
    def source(self, rel: str) -> str:
        src = self._source.get(rel)
        if src is None:
            src = (self.root / rel).read_text()
            self._source[rel] = src
        return src

    def lines(self, rel: str) -> List[str]:
        lines = self._lines.get(rel)
        if lines is None:
            lines = self.source(rel).splitlines()
            self._lines[rel] = lines
        return lines

    def tree(self, rel: str) -> ast.AST:
        """Parsed AST (cached). Raises SyntaxError — checks convert an
        unparseable file into a Finding so the run stays a report, not
        a crash."""
        tree = self._trees.get(rel)
        if tree is None:
            tree = ast.parse(self.source(rel))
            self._trees[rel] = tree
        return tree


def unparseable(check: Check, rel: str, err: SyntaxError) -> Finding:
    return check.finding(
        rel, err.lineno or 0,
        f"unparseable Python ({err.msg}) — every check skips this file "
        "until it parses", key=f"unparseable:{rel}")


def rationale_pattern(tag: str) -> "re.Pattern[str]":
    """The comment shape that suppresses a finding at source: a comment
    containing ``<tag>`` (e.g. ``# lock: probe is read-only``) — the tag
    must be followed by an actual reason on the same line, not bare."""
    return re.compile(rf"#.*\b{re.escape(tag.rstrip(':'))}:\s*\S")


def has_rationale(lines: Sequence[str], lineno: int, tag: str,
                  span: int = 3, def_lineno: Optional[int] = None) -> bool:
    """True when a ``# <tag>: <reason>`` comment owns the code at
    1-based ``lineno``: on the line itself or within ``span`` lines
    above it — or, when ``def_lineno`` is given, on/just above the
    enclosing function's ``def`` line (a method-level rationale covering
    every access in the method)."""
    pat = rationale_pattern(tag)
    lo = max(lineno - span, 0)
    if any(pat.search(ln) for ln in lines[lo:lineno]):
        return True
    if def_lineno is not None:
        lo = max(def_lineno - span, 0)
        return any(pat.search(ln) for ln in lines[lo:def_lineno])
    return False


def count_matches(pattern: "re.Pattern[str]", text: str) -> int:
    return len(pattern.findall(text))


def dedupe(findings: Iterable[Finding]) -> List[Finding]:
    """One finding per (check, path, key), keeping the first (lowest
    line) — multi-site defects report once under their stable key."""
    seen = set()
    out: List[Finding] = []
    for f in sorted(findings, key=lambda f: (f.path, f.line)):
        ident = (f.check, f.path, f.key)
        if ident in seen:
            continue
        seen.add(ident)
        out.append(f)
    return out
