"""Baseline suppression with mandatory reasons (ISSUE 13).

The third triage outcome for a finding (after "fix it" and "own it with
a rationale comment at the site"): a reasoned entry in the shared
baseline file ``scripts/dqnlint_baseline.json``. The contract that
keeps the baseline from becoming a landfill:

  * every entry carries a non-empty ``reason`` string — loading a
    reasonless entry is a hard :class:`BaselineError`, not a warning
    (zero silent suppressions, by construction);
  * entries match findings on ``(check, path, key)`` — ``key`` is the
    check's line-number-free fingerprint (e.g.
    ``DivergenceSentinel._trip:log_fn``), so unrelated edits to the
    file never invalidate or mis-apply an entry;
  * an entry that no longer matches any finding is STALE and becomes a
    finding itself — the defect was fixed (or the code deleted), so the
    entry must leave in the same PR; baselines only shrink toward zero.
"""
from __future__ import annotations

import json
from pathlib import Path
from typing import Dict, List, Sequence, Tuple

from dist_dqn_tpu.analysis.core import Finding

BASELINE_VERSION = 1
#: Repo-relative default location (next to the runner it feeds).
DEFAULT_BASELINE = "scripts/dqnlint_baseline.json"

_REQUIRED_FIELDS = ("check", "path", "key", "reason")


class BaselineError(ValueError):
    """The baseline file itself is invalid (missing reason, unknown
    shape) — the run fails loudly instead of suppressing on bad data."""


def load_baseline(path: Path) -> List[Dict]:
    """Parse + validate the baseline file; [] when absent (a repo with
    no baseline is simply a repo with nothing suppressed)."""
    path = Path(path)
    if not path.exists():
        return []
    try:
        payload = json.loads(path.read_text())
    except ValueError as e:
        raise BaselineError(f"{path}: not valid JSON ({e})") from e
    if not isinstance(payload, dict) or "entries" not in payload:
        raise BaselineError(
            f"{path}: expected {{\"version\": {BASELINE_VERSION}, "
            f"\"entries\": [...]}}")
    entries = payload["entries"]
    if not isinstance(entries, list):
        raise BaselineError(f"{path}: \"entries\" must be a list")
    for i, entry in enumerate(entries):
        if not isinstance(entry, dict):
            raise BaselineError(f"{path}: entry {i} is not an object")
        for field in _REQUIRED_FIELDS:
            if field not in entry:
                raise BaselineError(
                    f"{path}: entry {i} is missing {field!r}")
        reason = entry["reason"]
        if not isinstance(reason, str) or not reason.strip():
            raise BaselineError(
                f"{path}: entry {i} ({entry['check']}: {entry['key']}) "
                f"has no reason — every baseline suppression must say "
                f"WHY the finding is acceptable")
    return entries


def save_baseline(path: Path, entries: Sequence[Dict]) -> None:
    payload = {"version": BASELINE_VERSION,
               "entries": sorted(entries,
                                 key=lambda e: (e["check"], e["path"],
                                                e["key"]))}
    Path(path).write_text(json.dumps(payload, indent=1, sort_keys=True)
                          + "\n")


def apply_baseline(findings: Sequence[Finding], entries: Sequence[Dict],
                   checks_run: Sequence[str],
                   ) -> Tuple[List[Finding], List[Tuple[Finding, str]],
                              List[Finding]]:
    """Partition ``findings`` against the baseline.

    Returns ``(active, suppressed, stale)``: unsuppressed findings, the
    suppressed ones paired with their entry's reason, and one synthetic
    ``baseline`` finding per entry (for a check that actually ran) that
    matched nothing — stale entries fail the run until removed.
    """
    by_ident = {(e["check"], e["path"], e["key"]): e for e in entries}
    active: List[Finding] = []
    suppressed: List[Tuple[Finding, str]] = []
    matched = set()
    for f in findings:
        entry = by_ident.get((f.check, f.path, f.key))
        if entry is None:
            active.append(f)
        else:
            matched.add(id(entry))
            suppressed.append((f, entry["reason"]))
    ran = set(checks_run)
    stale = [
        Finding(check="baseline", path=e["path"], line=0,
                message=(f"stale baseline entry for {e['check']} "
                         f"(key {e['key']!r}): it no longer matches any "
                         "finding — the defect was fixed or the code "
                         "moved; delete the entry"),
                key=f"stale:{e['check']}:{e['key']}")
        for e in entries
        if id(e) not in matched and e["check"] in ran
    ]
    return active, suppressed, stale
