"""dqnlint reporters: human text and the machine-readable JSON artifact.

The JSON shape (``scripts/dqnlint.py --all --json``) is a versioned
contract — CI tooling diffs findings across runs on it, so additive
evolution only (bump ``JSON_SCHEMA_VERSION`` on a breaking change):

    {"dqnlint": 1,
     "ok": bool,
     "summary": {"checks_run": N, "findings": N, "suppressed": N,
                 "stale_baseline": N},
     "checks": [{"name": str, "description": str, "ok": bool,
                 "rationale_tag": str | null,
                 "findings": [{"check", "path", "line", "message",
                               "key"}],
                 "suppressed": [{finding..., "reason": str}]}]}
"""
from __future__ import annotations

import dataclasses
from typing import Dict, List, Tuple

from dist_dqn_tpu.analysis.core import Check, Finding

JSON_SCHEMA_VERSION = 1


@dataclasses.dataclass
class CheckResult:
    """One check's outcome after baseline application."""

    check: Check
    findings: List[Finding]                    # active (unsuppressed)
    suppressed: List[Tuple[Finding, str]]      # (finding, reason)

    @property
    def ok(self) -> bool:
        return not self.findings

    def to_dict(self) -> Dict:
        return {
            "name": self.check.name,
            "description": self.check.description,
            "rationale_tag": self.check.rationale_tag,
            "ok": self.ok,
            "findings": [f.to_dict() for f in self.findings],
            "suppressed": [{**f.to_dict(), "reason": reason}
                           for f, reason in self.suppressed],
        }


def render_json(results: List[CheckResult]) -> Dict:
    findings = sum(len(r.findings) for r in results)
    stale = sum(1 for r in results for f in r.findings
                if f.check == "baseline")
    return {
        "dqnlint": JSON_SCHEMA_VERSION,
        "ok": findings == 0,
        "summary": {
            "checks_run": len(results),
            "findings": findings,
            "suppressed": sum(len(r.suppressed) for r in results),
            "stale_baseline": stale,
        },
        "checks": [r.to_dict() for r in results],
    }


def render_text(results: List[CheckResult], verbose: bool = False) -> str:
    """The human report: one verdict line per check, finding details for
    the failing ones (and suppression notes with ``verbose``)."""
    out: List[str] = []
    for r in results:
        supp = f" ({len(r.suppressed)} baselined)" if r.suppressed else ""
        if r.ok:
            out.append(f"{r.check.name}: OK{supp}")
        else:
            out.append(f"{r.check.name}: FAIL "
                       f"({len(r.findings)} findings){supp}")
            for f in r.findings:
                out.append(f"  {f.location()}: {f.message}")
        if verbose:
            for f, reason in r.suppressed:
                out.append(f"  [baselined] {f.location()}: {f.message}")
                out.append(f"              reason: {reason}")
    total = sum(len(r.findings) for r in results)
    out.append(f"dqnlint: {'OK' if total == 0 else 'FAIL'} "
               f"({len(results)} checks, {total} findings, "
               f"{sum(len(r.suppressed) for r in results)} suppressed)")
    return "\n".join(out)
