"""Check registry + plugin discovery for dqnlint (ISSUE 13).

A plugin is a module under ``dist_dqn_tpu/analysis/plugins/`` that
instantiates a :class:`~dist_dqn_tpu.analysis.core.Check` subclass and
passes it to :func:`register` at import time. Discovery is one
``pkgutil`` walk over the plugins package — adding a check is adding a
file, not editing a central list (docs/static_analysis.md, "adding a
plugin").
"""
from __future__ import annotations

import importlib
import pkgutil
from typing import Dict, List, Optional, Sequence

from dist_dqn_tpu.analysis.core import Check

_CHECKS: Dict[str, Check] = {}
_discovered = False


def register(check: Check) -> Check:
    """Register one check instance (import-time, from its plugin
    module). Duplicate names are a programming error — two plugins
    fighting over a name would make ``--check NAME`` ambiguous."""
    if not check.name:
        raise ValueError(f"check {check!r} has no name")
    existing = _CHECKS.get(check.name)
    if existing is not None and type(existing) is not type(check):
        raise ValueError(f"duplicate check name {check.name!r}: "
                         f"{type(existing).__name__} vs "
                         f"{type(check).__name__}")
    _CHECKS[check.name] = check
    return check


def discover() -> None:
    """Import every module under analysis/plugins/ exactly once."""
    global _discovered
    if _discovered:
        return
    from dist_dqn_tpu.analysis import plugins

    for mod in pkgutil.iter_modules(plugins.__path__):
        importlib.import_module(f"{plugins.__name__}.{mod.name}")
    _discovered = True


def get_checks(names: Optional[Sequence[str]] = None) -> List[Check]:
    """The registered checks (all, in name order) or the named subset
    (in the requested order); unknown names raise with the known set."""
    discover()
    if names is None:
        return [_CHECKS[n] for n in sorted(_CHECKS)]
    out = []
    for n in names:
        if n not in _CHECKS:
            raise KeyError(f"unknown check {n!r} "
                           f"(known: {sorted(_CHECKS)})")
        out.append(_CHECKS[n])
    return out


def check_names() -> List[str]:
    discover()
    return sorted(_CHECKS)
