"""Chip-time attribution plane (ISSUE 19): per-program device-time
ledger, utilization decomposition, on-demand profiling, HBM telemetry.

ROADMAP item 1 says the chip is ~96% idle but nothing in the repo can
say *why*: ``dqn_learner_mfu`` was hand-wired per runtime and no metric
attributed chunk wall-time to device-busy vs host-blocked causes. This
module is the shared substrate:

``ProgramRegistry``
    Process-wide table of every jitted entry point (fused chunk,
    collect, train/scan-train step, act dispatch, sampler draw,
    evac split). Each :class:`ProgramRecord` carries FLOPs/bytes from
    the XLA cost analysis (``utils/flops.py``), dispatch counts, and
    device-seconds sampled at fences the loops ALREADY hold — no new
    synchronization on the hot path. Cost is harvested lazily via
    ``jitted.lower(*args).cost_analysis()`` at the first dispatch site
    (trace-only; never forces a second XLA compile and never perturbs
    the jit cache).

``UtilizationLedger``
    Decomposes each chunk's wall-time into device-busy plus the named
    host-blocked buckets ``sample | evac_fence | prefetch_wait | h2d |
    other`` and feeds the ``dqn_chip_idle_seconds_total{cause}`` /
    ``dqn_chip_busy_seconds_total`` families.

``set_learner_mfu``
    The registry-derived replacement for the per-loop MFU hand-wirings:
    FLOPs-per-exec x executions / device-seconds over the chip's bf16
    peak.

``sweep_device_memory`` / ``capture_profile``
    ``Device.memory_stats()`` -> ``dqn_device_memory_bytes{kind,device}``
    gauges with host-tracked peak, and the ``/debug/profile?seconds=N``
    backend (jax.profiler trace into the forensics dir).

Everything degrades on CPU: cost analysis that fails leaves FLOPs
``None`` (gauges absent, never a crash), ``memory_stats() is None``
sweeps to nothing, and jax itself is imported lazily so the module
stays importable from jax-free actor processes.
"""
from __future__ import annotations

import os
import tempfile
import threading
import time
from typing import Any, Callable, Dict, Optional

from dist_dqn_tpu.telemetry import collectors as tmc
from dist_dqn_tpu.telemetry.registry import Registry, get_registry
from dist_dqn_tpu.utils import flops as flops_util

#: Fixed idle-cause vocabulary for dqn_chip_idle_seconds_total. Keep in
#: lockstep with the docs/observability.md naming table.
IDLE_CAUSES = ("sample", "evac_fence", "prefetch_wait", "h2d", "other")

#: Hard ceiling on one /debug/profile capture; xprof windows past this
#: are better taken as several correlated short ones.
PROFILE_MAX_SECONDS = 60.0


def _cost_from(obj: Any) -> Dict[str, Optional[float]]:
    """FLOPs/bytes for one execution of ``obj`` — a Compiled, a Lowered,
    or a zero-arg callable returning either. Any failure (CPU backends
    without a cost model, interpreter mode, tracing errors) degrades to
    ``{"flops": None, "bytes": None}``."""
    try:
        if callable(obj) and not hasattr(obj, "cost_analysis"):
            obj = obj()
        flops = flops_util.compiled_flops(obj)
        nbytes = flops_util.compiled_bytes(obj)
    except Exception:
        flops = nbytes = None
    return {"flops": flops, "bytes": nbytes}


class ProgramRecord:
    """One jitted entry point: static cost + running dispatch tallies.

    ``flops``/``bytes`` are for ONE execution of the compiled program.
    Caveat inherited from the XLA cost census: a ``lax.scan`` body is
    counted once regardless of trip count, so scan-shaped programs
    should register with ``execs_per_dispatch`` = trip count to keep
    FLOPs x executions honest.
    """

    def __init__(self, registry: "ProgramRegistry", name: str, loop: str,
                 role: Optional[str], execs_per_dispatch: float):
        self._registry = registry
        self.name = name
        self.loop = loop
        self.role = role
        self.execs_per_dispatch = float(execs_per_dispatch)
        self.flops: Optional[float] = None
        self.bytes: Optional[float] = None
        self._cost_done = False
        self._lock = threading.Lock()
        labels = {"program": name, "loop": loop}
        reg = registry.metrics
        self._g_flops = reg.gauge(
            tmc.PROGRAM_FLOPS, "FLOPs per execution (XLA cost analysis)",
            labels)
        self._g_bytes = reg.gauge(
            tmc.PROGRAM_BYTES, "bytes accessed per execution", labels)
        self._c_dispatch = reg.counter(
            tmc.PROGRAM_DISPATCHES, "host-side launches", labels)
        self._c_devsec = reg.counter(
            tmc.PROGRAM_DEVICE_SECONDS,
            "device time attributed at existing fences", labels)
        self.dispatches = 0.0
        self.device_seconds = 0.0

    def attach_cost(self, source: Any) -> "ProgramRecord":
        """Harvest FLOPs/bytes once from ``source`` (Compiled / Lowered /
        zero-arg callable returning either). Idempotent: the first
        successful harvest wins; repeat calls and failures are free, so
        dispatch sites can call this unconditionally."""
        with self._lock:
            if self._cost_done:
                return self
            cost = _cost_from(source)
            if cost["flops"] is None and cost["bytes"] is None:
                # Leave _cost_done False only for *callables* that may
                # succeed later? No: retrying a failing trace every
                # dispatch is hot-path work. One shot, like the fences.
                self._cost_done = True
                return self
            self.flops, self.bytes = cost["flops"], cost["bytes"]
            self._cost_done = True
        if self.flops is not None:
            self._g_flops.set(self.flops)
        if self.bytes is not None:
            self._g_bytes.set(self.bytes)
        return self

    @property
    def cost_attached(self) -> bool:
        return self._cost_done

    def count_dispatch(self, n: float = 1.0) -> None:
        self.dispatches += n
        self._c_dispatch.inc(n)

    def add_device_seconds(self, seconds: float) -> None:
        if seconds <= 0:
            return
        self.device_seconds += seconds
        self._c_devsec.inc(seconds)

    @property
    def executions(self) -> float:
        return self.dispatches * self.execs_per_dispatch

    @property
    def arith_intensity(self) -> Optional[float]:
        if self.flops is None or not self.bytes:
            return None
        return self.flops / self.bytes

    def snapshot(self) -> Dict[str, Any]:
        out: Dict[str, Any] = {
            "program": self.name,
            "loop": self.loop,
            "flops": self.flops,
            "bytes": self.bytes,
            "dispatches": self.dispatches,
            "execs_per_dispatch": self.execs_per_dispatch,
            "device_seconds": self.device_seconds,
        }
        ai = self.arith_intensity
        if ai is not None:
            out["arith_intensity"] = ai
        return out


class ProgramRegistry:
    """Process-wide (name, loop) -> :class:`ProgramRecord` table."""

    def __init__(self, metrics: Optional[Registry] = None):
        self.metrics = metrics if metrics is not None else get_registry()
        self._records: Dict[tuple, ProgramRecord] = {}
        self._lock = threading.RLock()

    def register(self, name: str, loop: str = "default",
                 cost: Any = None, role: Optional[str] = None,
                 execs_per_dispatch: float = 1.0) -> ProgramRecord:
        """Get-or-create the record for ``(name, loop)``. ``cost`` (a
        Compiled/Lowered/zero-arg callable) is attached immediately when
        given; dispatch sites that only have real args later can call
        ``record.attach_cost`` themselves."""
        key = (name, loop)
        with self._lock:
            rec = self._records.get(key)
            if rec is None:
                rec = ProgramRecord(self, name, loop, role,
                                    execs_per_dispatch)
                self._records[key] = rec
            elif role is not None and rec.role is None:
                rec.role = role
        if cost is not None:
            rec.attach_cost(cost)
        return rec

    def records(self, loop: Optional[str] = None):
        with self._lock:
            recs = list(self._records.values())
        if loop is not None:
            recs = [r for r in recs if r.loop == loop]
        return recs

    def get(self, name: str, loop: str = "default"):
        with self._lock:
            return self._records.get((name, loop))

    def snapshot(self, loop: Optional[str] = None) -> Dict[str, Dict]:
        """JSON-able {program: fields} block for BENCH rows."""
        return {r.name: r.snapshot() for r in self.records(loop)}

    def learner_mfu(self, loop: str,
                    device: Any = None) -> Optional[float]:
        """Registry-derived MFU for ``loop``: summed FLOPs x executions
        over summed device-seconds of every record tagged role="train",
        against the chip's bf16 peak. None when no train program has
        both cost and device time, or the chip peak is unknown (CPU)."""
        if device is None:
            device = _default_device()
        if device is None:
            return None
        peak = flops_util.chip_peak_flops(device)
        if not peak:
            return None
        total_flops = 0.0
        total_secs = 0.0
        for rec in self.records(loop):
            if rec.role != "train" or rec.flops is None:
                continue
            total_flops += rec.flops * rec.executions
            total_secs += rec.device_seconds
        if total_secs <= 0 or total_flops <= 0:
            return None
        return (total_flops / total_secs) / peak


_program_registry = ProgramRegistry()


def get_program_registry() -> ProgramRegistry:
    """The process-global program registry (what every loop uses)."""
    return _program_registry


def reset_program_registry(metrics: Optional[Registry] = None
                           ) -> ProgramRegistry:
    """Swap in a fresh registry (tests / multi-leg benchmarks that want
    per-leg dispatch tallies). Returns the new instance."""
    global _program_registry
    _program_registry = ProgramRegistry(metrics)
    return _program_registry


def register_program(name: str, loop: str = "default", cost: Any = None,
                     role: Optional[str] = None,
                     execs_per_dispatch: float = 1.0) -> ProgramRecord:
    """Module-level convenience for the common dispatch-site idiom."""
    return _program_registry.register(
        name, loop=loop, cost=cost, role=role,
        execs_per_dispatch=execs_per_dispatch)


def programs_snapshot(loop: Optional[str] = None) -> Dict[str, Dict]:
    return _program_registry.snapshot(loop)


def _default_device():
    try:
        import jax
        return jax.devices()[0]
    except Exception:
        return None


def set_learner_mfu(loop: str, device: Any = None,
                    reg: Optional[Registry] = None) -> Optional[float]:
    """Publish the registry-derived ``dqn_learner_mfu{loop=...}`` gauge.
    No-op (gauge absent) when the MFU is underivable — unknown chip
    peak, no cost analysis, no device time yet."""
    value = _program_registry.learner_mfu(loop, device=device)
    if value is None:
        return None
    if reg is None:
        reg = get_registry()
    reg.gauge(tmc.LEARNER_MFU, "model FLOPs utilization vs chip peak "
              "(registry-derived)", {"loop": loop}).set(value)
    return value


class UtilizationLedger:
    """Per-chunk wall-time decomposition for one loop.

    ``observe_chunk(wall_s, busy_s, sample=..., evac_fence=...,
    prefetch_wait=..., h2d=...)`` files the measured device-busy time
    under ``dqn_chip_busy_seconds_total{loop}`` and the host-blocked
    remainder under ``dqn_chip_idle_seconds_total{loop, cause}``;
    whatever wall-time the named causes don't explain lands in
    ``other`` (clamped at zero — the buckets are estimates sampled at
    existing fences, never allowed to go negative). All five cause
    series are registered up front so the family is scrapeable at 0
    before the first chunk and dashboards never see a hole.
    """

    def __init__(self, loop: str, reg: Optional[Registry] = None):
        if reg is None:
            reg = get_registry()
        self.loop = loop
        self._busy = reg.counter(
            tmc.CHIP_BUSY_SECONDS,
            "chunk wall-time the device was measured busy",
            {"loop": loop})
        self._idle = {
            cause: reg.counter(
                tmc.CHIP_IDLE_SECONDS,
                "chunk wall-time the device sat idle, by cause",
                {"loop": loop, "cause": cause})
            for cause in IDLE_CAUSES
        }
        self.chunks = 0
        self.totals: Dict[str, float] = {"busy": 0.0}
        self.totals.update({c: 0.0 for c in IDLE_CAUSES})

    def observe_chunk(self, wall_s: float, busy_s: float,
                      sample: float = 0.0, evac_fence: float = 0.0,
                      prefetch_wait: float = 0.0,
                      h2d: float = 0.0) -> Dict[str, float]:
        """File one chunk; returns the breakdown (incl. the derived
        ``other`` residual) for the caller's own log row."""
        wall_s = max(float(wall_s), 0.0)
        busy_s = min(max(float(busy_s), 0.0), wall_s)
        named = {"sample": max(float(sample), 0.0),
                 "evac_fence": max(float(evac_fence), 0.0),
                 "prefetch_wait": max(float(prefetch_wait), 0.0),
                 "h2d": max(float(h2d), 0.0)}
        named["other"] = max(wall_s - busy_s - sum(named.values()), 0.0)
        self._busy.inc(busy_s)
        self.totals["busy"] += busy_s
        for cause, secs in named.items():
            if secs > 0:
                self._idle[cause].inc(secs)
            self.totals[cause] += secs
        self.chunks += 1
        out = {"wall": wall_s, "busy": busy_s}
        out.update(named)
        return out

    def snapshot(self) -> Dict[str, float]:
        return {"chunks": float(self.chunks), **self.totals}


# ---------------------------------------------------------------------------
# Device memory telemetry


_mem_lock = threading.Lock()
_mem_peaks: Dict[str, float] = {}


def sweep_device_memory(reg: Optional[Registry] = None,
                        devices: Any = None) -> Dict[str, Dict[str, float]]:
    """Sweep ``Device.memory_stats()`` into
    ``dqn_device_memory_bytes{kind, device}`` gauges.

    Backends that report nothing (CPU returns ``None``) or partial
    dicts sweep to exactly the keys they report — gauges degrade to
    absent, never crash. ``bytes_in_use`` additionally feeds a
    host-tracked high-water mark published as
    ``kind="peak_bytes_in_use_seen"`` (native ``peak_bytes_in_use``
    resets on some backends). Returns {device_label: {kind: bytes}}
    of what was actually swept (empty dict when nothing reported).
    """
    if reg is None:
        reg = get_registry()
    if devices is None:
        try:
            import jax
            devices = jax.local_devices()
        except Exception:
            return {}
    swept: Dict[str, Dict[str, float]] = {}
    for i, dev in enumerate(devices):
        try:
            stats = dev.memory_stats()
        except Exception:
            stats = None
        if not stats:
            continue
        label = str(getattr(dev, "id", i))
        kinds: Dict[str, float] = {}
        for kind, value in stats.items():
            try:
                value = float(value)
            except (TypeError, ValueError):
                continue
            kinds[kind] = value
            reg.gauge(tmc.DEVICE_MEMORY_BYTES,
                      "Device.memory_stats() sweep",
                      {"kind": str(kind), "device": label}).set(value)
        in_use = kinds.get("bytes_in_use")
        if in_use is not None:
            with _mem_lock:
                peak = max(_mem_peaks.get(label, 0.0), in_use)
                _mem_peaks[label] = peak
            kinds["peak_bytes_in_use_seen"] = peak
            reg.gauge(tmc.DEVICE_MEMORY_BYTES,
                      "host-tracked high-water mark of bytes_in_use",
                      {"kind": "peak_bytes_in_use_seen",
                       "device": label}).set(peak)
        if kinds:
            swept[label] = kinds
    return swept


# ---------------------------------------------------------------------------
# On-demand profiling (/debug/profile backend)


_profile_lock = threading.Lock()


def _profile_base_dir() -> str:
    """Where captures land: the armed watchdog/sentinel forensics dir,
    else $DQN_FORENSICS_DIR, else a tempdir — same resolution order the
    crash path uses, so traces sit next to the forensics bundles."""
    from dist_dqn_tpu.telemetry import watchdog as wd
    for get in (wd.get_watchdog, getattr(wd, "get_sentinel", None)):
        if get is None:
            continue
        try:
            holder = get()
        except Exception:
            continue
        d = getattr(holder, "forensics_dir", None)
        if d:
            return str(d)
    env = os.environ.get(wd.FORENSICS_ENV)
    if env:
        return env
    return tempfile.mkdtemp(prefix="dqn-profile-")


def capture_profile(seconds: float,
                    base_dir: Optional[str] = None) -> Dict[str, Any]:
    """Capture a ``jax.profiler`` trace for ``seconds`` (clamped to
    [0, PROFILE_MAX_SECONDS]) into a fresh subdirectory of the
    forensics dir. Serialized process-wide: a second caller while a
    capture is running gets ``{"error": "busy"}`` instead of corrupting
    the active trace. ``seconds=0`` opens and immediately closes the
    trace window — cheap smoke-path for tests and endpoint probes.
    """
    try:
        seconds = max(0.0, min(float(seconds), PROFILE_MAX_SECONDS))
    except (TypeError, ValueError):
        return {"error": f"bad seconds value: {seconds!r}"}
    if not _profile_lock.acquire(blocking=False):
        return {"error": "busy", "detail": "a capture is already running"}
    try:
        try:
            import jax.profiler
        except Exception as e:  # jax-free process (actor-side server)
            return {"error": f"jax unavailable: {e}"}
        base = base_dir or _profile_base_dir()
        trace_dir = os.path.join(
            base, f"profile-{os.getpid()}-{int(time.time() * 1000)}")
        os.makedirs(trace_dir, exist_ok=True)
        t0 = time.perf_counter()
        try:
            jax.profiler.start_trace(trace_dir)
            if seconds > 0:
                time.sleep(seconds)
        finally:
            try:
                jax.profiler.stop_trace()
            except Exception as e:
                return {"error": f"stop_trace failed: {e}",
                        "trace_dir": trace_dir}
        n_files = sum(len(fns) for _, _, fns in os.walk(trace_dir))
        return {"trace_dir": trace_dir,
                "seconds": seconds,
                "capture_wall_s": time.perf_counter() - t0,
                "files": n_files}
    finally:
        _profile_lock.release()


def maybe_trace_first_chunk(profile_dir: Optional[str]):
    """The --profile-dir contract, shared by all three runtimes: a
    context-manager-shaped pair of (start, stop) callables that trace
    exactly one post-warmup chunk into ``profile_dir`` and are no-ops
    when it is unset or jax.profiler is unavailable."""

    class _OneShot:
        def __init__(self, target: Optional[str]):
            self._target = target
            self._armed = bool(target)
            self._active = False

        def start(self) -> None:
            if not self._armed or self._active:
                return
            try:
                import jax.profiler
                jax.profiler.start_trace(self._target)
                self._active = True
            except Exception:
                self._armed = False

        def stop(self) -> Optional[str]:
            if not self._active:
                return None
            try:
                import jax.profiler
                jax.profiler.stop_trace()
            except Exception:
                pass
            self._active = False
            self._armed = False  # one shot
            return self._target

    return _OneShot(profile_dir)
