"""Prometheus scrape endpoint + debug surface: a stdlib http.server on a
daemon thread.

``TelemetryServer(port=0)`` binds an ephemeral port (the bound port is on
``.port``) and serves

  * ``/metrics``      — Prometheus text exposition (scrape this)
  * ``/metrics.json`` — the JSON snapshot (same data, offline tooling)
  * ``/healthz``      — liveness probe. WATCHDOG-BACKED (ISSUE 4): when
    a stall watchdog is installed (telemetry/watchdog.py) and any stage
    heartbeat is past its deadline, this returns **503** with the stale
    stages as JSON — so the same probe a balancer polls also says WHICH
    pipeline stage wedged. Without a watchdog it stays the static
    ``ok`` it always was.
  * ``/debug/stacks`` — every live thread's Python stack, by thread
    NAME (what you'd get from a forensics bundle's stacks.txt, live)
  * ``/debug/flight`` — the flight recorder's event tail as JSON
  * ``/debug/config`` — the run manifest (git sha, versions, config
    hash/dict, argv; telemetry/manifest.py) of this process
  * ``/debug/profile?seconds=N`` — ON-DEMAND PROFILING (ISSUE 19):
    capture a jax.profiler trace of the next N seconds (clamped to
    ``devtime.PROFILE_MAX_SECONDS``) into the forensics dir and return
    the trace directory as JSON — an xprof window is one HTTP call
    instead of a restart. 409 while another capture is running; JSON
    ``error`` (status 200) on jax-free processes so fleet fan-out can
    label rather than fail.

The handler renders under the registry's own locks, so a scrape never
blocks the training hot path for more than an instrument read. Loopback
by default — the metric/debug surface is unauthenticated, same posture
as the TCP record listener (actors/service.py).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional
from urllib.parse import parse_qs, urlsplit

from dist_dqn_tpu.telemetry import devtime as devtime_mod
from dist_dqn_tpu.telemetry import flight as flight_mod
from dist_dqn_tpu.telemetry import manifest as manifest_mod
from dist_dqn_tpu.telemetry import watchdog as watchdog_mod
from dist_dqn_tpu.telemetry.exposition import (CONTENT_TYPE,
                                               render_prometheus, snapshot)
from dist_dqn_tpu.telemetry.registry import Registry, get_registry


def healthz_body():
    """(status, body): 200 ``ok`` when nothing armed reports trouble;
    503 + JSON naming stale stages, latched divergence signals and/or
    failing health probes otherwise (telemetry/watchdog.py
    ``health_state``). Shared with the serving tier's HTTP surface
    (dist_dqn_tpu/serving/server.py) so /healthz means the same thing
    on every endpoint of a process."""
    ok, detail = watchdog_mod.health_state()
    if ok:
        return 200, b"ok\n"
    return 503, (json.dumps({"status": "unhealthy", **detail},
                            sort_keys=True) + "\n").encode()


class TelemetryServer:
    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[Registry] = None):
        registry = registry if registry is not None else get_registry()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                status = 200
                if path in ("/metrics", "/"):
                    body = render_prometheus(registry).encode()
                    ctype = CONTENT_TYPE
                elif path == "/metrics.json":
                    body = (json.dumps(snapshot(registry), sort_keys=True)
                            + "\n").encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    status, body = healthz_body()
                    ctype = ("text/plain" if status == 200
                             else "application/json")
                elif path == "/debug/stacks":
                    body = watchdog_mod.format_stacks().encode()
                    ctype = "text/plain"
                elif path == "/debug/flight":
                    body = (json.dumps(flight_mod.get_flight().snapshot())
                            + "\n").encode()
                    ctype = "application/json"
                elif path == "/debug/config":
                    man = manifest_mod.get_run_manifest()
                    body = (json.dumps(man if man is not None else {},
                                       sort_keys=True) + "\n").encode()
                    ctype = "application/json"
                elif path == "/debug/profile":
                    qs = parse_qs(urlsplit(self.path).query)
                    seconds = (qs.get("seconds") or ["1"])[0]
                    result = devtime_mod.capture_profile(seconds)
                    if result.get("error") == "busy":
                        status = 409
                    body = (json.dumps(result, sort_keys=True)
                            + "\n").encode()
                    ctype = "application/json"
                else:
                    self.send_error(404)
                    return
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam the JSON-line log stream

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="telemetry-http", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


def start_server(port: int, host: str = "127.0.0.1",
                 registry: Optional[Registry] = None) -> TelemetryServer:
    """Convenience: build + start (port 0 = ephemeral, see ``.port``)."""
    return TelemetryServer(port=port, host=host, registry=registry)
