"""Prometheus scrape endpoint: a stdlib http.server on a daemon thread.

``TelemetryServer(port=0)`` binds an ephemeral port (the bound port is on
``.port``) and serves

  * ``/metrics``      — Prometheus text exposition (scrape this)
  * ``/metrics.json`` — the JSON snapshot (same data, offline tooling)
  * ``/healthz``      — liveness probe (always ``ok``)

The handler renders under the registry's own locks, so a scrape never
blocks the training hot path for more than an instrument read. Loopback
by default — the metric surface is unauthenticated, same posture as the
TCP record listener (actors/service.py).
"""
from __future__ import annotations

import json
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Optional

from dist_dqn_tpu.telemetry.exposition import (CONTENT_TYPE,
                                               render_prometheus, snapshot)
from dist_dqn_tpu.telemetry.registry import Registry, get_registry


class TelemetryServer:
    def __init__(self, port: int = 0, host: str = "127.0.0.1",
                 registry: Optional[Registry] = None):
        registry = registry if registry is not None else get_registry()

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = render_prometheus(registry).encode()
                    ctype = CONTENT_TYPE
                elif path == "/metrics.json":
                    body = (json.dumps(snapshot(registry), sort_keys=True)
                            + "\n").encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass  # scrapes must not spam the JSON-line log stream

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="telemetry-http", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"http://{self.host}:{self.port}/metrics"

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


def start_server(port: int, host: str = "127.0.0.1",
                 registry: Optional[Registry] = None) -> TelemetryServer:
    """Convenience: build + start (port 0 = ephemeral, see ``.port``)."""
    return TelemetryServer(port=port, host=host, registry=registry)
