"""One flush lifecycle for all telemetry: atexit + SIGTERM callbacks.

Traces from killed actor/learner processes were silently lost before
this module (ISSUE 1 satellite): ``SpanTracer`` only flushed when the
owner remembered to call ``close()``, and a SIGTERM'd process never got
there. Every telemetry sink now registers its flush here exactly once:

  * ``SpanTracer`` registers its ``flush`` on construction;
  * ``install_snapshot_dump(path)`` registers a registry JSON dump
    (``DQN_TELEMETRY_SNAPSHOT=<path>`` does the same from the
    environment — how spawned actor/feeder processes opt in).

The SIGTERM handler CHAINS any pre-existing handler (device_cleanup.py
installs one in accelerator entry points; order of installation does not
matter — whichever runs first calls the other), and callbacks run at
most once per process so the atexit leg after a handled signal cannot
double-flush. Same honest limit as device_cleanup: a handler only runs
while the main thread executes Python bytecode — SIGKILL, or a SIGTERM
landing inside an uninterruptible syscall, still loses the tail.
"""
from __future__ import annotations

import atexit
import os
import signal
import threading
from typing import Callable, List, Optional

# Reentrant: the SIGTERM leg runs on the main thread and may interrupt a
# frame that already holds this lock (a registration in progress).
_lock = threading.RLock()
_callbacks: List[Callable[[], None]] = []
_installed = False
_ran = False

#: Environment knob: a path here makes ANY process that imports telemetry
#: (and calls maybe_install_snapshot_from_env, as actor/feeder entry
#: points do) dump its registry snapshot on exit. ``{pid}`` in the path
#: is substituted so a process fleet does not clobber one file.
SNAPSHOT_ENV = "DQN_TELEMETRY_SNAPSHOT"


def _run_callbacks() -> None:
    global _ran
    with _lock:
        if _ran:
            return
        _ran = True
        callbacks = list(_callbacks)
    for fn in callbacks:
        try:
            fn()
        except Exception:  # noqa: BLE001 — exit path must not raise
            pass


def _install() -> None:
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True

    atexit.register(_run_callbacks)
    prev = signal.getsignal(signal.SIGTERM)

    def on_term(signum, frame):
        _run_callbacks()
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)
        else:
            os._exit(128 + signum)

    try:
        signal.signal(signal.SIGTERM, on_term)
    except ValueError:
        pass  # not the main thread: atexit-only (same as device_cleanup)


def on_exit(fn: Callable[[], None]) -> None:
    """Register ``fn`` to run once at process exit (normal or SIGTERM)."""
    _install()
    with _lock:
        _callbacks.append(fn)


def off_exit(fn: Callable[[], None]) -> None:
    """Deregister an ``on_exit`` callback (no-op if absent). Owners with
    an explicit close() call this so a long-lived process constructing
    many short-lived sinks does not pin every one until exit."""
    with _lock:
        try:
            _callbacks.remove(fn)
        except ValueError:
            pass


def install_snapshot_dump(path: str, registry=None) -> None:
    """Dump the registry's JSON snapshot to ``path`` at exit — the
    snapshot twin of SpanTracer's exit flush."""
    from dist_dqn_tpu.telemetry.exposition import write_snapshot

    def dump():
        d = os.path.dirname(path)
        if d:
            os.makedirs(d, exist_ok=True)
        write_snapshot(path, registry)

    on_exit(dump)


def maybe_install_snapshot_from_env(tag: str = "") -> Optional[str]:
    """Honor ``DQN_TELEMETRY_SNAPSHOT`` if set; returns the resolved path.

    ``{pid}``/``{tag}`` placeholders keep per-process files distinct
    (actor fleets all inherit the same environment).
    """
    template = os.environ.get(SNAPSHOT_ENV)
    if not template:
        return None
    path = template.replace("{pid}", str(os.getpid())) \
                   .replace("{tag}", tag)
    install_snapshot_dump(path)
    return path


def _reset_for_tests() -> None:
    """Test hook: forget callbacks and allow the run-once latch to rearm
    (the installed signal/atexit hooks stay; they just see a new list)."""
    global _ran
    with _lock:
        _callbacks.clear()
        _ran = False
