"""Run manifest: who/what/where a run was, as one JSON-able block.

ISSUE 4 satellite: BENCH JSON rows, train-CLI log streams and forensics
bundles all need the same provenance record — git sha, library versions,
platform, the exact config (and a short hash of it), argv and a schema
version — so a number found in a file three weeks later self-describes
how it was produced. One builder here, reused by ``bench.py``
(``manifest`` block in the contract line extras), ``train.py`` (one
``{"manifest": ...}`` log line at startup), ``/debug/config``
(telemetry/server.py) and every forensics bundle
(telemetry/watchdog.py).

Stdlib only, and library versions are read from ``sys.modules`` WITHOUT
importing — a jax-free actor process building a manifest must stay
jax-free (actors/actor.py contract).
"""
from __future__ import annotations

import dataclasses
import hashlib
import json
import os
import platform
import socket
import subprocess
import sys
import threading
import time
from typing import Dict, Optional

#: Bump when the manifest's key set changes shape (consumers key on it).
SCHEMA_VERSION = 1

_lock = threading.RLock()
_run_manifest: Optional[Dict] = None


def _git_sha() -> Optional[str]:
    """HEAD sha of the repo this package runs from; None outside a
    checkout (installed wheel) or without git."""
    try:
        root = os.path.dirname(os.path.dirname(os.path.dirname(
            os.path.abspath(__file__))))
        out = subprocess.run(
            ["git", "rev-parse", "HEAD"], cwd=root, capture_output=True,
            text=True, timeout=5)
        sha = out.stdout.strip()
        return sha if out.returncode == 0 and sha else None
    except Exception:  # noqa: BLE001 — provenance must never break a run
        return None


def _module_version(name: str) -> Optional[str]:
    """Version of an ALREADY-IMPORTED module (never triggers an import:
    jax-free processes must stay jax-free)."""
    mod = sys.modules.get(name)
    return getattr(mod, "__version__", None) if mod is not None else None


def config_fingerprint(cfg) -> Dict:
    """{"config_name", "config", "config_hash"} for a config dataclass
    (ExperimentConfig or any other); hash is over the sorted JSON form,
    so two runs with identical knobs fingerprint identically."""
    as_dict = dataclasses.asdict(cfg) if dataclasses.is_dataclass(cfg) \
        else dict(cfg)
    blob = json.dumps(as_dict, sort_keys=True, default=str)
    return {
        "config_name": getattr(cfg, "name", None) or as_dict.get("name"),
        "config": json.loads(json.dumps(as_dict, default=str)),
        "config_hash": hashlib.sha256(blob.encode()).hexdigest()[:16],
    }


def build_manifest(cfg=None, argv=None, extra: Optional[Dict] = None
                   ) -> Dict:
    """One provenance block; every field is best-effort (a manifest must
    never fail the run it describes)."""
    man = {
        "schema_version": SCHEMA_VERSION,
        "git_sha": _git_sha(),
        "versions": {
            "python": platform.python_version(),
            "jax": _module_version("jax"),
            "numpy": _module_version("numpy"),
        },
        "platform": platform.platform(),
        "hostname": socket.gethostname(),
        "pid": os.getpid(),
        "argv": list(argv if argv is not None else sys.argv),
        "built_at_unix": time.time(),
    }
    if cfg is not None:
        try:
            man.update(config_fingerprint(cfg))
        except Exception as e:  # noqa: BLE001 — best-effort provenance
            man["config_error"] = f"{type(e).__name__}: {e}"
    if extra:
        man.update(extra)
    return man


def set_run_manifest(manifest: Dict) -> None:
    """Install the process's run manifest (served at ``/debug/config``
    and embedded in forensics bundles instead of a fresh cfg-less
    build)."""
    global _run_manifest
    with _lock:
        _run_manifest = dict(manifest)


def get_run_manifest() -> Optional[Dict]:
    with _lock:
        return None if _run_manifest is None else dict(_run_manifest)


def annotate_manifest(key: str, value) -> None:
    """Fold one key into the run manifest (ISSUE 8: an armed chaos
    plan records itself here, so forensics bundles and /debug/config
    say which faults were scheduled). Installs a fresh cfg-less
    manifest when none exists yet — processes that never built one
    (spawned actors, bare tests) still get the annotation recorded."""
    global _run_manifest
    with _lock:
        if _run_manifest is None:
            _run_manifest = build_manifest()
        _run_manifest[key] = value


def _reset_for_tests() -> None:
    global _run_manifest
    with _lock:
        _run_manifest = None
