"""Stall watchdog, divergence sentinel and crash-forensics bundles.

The threaded runtimes (PRs 2-3: EvacuationWorker, DoubleBufferedStager,
generation fences, feeder/transport queues) fail the way Podracer-style
stacks fail — silently. A wedged thread raises nothing; a NaN loss
trains politely to garbage. This module (ISSUE 4 tentpole) turns both
into evidence:

  * **Heartbeats + watchdog thread** — each pipeline stage registers a
    named heartbeat (``watchdog.heartbeat("host_replay.collect")``) and
    beats it every pass. A daemon thread sweeps them; a heartbeat past
    its deadline dumps a forensics bundle, increments
    ``dqn_watchdog_stalls_total{stage=...}``, flips ``/healthz`` to 503
    (telemetry/server.py consults ``get_watchdog().healthz()``), and —
    with ``abort=True`` — SIGTERMs the process (the GRACEFUL kill: the
    lifecycle flush and the device-grant release both chain off
    SIGTERM; an ``os._exit`` here would orphan the grant, the exact
    wedge utils/device_cleanup.py exists to prevent) with a bounded
    hard-exit fallback.
  * **Divergence sentinel** — the learner loops feed it loss/grad-norm/
    param-checksum scalars; NaN/Inf or a checksum explosion triggers
    the same bundle via ``dqn_divergence_trips_total{signal=...}``,
    latched per signal so a diverged run produces one bundle, not one
    per step.
  * **Forensics bundle** — a directory under ``--forensics-dir``
    holding ``stacks.txt`` (all threads BY NAME via
    ``sys._current_frames`` — the thread-hygiene lint
    scripts/check_threads.py exists so these dumps stay readable),
    ``flight.json`` (the flight-recorder tail), ``registry.json`` (the
    metrics snapshot), ``manifest.json`` (run provenance) and
    ``reason.json``.

Stdlib only (actor/feeder processes register heartbeats too) and
null-safe: ``heartbeat()`` returns a no-op twin when no watchdog is
installed, so loops wire unconditionally and pay nothing by default.
"""
from __future__ import annotations

import faulthandler
import json
import math
import os
import signal
import sys
import threading
import time
import traceback
from typing import Dict, Optional, Set

from dist_dqn_tpu.telemetry import flight as _flight_mod
from dist_dqn_tpu.telemetry.collectors import (DIVERGENCE_TRIPS,
                                               FLIGHT_CAPACITY,
                                               FLIGHT_EVENTS,
                                               FORENSICS_BUNDLES,
                                               WATCHDOG_HEARTBEAT_AGE,
                                               WATCHDOG_STAGES,
                                               WATCHDOG_STALLS)
from dist_dqn_tpu.telemetry.registry import get_registry

#: Environment knobs (inherited by spawned actor/feeder processes —
#: same pattern as DQN_TELEMETRY_SNAPSHOT): a directory here makes
#: ``maybe_install_from_env()`` arm the watchdog + sentinel in any
#: process that calls it (actor/feeder entry points do).
FORENSICS_ENV = "DQN_FORENSICS_DIR"
DEADLINE_ENV = "DQN_WATCHDOG_DEADLINE_S"

DEFAULT_DEADLINE_S = 120.0

_bundle_seq = 0
_bundle_lock = threading.RLock()


def format_stacks() -> str:
    """Every live thread's Python stack, labeled with the thread's NAME
    (``sys._current_frames`` keys on ident; ``threading.enumerate``
    provides the mapping) — what ``/debug/stacks`` serves and
    ``stacks.txt`` stores. Unnamed threads print as ``Thread-N``, which
    is why scripts/check_threads.py demands explicit names."""
    frames = sys._current_frames()
    by_ident = {t.ident: t for t in threading.enumerate()}
    parts = []
    for ident, frame in sorted(frames.items()):
        t = by_ident.get(ident)
        name = t.name if t is not None else f"<unregistered-{ident}>"
        daemon = t.daemon if t is not None else "?"
        parts.append(f"--- thread {name!r} (ident {ident}, "
                     f"daemon={daemon}) ---")
        parts.append("".join(traceback.format_stack(frame)).rstrip())
        parts.append("")
    return "\n".join(parts)


def dump_forensics(forensics_dir: str, reason: str,
                   detail: Optional[Dict] = None,
                   registry=None, log_fn=print) -> str:
    """Write one forensics bundle; returns the bundle directory.

    Bundle contents: ``reason.json`` (trigger + detail), ``stacks.txt``
    (named all-thread stacks, plus a ``faulthandler`` dump of the same —
    the C-level view survives interpreter states the traceback module
    cannot walk), ``flight.json``, ``registry.json``, ``manifest.json``.
    Best-effort per file: a half-broken process must still produce the
    parts it can.
    """
    global _bundle_seq
    from dist_dqn_tpu.telemetry import exposition, manifest as manifest_mod

    with _bundle_lock:
        seq = _bundle_seq
        _bundle_seq += 1
    stamp = time.strftime("%Y%m%d_%H%M%S")
    bundle = os.path.join(forensics_dir,
                          f"{stamp}_pid{os.getpid()}_{seq:03d}_{reason}")
    # Written under a temp name and renamed when complete, so a reader
    # polling the forensics dir (tests, a collection daemon) never sees
    # a half-written bundle as finished.
    staging_dir = bundle + ".writing"
    os.makedirs(staging_dir, exist_ok=True)

    def write(name, fn):
        try:
            with open(os.path.join(staging_dir, name), "w") as f:
                fn(f)
        except Exception as e:  # noqa: BLE001 — dump what we can
            try:
                with open(os.path.join(staging_dir, name + ".error"),
                          "w") as f:
                    f.write(f"{type(e).__name__}: {e}\n")
            except OSError:
                pass

    write("reason.json", lambda f: json.dump(
        {"reason": reason, "detail": detail or {}, "pid": os.getpid(),
         "unix_time": time.time()}, f, indent=1, sort_keys=True))

    def stacks(f):
        f.write(format_stacks())
        f.write("\n=== faulthandler ===\n")
        f.flush()
        faulthandler.dump_traceback(file=f)

    write("stacks.txt", stacks)
    write("flight.json", lambda f: json.dump(
        _flight_mod.get_flight().snapshot(), f, indent=1))
    write("registry.json", lambda f: json.dump(
        exposition.snapshot(registry), f, indent=1, sort_keys=True))
    man = manifest_mod.get_run_manifest() or manifest_mod.build_manifest()
    write("manifest.json", lambda f: json.dump(man, f, indent=1,
                                               sort_keys=True))
    os.rename(staging_dir, bundle)

    reg = registry if registry is not None else get_registry()
    reg.counter(FORENSICS_BUNDLES, "forensics bundles written",
                labels={"trigger": reason}).inc()
    if log_fn is not None:
        log_fn(json.dumps({"forensics_bundle": bundle, "reason": reason}))
    return bundle


#: Extra allowance between a loop heartbeat's REGISTRATION and its first
#: beat: the first pass usually carries the jit compile, whose wall is
#: unbounded-ish but legitimate. A stage that never beats at all still
#: trips once deadline + grace elapse — which is exactly the wedged-
#: compile tunnel hang this repo's incident history is about.
STARTUP_GRACE_S = 600.0


class Heartbeat:
    """One pipeline stage's liveness signal. ``beat()`` is two plain
    float stores (each atomic in CPython) — safe to call from any thread
    at any rate with no lock."""

    __slots__ = ("stage", "deadline_s", "_grace", "_last", "_owner")

    def __init__(self, stage: str, deadline_s: float, owner=None,
                 startup_grace_s: float = 0.0):
        self.stage = stage
        self.deadline_s = float(deadline_s)
        self._grace = float(startup_grace_s)
        self._last = time.monotonic()
        self._owner = owner

    def beat(self) -> None:
        # _last refreshes BEFORE the grace drops: a sweep between the
        # two stores must see (stale age, grace) or (fresh age, no
        # grace) — never (stale age, no grace), a false stall.
        self._last = time.monotonic()
        self._grace = 0.0  # the stage proved itself; normal deadline now

    def age(self, now: Optional[float] = None) -> float:
        return (time.monotonic() if now is None else now) - self._last

    def limit(self) -> float:
        """The currently allowed silence: deadline, plus the startup
        grace until the first beat."""
        return self.deadline_s + self._grace

    @property
    def expired(self) -> bool:
        return self.age() > self.limit()

    def close(self) -> None:
        """Deregister: a stage that FINISHED is not a stall (a completed
        run must not flip /healthz to 503)."""
        if self._owner is not None:
            self._owner.unregister(self.stage)


class NullHeartbeat:
    """No-watchdog twin: loops wire unconditionally, pay nothing."""

    stage = ""
    deadline_s = float("inf")
    expired = False

    def beat(self) -> None:
        pass

    def age(self, now=None) -> float:
        return 0.0

    def close(self) -> None:
        pass


NULL_HEARTBEAT = NullHeartbeat()


class Watchdog:
    """Sweeps registered heartbeats on a named daemon thread; a missed
    deadline dumps ONE forensics bundle per stall episode (latched until
    the stage beats again), counts
    ``dqn_watchdog_stalls_total{stage=...}`` and optionally aborts."""

    def __init__(self, forensics_dir: Optional[str] = None,
                 deadline_s: float = DEFAULT_DEADLINE_S,
                 poll_s: float = 1.0, abort: bool = False,
                 abort_grace_s: float = 10.0, log_fn=print,
                 registry=None, start: bool = True):
        self.forensics_dir = forensics_dir
        self.deadline_s = float(deadline_s)
        self.poll_s = float(poll_s)
        self.abort = abort
        self.abort_grace_s = float(abort_grace_s)
        self.log_fn = log_fn
        self._registry = registry
        self._lock = threading.RLock()
        self._beats: Dict[str, Heartbeat] = {}
        self._stalled: Set[str] = set()
        self._stall_counters: Dict[str, object] = {}
        self._age_gauges: Dict[str, object] = {}
        self._aborting = False
        self._stop = threading.Event()
        self._thread = threading.Thread(target=self._run,
                                        name="telemetry-watchdog",
                                        daemon=True)
        if start:
            self._thread.start()

    # -- registration --------------------------------------------------------
    def register(self, stage: str, deadline_s: Optional[float] = None,
                 startup_grace_s: float = 0.0) -> Heartbeat:
        """Get-or-create the stage's heartbeat (re-registering resets its
        clock — a restarted stage starts fresh, not pre-stalled).
        ``startup_grace_s`` extends the allowed silence until the FIRST
        beat (loop stages register before their first jit compile)."""
        with self._lock:
            hb = self._beats.get(stage)
            if hb is None:
                hb = Heartbeat(stage,
                               deadline_s if deadline_s is not None
                               else self.deadline_s, owner=self,
                               startup_grace_s=startup_grace_s)
                self._beats[stage] = hb
            else:
                if deadline_s is not None:
                    hb.deadline_s = float(deadline_s)
                hb.beat()
            self._stalled.discard(stage)
            return hb

    def unregister(self, stage: str) -> None:
        with self._lock:
            self._beats.pop(stage, None)
            self._stalled.discard(stage)

    def stages(self) -> Dict[str, float]:
        """{stage: age_s} for every registered heartbeat."""
        now = time.monotonic()
        with self._lock:
            return {s: hb.age(now) for s, hb in self._beats.items()}

    # -- health --------------------------------------------------------------
    def stale(self) -> Dict[str, float]:
        """{stage: age_s} for heartbeats past their allowed silence."""
        now = time.monotonic()
        with self._lock:
            return {s: hb.age(now) for s, hb in self._beats.items()
                    if hb.age(now) > hb.limit()}

    def healthz(self):
        """(ok, stale dict) — what /healthz serves (stale => 503)."""
        stale = self.stale()
        return (not stale, stale)

    # -- sweep ---------------------------------------------------------------
    def _reg(self):
        return self._registry if self._registry is not None \
            else get_registry()

    def _stage_instruments(self, stage: str):
        c = self._stall_counters.get(stage)
        if c is None:
            c = self._reg().counter(
                WATCHDOG_STALLS, "watchdog-detected stage stalls",
                labels={"stage": stage})
            self._stall_counters[stage] = c
        g = self._age_gauges.get(stage)
        if g is None:
            g = self._reg().gauge(
                WATCHDOG_HEARTBEAT_AGE,
                "seconds since the stage's last heartbeat",
                labels={"stage": stage})
            self._age_gauges[stage] = g
        return c, g

    def check(self) -> Dict[str, float]:
        """One sweep (the poll thread's body; callable directly from
        tests): update age gauges, detect NEWLY stale stages, dump one
        bundle covering them, arm the abort. Returns the stale map."""
        now = time.monotonic()
        with self._lock:
            beats = dict(self._beats)
        stale: Dict[str, float] = {}
        for stage, hb in beats.items():
            age = hb.age(now)
            c, g = self._stage_instruments(stage)
            g.set(age)
            if age > hb.limit():
                stale[stage] = age
        fr = _flight_mod.get_flight()
        reg = self._reg()
        reg.gauge(FLIGHT_EVENTS,
                  "events recorded by the flight ring").set(fr.total)
        reg.gauge(FLIGHT_CAPACITY, "flight ring capacity").set(fr.capacity)
        reg.gauge(WATCHDOG_STAGES,
                  "heartbeat stages registered").set(len(beats))

        with self._lock:
            fresh = [s for s in stale if s not in self._stalled]
            recovered = self._stalled - set(stale)
            self._stalled -= recovered
            self._stalled |= set(fresh)
        if fresh:
            detail = {"stale": {s: round(a, 3) for s, a in stale.items()},
                      "deadline_s": {s: beats[s].deadline_s for s in stale},
                      "newly_stale": fresh}
            fr.record("watchdog", "stall", stages=fresh)
            for s in fresh:
                self._stall_counters[s].inc()
            if self.log_fn is not None:
                self.log_fn(json.dumps({"watchdog_stall": fresh,
                                        "ages_s": detail["stale"]}))
            if self.forensics_dir:
                try:
                    dump_forensics(self.forensics_dir, "watchdog_stall",
                                   detail=detail, registry=self._registry,
                                   log_fn=self.log_fn)
                except Exception:  # noqa: BLE001 — the sweep must survive
                    pass
            if self.abort:
                self._abort()
        return stale

    def _abort(self) -> None:
        """Emergency checkpoint hooks first, then SIGTERM ourselves
        (graceful: chains the lifecycle flush and the device-grant
        release), then hard-exit if still alive past the grace window.
        Runs on the watchdog thread."""
        if self._aborting:
            return
        self._aborting = True
        if self.log_fn is not None:
            self.log_fn(json.dumps(
                {"watchdog_abort": True,
                 "grace_s": self.abort_grace_s}))
        # Emergency checkpoints (ISSUE 8 hardening): an aborting run's
        # newest learner state would otherwise be lost to whatever the
        # periodic save cadence left behind. Hooks are registered by
        # the loops that own checkpointers and run best-effort — a
        # hook that itself wedges must not block the abort past the
        # grace window, so they ride a bounded side thread.
        run_emergency_hooks(timeout_s=self.abort_grace_s,
                            log_fn=self.log_fn)
        os.kill(os.getpid(), signal.SIGTERM)
        time.sleep(self.abort_grace_s)
        os._exit(70)

    def _run(self) -> None:
        while not self._stop.wait(self.poll_s):
            try:
                self.check()
            except Exception:  # noqa: BLE001 — a sweep bug must not
                pass           # silently kill the watchdog thread loop

    def stop(self) -> None:
        self._stop.set()
        if self._thread.is_alive():
            self._thread.join(timeout=5)


# -- divergence sentinel ------------------------------------------------------

class DivergenceSentinel:
    """Watches loss / grad-norm / param-checksum streams; NaN/Inf or a
    checksum explosion dumps a forensics bundle. Latched per signal: a
    diverged run produces one bundle, then keeps running (or aborts,
    when configured) — not a bundle per step."""

    def __init__(self, forensics_dir: Optional[str] = None,
                 explosion_factor: float = 1e4, abort: bool = False,
                 log_fn=print, registry=None):
        self.forensics_dir = forensics_dir
        self.explosion_factor = float(explosion_factor)
        self.abort = abort
        self.log_fn = log_fn
        self._registry = registry
        self._lock = threading.RLock()
        self._tripped: Set[str] = set()
        self._ref_checksum: Optional[float] = None
        self._counters: Dict[str, object] = {}

    def configure(self, forensics_dir=None, explosion_factor=None,
                  abort=None, log_fn=None, registry=None) -> None:
        with self._lock:
            if forensics_dir is not None:
                self.forensics_dir = forensics_dir
            if explosion_factor is not None:
                self.explosion_factor = float(explosion_factor)
            if abort is not None:
                self.abort = abort
            if log_fn is not None:
                self.log_fn = log_fn
            if registry is not None:
                self._registry = registry

    def observe(self, loss: Optional[float] = None,
                grad_norm: Optional[float] = None,
                param_checksum: Optional[float] = None,
                step: Optional[int] = None) -> Optional[str]:
        """Feed one step's scalars; returns the tripped signal name (or
        None). Cheap on the healthy path: a few isfinite checks."""
        if loss is not None and not math.isfinite(loss):
            return self._trip("loss_nonfinite", loss, step)
        if grad_norm is not None and not math.isfinite(grad_norm):
            return self._trip("grad_norm_nonfinite", grad_norm, step)
        if param_checksum is not None:
            if not math.isfinite(param_checksum):
                return self._trip("param_checksum_nonfinite",
                                  param_checksum, step)
            mag = abs(param_checksum)
            with self._lock:
                ref = self._ref_checksum
                self._ref_checksum = mag
            if ref is not None and mag > self.explosion_factor \
                    * max(ref, 1.0):
                return self._trip("param_checksum_explosion",
                                  param_checksum, step,
                                  reference=ref)
        return None

    @property
    def tripped(self):
        """Latched signal names (sorted) — feeds /healthz when armed."""
        with self._lock:
            return sorted(self._tripped)

    def _trip(self, sig: str, value, step, **extra) -> str:
        with self._lock:
            latched = sig in self._tripped
            self._tripped.add(sig)
            c = self._counters.get(sig)
            if c is None:
                reg = self._registry if self._registry is not None \
                    else get_registry()
                c = reg.counter(DIVERGENCE_TRIPS,
                                "divergence-sentinel trips",
                                labels={"signal": sig})
                self._counters[sig] = c
        if latched:
            # One count per divergence EPISODE (the documented latch
            # semantics) — a run that stays NaN must not read as
            # thousands of trips.
            return sig
        c.inc()
        detail = {"signal": sig, "value": repr(value), "step": step,
                  **{k: repr(v) for k, v in extra.items()}}
        _flight_mod.get_flight().record("divergence", sig,
                                        value=repr(value), step=step)
        if self.log_fn is not None:
            self.log_fn(json.dumps({"divergence": detail}))
        if self.forensics_dir:
            try:
                dump_forensics(self.forensics_dir, f"divergence_{sig}",
                               detail=detail, registry=self._registry,
                               log_fn=self.log_fn)
            except Exception:  # noqa: BLE001 — never fail the train loop
                pass
        if self.abort:
            os.kill(os.getpid(), signal.SIGTERM)
        return sig

    def _reset(self) -> None:
        with self._lock:
            self._tripped.clear()
            self._ref_checksum = None


# -- process-global install ---------------------------------------------------

_global_lock = threading.RLock()
_watchdog: Optional[Watchdog] = None
_sentinel = DivergenceSentinel()
#: Extra /healthz contributors (ISSUE 7): name -> probe(). A probe
#: returns None while healthy, or a JSON-able detail dict to flip
#: /healthz to 503 with that detail under its name — how the serving
#: tier's SLO tracker (p99 latency / queue depth) joins the SAME health
#: surface the stall watchdog and divergence sentinel feed, on every
#: process's /healthz endpoint at once.
_health_probes: Dict[str, object] = {}


def install_watchdog(forensics_dir: Optional[str] = None,
                     deadline_s: float = DEFAULT_DEADLINE_S,
                     poll_s: float = 1.0, abort: bool = False,
                     log_fn=print) -> Watchdog:
    """Create (or reconfigure) the process-global watchdog. Idempotent:
    a second call updates knobs on the running instance instead of
    leaking a second sweep thread."""
    global _watchdog
    with _global_lock:
        if _watchdog is None:
            _watchdog = Watchdog(forensics_dir=forensics_dir,
                                 deadline_s=deadline_s, poll_s=poll_s,
                                 abort=abort, log_fn=log_fn)
        else:
            _watchdog.forensics_dir = forensics_dir
            _watchdog.deadline_s = float(deadline_s)
            _watchdog.abort = abort
            _watchdog.log_fn = log_fn
        return _watchdog


def get_watchdog() -> Optional[Watchdog]:
    return _watchdog


def heartbeat(stage: str, deadline_s: Optional[float] = None,
              startup_grace_s: float = 0.0):
    """Register (get-or-create) a stage heartbeat on the global watchdog;
    the no-op twin when none is installed — call sites never branch."""
    with _global_lock:
        if _watchdog is None:
            return NULL_HEARTBEAT
        return _watchdog.register(stage, deadline_s=deadline_s,
                                  startup_grace_s=startup_grace_s)


def install_sentinel(forensics_dir: Optional[str] = None,
                     explosion_factor: Optional[float] = None,
                     abort: Optional[bool] = None,
                     log_fn=None) -> DivergenceSentinel:
    """Point the always-present global sentinel at a forensics dir (it
    counts + logs trips even unconfigured; bundles need the dir)."""
    _sentinel.configure(forensics_dir=forensics_dir,
                        explosion_factor=explosion_factor,
                        abort=abort, log_fn=log_fn)
    return _sentinel


def get_sentinel() -> DivergenceSentinel:
    return _sentinel


def observe_divergence(**kwargs) -> Optional[str]:
    """Feed the global sentinel (see ``DivergenceSentinel.observe``)."""
    return _sentinel.observe(**kwargs)


def health_state():
    """(ok, detail) for /healthz: stale watchdog heartbeats AND latched
    divergence trips (the latter only from an ARMED sentinel — one with
    a forensics dir — so an unarmed process's health probe never turns
    on a training accident nobody asked it to police)."""
    ok, detail = True, {}
    if _watchdog is not None:
        w_ok, stale = _watchdog.healthz()
        if not w_ok:
            ok = False
            detail["stale_stages_age_s"] = {
                s: round(a, 3) for s, a in stale.items()}
    if _sentinel.forensics_dir:
        trips = _sentinel.tripped
        if trips:
            ok = False
            detail["diverged"] = trips
    with _global_lock:
        probes = list(_health_probes.items())
    for name, probe in probes:
        try:
            extra = probe()
        except Exception as e:  # a broken probe is itself unhealthy
            extra = {"probe_error": f"{type(e).__name__}: {e}"}
        if extra:
            ok = False
            detail[name] = extra
    return ok, detail


#: Emergency-checkpoint hooks (ISSUE 8): name -> zero-arg callable run
#: by a watchdog abort BEFORE the SIGTERM, so the newest learner state
#: survives the kill. Registered by the loops that own checkpointers
#: (train.py fused loop, host_replay_loop, the apex service) and
#: deregistered in their finally blocks.
_emergency_hooks: Dict[str, object] = {}


def register_emergency_hook(name: str, hook) -> None:
    """Register a best-effort pre-abort hook (re-registering a name
    replaces it). The hook must tolerate running on a side thread
    while the main loop is wedged — save immutable snapshots, don't
    take loop locks."""
    with _global_lock:
        _emergency_hooks[name] = hook


def unregister_emergency_hook(name: str) -> None:
    with _global_lock:
        _emergency_hooks.pop(name, None)


def run_emergency_hooks(timeout_s: float = 10.0, log_fn=print) -> None:
    """Run every registered hook on a bounded side thread; a hook that
    hangs past ``timeout_s`` is abandoned (daemon thread) rather than
    blocking the abort."""
    with _global_lock:
        hooks = list(_emergency_hooks.items())
    for name, hook in hooks:
        done = threading.Event()
        err: list = []

        def _run(hook=hook):
            try:
                hook()
            except Exception as e:  # noqa: BLE001 — best effort
                err.append(e)
            finally:
                done.set()

        t = threading.Thread(target=_run,
                             name=f"emergency-hook-{name}", daemon=True)
        t.start()
        finished = done.wait(timeout_s)
        if log_fn is not None:
            log_fn(json.dumps({"emergency_hook": name,
                               "completed": bool(finished and not err),
                               "error": (f"{type(err[0]).__name__}: "
                                         f"{err[0]}") if err else None}))


def register_health_probe(name: str, probe) -> None:
    """Add a /healthz contributor: ``probe()`` -> None (healthy) or a
    detail dict (unhealthy; served as 503 JSON under ``name``).
    Re-registering a name replaces its probe."""
    with _global_lock:
        _health_probes[name] = probe


def unregister_health_probe(name: str) -> None:
    with _global_lock:
        _health_probes.pop(name, None)


def maybe_install_from_env() -> Optional[str]:
    """Honor ``DQN_FORENSICS_DIR`` (and ``DQN_WATCHDOG_DEADLINE_S``) if
    set — how spawned actor/feeder processes arm their own watchdog +
    sentinel; returns the directory. The twin of
    ``maybe_install_snapshot_from_env``."""
    d = os.environ.get(FORENSICS_ENV)
    if not d:
        return None
    try:
        deadline = float(os.environ.get(DEADLINE_ENV, DEFAULT_DEADLINE_S))
    except ValueError:
        deadline = DEFAULT_DEADLINE_S
    install_watchdog(forensics_dir=d, deadline_s=deadline)
    install_sentinel(forensics_dir=d)
    return d


def _reset_for_tests() -> None:
    """Stop + forget the global watchdog; replace the global sentinel
    with a fresh unconfigured one."""
    global _watchdog, _sentinel
    with _global_lock:
        if _watchdog is not None:
            _watchdog.stop()
            _watchdog = None
        _sentinel = DivergenceSentinel()
        _health_probes.clear()
        _emergency_hooks.clear()
