"""Unified telemetry: registry, Prometheus exposition, flush lifecycle.

Quick start (what every layer does)::

    from dist_dqn_tpu import telemetry

    reg = telemetry.get_registry()
    steps = reg.counter("dqn_env_steps_total", "env steps processed")
    depth = reg.gauge("dqn_transport_tcp_backlog", "records queued")
    lat = reg.histogram("dqn_grad_step_latency_seconds",
                        "dispatch->materialize latency")

Serve ``/metrics`` with ``telemetry.start_server(port)``; dump a JSON
snapshot at exit with ``telemetry.install_snapshot_dump(path)``. The
package is stdlib-only (importable from jax-free actor processes) and
hands out Null-object twins via ``NullRegistry`` for zero-overhead
disabled paths. Naming scheme + the dashboards each gauge feeds:
docs/observability.md.

Crash forensics (ISSUE 4): ``telemetry.get_flight()`` is the process's
flight-recorder ring, ``telemetry.heartbeat(stage)`` registers a stall-
watchdog heartbeat (no-op twin until ``install_watchdog`` arms it), and
``telemetry.observe_divergence(loss=...)`` feeds the NaN/explosion
sentinel — see telemetry/flight.py and telemetry/watchdog.py.
"""
from dist_dqn_tpu.telemetry.devtime import (IDLE_CAUSES,  # noqa: F401
                                            ProgramRecord, ProgramRegistry,
                                            UtilizationLedger,
                                            capture_profile,
                                            get_program_registry,
                                            maybe_trace_first_chunk,
                                            programs_snapshot,
                                            register_program,
                                            reset_program_registry,
                                            set_learner_mfu,
                                            sweep_device_memory)
from dist_dqn_tpu.telemetry.exposition import (CONTENT_TYPE,  # noqa: F401
                                               render_prometheus, snapshot,
                                               write_snapshot)
from dist_dqn_tpu.telemetry.flight import (FlightRecorder,  # noqa: F401
                                           NullFlightRecorder, get_flight)
from dist_dqn_tpu.telemetry.lifecycle import (  # noqa: F401
    install_snapshot_dump, maybe_install_snapshot_from_env, on_exit)
from dist_dqn_tpu.telemetry.manifest import (build_manifest,  # noqa: F401
                                             get_run_manifest,
                                             set_run_manifest)
from dist_dqn_tpu.telemetry.registry import (DEFAULT_BUCKETS,  # noqa: F401
                                             Counter, Gauge, Histogram,
                                             NullRegistry, Registry,
                                             get_registry)
from dist_dqn_tpu.telemetry.server import (TelemetryServer,  # noqa: F401
                                           start_server)
from dist_dqn_tpu.telemetry.watchdog import (DivergenceSentinel,  # noqa: F401
                                             Heartbeat, Watchdog,
                                             dump_forensics, get_watchdog,
                                             heartbeat, install_sentinel,
                                             install_watchdog,
                                             maybe_install_from_env,
                                             observe_divergence)
