"""Unified telemetry: registry, Prometheus exposition, flush lifecycle.

Quick start (what every layer does)::

    from dist_dqn_tpu import telemetry

    reg = telemetry.get_registry()
    steps = reg.counter("dqn_env_steps_total", "env steps processed")
    depth = reg.gauge("dqn_transport_tcp_backlog", "records queued")
    lat = reg.histogram("dqn_grad_step_latency_seconds",
                        "dispatch->materialize latency")

Serve ``/metrics`` with ``telemetry.start_server(port)``; dump a JSON
snapshot at exit with ``telemetry.install_snapshot_dump(path)``. The
package is stdlib-only (importable from jax-free actor processes) and
hands out Null-object twins via ``NullRegistry`` for zero-overhead
disabled paths. Naming scheme + the dashboards each gauge feeds:
docs/observability.md.
"""
from dist_dqn_tpu.telemetry.exposition import (CONTENT_TYPE,  # noqa: F401
                                               render_prometheus, snapshot,
                                               write_snapshot)
from dist_dqn_tpu.telemetry.lifecycle import (  # noqa: F401
    install_snapshot_dump, maybe_install_snapshot_from_env, on_exit)
from dist_dqn_tpu.telemetry.registry import (DEFAULT_BUCKETS,  # noqa: F401
                                             Counter, Gauge, Histogram,
                                             NullRegistry, Registry,
                                             get_registry)
from dist_dqn_tpu.telemetry.server import (TelemetryServer,  # noqa: F401
                                           start_server)
