"""Cross-layer collector helpers: shared metric names + wiring glue.

The per-layer collectors live next to the code they observe (replay/
host.py owns its occupancy gauges, transport.py its queue counters); what
lives HERE is the glue that must be shared so names cannot drift between
layers, plus helpers for state the owning module cannot observe itself —
the jit-resident device rings, whose occupancy only exists host-side
between chunks.

No jax import: device scalars are read via ``int(...)`` duck-typing
(works on jax Arrays and numpy alike), keeping the telemetry package
importable from jax-free actor processes.
"""
from __future__ import annotations

from typing import Optional, Tuple

from dist_dqn_tpu.telemetry.registry import Registry, get_registry

# Canonical family names (docs/observability.md). Every layer records
# through these constants so a rename is one edit, not a grep.
REPLAY_SIZE = "dqn_replay_size"
REPLAY_CAPACITY = "dqn_replay_capacity"
REPLAY_OCCUPANCY = "dqn_replay_occupancy_ratio"
REPLAY_ADDED = "dqn_replay_added_total"
REPLAY_SAMPLED = "dqn_replay_sampled_total"
REPLAY_EVICTED = "dqn_replay_evicted_total"
REPLAY_MAX_PRIORITY = "dqn_replay_max_priority"
REPLAY_PRIORITY_MASS = "dqn_replay_priority_mass"

ENV_STEPS = "dqn_env_steps_total"
ENV_RATE = "dqn_env_steps_per_sec"
GRAD_STEPS = "dqn_grad_steps_total"
GRAD_LATENCY = "dqn_grad_step_latency_seconds"
PARAM_STALENESS = "dqn_param_broadcast_staleness_seconds"

# Ingest fast path (ISSUE 2): device round-trip accounting for the actor
# service, H2D staging for both learner paths. DEVICE_CALLS labels each
# dispatch by {call="act"|"fused_act_bootstrap"|"bootstrap"|"train"};
# DISPATCH_FANIN observes ROWS per batched act/fused dispatch (a count
# histogram — the one deliberate exception to the _seconds rule, see
# docs/observability.md).
SERVICE_DEVICE_CALLS = "dqn_service_device_calls_total"
DISPATCH_FANIN = "dqn_service_dispatch_fanin_rows"
INGEST_PASSES = "dqn_service_ingest_passes_total"
PRIO_WRITEBACK_PENDING = "dqn_service_prio_writeback_pending"
STAGING_OCCUPANCY = "dqn_staging_buffer_occupancy"
STAGING_STAGED = "dqn_staging_batches_total"
STAGING_BYTES = "dqn_staging_bytes_total"

# Host-replay D2H pipeline (ISSUE 3): the evacuation half of the
# staging story — streamed sub-chunk D2H fetches, the background
# evacuation worker, and the per-chunk overlap accounting. All labeled
# {loop="host_replay"} to mirror the H2D staging families above.
HOST_REPLAY_D2H_BYTES = "dqn_host_replay_d2h_bytes_total"
HOST_REPLAY_EVAC_SLICES = "dqn_host_replay_evac_slices_total"
HOST_REPLAY_EVAC_SECONDS = "dqn_host_replay_evac_seconds"
HOST_REPLAY_SLICE_LAG_SECONDS = "dqn_host_replay_slice_lag_seconds"
HOST_REPLAY_FENCE_WAIT_SECONDS = "dqn_host_replay_fence_wait_seconds"
HOST_REPLAY_OVERLAP = "dqn_host_replay_evac_overlap_frac"

# Sharded collect (ISSUE 15): data-parallel acting for the host-replay
# runtime. COLLECT_SECONDS observes each shard's collect DISPATCH
# enqueue wall ({loop, shard} — async dispatch, so growth means that
# shard's device queue is full and the host is rate-limited by it, the
# dqn_mesh_chunk_dispatch_seconds semantic); COLLECT_LANE_BLOCK is the
# env lanes each shard's own collect program acts over; the SHARD_*
# evac pair carries the per-shard D2H evidence — each shard's bytes
# leave ITS OWN device for ITS OWN ring, so per-shard conservation is
# the zero-cross-shard-scatter proof scaling_bench's collect arm reads.
HOST_REPLAY_COLLECT_SECONDS = "dqn_host_replay_collect_seconds"
HOST_REPLAY_COLLECT_LANE_BLOCK = "dqn_host_replay_collect_lane_block"
HOST_REPLAY_SHARD_EVAC_SECONDS = "dqn_host_replay_shard_evac_seconds"
HOST_REPLAY_SHARD_D2H_BYTES = "dqn_host_replay_shard_d2h_bytes_total"

# Host-replay sample-side pipeline (ISSUE 5): the H2D prefetcher — the
# sample/gather wall moved off the critical path, the residual
# main-thread wait, generation-stale drops, and the batched PER
# write-back stream. Labeled {loop="host_replay"} like the D2H half.
HOST_REPLAY_SAMPLE_SECONDS = "dqn_host_replay_sample_seconds"
HOST_REPLAY_PREFETCH_WAIT_SECONDS = \
    "dqn_host_replay_prefetch_wait_seconds"
HOST_REPLAY_PREFETCH_DEPTH = "dqn_host_replay_prefetch_depth"
HOST_REPLAY_STALE_BATCHES = "dqn_host_replay_stale_batches_total"
HOST_REPLAY_PRIO_WB_BATCHES = \
    "dqn_host_replay_prio_writeback_batches_total"
HOST_REPLAY_PRIO_WB_ROWS = "dqn_host_replay_prio_writeback_rows_total"
HOST_REPLAY_PRIO_WB_DROPPED = \
    "dqn_host_replay_prio_writeback_dropped_total"

# Learner-utilization engine (ISSUE 6): the replay-ratio / batch-width
# / actor-dtype configuration that produced a process's learner
# throughput, plus the achieved rate and (where a chip peak is known,
# bench.py) MFU. Config gauges are labeled {loop=...} like the
# host-replay families; ACTOR_DTYPE_INFO is a Prometheus info-style
# gauge — constant 1 with the dtype in the {dtype=...} label.
LEARNER_REPLAY_RATIO = "dqn_learner_replay_ratio"
LEARNER_TRAIN_BATCH = "dqn_learner_train_batch_size"
LEARNER_ACTOR_DTYPE_INFO = "dqn_learner_actor_dtype_info"
LEARNER_GRAD_RATE = "dqn_learner_grad_steps_per_sec"
LEARNER_MFU = "dqn_learner_mfu"

# Chip-time attribution plane (ISSUE 19): the per-program device-time
# ledger (telemetry/devtime.py). PROGRAM_* are labeled {program, loop}:
# FLOPS/BYTES are the XLA cost-analysis totals for ONE execution of the
# compiled program (a lax.scan body is counted once regardless of trip
# count); DISPATCHES counts host-side launches; DEVICE_SECONDS
# accumulates device time sampled at fences the loops already hold —
# an attribution, not a hardware counter. CHIP_IDLE/CHIP_BUSY decompose
# chunk wall-time per {loop}: idle is labeled by {cause} from the fixed
# vocabulary sample|evac_fence|prefetch_wait|h2d|other. DEVICE_MEMORY
# mirrors Device.memory_stats() per {kind, device} (absent on backends
# that report nothing, e.g. CPU); kind="peak_bytes_in_use_seen" is a
# host-tracked high-water mark for backends whose native peak resets.
PROGRAM_FLOPS = "dqn_program_flops"
PROGRAM_BYTES = "dqn_program_bytes"
PROGRAM_DISPATCHES = "dqn_program_dispatches_total"
PROGRAM_DEVICE_SECONDS = "dqn_program_device_seconds_total"
CHIP_IDLE_SECONDS = "dqn_chip_idle_seconds_total"
CHIP_BUSY_SECONDS = "dqn_chip_busy_seconds_total"
DEVICE_MEMORY_BYTES = "dqn_device_memory_bytes"

# Serving tier (ISSUE 7): the standalone policy-inference service
# (dist_dqn_tpu/serving/). REQUESTS/LATENCY are per accepted request
# (LATENCY spans admission -> response split, the client-visible
# service time minus transport); BATCH_FANIN observes real (unpadded)
# ROWS per dispatched act program — the count-histogram exception,
# like DISPATCH_FANIN above; SHED counts admissions refused by the
# bounded queue (HTTP 429 + retry-after); RELOADS/POLICY_VERSION track
# the ModelStore's checkpoint hot-reload per {policy}; SLO_BREACHES
# counts /healthz flips per {slo="p99_latency"|"queue_depth"}.
SERVING_REQUESTS = "dqn_serving_requests_total"
SERVING_SHED = "dqn_serving_shed_total"
SERVING_QUEUE_DEPTH = "dqn_serving_queue_depth"
SERVING_LATENCY = "dqn_serving_latency_seconds"
SERVING_BATCH_FANIN = "dqn_serving_batch_fanin_rows"
SERVING_DISPATCHES = "dqn_serving_dispatches_total"
SERVING_RELOADS = "dqn_serving_reloads_total"
SERVING_POLICY_VERSION = "dqn_serving_policy_version"
SERVING_SLO_BREACHES = "dqn_serving_slo_breaches_total"

# Chaos harness + proven graceful degradation (ISSUE 8): injections are
# labeled {seam, fault} (the seam registry is chaos/plan.py SEAMS);
# RECOVERY_SECONDS measures injection -> recovery-proof per {seam}
# (which call site proves which fault: docs/fault_tolerance.md).
# TRANSPORT_CORRUPT counts frames failing the wire integrity check
# (magic/length/CRC32) per {reason}; TRANSPORT_SHED counts records the
# TCP listener dropped after the bounded backpressure wait (shed +
# alarm instead of wedging the serve thread); INGEST_DEGRADED is 1
# while supervision sees at least half the actor fleet dead.
CHAOS_INJECTED = "dqn_chaos_injected_total"
CHAOS_RECOVERY_SECONDS = "dqn_recovery_seconds"
TRANSPORT_CORRUPT = "dqn_transport_corrupt_frames_total"
TRANSPORT_SHED = "dqn_transport_tcp_shed_total"
INGEST_DEGRADED = "dqn_ingest_degraded"

# Checkpoint/resume (ISSUE 12): fleet-grade sharded checkpointing in
# the data-parallel era. SAVE_SECONDS is the whole quiesced save wall
# (fence + sidecar + orbax commit) per {loop}; BYTES counts sidecar +
# snapshot bytes written; SHARDS_SAVED is the replay shard count each
# save carries (1 = single ring; dp/ingest shards otherwise); RESUMES
# counts successful whole-state restores per {loop}; REFUSED counts
# resume attempts rejected at the pins, per {reason=
# "sidecar_version"|"chunk_iters"|"dp"|"per"|"prio_writeback_batch"|
# "torn_sidecar"|"population"} — the sidecar pins are enumerated in
# docs/fault_tolerance.md ("population" joined in ISSUE 20: a stacked
# tree's member-axis width is checkpoint structure, pinned by the
# POPULATION marker in utils/checkpoint.py and the sidecar scalar).
CHECKPOINT_SAVE_SECONDS = "dqn_checkpoint_save_seconds"
CHECKPOINT_BYTES = "dqn_checkpoint_bytes_total"
CHECKPOINT_SHARDS_SAVED = "dqn_checkpoint_shards_saved"
CHECKPOINT_RESUMES = "dqn_checkpoint_resumes_total"
CHECKPOINT_REFUSED = "dqn_checkpoint_refused_resumes_total"

# Population training plane (ISSUE 20): M vmap-stacked policies in ONE
# fused program (dist_dqn_tpu/population.py). SIZE is the member-axis
# width M of the running program; LOSS/EVAL_RETURN are the per-{member}
# twins of dqn_loss and the eval_return log column — the selection
# signals a PBT controller would read. All three labeled {loop} like
# the learner families; the shared fused counters (dqn_env_steps_total,
# dqn_learner_grad_steps_total) count AGGREGATE member-steps under a
# population, because that is what the chip actually sustained.
POPULATION_SIZE = "dqn_population_size"
POPULATION_LOSS = "dqn_population_loss"
POPULATION_EVAL_RETURN = "dqn_population_eval_return"

# Zero-copy ingest subsystem (ISSUE 9): the schema-negotiated
# experience path (dist_dqn_tpu/ingest/). RECORDS/BYTES are labeled
# {transport="shm"|"tcp"|"legacy"} (slot ring / zero-copy wire / the
# JSON-codec fallback paths); SHARD_RECORDS counts sticky-router
# placement per {shard} (backed by the ISSUE 10 sharded store when
# --ingest-shards > 1; one shard otherwise);
# DECODE_ERRORS counts records rejected whole at the codec gate per
# {reason}; SHM_TORN counts slot-ring records dropped on a seqlock
# stamp mismatch; ACTOR_PRIO_TRANSITIONS counts transitions inserted
# with frame-shipped |TD| priorities (zero learner-side bootstrap
# dispatches — the ISSUE 9 acceptance pin).
INGEST_RECORDS = "dqn_ingest_records_total"
INGEST_BYTES = "dqn_ingest_bytes_total"
INGEST_SHARDS = "dqn_ingest_shards"
INGEST_SHARD_RECORDS = "dqn_ingest_shard_records_total"
INGEST_DECODE_ERRORS = "dqn_ingest_decode_errors_total"
INGEST_SHM_TORN = "dqn_ingest_shm_torn_reads_total"
INGEST_ACTOR_PRIO_TRANSITIONS = \
    "dqn_ingest_actor_priority_transitions_total"

# Near-data experience plane (ISSUE 14): DEDUP_FRAMES_REUSED counts
# frame-stack slots served by back-references into the per-lane frame
# ring instead of wire bytes, DEDUP_BYTES_SAVED the wire bytes those
# references avoided (vs the undeduped zero-copy layout, tables
# already netted out); SHM_BATCH_FANIN is records per slot publish
# (1 = the unbatched lock-step actor path); SHARD_SAMPLE_SECONDS is
# the per-{shard} ingest-side stratified-draw + gather wall and
# SHARD_SAMPLE_WAIT the learner's residual wait on the pre-packed
# block queue (near zero when the per-shard samplers keep ahead).
INGEST_DEDUP_FRAMES_REUSED = "dqn_ingest_dedup_frames_reused_total"
INGEST_DEDUP_BYTES_SAVED = "dqn_ingest_dedup_bytes_saved_total"
INGEST_SHM_BATCH_FANIN = "dqn_ingest_shm_batch_fanin"
REPLAY_SHARD_SAMPLE_SECONDS = "dqn_replay_shard_sample_seconds"
REPLAY_SHARD_SAMPLE_WAIT = "dqn_replay_shard_sample_wait_seconds"

# Sharded on-device priority sampling (ISSUE 18): DEVICE_SAMPLE_SECONDS
# is the per-{shard} device-plane draw wall (write-back flush + jit
# dispatch + host materialization — what the host tree's sample+get
# used to cost the learner thread), DEVICE_WRITEBACK_ROWS the priority
# rows scattered into each shard's plane (post last-write-wins dedup).
REPLAY_DEVICE_SAMPLE_SECONDS = "dqn_replay_device_sample_seconds"
REPLAY_DEVICE_WRITEBACK_ROWS = "dqn_replay_device_writeback_rows_total"

#: Slot-publish fan-in buckets: a feeder batch is bounded by slot
#: sizing well below the act-dispatch fan-ins FANIN_BUCKETS covers.
SHM_FANIN_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0)

# Flight recorder / stall watchdog / crash forensics (ISSUE 4): stage
# heartbeats are labeled {stage="host_replay.collect"|"apex.ingest"|...}
# (the full stage table is in docs/observability.md), divergence trips
# {signal="loss_nonfinite"|...}, bundles {trigger="watchdog_stall"|
# "divergence_*"}.
WATCHDOG_STALLS = "dqn_watchdog_stalls_total"
WATCHDOG_HEARTBEAT_AGE = "dqn_watchdog_heartbeat_age_seconds"
WATCHDOG_STAGES = "dqn_watchdog_stages"
DIVERGENCE_TRIPS = "dqn_divergence_trips_total"
FORENSICS_BUNDLES = "dqn_forensics_bundles_total"
FLIGHT_EVENTS = "dqn_flight_events"
FLIGHT_CAPACITY = "dqn_flight_capacity"

#: Fan-in histogram buckets: powers of two from a single-lane record up
#: to the largest plausible burst (hundreds of actors x lanes).
FANIN_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0, 256.0,
                 512.0, 1024.0, 2048.0, 4096.0, 8192.0)

# Experience-lineage staleness accounting (ISSUE 16): every sampled
# batch ages its records' wire lineage stamps. SAMPLE_AGE observes
# now - birth wall-time (seconds); SAMPLE_STALENESS observes
# current_grad_steps - acting_params_version — a count histogram, the
# FANIN-style exception to the _seconds rule (docs/observability.md).
# Both are labeled {loop="fused"|"apex"|"host_replay"} so the three
# runtimes land in ONE family the fleet aggregator can federate.
REPLAY_SAMPLE_AGE = "dqn_replay_sample_age_seconds"
REPLAY_SAMPLE_STALENESS = "dqn_replay_sample_staleness_versions"

#: Staleness-version buckets: grad-step gaps from lockstep (<=1) up to
#: the deep off-policy tail a wedged actor or cold shard produces.
STALENESS_BUCKETS = (1.0, 2.0, 4.0, 8.0, 16.0, 32.0, 64.0, 128.0,
                     256.0, 512.0, 1024.0, 4096.0, 16384.0, 65536.0)

#: Sample-age buckets: sub-second lockstep sampling out to the
#: hour-scale tail of a big, slowly-refreshed replay.
SAMPLE_AGE_BUCKETS = (0.05, 0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0,
                      60.0, 120.0, 300.0, 600.0, 1800.0, 3600.0)


def lineage_histograms(loop: str, registry: Optional[Registry] = None):
    """(sample-age, staleness-versions) histograms for one runtime loop
    — the shared constructor all three runtimes use, so the families
    cannot drift apart (the fused-vs-host-replay parity pin)."""
    reg = registry if registry is not None else get_registry()
    labels = {"loop": loop}
    return (reg.histogram(REPLAY_SAMPLE_AGE,
                          "age of sampled experience: sample wall-time "
                          "minus the record's birth stamp",
                          labels, buckets=SAMPLE_AGE_BUCKETS),
            reg.histogram(REPLAY_SAMPLE_STALENESS,
                          "grad steps between a sampled record's "
                          "acting-params version and the current step",
                          labels, buckets=STALENESS_BUCKETS))


def observe_sample_lineage(items, current_version: float, age_hist,
                           staleness_hist, now: Optional[float] = None
                           ) -> bool:
    """Age one sampled batch's lineage stamps into the histograms.
    ``items`` is any mapping of sampled arrays; batches without lineage
    keys (legacy-codec actors, pre-v4 checkpoints mid-migration) are a
    silent no-op — staleness accounting degrades, it never gates
    sampling. Returns whether anything was observed."""
    births = items.get("lineage_birth_time")
    if births is None or len(births) == 0:
        return False
    import time as _time

    now = _time.time() if now is None else now
    age_hist.observe_many([max(now - float(b), 0.0) for b in births])
    versions = items.get("lineage_params_version")
    if versions is not None:
        cur = float(current_version)
        staleness_hist.observe_many(
            [max(cur - float(v), 0.0) for v in versions])
    return True


class FusedLineageTable:
    """Host-side lineage accounting for the fused (on-device) runtime
    (ISSUE 16). The device ring carries no wall-clock lanes — adding
    them would cost HBM for data the compiled chunk never reads — so
    the fused loop stamps at COLLECT instead: each chunk boundary
    records (birth wall-time, params version) for the slots that chunk
    appended. Sampling inside the compiled chunk is uniform over the
    live ring window and every chunk contributes the same slot count,
    so observing each live chunk once per boundary matches the true
    sample-age distribution in expectation — same families, same
    buckets as the off-device runtimes' record-granular stamps."""

    def __init__(self, registry: Optional[Registry] = None):
        self._age, self._staleness = lineage_histograms("fused", registry)
        self._chunks: list = []  # (birth_time, params_version), newest last

    def on_chunk(self, grad_steps_total: float, window_chunks: int,
                 now: Optional[float] = None) -> None:
        """Record one collect boundary and age the live window.
        ``window_chunks`` is how many chunks the device ring holds
        (ring slots // chunk_iters) — older stamps have been evicted."""
        import time as _time

        now = _time.time() if now is None else now
        self._chunks.append((now, float(grad_steps_total)))
        del self._chunks[:-max(1, int(window_chunks))]
        cur = float(grad_steps_total)
        self._age.observe_many([max(now - b, 0.0)
                                for b, _ in self._chunks])
        self._staleness.observe_many([max(cur - v, 0.0)
                                      for _, v in self._chunks])


def histogram_quantile(hist, q: float) -> float:
    """Prometheus-style ``histogram_quantile``: linear interpolation
    within the bucket where the q-th observation falls. Operates on any
    instrument exposing ``cumulative_buckets()``/``count`` (including a
    just-rendered snapshot via ``telemetry.registry``). NaN when empty;
    the highest finite bound when the quantile lands in +Inf."""
    total = hist.count
    if not total:
        return float("nan")
    rank = q * total
    prev_bound, prev_cum = 0.0, 0
    for bound, cum in hist.cumulative_buckets():
        if cum >= rank:
            if bound == float("inf"):
                return prev_bound
            if cum == prev_cum:
                return bound
            frac = (rank - prev_cum) / (cum - prev_cum)
            return prev_bound + (bound - prev_bound) * frac
        prev_bound, prev_cum = bound, cum
    return prev_bound


def replay_gauges(store: str, registry: Optional[Registry] = None):
    """(size, capacity, ratio) gauges for one replay store. ``store``
    labels which buffer implementation is reporting (host / host_ring /
    device) — several can coexist in one process."""
    reg = registry if registry is not None else get_registry()
    labels = {"store": store}
    return (reg.gauge(REPLAY_SIZE, "replay items currently held", labels),
            reg.gauge(REPLAY_CAPACITY, "replay item capacity", labels),
            reg.gauge(REPLAY_OCCUPANCY, "replay fill fraction [0, 1]",
                      labels))


def observe_device_ring(replay_state,
                        registry: Optional[Registry] = None
                        ) -> Tuple[int, int]:
    """Record occupancy of a jit-resident device ring between chunks.

    Accepts any of the device replay states (TimeRingState, or the
    prioritized/sequence wrappers that carry one as ``.ring``) — the ring
    itself cannot emit from inside the compiled chunk, so host loops call
    this at their chunk boundary. Returns (filled_slots, total_slots).
    Reading ``size`` materializes one scalar — negligible next to the
    chunk metrics fetch every caller already performs.
    """
    ring = getattr(replay_state, "ring", replay_state)
    slots, lanes = (int(ring.action.shape[0]), int(ring.action.shape[1]))
    size = int(ring.size)
    g_size, g_cap, g_ratio = replay_gauges("device", registry)
    g_size.set(size * lanes)
    g_cap.set(slots * lanes)
    g_ratio.set(size / slots if slots else 0.0)
    # Prioritized/sequence device rings also carry their priority-seed
    # scalar — the device twin of the host shard's max-priority gauge.
    max_prio = getattr(replay_state, "max_priority", None)
    if max_prio is not None:
        reg = registry if registry is not None else get_registry()
        reg.gauge(REPLAY_MAX_PRIORITY, "running max |TD| priority",
                  {"store": "device"}).set(float(max_prio))
    return size, slots
