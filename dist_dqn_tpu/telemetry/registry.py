"""Central metrics registry: counter / gauge / histogram primitives.

The unified telemetry substrate (ISSUE 1): every runtime layer — replay
shards, transports, actors, learner loops — records through ONE registry
so rates, spans and gauges share a naming scheme and a flush lifecycle.
Design constraints, in order:

  * hot-path-safe: an ``inc``/``set``/``observe`` is a few attribute ops
    under a per-instrument lock (~100ns in CPython). Instruments are
    created once and cached by their owners; creation (the only dict
    mutation) takes the registry lock, updates never do. Locks are
    REENTRANT: the SIGTERM flush (telemetry/lifecycle.py) runs on the
    main thread and may interrupt a frame already inside an
    instrument's critical section — a plain Lock would deadlock the
    exit dump against the interrupted holder.
  * dependency-free: stdlib only — actor/feeder processes and the host
    ring must not import jax (actors/actor.py contract), so neither may
    anything they import.
  * Null-object disabled path (same pattern as ``SpanTracer``/
    ``NullTracer``): ``NullRegistry`` hands out no-op instruments with
    the identical surface, so call sites never branch.

Naming scheme (documented in docs/observability.md): ``dqn_<subsystem>_
<what>[_total|_seconds]``. Counters are monotonic and end in ``_total``;
histograms observe seconds and end in ``_seconds``; gauges are bare.
"""
from __future__ import annotations

import bisect
import threading
from typing import Dict, Iterable, Optional, Sequence, Tuple

#: Default histogram buckets, tuned for host-loop latencies: 100µs device
#: dispatches up to minute-scale compile/checkpoint stalls.
DEFAULT_BUCKETS: Tuple[float, ...] = (
    0.0001, 0.00025, 0.0005, 0.001, 0.0025, 0.005, 0.01, 0.025, 0.05,
    0.1, 0.25, 0.5, 1.0, 2.5, 5.0, 10.0, 30.0, 60.0)


def _label_key(labels: Optional[Dict[str, str]]) -> Tuple:
    return tuple(sorted((labels or {}).items()))


class Counter:
    """Monotonically increasing count (Prometheus ``counter``)."""

    kind = "counter"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.RLock()
        self._value = 0.0

    def inc(self, n: float = 1.0) -> None:
        if n < 0:
            raise ValueError(f"counter {self.name} cannot decrease "
                             f"(inc({n}))")
        with self._lock:
            self._value += n

    @property
    def value(self) -> float:
        return self._value

    def sample(self) -> Dict:
        return {"value": self._value}


class Gauge:
    """Point-in-time value that can move both ways."""

    kind = "gauge"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        self._lock = threading.RLock()
        self._value = 0.0

    def set(self, v: float) -> None:
        with self._lock:
            self._value = float(v)

    def inc(self, n: float = 1.0) -> None:
        with self._lock:
            self._value += n

    def dec(self, n: float = 1.0) -> None:
        self.inc(-n)

    @property
    def value(self) -> float:
        return self._value

    def sample(self) -> Dict:
        return {"value": self._value}


class Histogram:
    """Cumulative-bucket histogram (Prometheus ``histogram`` semantics).

    ``buckets`` are ascending upper bounds; the implicit ``+Inf`` bucket
    always exists. ``observe`` is one bisect + three adds.
    """

    kind = "histogram"

    def __init__(self, name: str, help: str = "",
                 labels: Optional[Dict[str, str]] = None,
                 buckets: Optional[Sequence[float]] = None):
        self.name = name
        self.help = help
        self.labels = dict(labels or {})
        bounds = tuple(buckets if buckets is not None else DEFAULT_BUCKETS)
        if list(bounds) != sorted(bounds) or len(set(bounds)) != len(bounds):
            raise ValueError(f"histogram {name} buckets must be strictly "
                             f"ascending: {bounds}")
        self.bounds = bounds
        self._lock = threading.RLock()
        # _counts[i] = observations <= bounds[i] would be cumulative; we
        # store PER-BUCKET counts and cumulate at render time so observe
        # touches exactly one cell.
        self._counts = [0] * (len(bounds) + 1)  # last cell = +Inf overflow
        self._sum = 0.0
        self._count = 0

    def observe(self, v: float) -> None:
        v = float(v)
        i = bisect.bisect_left(self.bounds, v)
        with self._lock:
            self._counts[i] += 1
            self._sum += v
            self._count += 1

    def observe_many(self, values: Iterable[float]) -> None:
        """Batch observe: one lock hold for a whole sample batch (the
        replay lineage path observes batch_size values per draw)."""
        vals = [float(v) for v in values]
        idxs = [bisect.bisect_left(self.bounds, v) for v in vals]
        with self._lock:
            for i in idxs:
                self._counts[i] += 1
            self._sum += sum(vals)
            self._count += len(vals)

    @property
    def count(self) -> int:
        return self._count

    @property
    def sum(self) -> float:
        return self._sum

    def cumulative_buckets(self) -> Iterable[Tuple[float, int]]:
        """[(upper_bound, cumulative_count), ...] ending at (+Inf, count)."""
        with self._lock:
            counts = list(self._counts)
            total = self._count
        acc = 0
        out = []
        for b, c in zip(self.bounds, counts):
            acc += c
            out.append((b, acc))
        out.append((float("inf"), total))
        return out

    def sample(self) -> Dict:
        return {"count": self._count, "sum": self._sum,
                "buckets": {str(b): c for b, c in
                            self.cumulative_buckets()}}


class _NullInstrument:
    """No-op twin carrying every instrument's surface (never branches)."""

    kind = "null"
    name = ""
    help = ""
    labels: Dict[str, str] = {}
    bounds: Tuple[float, ...] = ()
    value = 0.0
    count = 0
    sum = 0.0

    def inc(self, n: float = 1.0) -> None:
        pass

    def dec(self, n: float = 1.0) -> None:
        pass

    def set(self, v: float) -> None:
        pass

    def observe(self, v: float) -> None:
        pass

    def observe_many(self, values) -> None:
        pass

    def cumulative_buckets(self):
        return [(float("inf"), 0)]

    def sample(self) -> Dict:
        return {}


NULL_INSTRUMENT = _NullInstrument()


class Registry:
    """Process-wide instrument registry with get-or-create semantics.

    The same (name, labels) pair always returns the same instrument, so
    independent components (several replay shards, say) aggregate into
    one counter instead of clobbering registrations; asking for an
    existing name with a different TYPE is a bug and raises.
    """

    enabled = True

    def __init__(self):
        self._lock = threading.RLock()
        self._instruments: Dict[Tuple[str, Tuple], object] = {}

    def _get_or_create(self, cls, name: str, help: str,
                       labels: Optional[Dict[str, str]], **kwargs):
        key = (name, _label_key(labels))
        with self._lock:
            inst = self._instruments.get(key)
            if inst is None:
                inst = cls(name, help=help, labels=labels, **kwargs)
                self._instruments[key] = inst
            elif not isinstance(inst, cls):
                raise ValueError(
                    f"metric {name!r} already registered as {inst.kind}, "
                    f"not {cls.kind}")
            return inst

    def counter(self, name: str, help: str = "",
                labels: Optional[Dict[str, str]] = None) -> Counter:
        return self._get_or_create(Counter, name, help, labels)

    def gauge(self, name: str, help: str = "",
              labels: Optional[Dict[str, str]] = None) -> Gauge:
        return self._get_or_create(Gauge, name, help, labels)

    def histogram(self, name: str, help: str = "",
                  labels: Optional[Dict[str, str]] = None,
                  buckets: Optional[Sequence[float]] = None) -> Histogram:
        return self._get_or_create(Histogram, name, help, labels,
                                   buckets=buckets)

    def collect(self):
        """Instruments grouped by family name, registration-ordered."""
        with self._lock:
            instruments = list(self._instruments.values())
        families: Dict[str, list] = {}
        for inst in instruments:
            families.setdefault(inst.name, []).append(inst)
        return families

    def snapshot(self) -> Dict:
        """JSON-able snapshot: {name{labels}: sample dict}."""
        out: Dict[str, Dict] = {}
        for name, insts in self.collect().items():
            for inst in insts:
                key = name
                if inst.labels:
                    lbl = ",".join(f"{k}={v}" for k, v
                                   in sorted(inst.labels.items()))
                    key = f"{name}{{{lbl}}}"
                out[key] = {"type": inst.kind, **inst.sample()}
        return out


class NullRegistry:
    """Disabled path: identical surface, zero work, no instruments."""

    enabled = False

    def counter(self, name: str, help: str = "", labels=None):
        return NULL_INSTRUMENT

    def gauge(self, name: str, help: str = "", labels=None):
        return NULL_INSTRUMENT

    def histogram(self, name: str, help: str = "", labels=None,
                  buckets=None):
        return NULL_INSTRUMENT

    def collect(self):
        return {}

    def snapshot(self) -> Dict:
        return {}


_default_registry = Registry()


def get_registry() -> Registry:
    """The process-global default registry (what every collector uses
    unless handed an explicit one)."""
    return _default_registry
