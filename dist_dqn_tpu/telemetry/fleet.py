"""Fleet observability plane: endpoint registry + metrics federation.

ISSUE 16 tentpole. A distributed run is a PROCESS FLEET — learner,
remote actors, serving replicas, eval runs — each already serving its
own ``/metrics`` (telemetry/server.py), which means debugging a fleet
meant hand-collecting N ports from N log streams. This module gives the
run ONE pane:

  * **Run-scoped endpoint registry** — every process that starts a
    telemetry server calls ``register_endpoint(role, port)`` right
    after bind. When ``DQN_FLEET_DIR`` (or an explicit ``fleet_dir``)
    names a directory, the call atomically writes
    ``<fleet_dir>/<role>-<pid>.json`` (tmp + ``os.replace``) describing
    the endpoint: role, labels, host:port, manifest hash, start time.
    The descriptor is removed through the shared exit lifecycle
    (telemetry/lifecycle.py — atexit AND SIGTERM), so a gracefully
    stopped member leaves no litter. Unset env → no-op, zero cost.
  * **Federation** — ``FleetAggregator`` sweeps the registry, scrapes
    every member, and serves ONE merged Prometheus exposition with
    ``process="<role>-<pid>"``/``role`` labels injected into every
    sample line (``_bucket`` lines included). A member that stops
    answering degrades to LABELED staleness (``dqn_fleet_member_up`` 0,
    ``dqn_fleet_member_staleness_seconds`` climbing, last-good families
    still served) — a dead endpoint never fails the fleet scrape.
  * **Health rollup** — ``/fleet/status`` is the JSON rollup: per
    member live/stale/dead (scrape liveness x descriptor-pid liveness),
    each member's ``/healthz`` verdict (watchdog stalls, SLO breaches
    — the 503 detail JSON rides along verbatim), learner-reported
    ``dqn_ingest_degraded``, and the fleet's own actor-quorum
    degradation. ``/fleet/forensics`` pulls ``/debug/flight`` +
    ``/debug/stacks`` from every live member into one correlated
    bundle — the first step of the hang runbook
    (docs/observability.md). ``/fleet/profile?seconds=N`` (ISSUE 19)
    fans ``/debug/profile`` out to every live member in parallel for
    one correlated cross-fleet xprof window; dead members are labeled,
    never fatal.

Descriptor hygiene: a registration REFUSES (raises
``FleetRegistrationError``) when a live descriptor already claims the
same role+pid with a different identity — two processes must never
alias one series. Descriptors whose pid is dead are GC'd by the
AGGREGATOR only, never by a live peer registering alongside them: the
aggregator is the one place that can tell "crashed" from "slow to
start", and a crashed member must stay visible as ``dead`` in the
rollup until its grace period lapses.

Stdlib only (urllib + http.server + json), importable from jax-free
actor processes — same contract as the rest of the telemetry package.

CLI::

    python -m dist_dqn_tpu.telemetry.fleet --fleet-dir RUN/fleet --port 0

prints one ``{"fleet_port": N}`` line (the announcement contract every
serving CLI here follows) and serves until SIGTERM.
"""
from __future__ import annotations

import argparse
import json
import os
import socket
import threading
import time
import urllib.error
import urllib.request
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from urllib.parse import parse_qs, urlsplit
from typing import Dict, List, Optional

from dist_dqn_tpu.telemetry import lifecycle
from dist_dqn_tpu.telemetry import manifest as manifest_mod
from dist_dqn_tpu.telemetry.exposition import CONTENT_TYPE, _escape_label
from dist_dqn_tpu.telemetry.registry import Registry

#: Environment knob: the run's fleet registry directory. Exported by the
#: learner CLIs (--fleet-dir) so spawned actors/feeders inherit it.
FLEET_ENV = "DQN_FLEET_DIR"

#: Bump when the descriptor key set changes shape.
DESCRIPTOR_SCHEMA_VERSION = 1

#: Fields that constitute a member's IDENTITY: a same-role+pid
#: descriptor differing in any of these is a collision, not a refresh.
_IDENTITY_KEYS = ("host", "port", "start_time")


class FleetRegistrationError(ValueError):
    """Two live members claimed the same role+pid descriptor slot."""


def _pid_alive(pid: int) -> bool:
    """Best-effort liveness for a LOCAL pid (EPERM counts as alive)."""
    try:
        os.kill(int(pid), 0)
    except ProcessLookupError:
        return False
    except (PermissionError, OSError, ValueError):
        pass
    return True


def resolve_fleet_dir(explicit: Optional[str] = None) -> Optional[str]:
    """The registry directory: explicit arg wins, else ``DQN_FLEET_DIR``,
    else None (fleet plane disabled)."""
    return explicit if explicit else (os.environ.get(FLEET_ENV) or None)


class EndpointRegistration:
    """Handle for one written descriptor: ``close()`` removes it (also
    wired into the exit lifecycle, so SIGTERM'd members deregister)."""

    def __init__(self, path: str):
        self.path = path
        self._closed = False
        lifecycle.on_exit(self.close)

    def close(self) -> None:
        if self._closed:
            return
        self._closed = True
        lifecycle.off_exit(self.close)
        try:
            os.unlink(self.path)
        except OSError:
            pass


def register_endpoint(role: str, port: int, host: str = "127.0.0.1",
                      labels: Optional[Dict[str, str]] = None,
                      fleet_dir: Optional[str] = None
                      ) -> Optional[EndpointRegistration]:
    """Announce this process's telemetry endpoint to the run's fleet.

    Call AFTER the server bound (the descriptor must carry the real
    port — with ``--telemetry-port 0`` the ephemeral one). No-op
    returning None when no fleet dir is configured. Raises
    ``FleetRegistrationError`` on a live same-role+pid collision.
    """
    d = resolve_fleet_dir(fleet_dir)
    if not d:
        return None
    os.makedirs(d, exist_ok=True)
    pid = os.getpid()
    path = os.path.join(d, f"{role}-{pid}.json")
    man = manifest_mod.get_run_manifest() or {}
    desc = {
        "schema_version": DESCRIPTOR_SCHEMA_VERSION,
        "role": role,
        "pid": pid,
        "host": host,
        "port": int(port),
        "hostname": socket.gethostname(),
        "labels": dict(labels or {}),
        "start_time": time.time(),
        "manifest_hash": man.get("config_hash"),
    }
    if os.path.exists(path):
        try:
            with open(path) as f:
                prev = json.load(f)
        except (OSError, ValueError):
            prev = None  # torn/garbage descriptor: overwrite
        if prev and _pid_alive(prev.get("pid", -1)) \
                and int(prev.get("pid", -1)) == pid \
                and any(prev.get(k) != desc[k] for k in ("host", "port")):
            # Same role+pid, different endpoint identity, and the
            # claimant is alive — refusing beats silently aliasing two
            # processes into one fleet series. (A DEAD claimant is pid
            # recycling; its descriptor is stale litter the aggregator
            # will GC, and this process legitimately owns the slot.)
            raise FleetRegistrationError(
                f"fleet descriptor {path} already claimed by a live "
                f"member at {prev.get('host')}:{prev.get('port')} "
                f"(ours: {host}:{port})")
    tmp = path + f".tmp.{pid}"
    with open(tmp, "w") as f:
        json.dump(desc, f, sort_keys=True)
        f.write("\n")
    os.replace(tmp, path)  # atomic: sweepers never see a torn descriptor
    return EndpointRegistration(path)


# ---------------------------------------------------------------------------
# Federation: exposition merge


def _inject_labels(series: str, extra: Dict[str, str]) -> str:
    """Inject labels into one exposition series token (``name`` or
    ``name{...}`` — bucket lines are just series tokens too)."""
    if not extra:
        return series
    pairs = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(extra.items()))
    if series.endswith("}"):
        sep = "" if series.endswith("{") else ","
        return series[:-1] + sep + pairs + "}"
    return series + "{" + pairs + "}"


def merge_expositions(pages: List[Dict]) -> str:
    """Merge N scraped exposition texts into one, injecting each page's
    ``labels`` into every sample line. ``pages`` items: {"text": str,
    "labels": {..}}. HELP/TYPE are emitted once per family (first
    page's wording wins); families keep first-seen order."""
    families: Dict[str, Dict] = {}
    order: List[str] = []
    for page in pages:
        extra = page.get("labels") or {}
        for line in page["text"].splitlines():
            line = line.strip()
            if not line:
                continue
            if line.startswith("#"):
                parts = line.split(None, 3)
                if len(parts) < 3 or parts[1] not in ("HELP", "TYPE"):
                    continue
                name = parts[2]
                fam = families.get(name)
                if fam is None:
                    fam = families[name] = {"help": None, "type": None,
                                            "lines": []}
                    order.append(name)
                key = parts[1].lower()
                if fam[key] is None:
                    fam[key] = parts[3] if len(parts) > 3 else ""
                continue
            # Sample line: series token then value (labels may hold
            # spaces inside quotes, so split at the closing brace, not
            # the first whitespace).
            if "}" in line:
                cut = line.rindex("}") + 1
            else:
                cut = line.find(" ")
                if cut < 0:
                    continue
            series, value = line[:cut], line[cut:].strip()
            bare = series.split("{", 1)[0]
            # _bucket/_sum/_count samples belong to their histogram
            # family's HELP/TYPE block.
            name = bare
            for suffix in ("_bucket", "_sum", "_count"):
                if bare.endswith(suffix) and bare[:-len(suffix)] in families:
                    name = bare[:-len(suffix)]
                    break
            fam = families.get(name)
            if fam is None:
                fam = families[name] = {"help": None, "type": None,
                                        "lines": []}
                order.append(name)
            fam["lines"].append(f"{_inject_labels(series, extra)} {value}")
    out: List[str] = []
    for name in order:
        fam = families[name]
        if fam["help"]:
            out.append(f"# HELP {name} {fam['help']}")
        if fam["type"]:
            out.append(f"# TYPE {name} {fam['type']}")
        out.extend(fam["lines"])
    return "\n".join(out) + "\n"


# ---------------------------------------------------------------------------
# The aggregator


#: Sweeps a dead member's descriptor survives before the aggregator
#: GC's the file (the member stays in the in-memory rollup regardless).
DEAD_GC_SWEEPS = 3


class _Member:
    """Aggregator-side record of one registered endpoint."""

    def __init__(self, desc: Dict, path: str):
        self.desc = desc
        self.path = path
        self.name = f"{desc.get('role', 'unknown')}-{desc.get('pid', 0)}"
        self.state = "stale"  # until the first successful scrape
        self.healthy: Optional[bool] = None
        self.health_detail = None
        self.last_text: Optional[str] = None
        self.last_scrape: Optional[float] = None
        self.dead_sweeps = 0

    @property
    def base_url(self) -> str:
        return f"http://{self.desc['host']}:{self.desc['port']}"

    def inject(self) -> Dict[str, str]:
        extra = dict(self.desc.get("labels") or {})
        extra["process"] = self.name
        extra["role"] = str(self.desc.get("role", "unknown"))
        return extra


class FleetAggregator:
    """Sweep the registry dir, scrape every member, serve the one pane.

    ``sweep_once()`` is synchronous (tests and the chaos game day call
    it directly); ``start()`` runs it on a daemon thread every
    ``sweep_interval_s``. All HTTP out-calls carry ``scrape_timeout_s``
    so one wedged member delays, never wedges, the sweep.
    """

    def __init__(self, fleet_dir: str, sweep_interval_s: float = 2.0,
                 scrape_timeout_s: float = 2.0):
        self.fleet_dir = fleet_dir
        self.sweep_interval_s = float(sweep_interval_s)
        self.scrape_timeout_s = float(scrape_timeout_s)
        self.members: Dict[str, _Member] = {}
        self._lock = threading.RLock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        self._hostname = socket.gethostname()
        # The aggregator's OWN families live in a private registry so
        # embedding it in a learner process cannot collide with the
        # process-global instruments it is federating.
        self.registry = Registry()
        reg = self.registry
        self._g_members = {
            s: reg.gauge("dqn_fleet_members", "registered members by "
                         "state", {"state": s})
            for s in ("live", "stale", "dead")}
        self._c_sweeps = reg.counter("dqn_fleet_sweeps_total",
                                     "registry sweeps completed")
        self._c_scrape_errs = reg.counter(
            "dqn_fleet_scrape_errors_total",
            "member scrapes that failed (per attempt)")
        self._h_sweep = reg.histogram("dqn_fleet_sweep_seconds",
                                      "one full sweep's wall time")
        self._g_degraded = reg.gauge(
            "dqn_fleet_ingest_degraded",
            "1 while at least half the actor-role members are dead "
            "(the fleet-level twin of the learner's "
            "dqn_ingest_degraded supervision gauge)")

    # -- scraping -----------------------------------------------------

    def _http_get(self, url: str) -> Optional[bytes]:
        try:
            with urllib.request.urlopen(
                    url, timeout=self.scrape_timeout_s) as resp:
                return resp.read()
        except Exception:  # noqa: BLE001 — any failure = scrape miss
            return None

    def _healthz(self, member: _Member) -> None:
        """Fetch /healthz; 503 bodies carry the watchdog's detail JSON
        (stale stages, divergence latches, SLO probes) verbatim."""
        try:
            with urllib.request.urlopen(
                    member.base_url + "/healthz",
                    timeout=self.scrape_timeout_s) as resp:
                member.healthy = resp.status == 200
                member.health_detail = None
        except urllib.error.HTTPError as e:
            member.healthy = False
            try:
                member.health_detail = json.loads(e.read().decode())
            except Exception:  # noqa: BLE001
                member.health_detail = {"status": "unhealthy"}
        except Exception:  # noqa: BLE001 — connection-level failure
            member.healthy = None
            member.health_detail = None

    def sweep_once(self) -> None:
        t0 = time.perf_counter()
        try:
            entries = sorted(os.listdir(self.fleet_dir))
        except OSError:
            entries = []
        with self._lock:
            for fname in entries:
                if not fname.endswith(".json") or ".tmp." in fname:
                    continue
                path = os.path.join(self.fleet_dir, fname)
                try:
                    with open(path) as f:
                        desc = json.load(f)
                except (OSError, ValueError):
                    continue  # torn mid-replace or already GC'd
                name = f"{desc.get('role', 'unknown')}-{desc.get('pid', 0)}"
                known = self.members.get(name)
                if known is None or known.desc.get("start_time") \
                        != desc.get("start_time"):
                    self.members[name] = _Member(desc, path)
            members = list(self.members.values())
        for m in members:
            body = self._http_get(m.base_url + "/metrics")
            now = time.time()
            if body is not None:
                with self._lock:
                    m.state = "live"
                    m.last_text = body.decode("utf-8", "replace")
                    m.last_scrape = now
                    m.dead_sweeps = 0
                self._healthz(m)
                continue
            self._c_scrape_errs.inc()
            # Scrape missed: pid liveness (local members only) decides
            # stale-but-breathing vs dead. Remote-host members cannot
            # be probed, so they degrade to stale and stay there.
            pid = m.desc.get("pid", -1)
            local = m.desc.get("hostname") == self._hostname
            dead = local and not _pid_alive(pid)
            with self._lock:
                m.state = "dead" if dead else "stale"
                m.healthy = None
                if dead:
                    m.dead_sweeps += 1
                    # Aggregator-only GC (never a live peer): after the
                    # grace window the descriptor file goes; the member
                    # stays in the rollup as dead.
                    if m.dead_sweeps >= DEAD_GC_SWEEPS \
                            and os.path.exists(m.path):
                        try:
                            os.unlink(m.path)
                        except OSError:
                            pass
        with self._lock:
            counts = {"live": 0, "stale": 0, "dead": 0}
            actors_total = actors_dead = 0
            for m in self.members.values():
                counts[m.state] += 1
                if m.desc.get("role") == "actor":
                    actors_total += 1
                    actors_dead += m.state == "dead"
            for s, g in self._g_members.items():
                g.set(counts[s])
            degraded = bool(actors_total
                            and actors_dead * 2 >= actors_total)
            self._g_degraded.set(float(degraded))
        self._c_sweeps.inc()
        self._h_sweep.observe(time.perf_counter() - t0)

    # -- the pane -----------------------------------------------------

    def render_metrics(self) -> str:
        """ONE merged exposition: every member's last-good families
        under ``process``/``role`` labels, plus per-member liveness and
        the aggregator's own dqn_fleet_* families."""
        from dist_dqn_tpu.telemetry.exposition import render_prometheus

        now = time.time()
        pages: List[Dict] = []
        liveness = Registry()
        with self._lock:
            members = list(self.members.values())
        for m in members:
            lbl = {"process": m.name, "role": str(m.desc.get("role"))}
            liveness.gauge("dqn_fleet_member_up",
                           "1 = member answered the last sweep's scrape",
                           lbl).set(float(m.state == "live"))
            staleness = (now - m.last_scrape) if m.last_scrape else -1.0
            liveness.gauge("dqn_fleet_member_staleness_seconds",
                           "seconds since this member's last good "
                           "scrape (-1 = never scraped)",
                           lbl).set(staleness)
            if m.last_text is not None:
                pages.append({"text": m.last_text, "labels": m.inject()})
        pages.append({"text": render_prometheus(liveness), "labels": {}})
        pages.append({"text": render_prometheus(self.registry),
                      "labels": {}})
        return merge_expositions(pages)

    def _member_scrape_value(self, m: _Member, family: str
                             ) -> Optional[float]:
        """A single un-labeled gauge/counter value out of a member's
        last-good scrape text (rollup convenience, not a parser)."""
        if not m.last_text:
            return None
        for line in m.last_text.splitlines():
            if line.startswith(family) and not line.startswith("#"):
                series = line.split(" ")[0]
                if series == family:
                    try:
                        return float(line.rsplit(" ", 1)[1])
                    except ValueError:
                        return None
        return None

    def status(self) -> Dict:
        """The ``/fleet/status`` JSON rollup."""
        now = time.time()
        with self._lock:
            members = list(self.members.values())
        out_members: Dict[str, Dict] = {}
        counts = {"live": 0, "stale": 0, "dead": 0}
        alerts: List[str] = []
        ingest_degraded = False
        for m in members:
            counts[m.state] += 1
            staleness = (now - m.last_scrape) if m.last_scrape else None
            row = {
                "role": m.desc.get("role"),
                "pid": m.desc.get("pid"),
                "host": m.desc.get("host"),
                "port": m.desc.get("port"),
                "labels": m.desc.get("labels", {}),
                "state": m.state,
                "healthy": m.healthy,
                "start_time": m.desc.get("start_time"),
                "manifest_hash": m.desc.get("manifest_hash"),
                "last_scrape_unix": m.last_scrape,
                "staleness_s": staleness,
            }
            if m.health_detail:
                row["health_detail"] = m.health_detail
                detail = json.dumps(m.health_detail, sort_keys=True)
                alerts.append(f"{m.name}: unhealthy ({detail})")
            if m.state == "dead":
                alerts.append(f"{m.name}: dead (pid gone)")
            v = self._member_scrape_value(m, "dqn_ingest_degraded")
            if v is not None and v > 0:
                ingest_degraded = True
                alerts.append(f"{m.name}: reports dqn_ingest_degraded")
            out_members[m.name] = row
        if self._g_degraded.value:
            ingest_degraded = True
            alerts.append("fleet: at least half the actor members are "
                          "dead")
        return {
            "schema_version": 1,
            "fleet_dir": self.fleet_dir,
            "sweep_interval_s": self.sweep_interval_s,
            "updated_unix": now,
            "counts": counts,
            "ingest_degraded": ingest_degraded,
            "alerts": alerts,
            "members": out_members,
        }

    def forensics(self) -> Dict:
        """The ``/fleet/forensics`` bundle: flight tail + thread stacks
        (+ manifest) from every LIVE member, correlated under one
        timestamp; stale/dead members appear by name with their state
        so the bundle never silently omits a fleet member."""
        bundle: Dict = {"generated_unix": time.time(), "members": {}}
        with self._lock:
            members = list(self.members.values())
        for m in members:
            if m.state != "live":
                bundle["members"][m.name] = {"state": m.state}
                continue
            entry: Dict = {"state": "live", "role": m.desc.get("role")}
            flight = self._http_get(m.base_url + "/debug/flight")
            if flight is not None:
                try:
                    entry["flight"] = json.loads(flight.decode())
                except ValueError:
                    entry["flight"] = None
            stacks = self._http_get(m.base_url + "/debug/stacks")
            if stacks is not None:
                entry["stacks"] = stacks.decode("utf-8", "replace")
            man = self._http_get(m.base_url + "/debug/config")
            if man is not None:
                try:
                    entry["manifest"] = json.loads(man.decode())
                except ValueError:
                    pass
            bundle["members"][m.name] = entry
        return bundle

    def profile(self, seconds: float = 1.0) -> Dict:
        """The ``/fleet/profile`` bundle (ISSUE 19): fan
        ``/debug/profile?seconds=N`` out to every LIVE member IN
        PARALLEL, so the per-process jax.profiler windows overlap and
        the traces correlate into one cross-fleet xprof view. Stale/
        dead members appear by name with their state — a capture with
        a dead actor still succeeds and still says who was missing.
        Each member entry is that member's own capture result JSON
        (trace_dir on its host, or its error)."""
        try:
            seconds = max(0.0, float(seconds))
        except (TypeError, ValueError):
            seconds = 1.0
        bundle: Dict = {"generated_unix": time.time(),
                        "seconds": seconds, "members": {}}
        with self._lock:
            members = list(self.members.values())
        live = [m for m in members if m.state == "live"]
        for m in members:
            if m.state != "live":
                bundle["members"][m.name] = {"state": m.state}

        def _capture(member: _Member) -> None:
            url = (member.base_url
                   + f"/debug/profile?seconds={seconds:g}")
            # The member holds its trace window open for `seconds`
            # before answering — the scrape timeout alone would kill
            # every non-trivial capture.
            try:
                with urllib.request.urlopen(
                        url, timeout=seconds
                        + self.scrape_timeout_s) as resp:
                    body = resp.read()
            except urllib.error.HTTPError as e:  # 409 busy carries JSON
                body = e.read()
            except Exception:  # noqa: BLE001 — connection-level failure
                bundle["members"][member.name] = {
                    "state": "live", "error": "capture request failed"}
                return
            entry: Dict = {"state": "live",
                           "role": member.desc.get("role")}
            try:
                entry.update(json.loads(body.decode()))
            except ValueError:
                entry["error"] = "unparseable capture response"
            bundle["members"][member.name] = entry

        threads = [threading.Thread(target=_capture, args=(m,),
                                    name=f"fleet-profile-{m.name}",
                                    daemon=True) for m in live]
        for t in threads:
            t.start()
        for t in threads:
            t.join(timeout=seconds + 2 * self.scrape_timeout_s + 5.0)
        return bundle

    # -- lifecycle ----------------------------------------------------

    def start(self) -> None:
        if self._thread is not None:
            return
        self._thread = threading.Thread(target=self._loop,
                                        name="fleet-sweeper", daemon=True)
        self._thread.start()

    def _loop(self) -> None:
        while not self._stop.is_set():
            try:
                self.sweep_once()
            except Exception:  # noqa: BLE001 — the sweeper must survive
                pass
            self._stop.wait(self.sweep_interval_s)

    def stop(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5.0)
            self._thread = None


class FleetServer:
    """HTTP face of the aggregator: ``/metrics`` (merged exposition),
    ``/fleet/status``, ``/fleet/forensics``, ``/fleet/profile``,
    ``/healthz``. Same stdlib ThreadingHTTPServer-on-a-daemon-thread
    shape as TelemetryServer."""

    def __init__(self, aggregator: FleetAggregator, port: int = 0,
                 host: str = "127.0.0.1"):
        self.aggregator = aggregator
        agg = aggregator

        class Handler(BaseHTTPRequestHandler):
            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path in ("/metrics", "/"):
                    body = agg.render_metrics().encode()
                    ctype = CONTENT_TYPE
                elif path == "/fleet/status":
                    body = (json.dumps(agg.status(), sort_keys=True)
                            + "\n").encode()
                    ctype = "application/json"
                elif path == "/fleet/forensics":
                    body = (json.dumps(agg.forensics(), sort_keys=True)
                            + "\n").encode()
                    ctype = "application/json"
                elif path == "/fleet/profile":
                    qs = parse_qs(urlsplit(self.path).query)
                    seconds = (qs.get("seconds") or ["1"])[0]
                    body = (json.dumps(agg.profile(seconds),
                                       sort_keys=True) + "\n").encode()
                    ctype = "application/json"
                elif path == "/healthz":
                    body, ctype = b"ok\n", "text/plain"
                else:
                    self.send_error(404)
                    return
                self.send_response(200)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                self.end_headers()
                self.wfile.write(body)

            def log_message(self, fmt, *args):
                pass

        self._httpd = ThreadingHTTPServer((host, port), Handler)
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="fleet-http", daemon=True)
        self._thread.start()

    def close(self) -> None:
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass


def main(argv: Optional[List[str]] = None) -> int:
    parser = argparse.ArgumentParser(
        description="Fleet metrics federation + health rollup "
                    "(docs/observability.md, 'One pane for a fleet').")
    parser.add_argument("--fleet-dir", default=None,
                        help="registry directory the run's members "
                             "write descriptors into (defaults to "
                             f"${FLEET_ENV})")
    parser.add_argument("--port", type=int, default=0,
                        help="bind port (0 = ephemeral; the bound port "
                             "is announced as a fleet_port line)")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address (loopback by default — the "
                             "pane is unauthenticated)")
    parser.add_argument("--sweep-interval", type=float, default=2.0,
                        help="seconds between registry sweeps")
    parser.add_argument("--scrape-timeout", type=float, default=2.0,
                        help="per-member HTTP timeout")
    args = parser.parse_args(argv)
    fleet_dir = resolve_fleet_dir(args.fleet_dir)
    if not fleet_dir:
        parser.error(f"--fleet-dir or ${FLEET_ENV} required")
    os.makedirs(fleet_dir, exist_ok=True)
    agg = FleetAggregator(fleet_dir, sweep_interval_s=args.sweep_interval,
                          scrape_timeout_s=args.scrape_timeout)
    agg.sweep_once()
    agg.start()
    server = FleetServer(agg, port=args.port, host=args.host)
    print(json.dumps({"fleet_port": server.port}), flush=True)

    stop = threading.Event()
    lifecycle.on_exit(stop.set)
    try:
        while not stop.wait(0.5):
            pass
    except KeyboardInterrupt:
        pass
    finally:
        server.close()
        agg.stop()
    return 0


if __name__ == "__main__":
    raise SystemExit(main())
