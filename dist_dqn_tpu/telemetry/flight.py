"""Flight recorder: a fixed-size ring of structured runtime events.

The crash-forensics half of the observability story (ISSUE 4): the
registry answers "how fast is it going", the flight recorder answers
"what were the last N things this process did before it died/hung".
Every interesting host-side event — span close, queue put/get, fence
wait, chunk/train-event boundary, watchdog/sentinel trip — lands in one
per-process ring of ``capacity`` events; the tail is dumped into every
forensics bundle (telemetry/watchdog.py) and served live at
``/debug/flight`` (telemetry/server.py).

Design constraints, same order as the registry's:

  * hot-path-safe: ``record()`` is one clock read + one tuple build +
    one ring store under a REENTRANT lock (the SIGTERM forensics dump
    runs on the main thread and may interrupt a frame already inside
    the critical section — telemetry/lifecycle.py has the full
    argument). ~1µs in CPython; the overhead pin in
    tests/test_flight_watchdog.py keeps it honest.
  * dependency-free: stdlib only (actor/feeder processes must not
    import jax, and they record too).
  * Null-object disabled path: ``NullFlightRecorder`` carries the same
    surface at ~zero cost; ``--no-flight-recorder`` (train CLI) or
    ``DQN_FLIGHT_RECORDER=0`` (environment — how spawned actor/feeder
    processes opt out with their parent) swaps it in, so call sites
    never branch.

Events are tuples in the ring and dicts on the way out (``tail()``):
``{"t": unix_time, "thread": name, "kind": ..., "name": ..., **args}``.
``kind`` is a coarse taxonomy ("span", "instant", "counter", "chunk",
"queue", "fence", "train", "watchdog", "divergence") so a bundle reader
can filter without knowing every event name.
"""
from __future__ import annotations

import os
import threading
import time
from typing import Dict, List, Optional

#: Environment knobs (inherited by spawned actor/feeder processes):
#: ``DQN_FLIGHT_RECORDER=0`` disables, ``DQN_FLIGHT_CAPACITY=N`` sizes
#: the ring.
ENABLE_ENV = "DQN_FLIGHT_RECORDER"
CAPACITY_ENV = "DQN_FLIGHT_CAPACITY"

DEFAULT_CAPACITY = 4096


class FlightRecorder:
    """Lock-light ring of the last ``capacity`` structured events."""

    enabled = True

    def __init__(self, capacity: int = DEFAULT_CAPACITY):
        if capacity < 1:
            raise ValueError(
                f"flight recorder capacity must be >= 1, got {capacity}")
        self.capacity = int(capacity)
        self._buf: List[Optional[tuple]] = [None] * self.capacity
        self._total = 0
        self._lock = threading.RLock()

    def record(self, kind: str, name: str, **args) -> None:
        """Append one event; O(1), overwrites the oldest when full."""
        ev = (time.time(), threading.current_thread().name, kind, name,
              args or None)
        with self._lock:
            self._buf[self._total % self.capacity] = ev
            self._total += 1

    @property
    def total(self) -> int:
        """Events ever recorded (``total - capacity`` were overwritten)."""
        return self._total

    def __len__(self) -> int:
        return min(self._total, self.capacity)

    def tail(self, n: Optional[int] = None) -> List[Dict]:
        """The newest ``n`` (default: all retained) events, oldest first,
        as JSON-able dicts."""
        with self._lock:
            total = self._total
            held = min(total, self.capacity)
            take = held if n is None else max(0, min(int(n), held))
            start = total - take
            events = [self._buf[i % self.capacity]
                      for i in range(start, total)]
        out = []
        for t, thread, kind, name, args in events:
            ev = {"t": t, "thread": thread, "kind": kind, "name": name}
            if args:
                ev.update(args)
            out.append(ev)
        return out

    def snapshot(self) -> Dict:
        """JSON-able dump for forensics bundles / ``/debug/flight``."""
        return {"capacity": self.capacity, "total": self._total,
                "events": self.tail()}


class NullFlightRecorder:
    """Disabled path: identical surface, zero work, empty tail."""

    enabled = False
    capacity = 0
    total = 0

    def record(self, kind: str, name: str, **args) -> None:
        pass

    def __len__(self) -> int:
        return 0

    def tail(self, n: Optional[int] = None) -> List[Dict]:
        return []

    def snapshot(self) -> Dict:
        return {"capacity": 0, "total": 0, "events": []}


NULL_FLIGHT = NullFlightRecorder()

_lock = threading.RLock()
_flight = None  # lazy: first get_flight() reads the environment knobs


def get_flight():
    """The process-global flight recorder (Null twin when disabled)."""
    global _flight
    with _lock:
        if _flight is None:
            if os.environ.get(ENABLE_ENV, "1") == "0":
                _flight = NULL_FLIGHT
            else:
                try:
                    cap = int(os.environ.get(CAPACITY_ENV,
                                             DEFAULT_CAPACITY))
                except ValueError:
                    cap = DEFAULT_CAPACITY
                _flight = FlightRecorder(capacity=cap)
        return _flight


def configure(enabled: bool = True,
              capacity: int = DEFAULT_CAPACITY):
    """Replace the process-global recorder (train CLI
    ``--no-flight-recorder`` path). Existing call sites that cached the
    old recorder keep their reference — configure before wiring loops."""
    global _flight
    with _lock:
        _flight = FlightRecorder(capacity) if enabled else NULL_FLIGHT
        return _flight


def _reset_for_tests() -> None:
    global _flight
    with _lock:
        _flight = None
