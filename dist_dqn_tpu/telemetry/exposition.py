"""Prometheus text exposition (format 0.0.4) + JSON snapshot rendering.

``render_prometheus`` turns a Registry into the plain-text format every
Prometheus-compatible scraper parses; ``snapshot`` is the JSON twin for
offline runs (bench.py's BENCH JSON, the atexit dump). Stdlib only.
"""
from __future__ import annotations

import json
from typing import Dict, Optional

from dist_dqn_tpu.telemetry.registry import Registry, get_registry

CONTENT_TYPE = "text/plain; version=0.0.4; charset=utf-8"


def _fmt_value(v: float) -> str:
    if v != v:  # NaN
        return "NaN"
    if v == float("inf"):
        return "+Inf"
    if v == float("-inf"):
        return "-Inf"
    if float(v).is_integer() and abs(v) < 1e15:
        return str(int(v))
    return repr(float(v))


def _escape_label(v: str) -> str:
    return (str(v).replace("\\", r"\\").replace("\n", r"\n")
            .replace('"', r'\"'))


def _labels_str(labels: Dict[str, str], extra: Optional[Dict] = None) -> str:
    merged = dict(labels)
    if extra:
        merged.update(extra)
    if not merged:
        return ""
    inner = ",".join(f'{k}="{_escape_label(v)}"'
                     for k, v in sorted(merged.items()))
    return "{" + inner + "}"


def _escape_help(h: str) -> str:
    return h.replace("\\", r"\\").replace("\n", r"\n")


def render_prometheus(registry: Optional[Registry] = None) -> str:
    """The registry's current state as Prometheus text exposition."""
    registry = registry if registry is not None else get_registry()
    lines = []
    for name, insts in registry.collect().items():
        first = insts[0]
        if first.help:
            lines.append(f"# HELP {name} {_escape_help(first.help)}")
        lines.append(f"# TYPE {name} {first.kind}")
        for inst in insts:
            if inst.kind == "histogram":
                for bound, cum in inst.cumulative_buckets():
                    le = "+Inf" if bound == float("inf") \
                        else _fmt_value(bound)
                    lines.append(
                        f"{name}_bucket"
                        f"{_labels_str(inst.labels, {'le': le})} {cum}")
                lines.append(f"{name}_sum{_labels_str(inst.labels)} "
                             f"{_fmt_value(inst.sum)}")
                lines.append(f"{name}_count{_labels_str(inst.labels)} "
                             f"{inst.count}")
            else:
                lines.append(f"{name}{_labels_str(inst.labels)} "
                             f"{_fmt_value(inst.value)}")
    return "\n".join(lines) + "\n"


def snapshot(registry: Optional[Registry] = None) -> Dict:
    """JSON-able snapshot of every instrument (the offline-run twin of
    the /metrics endpoint; embedded in bench.py's BENCH JSON)."""
    registry = registry if registry is not None else get_registry()
    return registry.snapshot()


def write_snapshot(path: str, registry: Optional[Registry] = None) -> None:
    """Dump ``snapshot()`` to ``path`` as one JSON document."""
    with open(path, "w") as f:
        json.dump(snapshot(registry), f, indent=1, sort_keys=True)
        f.write("\n")
