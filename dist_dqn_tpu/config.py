"""Configuration system: the five driver configs (BASELINE.json:7-11)
plus three beyond-spec presets (qrdqn, iqn, mdqn).

Frozen dataclasses so configs are hashable and can be closed over by ``jit``
as static values. ``CONFIGS`` is the registry keyed by the names the train CLI
accepts; the first five correspond 1:1 to driver config lines. Derive
variants with ``dataclasses.replace`` or the CLIs' ``--set`` flag
(``apply_overrides``).
"""
from __future__ import annotations

import dataclasses
from typing import Dict, Tuple


@dataclasses.dataclass(frozen=True)
class NetworkConfig:
    """Q-network architecture knobs (models/qnets.py, models/recurrent.py)."""

    torso: str = "nature"  # "mlp" | "nature" (84x84 Atari CNN) | "small"
    #                        (cheap 84x84 CNN — models/qnets.py presets)
    mlp_features: Tuple[int, ...] = (256, 256)
    hidden: int = 512                  # post-torso embedding width
    dueling: bool = False              # dueling value/advantage streams
    noisy: bool = False                # NoisyNet exploration heads (Rainbow)
    num_atoms: int = 1                 # >1 => distributional head (C51/QR)
    v_min: float = -10.0
    v_max: float = 10.0
    quantile: bool = False             # num_atoms>1: QR-DQN instead of C51
    # IQN (Dabney et al., 2018b) — the third distributional family: the
    # head is CONDITIONED on sampled quantile fractions via a cosine
    # embedding instead of outputting a fixed set (models/qnets.py
    # ImplicitQuantileNetwork). Mutually exclusive with noisy /
    # num_atoms>1 / lstm_size.
    iqn: bool = False
    iqn_embed_dim: int = 64            # cosine embedding width
    iqn_tau_samples: int = 64          # N: online tau draws per loss
    iqn_tau_target_samples: int = 64   # N': target tau draws per loss
    iqn_tau_act: int = 32              # K: fixed acting fractions
    # Acting-time risk distortion: q_values averages the lower
    # risk_cvar_eta tail of the return distribution (CVaR_eta); 1.0 is
    # the risk-neutral mean.
    risk_cvar_eta: float = 1.0
    lstm_size: int = 0                 # >0 => recurrent core (R2D2)
    remat_torso: bool = False          # recompute torso acts in backward
    compute_dtype: str = "float32"     # "bfloat16" for the TPU MXU path
    # R2D2 learner-throughput knobs (models/recurrent.py): gate-matmul
    # dtype of the LSTM cell (carry stays float32 either way) and the
    # lax.scan unroll factor of the time loop (XLA fuses k cell steps per
    # scan iteration; the math is unchanged).
    lstm_dtype: str = "float32"        # "bfloat16" runs cell matmuls on MXU
    lstm_unroll: int = 1
    # Actor/learner dtype split (ISSUE 6): "bfloat16" casts the params
    # ONCE per chunk for actor inference (acting reads a bf16 snapshot
    # of the chunk-entry params — one target-network's worth of extra
    # staleness, Podracer-style) while the learner keeps fp32 master
    # params end to end. "float32" (default) acts on the live learner
    # params exactly as before — bit-identical, pinned by the
    # param_checksum A/B in tests/test_replay_ratio.py.
    actor_dtype: str = "float32"


@dataclasses.dataclass(frozen=True)
class ReplayConfig:
    """Replay buffer knobs (replay/)."""

    capacity: int = 100_000
    prioritized: bool = False
    priority_exponent: float = 0.6     # alpha
    importance_exponent: float = 0.4   # beta (annealed -> 1.0 over training)
    priority_eps: float = 1e-6
    min_fill: int = 1_000              # learning starts after this many items
    pallas_sampler: bool = False       # Pallas kernel for priority sampling
    # Store the pre-reset successor obs alongside each step so n-step windows
    # bootstrap exactly through time-limit truncation. None = auto: on for
    # cheap (non-uint8) observations, off for pixel rings, where the second
    # obs copy would double HBM and truncation is treated as terminal.
    store_final_obs: "bool | None" = None
    # Store multi-dim obs FLAT in the device ring ([slots, B, prod]).
    # XLA tiles multi-dim u8 ring buffers at (8,128) on the minor dims,
    # padding an 84x84 ring to ~1.6x its logical bytes — but the tiled
    # layout also gathers ~3% faster (v5e, 2026-08-01: 619k vs 602k
    # env-steps/s at a 16k ring). None = auto: flat only when the ring's
    # logical bytes exceed ~2 GB, where the padding waste dwarfs the
    # throughput cost (the atari config's 200k-slot ring compiles at
    # 5.26G flat vs 8.39G tiled — the difference between fitting a v5e
    # beside the training program and OOM).
    flat_storage: "bool | None" = None
    # Frame-dedup storage for rolling-stack pixel obs (fused loop only):
    # store each step's NEWEST frame instead of the whole stack and
    # rebuild stacks at sample time from frame_stack consecutive slots
    # (exact, including reset-boundary re-tiling — replay/device.py
    # stack_rebuild_indices). A 4x HBM saving on Atari stacks: the v5e
    # pixel window cap lifts from ~200k to ~1M transitions. Requires the
    # env to declare the rolling-stack contract (JaxEnv.frame_stack > 0)
    # and store_final_obs off. Covers BOTH fused loops: the feedforward
    # ring (replay/device.py) and the R2D2 sequence ring
    # (replay/sequence_device.py _rebuild_seq_stacks).
    frame_dedup: bool = False
    # On-device replay ratio (ISSUE 6, --replay-ratio): grad sub-steps
    # per train event, each drawing an INDEPENDENT replay batch from a
    # fresh RNG split, scanned inside the jitted chunk program (fused
    # loop) / one scanned device dispatch (apex) / one prefetched run
    # of batches (host-replay). Multiplies updates_per_train; 1 is
    # bit-identical to the pre-knob program (the train-event scan has
    # the same length and key stream), and with UNIFORM replay ratio N
    # == updates_per_train=N bit-for-bit. Under PER with ratio > 1 the
    # sub-steps' |TD| write-backs are deferred and flushed ONCE per
    # event with chronological last-wins semantics (PR 5's discipline),
    # so sub-steps sample against event-entry priorities — the same lag
    # contract as the host loops' prio_writeback_batch.
    updates_per_chunk: int = 1
    # Wide train batches (ISSUE 6): 0 = learner.batch_size unchanged;
    # > 0 widens the train-event batch to this many rows, rounded UP to
    # the next power of two (the ingest bucket discipline — bounded
    # compile variants, MXU-friendly tiles). Sized empirically with
    # benchmarks/learner_bench.py --batch-sweep.
    train_batch: int = 0
    # R2D2 sequence replay (>0 enables sequence mode):
    burn_in: int = 0
    unroll_length: int = 0
    sequence_stride: int = 0           # overlap between stored sequences
    priority_mix: float = 0.9          # eta: p = eta*max|td| + (1-eta)*mean


@dataclasses.dataclass(frozen=True)
class LearnerConfig:
    """Optimizer / TD-learning knobs (agents/)."""

    learning_rate: float = 1e-3
    adam_eps: float = 1e-8
    # Learning-rate schedule over GRAD steps, counted by the optimizer's
    # own state (survives checkpoint/resume): "constant" ignores the
    # other two knobs; "linear" anneals learning_rate -> lr_end_value
    # over lr_decay_steps; "cosine" decays along a half-cosine to
    # lr_end_value and holds there.
    lr_schedule: str = "constant"
    lr_decay_steps: int = 0
    lr_end_value: float = 0.0
    gamma: float = 0.99
    n_step: int = 1
    batch_size: int = 128
    double_dqn: bool = True
    huber_delta: float = 1.0
    max_grad_norm: float = 10.0        # 0 disables clipping
    # Target network sync (BASELINE.json:5 "target-network Polyak sync"):
    target_update_period: int = 500    # hard copy every N steps (if tau == 0)
    target_tau: float = 0.0            # >0 => soft Polyak every step
    value_rescale: bool = False        # R2D2 h/h^-1 transform
    # Munchausen-DQN (Vieillard et al., 2020): entropy-regularized soft
    # bootstrap plus a clipped scaled log-policy bonus on the reward.
    # Scalar-head only (agents/dqn.py); replaces the max/double-Q
    # bootstrap when set. Use with n_step=1: replay folds n-step rewards
    # at sample time, so the intermediate per-step log-policy bonuses
    # the telescoped soft recursion needs are not recoverable — with
    # n_step>1 only the first step's bonus is applied (make_learner
    # rejects the combination rather than silently approximating).
    munchausen: bool = False
    munchausen_alpha: float = 0.9      # bonus scale
    munchausen_tau: float = 0.03       # entropy temperature
    munchausen_clip: float = -1.0      # lower clip l0 on log pi(a|s)


@dataclasses.dataclass(frozen=True)
class ActorConfig:
    """Rollout / exploration knobs (actors/, train loops)."""

    num_envs: int = 16                 # vectorized envs per actor process
    epsilon_start: float = 1.0
    epsilon_end: float = 0.05
    epsilon_decay_steps: int = 10_000
    # Ape-X per-actor epsilon ladder: eps_i = base ** (1 + i/(N-1) * alpha)
    apex_epsilon_base: float = 0.4
    apex_epsilon_alpha: float = 7.0
    num_actors: int = 1                # actor processes (Ape-X: e.g. 256)


@dataclasses.dataclass(frozen=True)
class PopulationConfig:
    """Population training plane (ISSUE 20, fused runtime only).

    ``size`` M > 1 vmap-stacks M independent policies — params, optimizer
    state, target params, replay and collect carries all gain a leading
    member axis — and advances all of them in ONE dispatched chunk
    program per chunk (Podracer's "one program, many policies",
    PAPERS.md). ``spec_json`` optionally carries per-member
    hyperparameter vectors (``epsilon`` / ``lr`` / ``gamma``, each a
    length-M JSON array — the raw text of the ``--population-spec``
    file; dist_dqn_tpu/population.py parses and validates it). size=1
    runs the exact pre-knob program (bit-identity pin,
    tests/test_population.py).
    """

    size: int = 1
    spec_json: str = ""


@dataclasses.dataclass(frozen=True)
class ExperimentConfig:
    """One runnable experiment = env + net + replay + learner + actors."""

    name: str
    env_name: str                      # key into envs.make()
    network: NetworkConfig = NetworkConfig()
    replay: ReplayConfig = ReplayConfig()
    learner: LearnerConfig = LearnerConfig()
    actor: ActorConfig = ActorConfig()
    population: PopulationConfig = PopulationConfig()
    total_env_steps: int = 500_000
    train_every: int = 1               # learner updates per env *vector* step
    updates_per_train: int = 1
    eval_every_steps: int = 25_000
    eval_episodes: int = 10
    seed: int = 0


# ---------------------------------------------------------------------------
# The five driver configs (BASELINE.json:7-11), one entry each.
# ---------------------------------------------------------------------------

CARTPOLE = ExperimentConfig(
    # BASELINE.json:7 — "CartPole-v1 single-process DQN (CPU ref)"
    name="cartpole",
    env_name="cartpole",
    network=NetworkConfig(torso="mlp", mlp_features=(256, 256), hidden=0),
    replay=ReplayConfig(capacity=50_000, min_fill=1_000),
    learner=LearnerConfig(
        learning_rate=1e-3, gamma=0.99, n_step=3, batch_size=128,
        target_update_period=250,
    ),
    actor=ActorConfig(num_envs=16, epsilon_decay_steps=20_000),
    total_env_steps=400_000,
)

ATARI = ExperimentConfig(
    # BASELINE.json:8 — "Atari Pong/Breakout DQN (Nature CNN, 1 chip)"
    name="atari",
    env_name="pixel_pong",             # synthetic offline stand-in; real ALE
    network=NetworkConfig(torso="nature", hidden=512,
                          compute_dtype="bfloat16"),
    replay=ReplayConfig(capacity=200_000, min_fill=20_000),
    learner=LearnerConfig(
        learning_rate=6.25e-5, adam_eps=1.5e-4, gamma=0.99, n_step=3,
        batch_size=256, target_update_period=2_000,
    ),
    actor=ActorConfig(num_envs=64, epsilon_decay_steps=250_000),
    total_env_steps=10_000_000,
    train_every=4,
)

APEX = ExperimentConfig(
    # BASELINE.json:9 — "Ape-X DQN: 256 CPU actors + sharded learner on mesh"
    name="apex",
    env_name="pixel_pong",
    network=NetworkConfig(torso="nature", hidden=512, dueling=True,
                          compute_dtype="bfloat16"),
    replay=ReplayConfig(capacity=1_000_000, prioritized=True,
                        priority_exponent=0.6, importance_exponent=0.4,
                        min_fill=50_000,
                        # ~1M-cell shard: above the Pallas kernel's
                        # crossover (ops/pallas_sampler.py).
                        pallas_sampler=True),
    learner=LearnerConfig(
        learning_rate=1e-4, adam_eps=1.5e-4, gamma=0.99, n_step=3,
        batch_size=512, double_dqn=True, target_update_period=2_500,
    ),
    actor=ActorConfig(num_envs=16, num_actors=256),
    total_env_steps=100_000_000,
)

R2D2 = ExperimentConfig(
    # BASELINE.json:10 — "R2D2 recurrent DQN (LSTM Q-net, seq replay, burn-in)"
    name="r2d2",
    env_name="pixel_pong",
    network=NetworkConfig(torso="nature", hidden=512, dueling=True,
                          lstm_size=512, compute_dtype="bfloat16",
                          # Throughput knobs, numerics pinned by
                          # tests/test_recurrent_knobs.py. Defaults are the
                          # round-3 TPU sweep winner (v5e, learner_bench
                          # --r2d2-sweep, docs/tpu_runs/20260731_0100):
                          # no-remat + bf16 gates + unroll 8 = 58.8
                          # grad-steps/s vs 53.4 for remat+f32+unroll 1
                          # (+10%; +24% over the round-1 47.4/s). The
                          # 120-step x B=64 pixel unroll fits v5e HBM
                          # without remat; set remat_torso=True on
                          # HBM-constrained configs (models/recurrent.py).
                          remat_torso=False,
                          lstm_dtype="bfloat16", lstm_unroll=8),
    replay=ReplayConfig(capacity=100_000, prioritized=True,
                        priority_exponent=0.9, importance_exponent=0.6,
                        burn_in=40, unroll_length=80, sequence_stride=40,
                        min_fill=2_500),
    learner=LearnerConfig(
        learning_rate=1e-4, adam_eps=1e-3, gamma=0.997, n_step=5,
        batch_size=64, double_dqn=True, target_update_period=2_500,
        value_rescale=True,
    ),
    actor=ActorConfig(num_envs=16, num_actors=256),
    total_env_steps=100_000_000,
)

RAINBOW = ExperimentConfig(
    # BASELINE.json:11 — "Rainbow / C51 distributional DQN on DM-Control pixels"
    name="rainbow",
    env_name="dmc_pixels",             # synthetic pixel env offline fallback
    network=NetworkConfig(torso="nature", hidden=512, dueling=True,
                          noisy=True, num_atoms=51, v_min=-10.0, v_max=10.0,
                          compute_dtype="bfloat16"),
    replay=ReplayConfig(capacity=200_000, prioritized=True,
                        priority_exponent=0.5, importance_exponent=0.4,
                        min_fill=20_000),
    learner=LearnerConfig(
        learning_rate=6.25e-5, adam_eps=1.5e-4, gamma=0.99, n_step=3,
        batch_size=256, double_dqn=True, target_update_period=2_000,
    ),
    actor=ActorConfig(num_envs=64, epsilon_start=0.0, epsilon_end=0.0),
    total_env_steps=10_000_000,
    train_every=4,
)

QRDQN = ExperimentConfig(
    # Beyond the driver's five configs: QR-DQN (Dabney et al., 2018) — the
    # quantile-regression distributional family on the Atari-shaped path,
    # sharing the atari preset's schedule with the standard 200-quantile
    # head (no fixed support, so no v_min/v_max tuning).
    name="qrdqn",
    env_name="pixel_pong",
    network=NetworkConfig(torso="nature", hidden=512, num_atoms=200,
                          quantile=True, compute_dtype="bfloat16"),
    replay=ReplayConfig(capacity=200_000, prioritized=True,
                        priority_exponent=0.5, importance_exponent=0.4,
                        min_fill=20_000),
    learner=LearnerConfig(
        learning_rate=5e-5, adam_eps=3.125e-4, gamma=0.99, n_step=3,
        batch_size=256, double_dqn=True, target_update_period=2_000,
        huber_delta=1.0,
    ),
    actor=ActorConfig(num_envs=64, epsilon_decay_steps=250_000),
    total_env_steps=10_000_000,
    train_every=4,
)

IQN = ExperimentConfig(
    # Beyond the driver's five configs: IQN (Dabney et al., 2018b) — the
    # implicit-quantile distributional family on the Atari-shaped path.
    # Shares the qrdqn preset's schedule; the head samples 64 online /
    # 64 target quantile fractions per loss and acts on 32 fixed
    # fractions (risk-neutral by default; set network.risk_cvar_eta < 1
    # for CVaR risk-averse control).
    name="iqn",
    env_name="pixel_pong",
    network=NetworkConfig(torso="nature", hidden=512, iqn=True,
                          compute_dtype="bfloat16"),
    replay=ReplayConfig(capacity=200_000, prioritized=True,
                        priority_exponent=0.5, importance_exponent=0.4,
                        min_fill=20_000),
    learner=LearnerConfig(
        learning_rate=5e-5, adam_eps=3.125e-4, gamma=0.99, n_step=3,
        batch_size=256, double_dqn=True, target_update_period=2_000,
        huber_delta=1.0,
    ),
    actor=ActorConfig(num_envs=64, epsilon_decay_steps=250_000),
    total_env_steps=10_000_000,
    train_every=4,
)

MDQN = ExperimentConfig(
    # Beyond the driver's five configs: Munchausen-DQN (Vieillard et
    # al., 2020) — the atari preset's schedule with the soft
    # entropy-regularized bootstrap and the clipped log-policy reward
    # bonus (paper defaults alpha 0.9, tau 0.03, l0 -1) plus PER.
    name="mdqn",
    env_name="pixel_pong",
    network=NetworkConfig(torso="nature", hidden=512,
                          compute_dtype="bfloat16"),
    replay=ReplayConfig(capacity=200_000, prioritized=True,
                        priority_exponent=0.5, importance_exponent=0.4,
                        min_fill=20_000),
    learner=LearnerConfig(
        # n_step=1: the Munchausen recursion needs every step's
        # log-policy bonus, which folded n-step rewards can't carry
        # (see LearnerConfig.munchausen).
        learning_rate=6.25e-5, adam_eps=1.5e-4, gamma=0.99, n_step=1,
        # double_dqn is superseded by the soft bootstrap (there is no
        # argmax to decouple); the learner rejects the combination.
        batch_size=256, double_dqn=False, target_update_period=2_000,
        munchausen=True,
    ),
    actor=ActorConfig(num_envs=64, epsilon_decay_steps=250_000),
    total_env_steps=10_000_000,
    train_every=4,
)

CONFIGS: Dict[str, ExperimentConfig] = {
    c.name: c for c in (CARTPOLE, ATARI, APEX, R2D2, RAINBOW, QRDQN, IQN,
                        MDQN)
}


# ---------------------------------------------------------------------------
# Generic dotted-path config overrides (the CLIs' --set flag): derive any
# preset variant from the command line without writing a config file —
# the CLI counterpart of the dataclasses.replace idiom used in code.
# ---------------------------------------------------------------------------

def _coerce(raw: str, current, path: str):
    """Parse ``raw`` to the type of the field's current value."""
    low = raw.lower()
    if isinstance(current, bool):          # bool before int: bool is an int
        if low in ("true", "1", "yes", "on"):
            return True
        if low in ("false", "0", "no", "off"):
            return False
        raise ValueError(f"--set {path}: expected a bool, got {raw!r}")
    if isinstance(current, int):
        try:
            return int(raw, 0)
        except ValueError:
            # Common spellings with unambiguous intent: 1e6, 2.5e5,
            # 200_000 (int() already takes underscores; the float path
            # catches scientific notation). Accept only values that are
            # exactly integral — 1.5 stays an error (ADVICE round 3).
            import math

            try:
                as_float = float(raw)
            except ValueError:
                as_float = None
            if (as_float is not None and math.isfinite(as_float)
                    and as_float == int(as_float)):
                return int(as_float)
            raise ValueError(
                f"--set {path}: expected an int (decimal, hex, or an "
                f"exactly-integral form like 1e6 / 200_000), got "
                f"{raw!r}") from None
    if isinstance(current, float):
        try:
            return float(raw)
        except ValueError:
            raise ValueError(
                f"--set {path}: expected a float, got {raw!r}") from None
    if isinstance(current, tuple):
        items = [s for s in raw.strip("()").split(",") if s.strip()]
        elem = current[0] if current else 0
        return tuple(_coerce(s.strip(), elem, path) for s in items)
    if isinstance(current, str):
        return raw
    # Optional fields default to None (e.g. replay.store_final_obs);
    # accept none/bool and fall back through int/float to str.
    if current is None:
        if low in ("none", "null"):
            return None
        if low in ("true", "false", "1", "0", "yes", "no", "on", "off"):
            return _coerce(raw, True, path)
        for parse in (int, float):
            try:
                return parse(raw)
            except ValueError:
                pass
        return raw
    raise ValueError(
        f"--set {path}: field type {type(current).__name__} is not "
        "overridable from the command line")


def _is_optional(cls, name: str) -> bool:
    """True if the resolved annotation of ``cls.name`` admits None
    (covers both the ``X | None`` and ``Optional[X]`` spellings)."""
    import typing

    try:
        hint = typing.get_type_hints(cls).get(name)
    except Exception:
        return False
    return type(None) in typing.get_args(hint)


def _set_path(obj, keys, raw: str, path: str):
    if not dataclasses.is_dataclass(obj):
        raise ValueError(f"--set {path}: {keys[0]!r} is past a leaf field")
    names = {f.name for f in dataclasses.fields(obj)}
    name = keys[0]
    if name not in names:
        raise ValueError(
            f"--set {path}: unknown field {name!r}; valid here: "
            f"{', '.join(sorted(names))}")
    current = getattr(obj, name)
    if len(keys) == 1:
        if dataclasses.is_dataclass(current):
            sub = ", ".join(
                f.name for f in dataclasses.fields(current))
            raise ValueError(
                f"--set {path}: {name!r} is a config section; set one of "
                f"its fields ({sub})")
        # Optional fields (resolved annotation admits None) accept
        # "none" regardless of their current value's type.
        if raw.lower() in ("none", "null") and _is_optional(type(obj),
                                                            name):
            return dataclasses.replace(obj, **{name: None})
        return dataclasses.replace(obj, **{name: _coerce(raw, current,
                                                         path)})
    return dataclasses.replace(
        obj, **{name: _set_path(current, keys[1:], raw, path)})


def apply_overrides(cfg: ExperimentConfig, assignments) -> ExperimentConfig:
    """Apply ``--set dotted.path=value`` assignments to a config.

    e.g. apply_overrides(CONFIGS["atari"], ["network.dueling=true",
    "learner.batch_size=64", "replay.capacity=65536"]). Values are
    coerced to the field's current type (tuples parse "256,256");
    unknown fields and section-level assignments raise ValueError with
    the valid field names.
    """
    for a in assignments or ():
        path, eq, raw = a.partition("=")
        path = path.strip()
        if not eq or not path:
            raise ValueError(
                f"--set {a!r}: expected the form dotted.path=value")
        cfg = _set_path(cfg, path.split("."), raw.strip(), path)
    return cfg
