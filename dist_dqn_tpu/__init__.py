"""dist_dqn_tpu — a TPU-native distributed deep-RL (DQN-family) framework.

Brand-new design for JAX/XLA on TPU pods, with the capability surface of the
``hbfs/dist-dqn`` reference (driver spec: /root/repo/BASELINE.json:5-12):

* DQN (CartPole CPU-reference config, Atari Nature-CNN single-learner config)
* Ape-X: distributed prioritized replay, many CPU actors, sharded multi-learner
* R2D2: recurrent (LSTM) Q-network, sequence replay with burn-in
* Rainbow / C51: distributional Q-learning on DM-Control pixels

TPU-first architecture (NOT a port of the reference's CUDA/NCCL design):

* forward + TD-loss + backward + Polyak sync compile into a single XLA ``jit``
* multi-learner gradient allreduce = ``shard_map`` + ``psum`` over the ICI mesh
* replay shards across TPU-VM host DRAM; on-device priority sampling (Pallas)
* CPU rollout actors stream trajectories to the sharded buffer over the DCN
* fully on-device (Anakin-style) training loops for JAX-native envs

NOTE: the reference source was never mounted in this environment (SURVEY.md §0),
so docstrings cite the driver spec (BASELINE.json:line), not reference files.
"""

__version__ = "0.1.0"

from dist_dqn_tpu import config as config  # noqa: F401
