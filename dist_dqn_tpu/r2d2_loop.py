"""Fused on-device R2D2 training loop (BASELINE.json:10).

Same Anakin-style shape as the feed-forward loop (train_loop.py): act ->
env.step -> sequence-replay add -> sample -> sequence train step, all one
``lax.scan`` body in a single XLA program. The differences are the threaded
actor LSTM carry (zeroed on episode ends, stored into the ring alongside
each step so learner burn-in starts from the exact acting state) and the
sequence sampler/learner pair (replay/sequence_device.py, agents/r2d2.py).

SPMD-parameterizable like the feed-forward loop: with ``axis_name`` /
``num_shards`` set it is the per-device body for ``shard_map`` over the dp
mesh axis — env lanes and the sequence-replay shard are device-local, the
learner pmean-allreduces gradients over ICI (BASELINE.json:5).
"""
from __future__ import annotations

from typing import NamedTuple, Optional, Tuple

import jax
import jax.numpy as jnp

from dist_dqn_tpu import loop_common
from dist_dqn_tpu.agents.dqn import LearnerState
from dist_dqn_tpu.agents.r2d2 import make_r2d2_learner, \
    make_recurrent_actor_step
from dist_dqn_tpu.config import ExperimentConfig
from dist_dqn_tpu.envs.base import JaxEnv
from dist_dqn_tpu.replay import device as ring
from dist_dqn_tpu.replay import sequence_device as sring
from dist_dqn_tpu.types import PyTree

Array = jnp.ndarray


class R2D2Carry(NamedTuple):
    env_state: PyTree
    obs: PyTree
    actor_carry: Tuple[Array, Array]   # LSTM (c, h), each [B, lstm]
    replay: sring.SequenceRingState
    learner: LearnerState
    rng: Array                         # [1] key array in SPMD mode
    iteration: Array
    ep_return: Array
    completed_return: Array
    completed_count: Array
    loss_sum: Array
    train_count: Array


def make_r2d2_train(cfg: ExperimentConfig, env: JaxEnv, net,
                    axis_name: Optional[str] = None, num_shards: int = 1):
    """Returns (init, run_chunk) — same contract as train_loop.make_fused_train."""
    spmd = axis_name is not None
    rcfg = cfg.replay
    # Honest-unsupported-surface gate (the host_replay lstm_size
    # pattern): the ISSUE 6 replay-ratio scan exists only in the
    # feed-forward loops — a recurrent config setting the knob must
    # fail loudly, not silently train at ratio 1 (the --replay-ratio
    # CLI flag is warned-and-stripped by train.py before it gets here;
    # this catches the --set/config path). replay.train_batch IS
    # honored: it widens the sequence batch through shard_sizes below.
    if rcfg.updates_per_chunk != 1:
        raise ValueError(
            "replay.updates_per_chunk (the replay-ratio scan) is not "
            "supported by the recurrent R2D2 loop yet; leave it at 1 "
            "or use a feed-forward config")
    seq_len = rcfg.burn_in + rcfg.unroll_length + cfg.learner.n_step
    stride = rcfg.sequence_stride or rcfg.unroll_length
    init_learner, train_step = make_r2d2_learner(net, cfg.learner, rcfg,
                                                 axis_name=axis_name)
    act = make_recurrent_actor_step(net)

    B, batch_size = loop_common.shard_sizes(cfg, num_shards)
    min_fill = max(rcfg.min_fill // num_shards, 1)
    num_slots = max(cfg.replay.capacity // (B * num_shards), seq_len + 2)
    if num_slots < seq_len + stride:
        # A seeded start lives num_slots - seq_len + 1 writes and seeds come
        # every `stride` writes; a smaller ring can transiently hold zero
        # valid starts and the sampler would train on garbage windows.
        raise ValueError(
            f"sequence ring too small: num_slots={num_slots} < "
            f"seq_len+stride={seq_len + stride}; raise replay.capacity")

    # Frame-dedup (replay.frame_dedup): the sequence ring stores single
    # frames and the sampler rebuilds [L, S, H, W, stack] stacks — same
    # 4x HBM saving and exactness contract as the feedforward ring
    # (replay/sequence_device.py _rebuild_seq_stacks).
    _obs_shape = tuple(env.observation_shape)
    stack, _stored_shape, _frame_shape, _slice_newest = \
        loop_common.resolve_frame_dedup(rcfg, env, _obs_shape)
    # Context slots for the oldest start's rebuild, and headroom so a
    # seeded start is never ONLY transiently inside the masked oldest
    # region between two stride seeds (the static side of the can_train
    # guard below).
    num_slots = max(num_slots, seq_len + stride + max(stack - 1, 0))

    # Pixel sequence rings take the same merged-row flat storage as the
    # feedforward ring (loop_common.resolve_flat_storage): obs rows are
    # flattened at insert and reshaped back after the window gather.
    flat_storage = loop_common.resolve_flat_storage(
        rcfg, _stored_shape, env.observation_dtype, num_slots, B,
        prefer_flat=bool(stack))

    _flatten_batched, _unflatten_seq_codec = loop_common.flat_obs_codecs(
        flat_storage, _stored_shape)
    # Dedup sampling returns rebuilt (unflattened) stacks already.
    _unflatten_seq = ((lambda x: x) if stack else _unflatten_seq_codec)

    epsilon, beta_at = loop_common.make_schedules(cfg, B, num_shards)
    _split_rng = loop_common.make_rng_splitter(spmd)
    use_pallas, pallas_interpret = loop_common.pallas_routing(
        rcfg.pallas_sampler)

    def can_train(replay: sring.SequenceRingState, iteration: Array) -> Array:
        filled = replay.ring.size * B >= min_fill
        # The dynamic any() guard backs up the static ring-size check above:
        # never sample when no seeded window start is currently alive —
        # counting only starts the dedup sampler would actually draw
        # (the oldest stack-1 are masked: replay/device.py
        # contextful_start_mask), so a transiently all-masked plane
        # cannot produce zero-weight garbage batches.
        alive = replay.priorities > 0.0
        if stack:
            alive = jnp.logical_and(
                alive,
                ring.contextful_start_mask(replay.ring, stack)[:, None])
        has_starts = jnp.any(alive)
        return jnp.logical_and(
            jnp.logical_and(jnp.logical_and(filled, has_starts),
                            sring.sequence_ring_can_sample(replay, seq_len)),
            iteration % cfg.train_every == 0)

    def init(rng: Array) -> R2D2Carry:
        base = rng
        if spmd:
            rng = jax.random.fold_in(rng, jax.lax.axis_index(axis_name))
        k_env, k_learn, k_run = jax.random.split(rng, 3)
        if spmd:
            k_learn = jax.random.fold_in(base, 7)
        env_state, obs = env.v_reset(k_env, B)
        obs = jax.tree.map(jnp.copy, obs)
        obs_example = jax.tree.map(lambda x: x[0], obs)
        stored_example = jax.tree.map(lambda x: _slice_newest(x)[0], obs)
        ring_example = loop_common.ring_obs_example(stored_example,
                                                    flat_storage)
        replay = sring.sequence_ring_init(num_slots, B, ring_example,
                                          net.lstm_size,
                                          merge_obs_rows=flat_storage)
        learner = init_learner(k_learn, obs_example)
        zero = jnp.float32(0.0)
        return R2D2Carry(
            env_state=env_state, obs=obs,
            actor_carry=net.initial_state(B), replay=replay, learner=learner,
            rng=k_run[None] if spmd else k_run, iteration=jnp.int32(0),
            ep_return=jnp.zeros((B,), jnp.float32),
            completed_return=zero, completed_count=zero,
            loss_sum=zero, train_count=zero)

    def one_iteration(carry: R2D2Carry, _) -> Tuple[R2D2Carry, None]:
        rng, (k_act, k_sample) = _split_rng(carry.rng, 2)
        eps = epsilon(carry.iteration)
        new_actor_carry, actions = act(carry.learner.params,
                                       carry.actor_carry, carry.obs, k_act,
                                       eps)
        env_state, out = env.v_step(carry.env_state, actions)
        # Store the *pre-step* carry: the state the actor held entering obs.
        replay = sring.sequence_ring_add(
            carry.replay,
            _flatten_batched(jax.tree.map(_slice_newest, carry.obs)),
            actions, out.reward,
            out.terminated, out.truncated, carry.actor_carry, seq_len,
            stride, merge_obs_rows=flat_storage)
        # Zero the carry for envs that just finished an episode so the next
        # act (and the state stored with it) starts the new episode fresh.
        done = jnp.logical_or(out.terminated, out.truncated)
        keep = (~done).astype(jnp.float32)[:, None]
        new_actor_carry = (new_actor_carry[0] * keep,
                           new_actor_carry[1] * keep)
        beta = beta_at(carry.iteration)

        def do_train(operand):
            learner, rep = operand

            def one_update(c, key):
                l, rep = c
                s = sring.sequence_ring_sample(
                    rep, key, batch_size, seq_len,
                    rcfg.priority_exponent, beta, use_pallas=use_pallas,
                    pallas_interpret=pallas_interpret,
                    merge_obs_rows=flat_storage,
                    frame_stack=stack, frame_shape=_frame_shape)
                s = s._replace(obs=_unflatten_seq(s.obs))
                l, metrics = train_step(l, s)
                rep = sring.sequence_ring_update(
                    rep, s.t_idx, s.b_idx, metrics["priorities"],
                    eps=rcfg.priority_eps)
                return (l, rep), metrics["loss"]

            keys = jax.random.split(k_sample, cfg.updates_per_train)
            (learner, rep), losses_u = jax.lax.scan(one_update,
                                                    (learner, rep), keys)
            return (learner, rep, jnp.sum(losses_u),
                    jnp.float32(cfg.updates_per_train))

        def no_train(operand):
            learner, rep = operand
            return learner, rep, jnp.float32(0.0), jnp.float32(0.0)

        learner, replay, loss, trained = jax.lax.cond(
            can_train(replay, carry.iteration), do_train, no_train,
            (carry.learner, replay))

        ep_return, completed_return, completed_count = \
            loop_common.episode_stats_update(carry, out.reward, done)

        return R2D2Carry(
            env_state=env_state, obs=out.obs, actor_carry=new_actor_carry,
            replay=replay, learner=learner, rng=rng,
            iteration=carry.iteration + 1, ep_return=ep_return,
            completed_return=completed_return,
            completed_count=completed_count,
            loss_sum=carry.loss_sum + loss,
            train_count=carry.train_count + trained), None

    def run_chunk(carry: R2D2Carry, num_iters: int):
        zero = jnp.float32(0.0)
        carry = carry._replace(completed_return=zero, completed_count=zero,
                               loss_sum=zero, train_count=zero)
        carry, _ = jax.lax.scan(one_iteration, carry, None, length=num_iters)
        metrics, replace = loop_common.reduce_chunk_metrics(
            carry, axis_name, B, num_shards)
        if spmd:
            # Keep the new-window priority seed replicated (global max).
            replace["replay"] = carry.replay._replace(
                max_priority=jax.lax.pmax(carry.replay.max_priority,
                                          axis_name))
        if replace:
            carry = carry._replace(**replace)
        return carry, metrics

    return init, run_chunk


def make_r2d2_evaluator(cfg: ExperimentConfig, env: JaxEnv, net,
                        num_episodes: int = 10, epsilon: float = 0.001):
    """Greedy eval with the LSTM carry threaded (and zeroed on done)."""
    act = make_recurrent_actor_step(net)

    def evaluate(params: PyTree, rng: Array) -> Array:
        k_reset, k_run = jax.random.split(rng)
        env_state, obs = env.v_reset(k_reset, num_episodes)
        carry0 = net.initial_state(num_episodes)

        def step(c, _):
            env_state, obs, carry, ret, alive, rng = c
            rng, k = jax.random.split(rng)
            carry, a = act(params, carry, obs, k, jnp.float32(epsilon))
            env_state, out = env.v_step(env_state, a)
            ret = ret + out.reward * alive
            done = jnp.logical_or(out.terminated, out.truncated)
            keep = (~done).astype(jnp.float32)[:, None]
            carry = (carry[0] * keep, carry[1] * keep)
            alive = jnp.logical_and(alive > 0, ~done).astype(jnp.float32)
            return (env_state, out.obs, carry, ret, alive, rng), None

        init = (env_state, obs, carry0,
                jnp.zeros((num_episodes,), jnp.float32),
                jnp.ones((num_episodes,), jnp.float32), k_run)
        carry, _ = jax.lax.scan(step, init, None, length=env.max_steps)
        return jnp.mean(carry[3])

    return evaluate
