"""Deterministic chaos harness (ISSUE 8): seeded fault injection with
named seams threaded through the real code paths.

Public surface::

    from dist_dqn_tpu import chaos

    plan = chaos.FaultPlan.generate(seed=7, seams=["transport.recv"])
    with chaos.installed(plan) as inj:
        ...                       # run the system under test
    assert not inj.open_trips()   # every injection recovered

Seam call sites use ``chaos.fire("seam.name")`` (a no-op global read
while nothing is armed) and prove recovery with
``chaos.mark_recovered``. The process-level game-day runner is
``scripts/chaos_run.py``; the failure-mode matrix lives in
docs/fault_tolerance.md.
"""
from dist_dqn_tpu.chaos.injector import (CHAOS_PLAN_ENV,  # noqa: F401
                                         ChaosInjectedError,
                                         ChaosInjector, corrupt_bytes,
                                         fire, get_injector, install,
                                         installed,
                                         maybe_install_from_env,
                                         mark_recovered, sleep_for,
                                         truncate_bytes, uninstall)
from dist_dqn_tpu.chaos.plan import (SEAMS, FaultEvent,  # noqa: F401
                                     FaultPlan)
