"""ChaosInjector: arms a FaultPlan over the named seams.

The injection contract at every seam is two lines::

    ev = chaos.fire("transport.recv")
    if ev is not None: <interpret ev.fault locally>

``fire`` is a no-op (one global read + compare) while nothing is
armed, so the seams cost nothing on production hot paths. When a plan
is armed, every pass through a seam increments that seam's hit
counter; a pending event whose trigger matches (``at_hit`` == count,
or ``at_s`` elapsed) is popped and returned EXACTLY ONCE — the seam
code interprets the fault (drop the frame, raise, sleep, SIGKILL...).

Evidence trail per injection (ISSUE 8 telemetry satellite):
``dqn_chaos_injected_total{seam,fault}``, a flight-recorder event, and
— once the surviving path proves itself via ``mark_recovered(seam)`` —
``dqn_recovery_seconds{seam}`` measuring injection -> recovery. Both
families are documented in docs/observability.md and the failure-mode
matrix in docs/fault_tolerance.md says which recovery mark pins which
fault.

Stdlib-only (plus the telemetry registry, itself jax-free): actor and
feeder processes arm their slice of a plan from ``DQN_CHAOS_PLAN``
(inline JSON or a file path), the same env-inheritance pattern as
DQN_FORENSICS_DIR.
"""
from __future__ import annotations

import contextlib
import json
import os
import threading
import time
from typing import Dict, List, Optional

from dist_dqn_tpu.chaos.plan import FaultEvent, FaultPlan
from dist_dqn_tpu.telemetry import flight as _flight_mod
from dist_dqn_tpu.telemetry.collectors import (CHAOS_INJECTED,
                                               CHAOS_RECOVERY_SECONDS)
from dist_dqn_tpu.telemetry.registry import get_registry

#: Env knob: inline JSON plan or a path to one — how spawned actor/
#: feeder/worker processes arm the plan slice their parent exported.
CHAOS_PLAN_ENV = "DQN_CHAOS_PLAN"


class ChaosInjectedError(RuntimeError):
    """An exception fault raised at a seam. A distinct type so tests
    and supervisors can tell an injected failure from an organic one —
    the whole point is asserting the SURROUNDING machinery (tombstones,
    fences, retries) behaves identically for both."""

    def __init__(self, seam: str, fault: str):
        super().__init__(f"chaos: injected {fault!r} at seam {seam!r}")
        self.seam = seam
        self.fault = fault


class ChaosInjector:
    """One armed plan. Thread-safe: seams fire from transport serve
    threads, pipeline workers and the main loop concurrently."""

    def __init__(self, plan: FaultPlan, registry=None, log_fn=print):
        self.plan = plan
        self.log = log_fn
        self._lock = threading.Lock()
        self._armed_at = time.monotonic()
        self._hits: Dict[str, int] = {}
        self._pending: Dict[str, List[FaultEvent]] = {}
        for ev in plan.events:
            self._pending.setdefault(ev.seam, []).append(ev)
        for seam, evs in self._pending.items():
            # at_hit ascending, wall-clock events last (checked every
            # hit regardless); stable for equal keys.
            evs.sort(key=lambda e: (e.at_hit is None, e.at_hit or 0.0,
                                    e.at_s or 0.0))
        self.injected: List[Dict] = []   # chronological evidence log
        self._open_trips: Dict[str, float] = {}  # seam -> trip time
        reg = registry if registry is not None else get_registry()
        self._reg = reg
        self._c_injected: Dict[tuple, object] = {}
        self._h_recovery: Dict[str, object] = {}

    # -- seam surface --------------------------------------------------------
    def fire(self, seam: str) -> Optional[FaultEvent]:
        now = time.monotonic()
        with self._lock:
            hits = self._hits.get(seam, 0) + 1
            self._hits[seam] = hits
            pending = self._pending.get(seam)
            if not pending:
                return None
            ev = None
            for i, cand in enumerate(pending):
                if cand.at_hit is not None:
                    if cand.at_hit <= hits:
                        ev = pending.pop(i)
                        break
                elif now - self._armed_at >= cand.at_s:
                    ev = pending.pop(i)
                    break
            if ev is None:
                return None
            self.injected.append({"seam": seam, "fault": ev.fault,
                                  "hit": hits,
                                  "t_s": round(now - self._armed_at, 3)})
            # One open trip per seam: recovery measures injection ->
            # first proof of recovery; overlapping injections on one
            # seam keep the OLDEST trip (worst-case recovery).
            self._open_trips.setdefault(seam, now)
        self._count(seam, ev.fault)
        _flight_mod.get_flight().record("chaos", f"{seam}.{ev.fault}",
                                        hit=hits, args=ev.args)
        if self.log is not None:
            self.log(json.dumps({"chaos_injected": {
                "seam": seam, "fault": ev.fault, "hit": hits,
                "args": ev.args}}))
        return ev

    def mark_recovered(self, seam: str) -> Optional[float]:
        """The surviving path proved itself (next valid frame decoded,
        next job drained, next save landed...): close the seam's open
        trip and observe ``dqn_recovery_seconds{seam}``. No-op without
        an open trip, so call sites mark unconditionally."""
        with self._lock:
            t0 = self._open_trips.pop(seam, None)
        if t0 is None:
            return None
        dt = time.monotonic() - t0
        h = self._h_recovery.get(seam)
        if h is None:
            h = self._reg.histogram(
                CHAOS_RECOVERY_SECONDS,
                "fault injection -> recovery proof, per seam",
                labels={"seam": seam})
            self._h_recovery[seam] = h
        h.observe(dt)
        _flight_mod.get_flight().record("chaos", f"{seam}.recovered",
                                        recovery_s=round(dt, 4))
        return dt

    def open_trips(self) -> List[str]:
        """Seams with an injection not yet marked recovered — the
        game-day runner's end-of-scenario invariant is this being
        empty."""
        with self._lock:
            return sorted(self._open_trips)

    def _count(self, seam: str, fault: str) -> None:
        key = (seam, fault)
        c = self._c_injected.get(key)
        if c is None:
            c = self._reg.counter(
                CHAOS_INJECTED, "faults injected by the chaos harness",
                labels={"seam": seam, "fault": fault})
            self._c_injected[key] = c
        c.inc()


# -- fault interpretation helpers (shared by the seams) ----------------------

def corrupt_bytes(payload: bytes, ev: FaultEvent) -> bytes:
    """Flip one bit at a plan-determined offset — the canonical
    bit_flip interpretation, deterministic per event."""
    if not payload:
        return payload
    bit = int(ev.args.get("bit", 0)) % (len(payload) * 8)
    buf = bytearray(payload)
    buf[bit // 8] ^= 1 << (bit % 8)
    return bytes(buf)


def truncate_bytes(payload: bytes, ev: FaultEvent) -> bytes:
    keep = max(1, int(len(payload) * float(ev.args.get("keep_frac", 0.5))))
    return payload[:keep]


def sleep_for(ev: FaultEvent, default_s: float = 0.2) -> float:
    dt = float(ev.args.get("delay_s", default_s))
    time.sleep(dt)
    return dt


# -- process-global arming ---------------------------------------------------

_lock = threading.Lock()
_injector: Optional[ChaosInjector] = None


def install(plan: FaultPlan, registry=None, log_fn=print,
            export_env: bool = False) -> ChaosInjector:
    """Arm ``plan`` process-globally, record it into the run manifest
    (provenance: every chaos run is replayable from its manifest), and
    — with ``export_env`` — hand the plan down to child processes via
    ``DQN_CHAOS_PLAN`` (multiprocessing-spawned actors arm their own)."""
    global _injector
    from dist_dqn_tpu.telemetry import manifest as manifest_mod

    inj = ChaosInjector(plan, registry=registry, log_fn=log_fn)
    with _lock:
        _injector = inj
    manifest_mod.annotate_manifest("chaos_plan", plan.to_dict())
    if export_env:
        os.environ[CHAOS_PLAN_ENV] = plan.to_json()
    return inj


def uninstall() -> None:
    global _injector
    with _lock:
        _injector = None


def get_injector() -> Optional[ChaosInjector]:
    return _injector


@contextlib.contextmanager
def installed(plan: FaultPlan, registry=None, log_fn=None):
    """Scoped arming — the in-process pytest surface:

        with chaos.installed(plan) as inj:
            ... run the system under test ...
        assert inj.injected == [...]
    """
    inj = install(plan, registry=registry, log_fn=log_fn)
    try:
        yield inj
    finally:
        uninstall()


def fire(seam: str) -> Optional[FaultEvent]:
    """The seam entry point: None (fast path, nothing armed) or the
    fault event to interpret."""
    inj = _injector
    if inj is None:
        return None
    return inj.fire(seam)


def mark_recovered(seam: str) -> None:
    inj = _injector
    if inj is not None:
        inj.mark_recovered(seam)


def maybe_install_from_env() -> Optional[ChaosInjector]:
    """Arm from ``DQN_CHAOS_PLAN`` (inline JSON or a file path) if set
    and nothing is armed yet — how spawned actor/feeder processes join
    the parent's game day. Malformed plans fail LOUDLY: a chaos run
    whose faults silently never arm would pass its survival invariants
    vacuously."""
    raw = os.environ.get(CHAOS_PLAN_ENV)
    if not raw:
        return None
    if _injector is not None:
        return _injector
    if not raw.lstrip().startswith("{"):
        with open(raw) as fh:
            raw = fh.read()
    return install(FaultPlan.from_json(raw))
