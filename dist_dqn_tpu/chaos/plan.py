"""FaultPlan: a seeded, replayable schedule of fault injections.

A plan is ``seed`` + an ordered list of :class:`FaultEvent`
``(seam, fault, trigger, args)`` entries. Two trigger kinds:

  * ``at_hit`` — fire on the Nth time execution passes through the
    named seam (1-based). Deterministic regardless of wall time, which
    is what makes an in-process chaos test bit-replayable: the same
    seed produces the same events at the same dataflow positions.
  * ``at_s`` — fire once the seam is hit at/after this many seconds
    from injector arm time. Used by the process-level game-day runner
    for faults whose whole point is wall-clock shape (kill -9 mid-run,
    stall past a watchdog deadline).

``FaultPlan.generate(seed, seams)`` derives a schedule from a seed via
its own ``random.Random(seed)`` stream — same seed, same plan, pinned
by test — and every armed plan is recorded into the run manifest
(telemetry/manifest.py), so any chaos run's forensics bundle and BENCH
provenance say exactly which faults were injected where.

Stdlib only: actor/feeder processes (jax-free by contract) arm plans
from the environment.
"""
from __future__ import annotations

import dataclasses
import json
import random
from typing import Dict, List, Optional, Sequence, Tuple

#: Seam registry: every injection point threaded through the real code
#: paths, with the faults it interprets. A plan naming an unknown seam
#: or fault fails at arm time, not silently at run time.
SEAMS: Dict[str, Tuple[str, ...]] = {
    # actors/transport.py TcpRecordClient.push (the actor-side wire).
    "transport.send": ("drop", "delay", "bit_flip", "truncate",
                       "disconnect"),
    # actors/transport.py TcpRecordServer._serve (the learner-side wire,
    # applied to the raw frame BEFORE integrity verification).
    "transport.recv": ("drop", "delay", "bit_flip", "disconnect"),
    # actors/actor.py step loops (local + remote workers).
    "actor.step": ("wedge", "crash", "slow_start"),
    # replay/staging.py EvacuationWorker drain.
    "evac.drain": ("exception", "stall"),
    # replay/staging.py SamplePrefetcher worker.
    "prefetch.sample": ("exception", "stall"),
    # utils/checkpoint.py TrainCheckpointer.save.
    "checkpoint.save": ("fail", "crash_before_stamp"),
    # utils/checkpoint.py write_latest_pointer (the LATEST stamp).
    "latest.write": ("torn",),
    # host_replay_loop.py _save_checkpoint sidecar write (ISSUE 12):
    # "torn" lands a truncated sidecar at the final path while the
    # orbax step still commits — resume must delete the unusable step
    # and fall back to the previous intact one.
    "sidecar.write": ("torn",),
    # serving/batcher.py MicroBatcher._dispatch.
    "serving.dispatch": ("slow_model", "exception"),
    # serving/model_store.py ModelStore._restore (hot-reload path).
    "serving.reload": ("slow_reload", "fail"),
    # host_replay_loop.py chunk boundary (the deliberate mid-run crash
    # the resume-bit-identical pin kills the run with).
    "host_replay.chunk": ("crash",),
    # host_replay_loop.py per-shard collect dispatch (ISSUE 15): fires
    # once per SHARD dispatch, so an at_hit schedule can crash or stall
    # any one shard of a dp mesh. "stall" recovery = the dispatch pass
    # completes; "crash" recovery = the next process's resume (same
    # proof as host_replay.chunk, anchored at the resume site).
    "host_replay.collect": ("crash", "stall"),
    # actors/service.py run loop (learner-process kill for game days).
    "service.loop": ("crash",),
    # ingest/shm_ring.py ShmSlotRing.push (the zero-copy same-host
    # publish; ISSUE 9). "torn" = die-mid-write semantics: the seq
    # advances but the seqlock stamp stays odd — the consumer must
    # drop + count, never decode.
    "shm.publish": ("torn", "stall", "drop"),
    # ingest/codec.py StepDecoder.decode (the zero-copy record gate,
    # applied to the payload BEFORE validation — a corrupt record must
    # reject whole, mirroring the transport.recv bit_flip invariant).
    "ingest.decode": ("bit_flip", "truncate"),
    # replay/host.py DevicePrioritySampler draw path (ISSUE 18): fires
    # once per SHARD draw dispatch, so an at_hit schedule can fail or
    # stall any one shard's device plane. Recovery is anchored at the
    # next draw that materializes on that path (mark_recovered in
    # materialize_at/sample).
    "replay.device_sample": ("exception", "stall"),
}


@dataclasses.dataclass(frozen=True)
class FaultEvent:
    """One scheduled injection. Exactly one of ``at_hit``/``at_s`` is
    set. ``args`` parameterizes the fault (e.g. ``{"delay_s": 2.0}``,
    ``{"bit": 12345}``)."""

    seam: str
    fault: str
    at_hit: Optional[int] = None
    at_s: Optional[float] = None
    args: Dict = dataclasses.field(default_factory=dict)

    def __post_init__(self):
        if self.seam not in SEAMS:
            raise ValueError(f"unknown chaos seam {self.seam!r} "
                             f"(known: {sorted(SEAMS)})")
        if self.fault not in SEAMS[self.seam]:
            raise ValueError(
                f"seam {self.seam!r} does not interpret fault "
                f"{self.fault!r} (known: {SEAMS[self.seam]})")
        if (self.at_hit is None) == (self.at_s is None):
            raise ValueError("exactly one of at_hit/at_s must be set")
        if self.at_hit is not None and self.at_hit < 1:
            raise ValueError("at_hit is 1-based (first pass == 1)")

    def to_dict(self) -> Dict:
        d = {"seam": self.seam, "fault": self.fault, "args": self.args}
        if self.at_hit is not None:
            d["at_hit"] = self.at_hit
        else:
            d["at_s"] = self.at_s
        return d

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultEvent":
        return cls(seam=d["seam"], fault=d["fault"],
                   at_hit=d.get("at_hit"), at_s=d.get("at_s"),
                   args=dict(d.get("args") or {}))


@dataclasses.dataclass(frozen=True)
class FaultPlan:
    """Seed + ordered fault schedule. Immutable once built; arming one
    (chaos/injector.py ``install``) records it into the run manifest so
    every chaos run is replayable from its provenance line."""

    seed: int
    events: Tuple[FaultEvent, ...] = ()

    def to_dict(self) -> Dict:
        return {"seed": self.seed,
                "events": [e.to_dict() for e in self.events]}

    def to_json(self) -> str:
        return json.dumps(self.to_dict(), sort_keys=True)

    @classmethod
    def from_dict(cls, d: Dict) -> "FaultPlan":
        return cls(seed=int(d["seed"]),
                   events=tuple(FaultEvent.from_dict(e)
                                for e in d["events"]))

    @classmethod
    def from_json(cls, s: str) -> "FaultPlan":
        return cls.from_dict(json.loads(s))

    def for_seams(self, seams: Sequence[str]) -> "FaultPlan":
        """The sub-plan touching only ``seams`` — how a multi-process
        run hands each process the slice it can interpret."""
        keep = set(seams)
        return FaultPlan(self.seed, tuple(e for e in self.events
                                          if e.seam in keep))

    @classmethod
    def generate(cls, seed: int, seams: Sequence[str],
                 events_per_seam: int = 1,
                 max_hit: int = 40, horizon_s: float = 30.0) -> "FaultPlan":
        """Derive a deterministic schedule: ``events_per_seam`` events
        per listed seam, each picking a fault uniformly from the seam's
        registry and a trigger position from the seed's own stream.
        Hit-triggered seams draw ``at_hit`` in [2, max_hit] (never the
        very first pass — startup paths deserve one clean pass);
        wall-clock faults (process kills, stalls) are the game-day
        runner's to place explicitly, so generate() stays hit-based.
        Same (seed, seams, knobs) -> same plan, pinned by test."""
        rng = random.Random(seed)
        events: List[FaultEvent] = []
        for seam in seams:
            faults = SEAMS[seam]
            for _ in range(events_per_seam):
                fault = faults[rng.randrange(len(faults))]
                at_hit = rng.randint(2, max(max_hit, 2))
                args: Dict = {}
                if fault in ("delay", "wedge", "stall", "slow_model",
                             "slow_reload", "slow_start"):
                    args["delay_s"] = round(
                        rng.uniform(0.05, max(horizon_s / 10.0, 0.05)), 3)
                if fault == "bit_flip":
                    args["bit"] = rng.randrange(1 << 16)
                if fault == "truncate":
                    args["keep_frac"] = round(rng.uniform(0.1, 0.9), 3)
                events.append(FaultEvent(seam=seam, fault=fault,
                                         at_hit=at_hit, args=args))
        # Stable order: by seam name then hit position — the schedule
        # reads chronologically per seam and never depends on dict order.
        events.sort(key=lambda e: (e.seam, e.at_hit or 0, e.fault))
        return cls(seed=seed, events=tuple(events))
