"""Training entrypoint: ``python -m dist_dqn_tpu.train --config cartpole``.

The repo's own training entrypoint in the sense of BASELINE.json:5 — picks a
driver config (BASELINE.json:7-11), builds the env/network/learner, and runs
the fused on-device loop (JAX-native envs) with periodic greedy evaluation
and throughput logging of the north-star metrics (env-steps/sec/chip,
learner grad-steps/sec — BASELINE.json:2).
"""
from __future__ import annotations

import argparse
import json
import time

import jax
import numpy as np

from dist_dqn_tpu.config import CONFIGS, ExperimentConfig, apply_overrides
from dist_dqn_tpu.envs import make_jax_env
from dist_dqn_tpu.models import build_network
from dist_dqn_tpu.train_loop import make_evaluator, make_fused_train


def _pick_mesh_devices(num_devices: int, multiprocess: bool):
    """Device list for the dp mesh. Multi-process meshes must span the
    GLOBAL device list — a prefix slice would leave other processes without
    addressable shards; single-process requests larger than the machine
    fail loudly instead of silently truncating."""
    devs = jax.devices()
    if multiprocess:
        if num_devices not in (0, 1, len(devs)):
            raise ValueError(
                f"multi-process runs use all {len(devs)} global devices; "
                f"--mesh-devices {num_devices} is not meaningful (pass 0)")
        return devs
    if num_devices in (0, None):
        return devs
    if len(devs) < num_devices:
        raise ValueError(f"--mesh-devices {num_devices} requested but only "
                         f"{len(devs)} available")
    return devs[:num_devices]


def train(cfg: ExperimentConfig, total_env_steps: int = 0, seed: int = None,
          chunk_iters: int = 2000, log_fn=print,
          checkpoint_dir: str = None, save_every_frames: int = 0,
          profile_dir: str = None, num_devices: int = 1, stop_fn=None,
          checkpoint_replay: bool = False, telemetry_port: int = None,
          telemetry_host: str = "127.0.0.1"):
    """Run training; returns (final_carry, history list of metric dicts).

    With ``checkpoint_replay`` the checkpoint holds the WHOLE fused
    carry — replay ring, env states, rng, episode trackers — so a
    resumed run continues BIT-EQUAL to an uninterrupted one (no replay
    refill, no distribution shift). Cost: the ring dominates the
    checkpoint (a 65k-slot pixel ring is ~1.8 GB vs ~7 MB of learner
    state), so saves are proportionally slower — the default
    learner-only mode instead refills replay from live experience in
    ``min_fill / steady-rate`` seconds (sub-second at fused-loop rates;
    see utils/checkpoint.py for the trade-off numbers).

    With ``checkpoint_dir`` set, the learner state is checkpointed every
    ``save_every_frames`` env frames (default: every eval period) and the
    newest checkpoint is restored on startup — actors/replay are stateless
    and refill, per the failure model in SURVEY.md §5. With ``profile_dir``
    set, the second chunk (first post-compile) is captured as a
    ``jax.profiler`` trace for TensorBoard/xprof (SURVEY.md §5).

    ``num_devices != 1`` selects the mesh trainers (parallel/learner.py):
    env lanes + the replay shard spread over a ``dp`` mesh of that many
    devices (0 = every device) and gradients pmean over the mesh. Under a
    ``jax.distributed`` runtime (parallel/distributed.py) the device list —
    and therefore the mesh — is global, so the same call scales over
    multiple hosts: each process runs this function, process 0 logs, and
    checkpoint/eval work from the replicated learner copy.
    """
    # Population training plane (ISSUE 20): M > 1 routes to the
    # vmap-stacked trainer; M == 1 with a spec applies member 0's
    # overrides STATICALLY and falls through to the plain program —
    # so `--population 1` is bit-identical to today's run by
    # construction (no traced-hyperparameter lanes, no vmap).
    if cfg.population.size > 1:
        return _train_population(
            cfg, total_env_steps=total_env_steps, seed=seed,
            chunk_iters=chunk_iters, log_fn=log_fn,
            checkpoint_dir=checkpoint_dir,
            save_every_frames=save_every_frames,
            profile_dir=profile_dir, num_devices=num_devices,
            stop_fn=stop_fn, checkpoint_replay=checkpoint_replay,
            telemetry_port=telemetry_port,
            telemetry_host=telemetry_host)
    if cfg.population.spec_json:
        from dist_dqn_tpu import population as _pop
        cfg = _pop.member_config(cfg, _pop.resolve_spec(cfg), 0)
    multiprocess = jax.process_count() > 1
    if multiprocess:
        from dist_dqn_tpu.parallel.distributed import main_process_log
        log_fn = main_process_log(log_fn)
    # Telemetry (ISSUE 1): registry instruments for the fused loop, plus
    # the optional /metrics scrape endpoint (--telemetry-port; 0 binds an
    # ephemeral port, reported as a telemetry_port log line). Instruments
    # exist from the first scrape even before the first chunk lands.
    from dist_dqn_tpu import telemetry
    from dist_dqn_tpu.telemetry import collectors as tmc
    from dist_dqn_tpu.telemetry import watchdog as tm_watchdog
    # Crash forensics (ISSUE 4; null-safe no-ops until --forensics-dir /
    # --no-flight-recorder arm or disarm them): a per-chunk stage
    # heartbeat, a per-chunk flight event, and the divergence sentinel
    # on every chunk's loss. Registered WITH startup grace: the first
    # chunk carries the jit compile, whose legitimate wall must not read
    # as a stall — but a compile that outlives grace + deadline is the
    # classic wedged-tunnel hang and trips with its stack on record.
    _flight = telemetry.get_flight()
    _hb_chunk = tm_watchdog.heartbeat(
        "fused.chunk", startup_grace_s=tm_watchdog.STARTUP_GRACE_S)
    _reg = telemetry.get_registry()
    _tm = {
        "env_steps": _reg.counter(tmc.ENV_STEPS, "env frames processed"),
        "env_rate": _reg.gauge(tmc.ENV_RATE, "env-steps/sec (last chunk)"),
        "grad_steps": _reg.counter(tmc.GRAD_STEPS,
                                   "learner grad steps taken"),
        "grad_latency": _reg.histogram(
            tmc.GRAD_LATENCY,
            "per-grad-step share of the fused chunk wall"),
        "staleness": _reg.histogram(
            tmc.PARAM_STALENESS,
            "age of the host-visible params at each chunk boundary "
            "(the fused loop refreshes them once per chunk)"),
        "chunk": _reg.histogram("dqn_chunk_seconds",
                                "fused chunk wall time"),
        "loss": _reg.gauge("dqn_loss", "chunk-mean TD loss"),
        "episodes": _reg.counter("dqn_episodes_completed_total",
                                 "training episodes finished"),
        "ep_return": _reg.gauge("dqn_episode_return",
                                "chunk-mean finished-episode return"),
        "grad_rate": _reg.gauge(tmc.LEARNER_GRAD_RATE,
                                "grad steps per second (last chunk)",
                                {"loop": "fused"}),
    }
    # Experience-lineage accounting (ISSUE 16): host-side chunk stamp
    # table — the fused loop's collect-granular twin of the record
    # stamps the wire-fed runtimes carry.
    _lineage = tmc.FusedLineageTable()
    # Learner-utilization config surface (ISSUE 6): the replay ratio /
    # bucketed batch width / actor dtype this run's rates were shaped by.
    from dist_dqn_tpu import loop_common as _lc
    _fl = {"loop": "fused"}
    _reg.gauge(tmc.LEARNER_REPLAY_RATIO,
               "grad sub-steps per train event",
               _fl).set(_lc.resolve_replay_ratio(cfg))
    _reg.gauge(tmc.LEARNER_TRAIN_BATCH,
               "effective (bucketed) train batch width",
               _fl).set(_lc.resolve_train_batch(cfg))
    _reg.gauge(tmc.LEARNER_ACTOR_DTYPE_INFO,
               "1 for the active actor inference dtype",
               {**_fl, "dtype": cfg.network.actor_dtype
                or "float32"}).set(1)
    telemetry_server = None
    if telemetry_port is not None and (not multiprocess
                                       or jax.process_index() == 0):
        telemetry_server = telemetry.start_server(telemetry_port,
                                                  host=telemetry_host)
        log_fn(json.dumps({"telemetry_port": telemetry_server.port}))
        # Fleet registry (ISSUE 16): after bind, so the descriptor
        # carries the resolved port; no-op without DQN_FLEET_DIR.
        from dist_dqn_tpu.telemetry import fleet as _fleet
        _fleet.register_endpoint("learner", telemetry_server.port,
                                 host=telemetry_host,
                                 labels={"loop": "fused"})
    seed = cfg.seed if seed is None else seed
    total = total_env_steps or cfg.total_env_steps
    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)

    use_mesh = num_devices != 1 or multiprocess
    mesh = None
    if use_mesh:
        from dist_dqn_tpu.parallel import (make_mesh, make_mesh_fused_train,
                                           make_mesh_r2d2_train)
        mesh = make_mesh(devices=_pick_mesh_devices(num_devices,
                                                    multiprocess))
    if cfg.network.lstm_size:
        from dist_dqn_tpu.r2d2_loop import make_r2d2_evaluator, \
            make_r2d2_train
        if use_mesh:
            init, run = make_mesh_r2d2_train(cfg, env, net, mesh)
        else:
            init, run_chunk = make_r2d2_train(cfg, env, net)
        evaluate = jax.jit(make_r2d2_evaluator(
            cfg, env, net, num_episodes=cfg.eval_episodes))
    else:
        if use_mesh:
            init, run = make_mesh_fused_train(cfg, env, net, mesh)
        else:
            init, run_chunk = make_fused_train(cfg, env, net)
        evaluate = jax.jit(make_evaluator(cfg, env, net,
                                          num_episodes=cfg.eval_episodes))
    if not use_mesh:
        run = jax.jit(run_chunk, static_argnums=1, donate_argnums=0)
    # Chip-time attribution (ISSUE 19): the fused chunk is ONE program —
    # acting, replay and the grad scan fused into a single dispatch — so
    # it registers with role="train" and execs_per_dispatch=1 (the XLA
    # cost census already spans the whole chunk body, scan-once caveat
    # noted in telemetry/devtime.py). Cost is harvested at the first
    # dispatch below via run.lower(...) — trace-only, no second compile.
    _prog_chunk = telemetry.register_program(
        "fused.chunk", loop="fused", role="train")
    _ledger = telemetry.UtilizationLedger("fused", _reg)

    # Eval-path choice, decided once: multi-process runs eval only on the
    # logging process, from the host copy of the replicated params (the
    # eval program is process-local).
    if not multiprocess:
        run_eval = lambda params, k: float(evaluate(params, k))  # noqa: E731
    elif jax.process_index() == 0:
        from dist_dqn_tpu.parallel.distributed import host_replica
        run_eval = lambda params, k: float(  # noqa: E731
            evaluate(host_replica(params), k))
    else:
        run_eval = None

    rng = jax.random.PRNGKey(seed)
    rng, k_init = jax.random.split(rng)
    # Multi-process: jit inputs must not be process-local committed arrays;
    # plain numpy keys are treated as replicated (identical on every
    # process by construction — same seed).
    carry = init(np.asarray(k_init))

    ckpt = None
    frame_offset = 0      # added to the carry's cumulative frame metric
    resumed_frames = 0    # where the loop's cursor actually starts
    if checkpoint_dir:
        from dist_dqn_tpu.utils.checkpoint import (TrainCheckpointer,
                                                   record_checkpoint_kind)
        # The cadence chain must never bottom out at 0 (an explicit
        # --eval-every-steps 0 zeroes the eval period): save_every=0
        # would make maybe_save fire on EVERY chunk.
        ckpt = TrainCheckpointer(
            checkpoint_dir,
            save_every_frames=save_every_frames or cfg.eval_every_steps
            or 100_000)
        # Raises with the actual cause if the directory was written with
        # the OTHER --checkpoint-replay setting (the restore would
        # otherwise fail as a misleading structure-mismatch error).
        record_checkpoint_kind(checkpoint_dir,
                               "carry" if checkpoint_replay else "learner")
        restored = ckpt.restore_latest(
            carry if checkpoint_replay else carry.learner)
        if restored is not None:
            # Resume continues toward the SAME total: the frame cursor picks
            # up at the checkpoint step so relaunching the identical command
            # finishes the remaining frames (and later saves land at
            # monotonically increasing orbax steps).
            frame_offset, tree = restored
            resumed_frames = frame_offset
            # Mesh path: the restore is templated on the live learner's
            # shardings (utils/checkpoint.py), so global replicated arrays
            # come back as such. Multi-process runs call save/restore on
            # every process (orbax collective IO) against a SHARED
            # checkpoint directory.
            log_fn(json.dumps({"resumed_at_frames": frame_offset,
                               "with_replay": checkpoint_replay}))
            if checkpoint_replay:
                # The carry's own iteration counter came back with it, so
                # the cumulative env_frames metric already continues from
                # the checkpoint — a host-side offset would double-count.
                carry = tree
                frame_offset = 0
            else:
                carry = carry._replace(learner=tree)

    # Emergency checkpoint on watchdog abort (ISSUE 8): the abort path
    # saves the NEWEST chunk-boundary state before SIGTERM, so a wedged
    # run loses at most one chunk instead of a whole save period. The
    # holder is refreshed each chunk; device arrays are immutable, so
    # the side-thread save reads a consistent snapshot. Saved to a SIDE
    # location with a one-shot checkpointer — the shared manager may be
    # the very thing the main thread is wedged inside (slow storage),
    # and a concurrent save on it would tear the in-flight commit.
    _emerg = {"frames": resumed_frames, "carry": carry}
    if ckpt is not None:
        from dist_dqn_tpu.utils.checkpoint import save_pytree as _save_pt

        def _emergency_save():
            import os

            tree = (_emerg["carry"] if checkpoint_replay
                    else _emerg["carry"].learner)
            _save_pt(os.path.join(checkpoint_dir, "emergency_learner"),
                     {"learner": tree})

        tm_watchdog.register_emergency_hook("fused.checkpoint",
                                            _emergency_save)

    B = cfg.actor.num_envs
    history = []
    frames = resumed_frames
    # 0 disables eval entirely (same convention as the apex runtime's
    # eval_every_steps); otherwise the first chunk gets a baseline eval.
    next_eval = frames if cfg.eval_every_steps else float("inf")
    chunk_index = 0
    _t_prev_fence = None  # previous chunk's fence, for the ledger wall
    # Trace the second chunk (the first is compile+warmup noise) — unless
    # the whole run fits in one chunk, then trace that one rather than none.
    profile_chunk = 1 if total > frames + chunk_iters * B else 0
    try:
        while frames < total:
            profiling = (profile_dir is not None
                         and chunk_index == profile_chunk)
            if profiling:
                jax.profiler.start_trace(profile_dir)
            if not _prog_chunk.cost_attached:
                # Trace-only lowering against the live args; shares no
                # state with the jit cache, so the dispatch below still
                # hits the already-compiled executable.
                _c, _ci = carry, chunk_iters
                _prog_chunk.attach_cost(lambda: run.lower(_c, _ci))
            t0 = time.perf_counter()
            carry, metrics = run(carry, chunk_iters)
            metrics = jax.tree.map(np.asarray, jax.device_get(metrics))
            dt = time.perf_counter() - t0
            _prog_chunk.count_dispatch()
            # The device_get above IS the chunk fence: dt bounds the
            # program's device time (one fused program fills the chunk).
            _prog_chunk.add_device_seconds(dt)
            if profiling:
                jax.profiler.stop_trace()
                log_fn(json.dumps({"profile_trace": profile_dir}))
            chunk_index += 1
            prev_frames = frames
            frames = frame_offset + int(metrics["env_frames"])
            grad_steps_chunk = float(metrics["grad_steps_in_chunk"])
            frames_delta = max(frames - prev_frames, 0)
            _tm["env_steps"].inc(frames_delta)
            # Global frames over wall time — under a mesh the chunk covers
            # num_shards * chunk_iters * B frames, so chunk_iters * B / dt
            # (the per-process log row) would under-report by the shard count.
            _tm["env_rate"].set(frames_delta / dt)
            _tm["grad_steps"].inc(grad_steps_chunk)
            _tm["chunk"].observe(dt)
            # Host-visible params refresh once per chunk boundary, so the
            # chunk wall bounds their staleness; grad-step latency is the
            # per-step share of the fused chunk (the steps run inside one
            # XLA program — there is no finer host-observable boundary).
            _tm["staleness"].observe(dt)
            if grad_steps_chunk:
                _tm["grad_latency"].observe(dt / grad_steps_chunk)
            _tm["grad_rate"].set(grad_steps_chunk / dt)
            _hb_chunk.beat()
            _loss = float(metrics["loss"])
            _flight.record("chunk", "fused.chunk", frames=frames,
                           loss=_loss, wall_s=round(dt, 4))
            tm_watchdog.observe_divergence(loss=_loss, step=frames)
            _tm["loss"].set(_loss)
            _tm["episodes"].inc(max(float(metrics["episodes"]), 0.0))
            if float(metrics["episodes"]):
                _tm["ep_return"].set(float(metrics["episode_return"]))
            _, ring_slots = tmc.observe_device_ring(carry.replay)
            # Experience lineage (ISSUE 16): the fused loop stamps at
            # collect — one (birth, version) row per chunk, aged over
            # the live ring window into the same families the apex and
            # host-replay runtimes observe per sampled record.
            _lineage.on_chunk(_tm["grad_steps"].value,
                              max(1, ring_slots // chunk_iters))
            # Utilization ledger (ISSUE 19): the fused loop's wall is
            # the dispatch-to-fence dt (device busy, one program) plus
            # whatever host bookkeeping separated it from the previous
            # fence — no sample/evac/prefetch seams here, so the host
            # share lands in the derived `other` bucket.
            _t_now = time.perf_counter()
            _ledger.observe_chunk(
                _t_now - (_t_prev_fence if _t_prev_fence is not None
                          else t0), dt)
            _t_prev_fence = _t_now
            telemetry.set_learner_mfu("fused", reg=_reg)
            telemetry.sweep_device_memory(_reg)
            row = {
                "env_frames": frames,
                "episode_return": float(metrics["episode_return"]),
                # Disambiguates episode_return's no-episodes sentinel (0.0
                # with episodes == 0) from a genuine 0.0 average return.
                "episodes": float(metrics["episodes"]),
                "loss": float(metrics["loss"]),
                "env_steps_per_sec": chunk_iters * B / dt,
                "grad_steps_in_chunk": float(metrics["grad_steps_in_chunk"]),
                "grad_steps_per_sec":
                    float(metrics["grad_steps_in_chunk"]) / dt,
            }
            if frames >= next_eval:
                # Every process consumes k_eval so rng streams stay in
                # lockstep even where run_eval is None (non-logging processes).
                rng, k_eval = jax.random.split(rng)
                if run_eval is not None:
                    row["eval_return"] = run_eval(carry.learner.params, k_eval)
                next_eval = frames + cfg.eval_every_steps
            history.append(row)
            log_fn(json.dumps({k: round(v, 3) if isinstance(v, float) else v
                               for k, v in row.items()}))
            _emerg["frames"], _emerg["carry"] = frames, carry
            if ckpt is not None:
                ckpt.maybe_save(frames,
                                carry if checkpoint_replay else carry.learner)
            # Early stop (single-process only: a data-dependent exit would
            # desync multi-process lockstep): stop_fn sees each metric row —
            # solve-detection for tests, target-return stops for users.
            if stop_fn is not None and jax.process_count() == 1 \
                    and stop_fn(row):
                break
    finally:
        # Deregistered even when the loop raises: a leaked
        # heartbeat would read as a permanent stall in a
        # process that caught the exception and lived on.
        _hb_chunk.close()
        tm_watchdog.unregister_emergency_hook("fused.checkpoint")
    if ckpt is not None:
        ckpt.save(frames, carry if checkpoint_replay else carry.learner)
        ckpt.close()
    if telemetry_server is not None:
        telemetry_server.close()
    return carry, history


def _train_population(cfg: ExperimentConfig, total_env_steps: int = 0,
                      seed: int = None, chunk_iters: int = 2000,
                      log_fn=print, checkpoint_dir: str = None,
                      save_every_frames: int = 0, profile_dir: str = None,
                      num_devices: int = 1, stop_fn=None,
                      checkpoint_replay: bool = False,
                      telemetry_port: int = None,
                      telemetry_host: str = "127.0.0.1"):
    """The population twin of :func:`train` (ISSUE 20): M vmap-stacked
    policies advance as ONE jitted program, one dispatch per chunk.

    Every carry leaf — params, optimizer state, target params, replay
    ring, env vector, rng — carries a leading member axis; per-member
    hyperparameters (``population.spec_json``) ride as traced [M]
    lanes. Member independence is pinned (tests/test_population.py):
    member k's lane bit-matches an M=1 stacked run configured with
    member k's spec entry and seeded with member k's spawn-key stream
    (``population.member_seeds``), so the population is M independent
    experiments sharing a chip, not a coupled batch.

    Frame accounting: the ``frames`` cursor (and ``total_env_steps``)
    is PER MEMBER — each member trains the same budget a solo run
    would — while telemetry counters and the ``env_steps_per_sec`` /
    ``grad_steps_per_sec`` log columns report the AGGREGATE
    member-steps the chip actually sustained (the north-star the
    population exists to raise). Checkpoints hold the [M]-stacked tree
    (learner-only by default, the whole stacked carry under
    ``checkpoint_replay``) plus a ``POPULATION`` width marker; resume
    at a different ``--population`` is refused with the actual cause,
    and ``restore_params(member=k)`` extracts one member for
    evaluate.py / the serving ModelStore.
    """
    from dist_dqn_tpu import population as pop
    from dist_dqn_tpu import telemetry
    from dist_dqn_tpu.telemetry import collectors as tmc
    from dist_dqn_tpu.telemetry import watchdog as tm_watchdog

    M = cfg.population.size
    if num_devices != 1 or jax.process_count() > 1:
        raise ValueError(
            "--population composes with the single-device fused runtime "
            "only for now: the population fills ONE chip by vmap-stacking "
            "members; run one population process per device instead of "
            "--mesh-devices")
    if cfg.network.lstm_size:
        raise ValueError(
            "--population is not supported by the recurrent (R2D2) fused "
            "loop yet (its sequence learner has no member axis)")
    spec = pop.resolve_spec(cfg)
    hp = pop.member_hp(cfg, spec)
    seed = cfg.seed if seed is None else seed
    total = total_env_steps or cfg.total_env_steps
    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)

    _flight = telemetry.get_flight()
    _hb_chunk = tm_watchdog.heartbeat(
        "population.chunk", startup_grace_s=tm_watchdog.STARTUP_GRACE_S)
    _reg = telemetry.get_registry()
    _fl = {"loop": "fused"}
    _reg.gauge(tmc.POPULATION_SIZE,
               "vmap-stacked members in this run", _fl).set(M)
    _member_loss = [
        _reg.gauge(tmc.POPULATION_LOSS, "chunk-mean TD loss per member",
                   {**_fl, "member": str(k)}) for k in range(M)]
    _member_eval = [
        _reg.gauge(tmc.POPULATION_EVAL_RETURN,
                   "greedy eval return per member",
                   {**_fl, "member": str(k)}) for k in range(M)]
    # The shared fused-loop families count AGGREGATE member-steps: the
    # chip runs M policies, so its env/grad throughput is M-fold.
    _tm = {
        "env_steps": _reg.counter(tmc.ENV_STEPS, "env frames processed"),
        "env_rate": _reg.gauge(tmc.ENV_RATE, "env-steps/sec (last chunk)"),
        "grad_steps": _reg.counter(tmc.GRAD_STEPS,
                                   "learner grad steps taken"),
        "chunk": _reg.histogram("dqn_chunk_seconds",
                                "fused chunk wall time"),
        "loss": _reg.gauge("dqn_loss", "chunk-mean TD loss"),
        "episodes": _reg.counter("dqn_episodes_completed_total",
                                 "training episodes finished"),
        "ep_return": _reg.gauge("dqn_episode_return",
                                "chunk-mean finished-episode return"),
        "grad_rate": _reg.gauge(tmc.LEARNER_GRAD_RATE,
                                "grad steps per second (last chunk)",
                                _fl),
    }
    from dist_dqn_tpu import loop_common as _lc
    _reg.gauge(tmc.LEARNER_REPLAY_RATIO,
               "grad sub-steps per train event",
               _fl).set(_lc.resolve_replay_ratio(cfg))
    _reg.gauge(tmc.LEARNER_TRAIN_BATCH,
               "effective (bucketed) train batch width",
               _fl).set(_lc.resolve_train_batch(cfg))
    telemetry_server = None
    if telemetry_port is not None:
        telemetry_server = telemetry.start_server(telemetry_port,
                                                  host=telemetry_host)
        log_fn(json.dumps({"telemetry_port": telemetry_server.port}))
        from dist_dqn_tpu.telemetry import fleet as _fleet
        _fleet.register_endpoint("learner", telemetry_server.port,
                                 host=telemetry_host,
                                 labels={"loop": "fused"})

    # Per-member host rng streams: member k's stream is EXACTLY the one
    # a solo run seeded with member_seeds(seed, M)[k] would consume —
    # init key and eval keys split in the same order (the PR 5
    # spawn-key discipline; the member-independence pin depends on it).
    seeds = pop.member_seeds(seed, M)
    host_rngs = [jax.random.PRNGKey(s) for s in seeds]
    k_inits = []
    for k in range(M):
        host_rngs[k], k_init = jax.random.split(host_rngs[k])
        k_inits.append(np.asarray(k_init))
    init_p, run_population_chunk = pop.make_population_train(cfg, env, net)
    carries = init_p(np.stack(k_inits), hp)
    run = jax.jit(run_population_chunk, static_argnums=2, donate_argnums=0)
    evaluate = jax.jit(jax.vmap(make_evaluator(
        cfg, env, net, num_episodes=cfg.eval_episodes)))
    # Chip-time attribution (ISSUE 19): the population chunk is still
    # ONE program — M members' acting, replay and grad scans fused into
    # a single dispatch — so dqn_learner_mfu prices the whole
    # population's FLOPs against the same chunk wall.
    _prog_chunk = telemetry.register_program(
        "population.chunk", loop="fused", role="train")
    _ledger = telemetry.UtilizationLedger("fused", _reg)

    ckpt = None
    frame_offset = 0
    resumed_frames = 0
    if checkpoint_dir:
        from dist_dqn_tpu.utils.checkpoint import (TrainCheckpointer,
                                                   record_checkpoint_kind,
                                                   record_population_size)
        ckpt = TrainCheckpointer(
            checkpoint_dir,
            save_every_frames=save_every_frames or cfg.eval_every_steps
            or 100_000)
        record_checkpoint_kind(checkpoint_dir,
                               "carry" if checkpoint_replay else "learner")
        try:
            record_population_size(checkpoint_dir, M)
        except ValueError:
            # The stacked tree's member axis is structural: resuming a
            # population-M' directory at M would fail as an opaque
            # shape mismatch — refuse with the cause, counted under the
            # same family as the host-replay sidecar pins.
            _reg.counter(tmc.CHECKPOINT_REFUSED,
                         "resume attempts refused at the sidecar pins",
                         {**_fl, "reason": "population"}).inc()
            raise
        restored = ckpt.restore_latest(
            carries if checkpoint_replay else carries.learner)
        if restored is not None:
            frame_offset, tree = restored
            resumed_frames = frame_offset
            log_fn(json.dumps({"resumed_at_frames": frame_offset,
                               "with_replay": checkpoint_replay,
                               "population": M}))
            if checkpoint_replay:
                carries = tree
                frame_offset = 0
            else:
                carries = carries._replace(learner=tree)

    _emerg = {"frames": resumed_frames, "carry": carries}
    if ckpt is not None:
        from dist_dqn_tpu.utils.checkpoint import save_pytree as _save_pt

        def _emergency_save():
            import os

            tree = (_emerg["carry"] if checkpoint_replay
                    else _emerg["carry"].learner)
            _save_pt(os.path.join(checkpoint_dir, "emergency_learner"),
                     {"learner": tree})

        tm_watchdog.register_emergency_hook("population.checkpoint",
                                            _emergency_save)

    B = cfg.actor.num_envs
    history = []
    frames = resumed_frames   # PER-MEMBER cursor (see docstring)
    next_eval = frames if cfg.eval_every_steps else float("inf")
    chunk_index = 0
    _t_prev_fence = None
    profile_chunk = 1 if total > frames + chunk_iters * B else 0
    try:
        while frames < total:
            profiling = (profile_dir is not None
                         and chunk_index == profile_chunk)
            if profiling:
                jax.profiler.start_trace(profile_dir)
            if not _prog_chunk.cost_attached:
                _c, _hp, _ci = carries, hp, chunk_iters
                _prog_chunk.attach_cost(lambda: run.lower(_c, _hp, _ci))
            t0 = time.perf_counter()
            carries, metrics = run(carries, hp, chunk_iters)
            # Every metric leaf is [M]; fetch once, fence the chunk.
            metrics = jax.tree.map(np.asarray, jax.device_get(metrics))
            dt = time.perf_counter() - t0
            _prog_chunk.count_dispatch()
            _prog_chunk.add_device_seconds(dt)
            if profiling:
                jax.profiler.stop_trace()
                log_fn(json.dumps({"profile_trace": profile_dir}))
            chunk_index += 1
            prev_frames = frames
            # Members advance in lockstep (same lane count, same chunk),
            # so member 0's cumulative frame metric IS the cursor.
            frames = frame_offset + int(metrics["env_frames"][0])
            frames_delta = max(frames - prev_frames, 0)
            grad_member = float(np.mean(metrics["grad_steps_in_chunk"]))
            grad_total = float(np.sum(metrics["grad_steps_in_chunk"]))
            _tm["env_steps"].inc(frames_delta * M)
            _tm["env_rate"].set(frames_delta * M / dt)
            _tm["grad_steps"].inc(grad_total)
            _tm["chunk"].observe(dt)
            _tm["grad_rate"].set(grad_total / dt)
            _hb_chunk.beat()
            losses = [float(v) for v in metrics["loss"]]
            _loss = float(np.mean(losses))
            for k in range(M):
                _member_loss[k].set(losses[k])
            _flight.record("chunk", "population.chunk", frames=frames,
                           loss=_loss, wall_s=round(dt, 4))
            # The sentinel watches the population MEAN: one diverged
            # member shifts it enough to trip, and the forensics
            # bundle's registry snapshot carries the per-member gauges
            # to say which.
            tm_watchdog.observe_divergence(loss=_loss, step=frames)
            _tm["loss"].set(_loss)
            episodes = float(np.sum(metrics["episodes"]))
            _tm["episodes"].inc(max(episodes, 0.0))
            ep_members = metrics["episodes"] > 0
            if np.any(ep_members):
                _tm["ep_return"].set(float(np.mean(
                    metrics["episode_return"][ep_members])))
            _t_now = time.perf_counter()
            _ledger.observe_chunk(
                _t_now - (_t_prev_fence if _t_prev_fence is not None
                          else t0), dt)
            _t_prev_fence = _t_now
            telemetry.set_learner_mfu("fused", reg=_reg)
            telemetry.sweep_device_memory(_reg)
            row = {
                "env_frames": frames,
                "population": M,
                "episode_return": (float(np.mean(
                    metrics["episode_return"][ep_members]))
                    if np.any(ep_members) else 0.0),
                "episodes": episodes,
                "loss": _loss,
                "loss_members": losses,
                # Aggregate member-steps/sec — the chip's actual
                # throughput and the bench acceptance column.
                "env_steps_per_sec": M * chunk_iters * B / dt,
                "grad_steps_in_chunk": grad_member,
                "grad_steps_per_sec": grad_total / dt,
                "grad_steps_per_sec_member": grad_member / dt,
            }
            if frames >= next_eval:
                keys = []
                for k in range(M):
                    host_rngs[k], k_eval = jax.random.split(host_rngs[k])
                    keys.append(np.asarray(k_eval))
                rets = np.asarray(jax.device_get(evaluate(
                    carries.learner.params, np.stack(keys))))
                row["eval_return_members"] = [float(r) for r in rets]
                row["eval_return"] = float(np.mean(rets))
                for k in range(M):
                    _member_eval[k].set(float(rets[k]))
                next_eval = frames + cfg.eval_every_steps
            history.append(row)

            def _round(v):
                if isinstance(v, float):
                    return round(v, 3)
                if isinstance(v, list):
                    return [round(x, 3) if isinstance(x, float) else x
                            for x in v]
                return v

            log_fn(json.dumps({k: _round(v) for k, v in row.items()}))
            _emerg["frames"], _emerg["carry"] = frames, carries
            if ckpt is not None:
                ckpt.maybe_save(frames, carries if checkpoint_replay
                                else carries.learner)
            if stop_fn is not None and stop_fn(row):
                break
    finally:
        _hb_chunk.close()
        tm_watchdog.unregister_emergency_hook("population.checkpoint")
    if ckpt is not None:
        ckpt.save(frames, carries if checkpoint_replay
                  else carries.learner)
        ckpt.close()
    if telemetry_server is not None:
        telemetry_server.close()
    return carries, history


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", choices=sorted(CONFIGS), required=True)
    parser.add_argument("--set", dest="overrides", action="append",
                        metavar="PATH=VALUE", default=[],
                        help="override any config field by dotted path, "
                             "repeatable (e.g. --set network.dueling=true "
                             "--set learner.batch_size=64); values are "
                             "coerced to the field's type")
    parser.add_argument("--total-env-steps", type=int, default=0)
    parser.add_argument("--seed", type=int, default=None)
    parser.add_argument("--chunk-iters", type=int, default=2000)
    parser.add_argument("--population", type=int, default=None,
                        metavar="M",
                        help="fused runtime (ISSUE 20): train M "
                             "vmap-stacked policies (population.size) as "
                             "ONE program — every carry leaf gains a "
                             "leading member axis and one dispatch per "
                             "chunk advances all members. Per-member "
                             "seeds spawn from --seed (member k of an "
                             "M-run bit-matches a stacked run with only "
                             "member k); --population 1 is bit-identical "
                             "to the plain program. Mutually exclusive "
                             "with --mesh-devices; see --population-spec "
                             "and docs/performance.md")
    parser.add_argument("--population-spec", default=None, metavar="JSON",
                        help="per-member hyperparameter vectors "
                             "(population.spec_json): a JSON object with "
                             "any of \"epsilon\" (exploration floor "
                             "epsilon_end), \"lr\", \"gamma\" — each a "
                             "length-M array; members without an "
                             "override inherit the config. Example: "
                             "--population 2 --population-spec "
                             "'{\"lr\": [1e-3, 3e-4]}'")
    parser.add_argument("--replay-ratio", type=int, default=None,
                        metavar="N",
                        help="on-device replay ratio "
                             "(replay.updates_per_chunk): N grad "
                             "sub-steps per train event, each drawing "
                             "an independent replay batch, scanned "
                             "inside one jitted program. Supported by "
                             "the fused (feed-forward), host-replay "
                             "and single-learner apex runtimes; 1 is "
                             "bit-identical to the pre-knob program")
    parser.add_argument("--actor-dtype", choices=("float32", "bfloat16"),
                        default=None,
                        help="actor-inference dtype split "
                             "(network.actor_dtype): bfloat16 casts "
                             "the params once per chunk for acting "
                             "while the learner keeps fp32 masters. "
                             "fused + host-replay runtimes; float32 "
                             "(default) is bit-identical to the "
                             "pre-knob program")
    parser.add_argument("--no-double-buffer", action="store_true",
                        help="--runtime host-replay only: disable the "
                             "double-buffered H2D staging path "
                             "(replay/staging.py) and sample->upload->"
                             "train serially — the numerically identical "
                             "A/B reference for a suspected staging "
                             "issue")
    parser.add_argument("--no-pipeline", action="store_true",
                        help="--runtime host-replay only: disable the "
                             "three-stage collect/evacuate/train "
                             "pipeline (streamed sub-chunk D2H + "
                             "background evacuation worker) and "
                             "evacuate each chunk with one blocking "
                             "monolithic fetch — the numerically "
                             "identical serial A/B reference (same "
                             "collect-ahead schedule, zero overlap)")
    parser.add_argument("--evac-slices", type=int, default=4,
                        help="--runtime host-replay only: time slices "
                             "each chunk's D2H evacuation streams "
                             "through (replay/staging.py "
                             "StreamedEvacuator); higher overlaps "
                             "transfers and ring appends at finer "
                             "grain, 1 = one streamed piece. Ignored "
                             "under --no-pipeline")
    parser.add_argument("--no-prefetch", action="store_true",
                        help="--runtime host-replay only: disable the "
                             "background SamplePrefetcher (replay/"
                             "staging.py) and sample train batches on "
                             "the main thread between steps — the "
                             "numerically identical serial A/B "
                             "reference for the sample-side pipeline "
                             "(bit-identical under a fixed seed in "
                             "uniform mode)")
    parser.add_argument("--prefetch-depth", type=int, default=2,
                        help="--runtime host-replay only: device-"
                             "resident batches the SamplePrefetcher "
                             "may stage ahead of the learner (bounds "
                             "host staging memory and sample "
                             "run-ahead). Ignored under --no-prefetch")
    parser.add_argument("--per", action="store_true",
                        help="--runtime host-replay only: force "
                             "prioritized (sum-tree) replay sampling "
                             "with IS weights and batched TD-error "
                             "write-backs; presets with "
                             "replay.prioritized=True enable it by "
                             "default (uniform otherwise)")
    parser.add_argument("--checkpoint-dir", default=None,
                        help="enable checkpoint/resume under this "
                             "directory (orbax; restores newest on "
                             "start). Every runtime configuration that "
                             "trains can checkpoint: host-replay saves "
                             "whole state at any --mesh-devices width "
                             "and under --per (bit-identical resume, "
                             "shard/sampler pins enforced); apex "
                             "--checkpoint-replay snapshots survive "
                             "--ingest-shards changes via the resharding "
                             "migration")
    parser.add_argument("--save-every-frames", type=int, default=0,
                        help="checkpoint period in env frames "
                             "(default: eval_every_steps)")
    parser.add_argument("--checkpoint-replay", action="store_true",
                        help="also checkpoint replay state: the fused "
                             "runtime saves the WHOLE carry (resume is "
                             "bit-equal to an uninterrupted run); the "
                             "apex runtime snapshots the host shard "
                             "beside the learner checkpoint (warm-buffer "
                             "resume). Ring-sized checkpoints (a 65k "
                             "pixel ring is ~1.8 GB vs ~7 MB learner-"
                             "only); default refills from live experience")
    parser.add_argument("--eval-every-steps", type=int, default=None,
                        help="eval period in env steps. Default: config "
                             "value on the fused runtime; DISABLED on the "
                             "apex runtime (its eval steps host envs "
                             "synchronously and stalls the service loop)")
    parser.add_argument("--profile-dir", default=None,
                        help="capture a jax.profiler trace of the first "
                             "post-warmup chunk into this directory "
                             "(view with TensorBoard / xprof). All three "
                             "runtimes. For a window at an arbitrary "
                             "point of a LIVE run, use the telemetry "
                             "server's /debug/profile?seconds=N endpoint "
                             "(or /fleet/profile on the aggregator) "
                             "instead — no restart needed")
    parser.add_argument("--trace-path", default=None,
                        help="apex runtime: write a Chrome trace-event "
                             "file of the host loop (ingest/sample/train "
                             "spans; open in Perfetto) to this path")
    parser.add_argument("--telemetry-port", type=int, default=None,
                        help="serve the process telemetry registry's "
                             "/metrics endpoint (Prometheus text format) "
                             "on this port; 0 binds an ephemeral port "
                             "(reported as a telemetry_port log line). "
                             "Works on both runtimes; see "
                             "docs/observability.md")
    parser.add_argument("--telemetry-host", default="127.0.0.1",
                        help="bind address for --telemetry-port: loopback "
                             "by default (the metric/debug surface is "
                             "unauthenticated); 0.0.0.0 makes /metrics "
                             "and /healthz scrapeable from outside the "
                             "container/VM. All runtimes")
    parser.add_argument("--telemetry-snapshot", default=None,
                        help="dump a JSON snapshot of the telemetry "
                             "registry to this path at exit (offline "
                             "runs; same data as /metrics.json)")
    parser.add_argument("--fleet-dir", default=None,
                        help="fleet registry directory (ISSUE 16): this "
                             "process writes a role-labeled endpoint "
                             "descriptor next to every other member of "
                             "the run so the fleet aggregator (python "
                             "-m dist_dqn_tpu.telemetry.fleet) can "
                             "federate one /metrics pane + /fleet/"
                             "status rollup. Exported as DQN_FLEET_DIR "
                             "so spawned actor/feeder processes "
                             "register their own endpoints. Requires "
                             "--telemetry-port")
    parser.add_argument("--forensics-dir", default=None,
                        help="arm the stall watchdog + divergence "
                             "sentinel (telemetry/watchdog.py): a "
                             "pipeline stage missing its heartbeat "
                             "deadline, or a NaN/Inf loss, dumps a "
                             "forensics bundle (named thread stacks, "
                             "flight-recorder tail, registry snapshot, "
                             "run manifest) under this directory and "
                             "flips /healthz to 503. Exported as "
                             "DQN_FORENSICS_DIR so spawned actor/feeder "
                             "processes arm their own. See the "
                             "'debugging a hang' runbook in "
                             "docs/observability.md")
    parser.add_argument("--watchdog-deadline-s", type=float, default=120.0,
                        help="heartbeat staleness that counts as a stall "
                             "(per stage; requires --forensics-dir)")
    parser.add_argument("--watchdog-abort", action="store_true",
                        help="after dumping the forensics bundle, "
                             "SIGTERM the process (graceful: telemetry "
                             "flush + device-grant release chain off "
                             "SIGTERM) with a bounded hard-exit "
                             "fallback — for supervisors that restart "
                             "on exit rather than scrape /healthz")
    parser.add_argument("--no-flight-recorder", action="store_true",
                        help="disable the in-memory flight-recorder "
                             "ring (telemetry/flight.py; ~1µs/event "
                             "when on). Forensics bundles and "
                             "/debug/flight then carry no event tail")
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform (e.g. cpu, tpu); "
                             "overrides site-level platform selection")
    parser.add_argument("--wall-budget-s", type=float, default=None,
                        help="device runs: refuse to start unless the "
                             "predicted wall time fits comfortably inside "
                             "this kill budget (set it to the external "
                             "`timeout` you wrap the run in; a run killed "
                             "mid-device-op wedges the shared TPU tunnel)")
    parser.add_argument("--mesh-devices", type=int, default=1,
                        help="fused + host-replay runtimes: run over a "
                             "dp mesh of this many devices (0 = all; "
                             "multi-process runs use the GLOBAL device "
                             "list). Fused: env lanes + replay shard "
                             "per device. Host-replay: one COLLECT "
                             "program + env-lane block + host ring / "
                             "evac worker / sample prefetcher per "
                             "device (sharded collect — acting is "
                             "data-parallel too, zero cross-shard "
                             "lane scatter). Gradients pmean over the "
                             "mesh either way; apex uses "
                             "--learner-devices instead")
    parser.add_argument("--coordinator", default=None,
                        help="multi-host: host:port of process 0's "
                             "jax.distributed coordinator. Every host runs "
                             "this same command with its own --process-id; "
                             "checkpoints need a shared directory")
    parser.add_argument("--num-processes", type=int, default=1,
                        help="multi-host: total process count")
    parser.add_argument("--process-id", type=int, default=0,
                        help="multi-host: this process's id (0-based)")
    parser.add_argument("--stop-at-return", type=float, default=None,
                        help="fused runtime, single-process: stop early "
                             "once eval_return reaches this value (e.g. "
                             "475 = CartPole solved)")
    parser.add_argument("--runtime", choices=("fused", "apex",
                                              "host-replay"),
                        default="fused",
                        help="fused: on-device Anakin loop (JAX envs); "
                             "apex: CPU actor processes + learner service "
                             "over the shm/DCN transport (host envs)")
    parser.add_argument("--host-env", default="CartPole-v1",
                        help="apex runtime: host env actors step "
                             "(e.g. CartPole-v1, ale:Pong)")
    parser.add_argument("--num-actors", type=int, default=4)
    parser.add_argument("--envs-per-actor", type=int, default=8)
    parser.add_argument("--num-remote-actors", type=int, default=0,
                        help="apex runtime: remote (TCP) actor slots")
    parser.add_argument("--learner-devices", type=int, default=1,
                        help="apex runtime: shard train batches over this "
                             "many local devices (0 = all; gradients "
                             "pmean over ICI)")
    parser.add_argument("--tcp-port", type=int, default=None,
                        help="apex runtime: listen for remote actors "
                             "(actors/remote.py) on this port; 0 = "
                             "ephemeral")
    parser.add_argument("--device-sampling", action="store_true",
                        help="sample replay priorities ON DEVICE (Pallas "
                             "stratified kernel; items stay in host "
                             "DRAM). Apex runtime: one priority plane "
                             "per --ingest-shards replay shard, each on "
                             "its own chip. Host-replay runtime (with "
                             "--per): one plane per --mesh-devices "
                             "shard, replacing the host sum-trees")
    parser.add_argument("--transport", choices=("zerocopy", "legacy"),
                        default="zerocopy",
                        help="apex runtime experience path (ISSUE 9): "
                             "zerocopy = schema-negotiated raw-array "
                             "frames (shm slot rings locally, zero-copy "
                             "framing on TCP) with actor-shipped "
                             "priorities; legacy = the bit-pinned "
                             "JSON-codec fallback")
    parser.add_argument("--no-actor-priorities", action="store_true",
                        help="apex runtime: keep the learner-side "
                             "priority bootstrap dispatches even on "
                             "--transport zerocopy (A/B baseline; "
                             "re-enables native assembly)")
    parser.add_argument("--ingest-shards", type=int, default=1,
                        help="apex runtime: replay-shard count — the "
                             "store splits into N PrioritizedHostReplay "
                             "shards and every actor's stream lands in "
                             "its sticky crc32 shard (ingest/router.py; "
                             "records_by_shard in the summary proves "
                             "the spread). N > 1 requires the zerocopy "
                             "transport with actor priorities (or a "
                             "recurrent config) for per-actor insert "
                             "attribution; sampling runs on the host "
                             "trees or, with --device-sampling, on one "
                             "per-shard device priority plane each")
    parser.add_argument("--no-wire-dedup", action="store_true",
                        help="apex runtime (ISSUE 14): disable the "
                             "frame-stack dedup wire plane — actors on "
                             "frame-stacked pixel envs then ship full "
                             "stacks on the plain zero-copy layout "
                             "(the dedup-off A/B arm)")
    parser.add_argument("--shm-batch", type=int, default=1,
                        help="apex runtime (ISSUE 14): feeder processes "
                             "coalesce this many step records into one "
                             "seqlock slot publish (amortizes the "
                             "publish/consume handshake for unthrottled "
                             "producers; 1 = bit-pinned per-record "
                             "publishes; rollout actors are lock-step "
                             "and unaffected)")
    parser.add_argument("--shard-sampling", action="store_true",
                        help="apex runtime (ISSUE 14, requires "
                             "--ingest-shards > 1): run the stratified "
                             "draw + gather in per-shard worker threads "
                             "and hand the learner pre-packed batches "
                             "through a bounded queue — train events "
                             "stop paying sample time on the learner "
                             "thread")
    parser.add_argument("--remote-actor-mode", choices=("local", "external"),
                        default="local",
                        help="local: the service spawns its remote actors "
                             "as local processes (single-host DCN "
                             "stand-in); external: slots stay open for "
                             "workers started on other hosts via "
                             "python -m dist_dqn_tpu.actors.remote")
    args = parser.parse_args()
    # SIGTERM/exit device release: a killed run must not orphan its device
    # grant (the round-1 tunnel wedge, utils/device_cleanup.py).
    from dist_dqn_tpu.utils.device_cleanup import install as _install_cleanup
    _install_cleanup()
    if args.telemetry_snapshot:
        from dist_dqn_tpu.telemetry import install_snapshot_dump
        install_snapshot_dump(args.telemetry_snapshot)
    import os as _os
    import sys as _sys
    if args.no_flight_recorder:
        # Before any loop wires its recorder reference, and through the
        # environment so spawned actor/feeder processes disable theirs.
        from dist_dqn_tpu.telemetry import flight as _flight_mod
        _os.environ["DQN_FLIGHT_RECORDER"] = "0"
        _flight_mod.configure(enabled=False)
    if args.fleet_dir:
        # Through the environment (like DQN_FORENSICS_DIR) so spawned
        # actor/feeder processes register their own fleet descriptors.
        _os.environ["DQN_FLEET_DIR"] = args.fleet_dir
    if args.forensics_dir:
        from dist_dqn_tpu.telemetry import watchdog as _wd
        _os.environ["DQN_FORENSICS_DIR"] = args.forensics_dir
        _os.environ["DQN_WATCHDOG_DEADLINE_S"] = \
            str(args.watchdog_deadline_s)
        _wd.install_watchdog(forensics_dir=args.forensics_dir,
                             deadline_s=args.watchdog_deadline_s,
                             abort=args.watchdog_abort)
        _wd.install_sentinel(forensics_dir=args.forensics_dir,
                             abort=args.watchdog_abort)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    if args.coordinator:
        # Must precede the first backend touch; platform choice above feeds
        # the CPU-collectives selection (parallel/distributed.py).
        from dist_dqn_tpu.parallel.distributed import initialize
        initialize(args.coordinator, args.num_processes, args.process_id)
    try:
        cfg = apply_overrides(CONFIGS[args.config], args.overrides)
    except ValueError as e:
        parser.error(str(e))
    if args.eval_every_steps is not None:
        # An explicit 0 DISABLES eval (the loop convention) — a plain
        # truthiness test here silently fell back to the config period.
        import dataclasses as _dc
        cfg = _dc.replace(cfg, eval_every_steps=args.eval_every_steps)
    # Learner-utilization knobs (ISSUE 6): applied per runtime, with
    # the standard ignored-flag warnings where a runtime does not
    # support them yet — BEFORE the manifest so provenance records the
    # config actually run.
    import dataclasses as _dc
    _recurrent_fused = args.runtime == "fused" and cfg.network.lstm_size > 0
    if args.replay_ratio is not None:
        if _recurrent_fused:
            print("# --replay-ratio is not supported by the recurrent "
                  "(R2D2) fused loop yet (its sequence learner has no "
                  "scan-ratio path); ignored")
        else:
            cfg = _dc.replace(cfg, replay=_dc.replace(
                cfg.replay, updates_per_chunk=args.replay_ratio))
    if args.actor_dtype is not None:
        if args.runtime == "apex":
            print("# --actor-dtype applies to the fused/host-replay "
                  "runtimes only; the apex service acts on the live "
                  "learner params — ignored")
        elif _recurrent_fused:
            print("# --actor-dtype is not supported by the recurrent "
                  "(R2D2) fused loop yet; ignored")
        else:
            cfg = _dc.replace(cfg, network=_dc.replace(
                cfg.network, actor_dtype=args.actor_dtype))
    # Population plane (ISSUE 20): fused-runtime-only, like the knobs
    # above — warn-and-ignore on runtimes without a member axis, but
    # REFUSE the population x mesh cross outright (silently dropping
    # either flag would run a different experiment than asked for).
    if args.population is not None or args.population_spec is not None:
        if args.population is not None and args.population < 1:
            parser.error(f"--population must be >= 1, got "
                         f"{args.population}")
        if args.runtime != "fused":
            print("# --population/--population-spec apply to the fused "
                  "runtime only (the apex/host-replay runtimes have no "
                  "stacked-member plane yet); ignored")
        elif _recurrent_fused:
            print("# --population is not supported by the recurrent "
                  "(R2D2) fused loop yet (its sequence learner has no "
                  "member axis); ignored")
        elif args.mesh_devices != 1 and (args.population or 1) > 1:
            parser.error(
                "--population and --mesh-devices are mutually exclusive: "
                "the population fills ONE chip by vmap-stacking members; "
                "run one population process per device (or drop one "
                "flag)")
        else:
            cfg = _dc.replace(cfg, population=_dc.replace(
                cfg.population,
                size=(args.population if args.population is not None
                      else cfg.population.size),
                spec_json=(args.population_spec
                           if args.population_spec is not None
                           else cfg.population.spec_json)))
            try:
                # Validate at the CLI boundary (spec shape/range + the
                # lr-schedule pin), not as a mid-startup stack trace.
                from dist_dqn_tpu.population import resolve_spec as _rs
                _rs(cfg)
            except ValueError as e:
                parser.error(str(e))
    # Run manifest (ISSUE 4 satellite): one provenance line per run —
    # git sha, versions, config hash, argv — reused verbatim by the
    # forensics bundles and served at /debug/config.
    from dist_dqn_tpu.telemetry import manifest as _manifest
    _man = _manifest.build_manifest(cfg, argv=_sys.argv)
    _manifest.set_run_manifest(_man)
    print(json.dumps({"manifest": _man}))
    # Chaos (ISSUE 8): game-day runs arm a fault plan via DQN_CHAOS_PLAN
    # — AFTER the manifest is set so the armed plan annotates it (the
    # provenance line above already printed; /debug/config and the
    # forensics bundles read the annotated copy).
    from dist_dqn_tpu import chaos as _chaos
    _chaos.maybe_install_from_env()
    if args.runtime == "host-replay":
        # Hybrid fused loop with the replay window in host DRAM
        # (host_replay_loop.py): device env chunks stream transitions
        # down once, sampled batches stream back double-buffered. The
        # window is DRAM-priced — set replay.capacity accordingly
        # (e.g. --set replay.capacity=8000000 with frame_dedup).
        if args.stop_at_return is not None:
            print("# --stop-at-return is not supported by --runtime "
                  "host-replay (prototype surface); ignored")
        if args.checkpoint_replay:
            print("# --checkpoint-replay is implied by --runtime "
                  "host-replay --checkpoint-dir: its checkpoints are "
                  "always whole-state (per-shard rings + PER sampler "
                  "state + carry + learner) so resume is bit-identical "
                  "at any --mesh-devices width; flag ignored")
        if args.save_every_frames and not args.checkpoint_dir:
            print("# --save-every-frames does nothing without "
                  "--checkpoint-dir; ignored")
        if args.eval_every_steps:
            print("# periodic eval is not supported by --runtime "
                  "host-replay; ignored")
        if args.wall_budget_s is not None:
            # No calibrated time model exists for this loop (it is
            # link-bound, not chunk-count-bound), so the fused sizing
            # gate cannot vet the budget — say so rather than silently
            # dropping the flag (the wedge-prevention contract).
            print("# --wall-budget-s is not modeled for --runtime "
                  "host-replay: size the run manually (worst case = "
                  "compiles + chunks x measured chunk wall; see "
                  "benchmarks/host_replay_bench.py probe pattern) — "
                  "a run SIGTERM'd mid-device-op can wedge the tunnel")
        if args.seed is not None:
            import dataclasses as _dc
            cfg = _dc.replace(cfg, seed=args.seed)
        from dist_dqn_tpu.host_replay_loop import run_host_replay

        if args.telemetry_port is not None:
            # The host ring and chunk loops record into the process
            # registry regardless; this just exposes the scrape surface.
            from dist_dqn_tpu import telemetry as _telemetry
            _srv = _telemetry.start_server(args.telemetry_port,
                                           host=args.telemetry_host)
            print(json.dumps({"telemetry_port": _srv.port}))
            from dist_dqn_tpu.telemetry import fleet as _fleet
            _fleet.register_endpoint("learner", _srv.port,
                                     host=args.telemetry_host,
                                     labels={"loop": "host_replay"})
        out = run_host_replay(
            cfg, total_env_steps=args.total_env_steps or cfg.total_env_steps,
            chunk_iters=args.chunk_iters, log_fn=print,
            double_buffer=not args.no_double_buffer,
            pipeline=not args.no_pipeline,
            evac_slices=args.evac_slices,
            prefetch=not args.no_prefetch,
            prefetch_depth=args.prefetch_depth,
            # None = follow cfg.replay.prioritized; --per forces it on.
            prioritized=True if args.per else None,
            checkpoint_dir=args.checkpoint_dir,
            save_every_frames=args.save_every_frames,
            mesh_devices=args.mesh_devices,
            device_sampling=args.device_sampling,
            profile_dir=args.profile_dir)
        out.pop("history", None)
        print(json.dumps(out))
        return
    if args.runtime == "apex":
        if args.mesh_devices != 1:
            print("# --mesh-devices applies to the fused/host-replay "
                  "runtimes; use --learner-devices for apex batch "
                  "sharding")
        if args.stop_at_return is not None:
            print("# --stop-at-return applies to the fused runtime only; "
                  "ignored under --runtime apex")
        if args.no_double_buffer:
            print("# --no-double-buffer applies to --runtime host-replay "
                  "only; the apex service staging knob is "
                  "ApexRuntimeConfig.stage_depth — ignored")
        if args.no_pipeline \
                or args.evac_slices != parser.get_default("evac_slices"):
            print("# --no-pipeline/--evac-slices apply to --runtime "
                  "host-replay only; ignored under --runtime apex")
        if args.no_prefetch or args.per \
                or args.prefetch_depth != parser.get_default(
                    "prefetch_depth"):
            print("# --no-prefetch/--prefetch-depth/--per apply to "
                  "--runtime host-replay only; the apex service is "
                  "always prioritized and staged via "
                  "ApexRuntimeConfig — ignored")
        import dataclasses

        from dist_dqn_tpu.actors.service import ApexRuntimeConfig, run_apex
        from dist_dqn_tpu.envs.gym_adapter import is_pixel_env
        if not is_pixel_env(args.host_env):
            # Non-pixel host env: the config's Nature-CNN torso can't eat
            # flat observations — swap in the MLP torso, keep the rest.
            print(f"# host env {args.host_env} is non-pixel: using MLP torso")
            cfg = dataclasses.replace(
                cfg, network=dataclasses.replace(
                    cfg.network, torso="mlp", compute_dtype="float32"))
        rt = ApexRuntimeConfig(
            host_env=args.host_env, num_actors=args.num_actors,
            envs_per_actor=args.envs_per_actor,
            total_env_steps=args.total_env_steps or cfg.total_env_steps,
            checkpoint_dir=args.checkpoint_dir,
            checkpoint_replay=args.checkpoint_replay,
            save_every_steps=(args.save_every_frames or cfg.eval_every_steps
                              or 100_000),
            eval_every_steps=(args.eval_every_steps
                              if args.eval_every_steps is not None else 0),
            eval_episodes=cfg.eval_episodes,
            tcp_port=args.tcp_port,
            num_remote_actors=args.num_remote_actors,
            spawn_remote_actors=args.remote_actor_mode == "local",
            learner_devices=args.learner_devices,
            trace_path=args.trace_path,
            device_sampling=args.device_sampling,
            transport=args.transport,
            actor_priorities=not args.no_actor_priorities,
            ingest_shards=args.ingest_shards,
            wire_dedup=not args.no_wire_dedup,
            shm_batch=args.shm_batch,
            shard_sampling=args.shard_sampling,
            telemetry_port=args.telemetry_port,
            telemetry_host=args.telemetry_host,
            profile_dir=args.profile_dir)
        print(json.dumps(run_apex(cfg, rt)))
        return
    if args.transport != parser.get_default("transport") \
            or args.no_actor_priorities \
            or args.ingest_shards != parser.get_default("ingest_shards") \
            or args.no_wire_dedup or args.shard_sampling \
            or args.shm_batch != parser.get_default("shm_batch"):
        print("# --transport/--no-actor-priorities/--ingest-shards/"
              "--no-wire-dedup/--shm-batch/--shard-sampling apply "
              "to --runtime apex only (the fused/host-replay runtimes "
              "have no actor transport); ignored")
    if args.no_double_buffer:
        print("# --no-double-buffer applies to --runtime host-replay only; "
              "ignored under the fused runtime (its replay never leaves "
              "the device)")
    if args.no_pipeline \
            or args.evac_slices != parser.get_default("evac_slices"):
        print("# --no-pipeline/--evac-slices apply to --runtime "
              "host-replay only; ignored under the fused runtime (its "
              "replay never leaves the device)")
    if args.no_prefetch or args.per \
            or args.prefetch_depth != parser.get_default("prefetch_depth"):
        print("# --no-prefetch/--prefetch-depth/--per apply to "
              "--runtime host-replay only; ignored under the fused "
              "runtime (its replay samples on device — "
              "replay.prioritized selects the device sampler there)")
    if args.device_sampling:
        print("# --device-sampling applies to the apex/host-replay "
              "runtimes; ignored under the fused runtime (its replay "
              "is device-resident already)")
    stop_fn = None
    if args.stop_at_return is not None:
        target = args.stop_at_return
        stop_fn = lambda row: row.get("eval_return",  # noqa: E731
                                      -float("inf")) >= target
    if jax.default_backend() != "cpu":
        # Pre-flight sizing gate for device runs (VERDICT round-3 ask
        # #1b): incident #2 was exactly this CLI started with a frame
        # budget that could not finish inside its external `timeout`,
        # SIGTERM'd mid-device-op, wedging the tunnel. Predict the wall
        # time up front; with --wall-budget-s given, REFUSE to start a
        # run not predicted to fit comfortably inside it. Without the
        # flag the prediction is still printed so the operator can size
        # the external timeout.
        import math

        from dist_dqn_tpu.utils.sizing import gate_fused

        menv = make_jax_env(cfg.env_name)
        total = args.total_env_steps or cfg.total_env_steps
        lanes = cfg.actor.num_envs
        n_chunks = max(1, math.ceil(total / (args.chunk_iters * lanes)))
        n_evals = (math.ceil(total / cfg.eval_every_steps)
                   if cfg.eval_every_steps else 0)
        verdict = gate_fused(
            budget_s=args.wall_budget_s or float("inf"),
            num_envs=lanes, batch_size=cfg.learner.batch_size,
            train_every=cfg.train_every, chunk_iters=args.chunk_iters,
            num_chunks=n_chunks, ring=cfg.replay.capacity,
            num_evals=n_evals, eval_iters=3_000 * cfg.eval_episodes,
            pixel_obs=len(menv.observation_shape) == 3,
            num_actions=menv.num_actions,
            frame_dedup_stack=(getattr(menv, "frame_stack", 0)
                               if cfg.replay.frame_dedup
                               and not cfg.network.lstm_size else 0))
        print(json.dumps({"sizing_predicted_s": round(verdict.predicted_s, 1),
                          "wall_budget_s": args.wall_budget_s}))
        if not verdict.ok:
            if args.wall_budget_s is None:
                # No kill budget -> nothing will SIGTERM this run
                # mid-device-op, so nothing to refuse: the wedge
                # scenario needs a kill. Surface the concern and run.
                print(json.dumps({"sizing_gate": "warning",
                                  "reason": verdict.reason}))
            else:
                print(json.dumps({"sizing_gate": "refused",
                                  "reason": verdict.reason}))
                raise SystemExit(4)
    train(cfg, total_env_steps=args.total_env_steps, seed=args.seed,
          chunk_iters=args.chunk_iters, checkpoint_dir=args.checkpoint_dir,
          save_every_frames=args.save_every_frames,
          profile_dir=args.profile_dir, num_devices=args.mesh_devices,
          stop_fn=stop_fn, checkpoint_replay=args.checkpoint_replay,
          telemetry_port=args.telemetry_port,
          telemetry_host=args.telemetry_host)


if __name__ == "__main__":
    main()
