"""Version-adaptive JAX API resolution (ISSUE 10 satellite).

One seam owns the ``shard_map`` spelling. JAX moved it from
``jax.experimental.shard_map.shard_map`` (<= 0.4.x, replication check
spelled ``check_rep``) to top-level ``jax.shard_map`` (>= 0.5, spelled
``check_vma``); code written against either spelling import-errors on
the other, which is exactly how this repo's multi-chip paths (and the
13 env-dependent tier-1 failures they carried) broke on a 0.4.37 box.
Every call site in the repo resolves through :func:`shard_map` below —
``scripts/check_mesh_axis.py`` lints direct ``jax.shard_map`` /
``jax.experimental.shard_map`` references back to this module.
"""
from __future__ import annotations

import jax

#: True when this jax exposes the top-level (post-experimental) API.
HAS_NATIVE_SHARD_MAP = hasattr(jax, "shard_map")


def shard_map(f, mesh, in_specs, out_specs, check_vma: bool = False):
    """``jax.shard_map`` with one signature on every supported jax.

    ``check_vma`` follows the modern spelling; on 0.4.x it maps onto the
    experimental API's ``check_rep`` (the same replication check under
    its old name). The repo always passes False: the carries deliberately
    mix replicated and sharded leaves, which the checker rejects.
    """
    if HAS_NATIVE_SHARD_MAP:
        return jax.shard_map(f, mesh=mesh, in_specs=in_specs,
                             out_specs=out_specs, check_vma=check_vma)
    from jax.experimental.shard_map import shard_map as _shard_map

    return _shard_map(f, mesh=mesh, in_specs=in_specs,
                      out_specs=out_specs, check_rep=check_vma)
