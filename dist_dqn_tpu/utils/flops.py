"""Model-FLOPs accounting: program FLOPs and MFU vs chip peak.

The judge axis for single-chip efficiency is MFU — achieved model FLOP/s
over the chip's peak (VERDICT round 1, missing #2). FLOPs come from XLA's
own cost analysis of the *compiled* program (an exact op census of what
actually runs, including fusion decisions), not a hand-derived formula;
``tests/test_flops.py`` cross-checks it against the analytic Nature-CNN
count to guard against cost-model regressions.

Peak numbers are dense bf16 FLOP/s per chip from public TPU specs — the
training programs here run their matmuls/convs in bf16 (config
``compute_dtype``), so bf16 peak is the honest denominator.
"""
from __future__ import annotations

from typing import Optional

# device_kind (as reported by jax.Device.device_kind) -> dense bf16 peak
# FLOP/s per chip. Public numbers: v4 275 TFLOPs, v5e 197, v5p 459,
# v6e (Trillium) 918.
_PEAK_BF16 = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def chip_peak_flops(device) -> Optional[float]:
    """Dense bf16 peak FLOP/s for a jax.Device, or None if unknown (CPU)."""
    return _PEAK_BF16.get(getattr(device, "device_kind", ""))


def compiled_flops(compiled) -> Optional[float]:
    """FLOPs of one execution of a ``jax.stages.Compiled`` program.

    Returns None when the backend does not expose a cost analysis (some
    plugin backends) — callers must treat MFU as unavailable, not zero.
    """
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    # Older jax returns [dict], newer returns dict.
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return None
    flops = cost.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def mfu(flops_per_sec: Optional[float], device) -> Optional[float]:
    """Achieved-FLOP/s / chip-peak, or None when either side is unknown."""
    peak = chip_peak_flops(device)
    if peak is None or flops_per_sec is None:
        return None
    return flops_per_sec / peak


def mfu_fields(flops_per_exec: Optional[float], execs: int, dt: float,
               device) -> dict:
    """The benchmark-JSON fields derived from a timed run of a compiled
    program: {} when FLOPs are unavailable, model_flops_per_sec always
    otherwise, mfu only when the chip peak is known."""
    if flops_per_exec is None or dt <= 0:
        return {}
    flops_per_sec = flops_per_exec * execs / dt
    out = {"model_flops_per_sec": round(flops_per_sec, 1)}
    m = mfu(flops_per_sec, device)
    if m is not None:
        out["mfu"] = round(m, 4)
    return out
