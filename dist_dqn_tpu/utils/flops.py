"""Model-FLOPs accounting: program FLOPs and MFU vs chip peak.

The judge axis for single-chip efficiency is MFU — achieved model FLOP/s
over the chip's peak (VERDICT round 1, missing #2). FLOPs come from XLA's
own cost analysis of the *compiled* program (an exact op census of what
actually runs, including fusion decisions), not a hand-derived formula;
``tests/test_flops.py`` cross-checks it against the analytic Nature-CNN
count to guard against cost-model regressions.

Peak numbers are dense bf16 FLOP/s per chip from public TPU specs — the
training programs here run their matmuls/convs in bf16 (config
``compute_dtype``), so bf16 peak is the honest denominator.
"""
from __future__ import annotations

from typing import Optional

# device_kind (as reported by jax.Device.device_kind) -> dense bf16 peak
# FLOP/s per chip. Public numbers: v4 275 TFLOPs, v5e 197, v5p 459,
# v6e (Trillium) 918.
_PEAK_BF16 = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


# device_kind -> peak HBM bandwidth, bytes/s per chip. Public numbers:
# v4 1228 GB/s, v5e 819, v5p 2765, v6e (Trillium) 1640.
_PEAK_HBM_BW = {
    "TPU v2": 700e9,
    "TPU v3": 900e9,
    "TPU v4": 1228e9,
    "TPU v5 lite": 819e9,
    "TPU v5e": 819e9,
    "TPU v5": 2765e9,
    "TPU v5p": 2765e9,
    "TPU v6 lite": 1640e9,
    "TPU v6e": 1640e9,
}


def chip_peak_flops(device) -> Optional[float]:
    """Dense bf16 peak FLOP/s for a jax.Device, or None if unknown (CPU)."""
    return _PEAK_BF16.get(getattr(device, "device_kind", ""))


def chip_peak_hbm_bw(device) -> Optional[float]:
    """Peak HBM bytes/s for a jax.Device, or None if unknown (CPU)."""
    return _PEAK_HBM_BW.get(getattr(device, "device_kind", ""))


def _cost_value(compiled, key: str) -> Optional[float]:
    """One positive value from a compiled program's XLA cost analysis,
    or None when the backend exposes no analysis / no such key —
    callers must treat the metric as unavailable, not zero."""
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    # Older jax returns [dict], newer returns dict.
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return None
    value = cost.get(key)
    if value is None or value <= 0:
        return None
    return float(value)


def compiled_flops(compiled) -> Optional[float]:
    """FLOPs of one execution of a ``jax.stages.Compiled`` program.

    CAVEAT (measured on this box, round 3): the census counts the body of
    a ``lax.scan``/``while_loop`` ONCE, regardless of trip count — a
    5-iteration and a 40-iteration chunk of the fused loop return the
    SAME flops. Only call this on programs without data/trip-dependent
    loops over compute (the feedforward train step qualifies; fused
    chunks and the scanned R2D2 time loop do not).
    """
    return _cost_value(compiled, "flops")


def compiled_bytes(compiled) -> Optional[float]:
    """"bytes accessed" census of one execution of a compiled program —
    the HLO cost model's post-fusion sum of every fusion's operand +
    result traffic, i.e. the memory-side counterpart of
    ``compiled_flops`` for a roofline bound (VERDICT round-3 next #5).

    Same scan caveat as ``compiled_flops`` (a scan body is counted once
    — feedforward steps only), plus one of its own: the cost model does
    not see VMEM reuse across fusions, so this is the compiler's
    HBM-traffic estimate, not a hardware counter. Good enough to decide
    memory-bound vs compute-bound; not a promise of achieved GB/s.
    """
    return _cost_value(compiled, "bytes accessed")


def roofline_fields(flops_per_exec: Optional[float],
                    bytes_per_exec: Optional[float], device) -> dict:
    """Roofline verdict for one program execution: which bound governs,
    and the predicted step time under peak compute / peak bandwidth.

    Returns {} when any input is unknown. ``roofline_s`` is
    max(flops/peak_flops, bytes/peak_bw); measured time far above it
    means dispatch/latency overhead, near it means the named bound is
    real, and the ``roofline_bound`` field says which ceiling the
    program sits under (the answer to "is 2% MFU headroom or the
    bandwidth ceiling?" — BASELINE.md's CNN-family question).
    """
    peak_f = chip_peak_flops(device)
    peak_b = chip_peak_hbm_bw(device)
    if None in (flops_per_exec, bytes_per_exec, peak_f, peak_b):
        return {}
    t_compute = flops_per_exec / peak_f
    t_memory = bytes_per_exec / peak_b
    t_roof = max(t_compute, t_memory)
    return {
        "bytes_per_step": round(bytes_per_exec, 1),
        "arith_intensity": round(flops_per_exec / bytes_per_exec, 2),
        "roofline_compute_s": round(t_compute, 6),
        "roofline_memory_s": round(t_memory, 6),
        "roofline_s": round(t_roof, 6),
        "roofline_bound": "memory" if t_memory >= t_compute else "compute",
        "roofline_grad_steps_per_sec": round(1.0 / t_roof, 1),
    }


def mfu(flops_per_sec: Optional[float], device) -> Optional[float]:
    """Achieved-FLOP/s / chip-peak, or None when either side is unknown."""
    peak = chip_peak_flops(device)
    if peak is None or flops_per_sec is None:
        return None
    return flops_per_sec / peak


def nature_cnn_fwd_flops(batch: float, hidden: int = 512,
                         num_actions: int = 0) -> float:
    """Analytic forward FLOPs (2*MACs) of the Nature CNN torso on 84x84x4
    frames: VALID convs 8x8/4, 4x4/2, 3x3/1, then the fc to ``hidden``.
    ``num_actions`` > 0 adds the Q head (the recurrent net's head hangs
    off the LSTM instead — pass 0 there). Cross-checked against the XLA
    op census in tests/test_flops.py."""
    macs = (20 * 20 * 8 * 8 * 4 * 32        # conv1 -> [20,20,32]
            + 9 * 9 * 4 * 4 * 32 * 64       # conv2 -> [9,9,64]
            + 7 * 7 * 3 * 3 * 64 * 64       # conv3 -> [7,7,64]
            + 3136 * hidden                 # fc
            + hidden * num_actions)         # head (feedforward nets only)
    return 2.0 * macs * batch


def lstm_cell_fwd_flops(batch: float, features: int, hidden: int) -> float:
    """Analytic forward FLOPs of one LSTM cell step: the [B, F+H] x
    [F+H, 4H] gate matmul, 2 FLOPs per MAC (elementwise gate math is
    noise next to it)."""
    return 2.0 * batch * (features + hidden) * 4.0 * hidden


def r2d2_grad_step_flops(T: int, B: int, *, hidden: int = 512,
                         lstm: int = 512, remat: bool = True) -> dict:
    """Analytic FLOPs of one R2D2 grad step (agents/r2d2.py), split into
    the terms the throughput knobs act on.

    Accounting (matches the program structure in models/recurrent.py —
    the torso embeds all T*B frames in ONE batched conv outside the time
    scan; only the cell recurrence is scanned):
      torso: online fwd + target fwd + backward (~2x fwd) over T*B frames,
             plus one recompute fwd under remat;
      cell:  online fwd + target fwd + backward (~2x fwd) over T steps.

    This analytic count exists because the XLA op census CANNOT measure
    this program: cost analysis counts a scan body once regardless of
    trip count (see compiled_flops). tests/test_flops.py pins the model
    against an EXACT census of a tiny fully-unrolled variant
    (lstm_unroll >= T emits straight-line code, no loop).
    """
    frames = float(T) * B
    torso_passes = 4.0 + (1.0 if remat else 0.0)
    torso = torso_passes * nature_cnn_fwd_flops(frames, hidden=hidden)
    cell = 4.0 * lstm_cell_fwd_flops(frames, hidden, lstm)
    return {"torso": torso, "cell": cell, "total": torso + cell}


def r2d2_time_model(T: int, B: int, *, hidden: int = 512, lstm: int = 512,
                    remat: bool = True, lstm_bf16: bool = False,
                    unroll: int = 1, peak_bf16: float = 197e12,
                    f32_matmul_slowdown: float = 3.0,
                    scan_iter_overhead_s: float = 2e-6) -> dict:
    """Modeled seconds per R2D2 grad step as a function of the three
    throughput knobs (VERDICT round 2, next #6 — model-level evidence
    while the TPU tunnel blocks the real sweep).

    Terms: torso FLOPs at bf16 peak (the torso always computes in
    ``compute_dtype`` bf16); cell FLOPs at bf16 peak or at peak /
    ``f32_matmul_slowdown`` (XLA emulates an f32 matmul on the MXU with
    ~3 bf16 passes); plus per-scan-iteration overhead for the three time
    loops (online fwd, target fwd, backward), each ceil(T/unroll)
    iterations. ``remat`` adds torso FLOPs — it is an HBM knob, modeled
    here only on the FLOPs side.
    """
    import math

    f = r2d2_grad_step_flops(T, B, hidden=hidden, lstm=lstm, remat=remat)
    cell_rate = peak_bf16 if lstm_bf16 else peak_bf16 / f32_matmul_slowdown
    iters = math.ceil(T / max(unroll, 1))
    overhead = 3.0 * iters * scan_iter_overhead_s
    torso_s = f["torso"] / peak_bf16
    cell_s = f["cell"] / cell_rate
    return {"torso_s": torso_s, "cell_s": cell_s, "scan_overhead_s": overhead,
            "total_s": torso_s + cell_s + overhead,
            "modeled_grad_steps_per_sec":
                1.0 / (torso_s + cell_s + overhead)}


def mfu_fields(flops_per_exec: Optional[float], execs: int, dt: float,
               device) -> dict:
    """The benchmark-JSON fields derived from a timed run of a compiled
    program: {} when FLOPs are unavailable, model_flops_per_sec always
    otherwise, mfu only when the chip peak is known."""
    if flops_per_exec is None or dt <= 0:
        return {}
    flops_per_sec = flops_per_exec * execs / dt
    out = {"model_flops_per_sec": round(flops_per_sec, 1)}
    m = mfu(flops_per_sec, device)
    if m is not None:
        out["mfu"] = round(m, 4)
    return out
