"""Model-FLOPs accounting: program FLOPs and MFU vs chip peak.

The judge axis for single-chip efficiency is MFU — achieved model FLOP/s
over the chip's peak (VERDICT round 1, missing #2). FLOPs come from XLA's
own cost analysis of the *compiled* program (an exact op census of what
actually runs, including fusion decisions), not a hand-derived formula;
``tests/test_flops.py`` cross-checks it against the analytic Nature-CNN
count to guard against cost-model regressions.

Peak numbers are dense bf16 FLOP/s per chip from public TPU specs — the
training programs here run their matmuls/convs in bf16 (config
``compute_dtype``), so bf16 peak is the honest denominator.
"""
from __future__ import annotations

from typing import Optional

# device_kind (as reported by jax.Device.device_kind) -> dense bf16 peak
# FLOP/s per chip. Public numbers: v4 275 TFLOPs, v5e 197, v5p 459,
# v6e (Trillium) 918.
_PEAK_BF16 = {
    "TPU v2": 46e12,
    "TPU v3": 123e12,
    "TPU v4": 275e12,
    "TPU v5 lite": 197e12,
    "TPU v5e": 197e12,
    "TPU v5": 459e12,
    "TPU v5p": 459e12,
    "TPU v6 lite": 918e12,
    "TPU v6e": 918e12,
}


def chip_peak_flops(device) -> Optional[float]:
    """Dense bf16 peak FLOP/s for a jax.Device, or None if unknown (CPU)."""
    return _PEAK_BF16.get(getattr(device, "device_kind", ""))


def compiled_flops(compiled) -> Optional[float]:
    """FLOPs of one execution of a ``jax.stages.Compiled`` program.

    Returns None when the backend does not expose a cost analysis (some
    plugin backends) — callers must treat MFU as unavailable, not zero.

    CAVEAT (measured on this box, round 3): the census counts the body of
    a ``lax.scan``/``while_loop`` ONCE, regardless of trip count — a
    5-iteration and a 40-iteration chunk of the fused loop return the
    SAME flops. Only call this on programs without data/trip-dependent
    loops over compute (the feedforward train step qualifies; fused
    chunks and the scanned R2D2 time loop do not).
    """
    try:
        cost = compiled.cost_analysis()
    except Exception:
        return None
    # Older jax returns [dict], newer returns dict.
    if isinstance(cost, (list, tuple)):
        cost = cost[0] if cost else {}
    if not isinstance(cost, dict):
        return None
    flops = cost.get("flops")
    if flops is None or flops <= 0:
        return None
    return float(flops)


def mfu(flops_per_sec: Optional[float], device) -> Optional[float]:
    """Achieved-FLOP/s / chip-peak, or None when either side is unknown."""
    peak = chip_peak_flops(device)
    if peak is None or flops_per_sec is None:
        return None
    return flops_per_sec / peak


def nature_cnn_fwd_flops(batch: float, hidden: int = 512,
                         num_actions: int = 0) -> float:
    """Analytic forward FLOPs (2*MACs) of the Nature CNN torso on 84x84x4
    frames: VALID convs 8x8/4, 4x4/2, 3x3/1, then the fc to ``hidden``.
    ``num_actions`` > 0 adds the Q head (the recurrent net's head hangs
    off the LSTM instead — pass 0 there). Cross-checked against the XLA
    op census in tests/test_flops.py."""
    macs = (20 * 20 * 8 * 8 * 4 * 32        # conv1 -> [20,20,32]
            + 9 * 9 * 4 * 4 * 32 * 64       # conv2 -> [9,9,64]
            + 7 * 7 * 3 * 3 * 64 * 64       # conv3 -> [7,7,64]
            + 3136 * hidden                 # fc
            + hidden * num_actions)         # head (feedforward nets only)
    return 2.0 * macs * batch


def lstm_cell_fwd_flops(batch: float, features: int, hidden: int) -> float:
    """Analytic forward FLOPs of one LSTM cell step: the [B, F+H] x
    [F+H, 4H] gate matmul, 2 FLOPs per MAC (elementwise gate math is
    noise next to it)."""
    return 2.0 * batch * (features + hidden) * 4.0 * hidden


def r2d2_grad_step_flops(T: int, B: int, *, hidden: int = 512,
                         lstm: int = 512, remat: bool = True) -> dict:
    """Analytic FLOPs of one R2D2 grad step (agents/r2d2.py), split into
    the terms the throughput knobs act on.

    Accounting (matches the program structure in models/recurrent.py —
    the torso embeds all T*B frames in ONE batched conv outside the time
    scan; only the cell recurrence is scanned):
      torso: online fwd + target fwd + backward (~2x fwd) over T*B frames,
             plus one recompute fwd under remat;
      cell:  online fwd + target fwd + backward (~2x fwd) over T steps.

    This analytic count exists because the XLA op census CANNOT measure
    this program: cost analysis counts a scan body once regardless of
    trip count (see compiled_flops). tests/test_flops.py pins the model
    against an EXACT census of a tiny fully-unrolled variant
    (lstm_unroll >= T emits straight-line code, no loop).
    """
    frames = float(T) * B
    torso_passes = 4.0 + (1.0 if remat else 0.0)
    torso = torso_passes * nature_cnn_fwd_flops(frames, hidden=hidden)
    cell = 4.0 * lstm_cell_fwd_flops(frames, hidden, lstm)
    return {"torso": torso, "cell": cell, "total": torso + cell}


def r2d2_time_model(T: int, B: int, *, hidden: int = 512, lstm: int = 512,
                    remat: bool = True, lstm_bf16: bool = False,
                    unroll: int = 1, peak_bf16: float = 197e12,
                    f32_matmul_slowdown: float = 3.0,
                    scan_iter_overhead_s: float = 2e-6) -> dict:
    """Modeled seconds per R2D2 grad step as a function of the three
    throughput knobs (VERDICT round 2, next #6 — model-level evidence
    while the TPU tunnel blocks the real sweep).

    Terms: torso FLOPs at bf16 peak (the torso always computes in
    ``compute_dtype`` bf16); cell FLOPs at bf16 peak or at peak /
    ``f32_matmul_slowdown`` (XLA emulates an f32 matmul on the MXU with
    ~3 bf16 passes); plus per-scan-iteration overhead for the three time
    loops (online fwd, target fwd, backward), each ceil(T/unroll)
    iterations. ``remat`` adds torso FLOPs — it is an HBM knob, modeled
    here only on the FLOPs side.
    """
    import math

    f = r2d2_grad_step_flops(T, B, hidden=hidden, lstm=lstm, remat=remat)
    cell_rate = peak_bf16 if lstm_bf16 else peak_bf16 / f32_matmul_slowdown
    iters = math.ceil(T / max(unroll, 1))
    overhead = 3.0 * iters * scan_iter_overhead_s
    torso_s = f["torso"] / peak_bf16
    cell_s = f["cell"] / cell_rate
    return {"torso_s": torso_s, "cell_s": cell_s, "scan_overhead_s": overhead,
            "total_s": torso_s + cell_s + overhead,
            "modeled_grad_steps_per_sec":
                1.0 / (torso_s + cell_s + overhead)}


def mfu_fields(flops_per_exec: Optional[float], execs: int, dt: float,
               device) -> dict:
    """The benchmark-JSON fields derived from a timed run of a compiled
    program: {} when FLOPs are unavailable, model_flops_per_sec always
    otherwise, mfu only when the chip peak is known."""
    if flops_per_exec is None or dt <= 0:
        return {}
    flops_per_sec = flops_per_exec * execs / dt
    out = {"model_flops_per_sec": round(flops_per_sec, 1)}
    m = mfu(flops_per_sec, device)
    if m is not None:
        out["mfu"] = round(m, 4)
    return out
