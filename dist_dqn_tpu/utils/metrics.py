"""First-class throughput metrics (BASELINE.json:2).

env-steps/sec/chip and learner grad-steps/sec are the framework's north-star
numbers, so they get a dedicated, dependency-free implementation used by the
train CLI, the Ape-X runtime and bench.py alike.

Since ISSUE 1 the logger is a registry client: every flush mirrors the
rates and extras into the process telemetry registry (telemetry/), so the
same numbers that land on the JSON-line stream are scrapeable from the
/metrics endpoint and captured in registry snapshots — one naming scheme,
one flush lifecycle.
"""
from __future__ import annotations

import json
import re
import time
from typing import Dict, Optional

from dist_dqn_tpu import telemetry


class RateTracker:
    """Windowed rate estimator for a monotonically increasing counter."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = window_s
        self._events = []  # (t, count) pairs

    def update(self, count: float, now: Optional[float] = None) -> None:
        now = time.perf_counter() if now is None else now
        self._events.append((now, count))
        cutoff = now - self.window_s
        while len(self._events) > 2 and self._events[0][0] < cutoff:
            self._events.pop(0)

    def rate(self, now: Optional[float] = None) -> float:
        """Events/sec over the window — 0 once the window has gone quiet.

        A tracker whose updates STOPPED must not report its last computed
        rate forever (the stale-rate bug, ISSUE 1 satellite): with no
        event inside the last ``window_s``, the honest windowed rate is
        0, the same value a fresh tracker reports.
        """
        if len(self._events) < 2:
            return 0.0
        now = time.perf_counter() if now is None else now
        (t0, c0), (t1, c1) = self._events[0], self._events[-1]
        if now - t1 >= self.window_s:
            return 0.0
        return (c1 - c0) / max(t1 - t0, 1e-9)


def _metric_name(key: str) -> str:
    """JSON-row key -> registry family name (``dqn_`` + sanitized key)."""
    return "dqn_" + re.sub(r"[^a-zA-Z0-9_]", "_", key)


class MetricLogger:
    """Accumulates scalar metrics; emits one JSON line per flush.

    Every flush also mirrors the row into ``registry`` (the process
    default unless one is passed): the two rates become
    ``dqn_env_steps_per_sec`` / ``dqn_grad_steps_per_sec`` gauges and
    each extra becomes ``dqn_<key>`` — so scrapers see exactly what the
    log stream sees.
    """

    def __init__(self, log_fn=print, num_chips: int = 1, registry=None):
        self.log_fn = log_fn
        self.num_chips = max(num_chips, 1)
        self.env_steps = RateTracker()
        self.grad_steps = RateTracker()
        self._extra: Dict[str, float] = {}
        self.registry = (registry if registry is not None
                         else telemetry.get_registry())
        self._g_env_rate = self.registry.gauge(
            "dqn_env_steps_per_sec", "windowed env-steps/sec (all chips)")
        self._g_env_rate_chip = self.registry.gauge(
            "dqn_env_steps_per_sec_per_chip",
            "windowed env-steps/sec/chip (north-star, BASELINE.json:2)")
        self._g_grad_rate = self.registry.gauge(
            "dqn_grad_steps_per_sec", "windowed learner grad-steps/sec")
        self._extra_gauges: Dict[str, object] = {}

    def record(self, env_steps: Optional[float] = None,
               grad_steps: Optional[float] = None,
               **extra: float) -> None:
        now = time.perf_counter()
        if env_steps is not None:
            self.env_steps.update(env_steps, now)
        if grad_steps is not None:
            self.grad_steps.update(grad_steps, now)
        self._extra.update(extra)

    def _mirror_extra(self, key: str, value) -> None:
        g = self._extra_gauges.get(key)
        if g is None:
            try:
                g = self.registry.gauge(_metric_name(key),
                                        f"mirrored log field {key!r}")
            except ValueError:
                # The sanitized name collides with an existing non-gauge
                # family (a collector's counter/histogram already owns
                # it): that instrument is the canonical series — the
                # mirror stands down permanently for this key instead of
                # crashing the flush.
                g = False
            self._extra_gauges[key] = g
        if g is False:
            return
        try:
            g.set(float(value))
        except (TypeError, ValueError):
            pass  # non-numeric extras stay log-only

    def flush(self) -> Dict[str, float]:
        """Emit one JSON row: the rates plus extras recorded SINCE the last
        flush (one-shot values like eval_return must not go stale-sticky
        into every later throughput row)."""
        env_rate = self.env_steps.rate()
        grad_rate = self.grad_steps.rate()
        row = {
            "env_steps_per_sec_per_chip":
                round(env_rate / self.num_chips, 2),
            "grad_steps_per_sec": round(grad_rate, 2),
        }
        row.update({k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in self._extra.items()})
        self._g_env_rate.set(env_rate)
        self._g_env_rate_chip.set(env_rate / self.num_chips)
        self._g_grad_rate.set(grad_rate)
        for k, v in self._extra.items():
            self._mirror_extra(k, v)
        self._extra.clear()
        self.log_fn(json.dumps(row))
        return row
