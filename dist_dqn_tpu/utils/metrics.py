"""First-class throughput metrics (BASELINE.json:2).

env-steps/sec/chip and learner grad-steps/sec are the framework's north-star
numbers, so they get a dedicated, dependency-free implementation used by the
train CLI, the Ape-X runtime and bench.py alike.
"""
from __future__ import annotations

import json
import time
from typing import Dict, Optional


class RateTracker:
    """Windowed rate estimator for a monotonically increasing counter."""

    def __init__(self, window_s: float = 30.0):
        self.window_s = window_s
        self._events = []  # (t, count) pairs

    def update(self, count: float, now: Optional[float] = None) -> None:
        now = time.perf_counter() if now is None else now
        self._events.append((now, count))
        cutoff = now - self.window_s
        while len(self._events) > 2 and self._events[0][0] < cutoff:
            self._events.pop(0)

    def rate(self) -> float:
        if len(self._events) < 2:
            return 0.0
        (t0, c0), (t1, c1) = self._events[0], self._events[-1]
        return (c1 - c0) / max(t1 - t0, 1e-9)


class MetricLogger:
    """Accumulates scalar metrics; emits one JSON line per flush."""

    def __init__(self, log_fn=print, num_chips: int = 1):
        self.log_fn = log_fn
        self.num_chips = max(num_chips, 1)
        self.env_steps = RateTracker()
        self.grad_steps = RateTracker()
        self._extra: Dict[str, float] = {}

    def record(self, env_steps: Optional[float] = None,
               grad_steps: Optional[float] = None,
               **extra: float) -> None:
        now = time.perf_counter()
        if env_steps is not None:
            self.env_steps.update(env_steps, now)
        if grad_steps is not None:
            self.grad_steps.update(grad_steps, now)
        self._extra.update(extra)

    def flush(self) -> Dict[str, float]:
        """Emit one JSON row: the rates plus extras recorded SINCE the last
        flush (one-shot values like eval_return must not go stale-sticky
        into every later throughput row)."""
        row = {
            "env_steps_per_sec_per_chip":
                round(self.env_steps.rate() / self.num_chips, 2),
            "grad_steps_per_sec": round(self.grad_steps.rate(), 2),
        }
        row.update({k: (round(v, 4) if isinstance(v, float) else v)
                    for k, v in self._extra.items()})
        self._extra.clear()
        self.log_fn(json.dumps(row))
        return row
