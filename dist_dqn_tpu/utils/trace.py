"""Host-side structured tracing (SURVEY.md §5 aux subsystems).

The device program is profiled with ``jax.profiler`` (train.py
--profile-dir); this module covers the other half of the system — the
learner service's HOST loop (actors/service.py), where Ape-X throughput is
won or lost: record ingestion, trajectory assembly, priority bootstraps,
replay sampling, train-step dispatch. ``SpanTracer`` records wall-clock
spans/instants/counters with ~µs overhead per event (a perf_counter_ns and
a tuple append; serialization happens at flush) and writes the Chrome
trace-event format, so traces open in chrome://tracing or Perfetto next to
the xprof device timeline.

A ``NullTracer`` with the same surface is the disabled path — call sites
never branch.
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import List, Optional, Tuple


class NullTracer:
    """No-op twin of SpanTracer (the default when tracing is off)."""

    enabled = False

    @contextmanager
    def span(self, name: str, **args):
        yield

    def instant(self, name: str, **args) -> None:
        pass

    def counter(self, name: str, value: float) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class SpanTracer(NullTracer):
    """Chrome trace-event recorder for one host process.

    Events buffer in memory as tuples and serialize on ``flush()`` /
    ``close()`` — the hot path never touches JSON or the filesystem.
    Thread-safe appends (the TCP drain thread traces too); each event
    carries its thread id so Perfetto lays concurrent work out per track.
    """

    enabled = True

    def __init__(self, path: str, process_name: str = "dist_dqn_tpu"):
        self.path = path
        self.process_name = process_name
        self._events: List[Tuple] = []
        self._lock = threading.Lock()
        self._pid = os.getpid()
        self._t0 = time.perf_counter_ns()
        self._started = False
        self._closed = False

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    @contextmanager
    def span(self, name: str, **args):
        start = self._now_us()
        try:
            yield
        finally:
            end = self._now_us()
            with self._lock:
                self._events.append(
                    ("X", name, start, end - start,
                     threading.get_ident(), args or None))

    def instant(self, name: str, **args) -> None:
        with self._lock:
            self._events.append(("i", name, self._now_us(), 0.0,
                                 threading.get_ident(), args or None))

    def counter(self, name: str, value: float) -> None:
        with self._lock:
            self._events.append(("C", name, self._now_us(), float(value),
                                 threading.get_ident(), None))

    def flush(self) -> None:
        """Append buffered events to ``path`` and clear the buffer.

        The file is the trace-event JSON-array format, streamed: each flush
        writes only the NEW events (O(new), bounded memory over long runs);
        ``close()`` terminates the array. The format spec allows a missing
        terminator, so a trace from a crashed run still loads in Perfetto.
        """
        with self._lock:
            if self._closed:
                return
            events = self._events
            self._events = []
            first = not self._started
            self._started = True
        lines = []
        if first:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            lines.append("[\n" + json.dumps(
                {"name": "process_name", "ph": "M", "pid": self._pid,
                 "args": {"name": self.process_name}}))
        for ph, name, ts, extra, tid, args in events:
            ev = {"name": name, "ph": ph, "ts": ts, "pid": self._pid,
                  "tid": tid}
            if ph == "X":
                ev["dur"] = extra
            elif ph == "C":
                ev["args"] = {"value": extra}
            elif ph == "i":
                ev["s"] = "t"
            if args:
                ev["args"] = {**ev.get("args", {}), **args}
            lines.append(json.dumps(ev))
        if not lines:
            return
        mode = "w" if first else "a"
        with open(self.path, mode) as f:
            f.write(",\n".join(lines) if first
                    else ",\n" + ",\n".join(lines))

    def close(self) -> None:
        self.flush()
        with self._lock:
            if self._closed or not self._started:
                self._closed = True
                return
            self._closed = True
        with open(self.path, "a") as f:
            f.write("\n]\n")


def make_tracer(trace_path: Optional[str],
                process_name: str = "dist_dqn_tpu"):
    """Tracer factory: a real SpanTracer when a path is given, else the
    no-op twin."""
    if trace_path:
        return SpanTracer(trace_path, process_name=process_name)
    return NullTracer()
