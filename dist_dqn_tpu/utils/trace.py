"""Host-side structured tracing (SURVEY.md §5 aux subsystems).

The device program is profiled with ``jax.profiler`` (train.py
--profile-dir); this module covers the other half of the system — the
learner service's HOST loop (actors/service.py), where Ape-X throughput is
won or lost: record ingestion, trajectory assembly, priority bootstraps,
replay sampling, train-step dispatch. ``SpanTracer`` records wall-clock
spans/instants/counters with ~µs overhead per event (a perf_counter_ns and
a tuple append; serialization happens at flush) and writes the Chrome
trace-event format, so traces open in chrome://tracing or Perfetto next to
the xprof device timeline.

A ``NullTracer`` with the same surface is the disabled path — call sites
never branch.

Registry integration (ISSUE 1): a live ``SpanTracer`` mirrors its events
into the process telemetry registry — span durations feed the
``dqn_host_span_seconds`` histogram family (one labeled series per span
name), trace counters the ``dqn_trace_counter`` gauge family — so the
Chrome trace and the /metrics endpoint tell one consistent story. Flush
is registered on the shared exit lifecycle (telemetry/lifecycle.py):
traces from atexit'd or SIGTERM'd processes keep every flushed-plus-
buffered event instead of silently losing the tail.

Flight-recorder integration (ISSUE 4): the same span call sites feed the
process flight ring (telemetry/flight.py) — with a trace path, the full
``SpanTracer`` mirrors every event there too; WITHOUT one,
``make_tracer`` now hands back a ``FlightTracer`` (ring-only, no file,
no per-event serialization) instead of the inert ``NullTracer``, so a
hung service's forensics bundle carries its last ~thousand host-loop
events even when nobody asked for a Chrome trace up front. The
``NullTracer`` remains the true zero path (``--no-flight-recorder``).
"""
from __future__ import annotations

import json
import os
import threading
import time
from contextlib import contextmanager
from typing import Dict, List, Optional, Tuple

from dist_dqn_tpu import telemetry
from dist_dqn_tpu.telemetry import lifecycle

#: Span-duration histogram buckets: host-loop spans run ~10µs (ring pop)
#: to whole seconds (first jit compile under a span, checkpoint writes).
SPAN_BUCKETS = (1e-5, 2.5e-5, 5e-5, 1e-4, 2.5e-4, 5e-4, 1e-3, 2.5e-3,
                5e-3, 1e-2, 2.5e-2, 5e-2, 0.1, 0.25, 0.5, 1.0, 5.0, 30.0)


class NullTracer:
    """No-op twin of SpanTracer (the default when tracing is off)."""

    enabled = False

    @contextmanager
    def span(self, name: str, **args):
        yield

    def instant(self, name: str, **args) -> None:
        pass

    def counter(self, name: str, value: float) -> None:
        pass

    def flush(self) -> None:
        pass

    def close(self) -> None:
        pass


class FlightTracer(NullTracer):
    """Span surface that records ONLY into the flight-recorder ring.

    The default tracer when Chrome tracing is off but the flight
    recorder is on: one ``record()`` per span close / instant / counter
    (~1µs), no buffering, no file. ``enabled`` stays False — callers
    that gate EXPENSIVE argument computation on ``tracer.enabled`` keep
    skipping it; the ring gets the cheap events.
    """

    def __init__(self, flight=None):
        self._flight = (flight if flight is not None
                        else telemetry.get_flight())

    @contextmanager
    def span(self, name: str, **args):
        start = time.perf_counter()
        try:
            yield
        finally:
            self._flight.record(
                "span", name,
                dur_s=round(time.perf_counter() - start, 6),
                **args)

    def instant(self, name: str, **args) -> None:
        self._flight.record("instant", name, **args)

    def counter(self, name: str, value: float) -> None:
        self._flight.record("counter", name, value=float(value))


class SpanTracer(NullTracer):
    """Chrome trace-event recorder for one host process.

    Events buffer in memory as tuples and serialize on ``flush()`` /
    ``close()`` — the hot path never touches JSON or the filesystem.
    Thread-safe appends (the TCP drain thread traces too); each event
    carries its thread id so Perfetto lays concurrent work out per track.
    """

    enabled = True

    def __init__(self, path: str, process_name: str = "dist_dqn_tpu",
                 registry=None):
        self.path = path
        self.process_name = process_name
        self._events: List[Tuple] = []
        # Reentrant: the SIGTERM exit flush runs on the main thread and
        # can land while an interrupted frame holds this lock mid-append
        # (telemetry/lifecycle.py) — a plain Lock would deadlock there.
        self._lock = threading.RLock()
        self._pid = os.getpid()
        self._t0 = time.perf_counter_ns()
        self._started = False
        self._closed = False
        self.registry = (registry if registry is not None
                         else telemetry.get_registry())
        self._flight = telemetry.get_flight()
        self._span_hists: Dict[str, object] = {}
        self._counter_gauges: Dict[str, object] = {}
        # Shared flush lifecycle: a SIGTERM'd/atexit'd process keeps its
        # buffered events (the format tolerates a missing terminator).
        lifecycle.on_exit(self.flush)

    def _now_us(self) -> float:
        return (time.perf_counter_ns() - self._t0) / 1e3

    def _span_hist(self, name: str):
        h = self._span_hists.get(name)
        if h is None:
            h = self.registry.histogram(
                "dqn_host_span_seconds", "host-loop span durations",
                labels={"span": name}, buckets=SPAN_BUCKETS)
            self._span_hists[name] = h
        return h

    @contextmanager
    def span(self, name: str, **args):
        start = self._now_us()
        try:
            yield
        finally:
            end = self._now_us()
            with self._lock:
                self._events.append(
                    ("X", name, start, end - start,
                     threading.get_ident(), args or None))
            self._span_hist(name).observe((end - start) / 1e6)
            self._flight.record("span", name,
                                dur_s=round((end - start) / 1e6, 6),
                                **(args or {}))

    def instant(self, name: str, **args) -> None:
        with self._lock:
            self._events.append(("i", name, self._now_us(), 0.0,
                                 threading.get_ident(), args or None))
        self._flight.record("instant", name, **args)

    def counter(self, name: str, value: float) -> None:
        with self._lock:
            self._events.append(("C", name, self._now_us(), float(value),
                                 threading.get_ident(), None))
        g = self._counter_gauges.get(name)
        if g is None:
            g = self.registry.gauge("dqn_trace_counter",
                                    "trace counter-track values",
                                    labels={"counter": name})
            self._counter_gauges[name] = g
        g.set(value)

    def flush(self) -> None:
        """Append buffered events to ``path`` and clear the buffer.

        The file is the trace-event JSON-array format, streamed: each flush
        writes only the NEW events (O(new), bounded memory over long runs);
        ``close()`` terminates the array. The format spec allows a missing
        terminator, so a trace from a crashed run still loads in Perfetto.
        """
        with self._lock:
            if self._closed:
                return
            events = self._events
            self._events = []
            first = not self._started
            self._started = True
        lines = []
        if first:
            os.makedirs(os.path.dirname(self.path) or ".", exist_ok=True)
            lines.append("[\n" + json.dumps(
                {"name": "process_name", "ph": "M", "pid": self._pid,
                 "args": {"name": self.process_name}}))
        for ph, name, ts, extra, tid, args in events:
            ev = {"name": name, "ph": ph, "ts": ts, "pid": self._pid,
                  "tid": tid}
            if ph == "X":
                ev["dur"] = extra
            elif ph == "C":
                ev["args"] = {"value": extra}
            elif ph == "i":
                ev["s"] = "t"
            if args:
                ev["args"] = {**ev.get("args", {}), **args}
            lines.append(json.dumps(ev))
        if not lines:
            return
        mode = "w" if first else "a"
        with open(self.path, mode) as f:
            f.write(",\n".join(lines) if first
                    else ",\n" + ",\n".join(lines))

    def close(self) -> None:
        self.flush()
        # A closed tracer no longer needs the exit-flush hook; dropping
        # it releases this tracer for GC in long-lived processes that
        # construct many tracers (sweeps, test suites).
        lifecycle.off_exit(self.flush)
        with self._lock:
            if self._closed or not self._started:
                self._closed = True
                return
            self._closed = True
        with open(self.path, "a") as f:
            f.write("\n]\n")


def make_tracer(trace_path: Optional[str],
                process_name: str = "dist_dqn_tpu"):
    """Tracer factory: a real SpanTracer when a path is given; the
    flight-ring-only tracer when the flight recorder is on (the default
    — ISSUE 4); the inert twin when both are off."""
    if trace_path:
        return SpanTracer(trace_path, process_name=process_name)
    flight = telemetry.get_flight()
    if flight.enabled:
        return FlightTracer(flight)
    return NullTracer()
