"""Buffer-donation / aliasing audit for the jitted chunk programs
(ISSUE 6).

The fused loops donate GB-sized carries (replay ring + learner state)
into every chunk dispatch so XLA updates HBM in place; a silently
dropped ``donate_argnums`` — or a carry leaf XLA cannot alias (dtype
change, layout mismatch, an accidental second use of the donated
value) — doubles the program's working set and shows up only as an OOM
on a chip that used to fit. These helpers read the evidence straight
from the ``jax.stages.Compiled`` artifact:

* the HLO entry module's ``input_output_alias`` table — one entry per
  donated buffer XLA actually honored (``may-alias``/``must-alias``);
* ``Compiled.memory_analysis()`` — ``alias_size_in_bytes`` (bytes the
  donation saved) vs ``argument`` / ``output`` / ``temp`` bytes.

``assert_donation`` is the audit entry point: compile the program as
the loop dispatches it, then require the alias table to cover the
donated bytes. tests/test_replay_ratio.py pins the fused chunk and the
host-replay collect through it; scripts/check_donation.py is the
static sibling (every jitted train/collect entry point must declare
``donate_argnums`` or carry a donation rationale).
"""
from __future__ import annotations

import re
from typing import Optional

#: One token per honored alias entry in the HLO module header
#: (``{0}: (0, {}, may-alias)`` / ``must-alias``). The table appears
#: only on the entry module line, so a whole-text count is exact.
_ALIAS_TOKEN = re.compile(r"(?:must|may)-alias")


def aliased_pairs(compiled) -> Optional[int]:
    """Input->output alias entries XLA committed to for a compiled
    program, or None when the backend exposes no HLO text."""
    try:
        txt = compiled.as_text()
    except Exception:
        return None
    if txt is None or "input_output_alias" not in txt:
        return 0
    return len(_ALIAS_TOKEN.findall(txt))


def donation_report(compiled) -> dict:
    """The aliasing evidence for one compiled program.

    Keys: ``aliased_pairs`` (None when HLO text is unavailable) plus,
    when ``memory_analysis`` works on this backend, ``argument_bytes``,
    ``output_bytes``, ``alias_bytes`` (donation savings) and
    ``temp_bytes`` (scratch the program still allocates).
    """
    out = {"aliased_pairs": aliased_pairs(compiled)}
    try:
        ma = compiled.memory_analysis()
    except Exception:
        ma = None
    if ma is not None:
        for key, attr in (("argument_bytes", "argument_size_in_bytes"),
                          ("output_bytes", "output_size_in_bytes"),
                          ("alias_bytes", "alias_size_in_bytes"),
                          ("temp_bytes", "temp_size_in_bytes")):
            v = getattr(ma, attr, None)
            if v is not None:
                out[key] = int(v)
    return out


def assert_donation(compiled, min_aliased_pairs: int = 1,
                    min_alias_bytes: int = 0, what: str = "program"
                    ) -> dict:
    """Require a compiled program's donation to have been honored.

    ``min_aliased_pairs`` is the floor on alias-table entries (e.g. the
    number of large carry leaves that must update in place);
    ``min_alias_bytes`` the floor on bytes saved (e.g. the replay
    ring's nbytes — the canonical "no unintended device copy" check).
    Returns the report; raises AssertionError naming the deficit.
    Backends that expose neither HLO text nor a memory analysis pass
    vacuously (the static lint still covers the call sites).
    """
    rep = donation_report(compiled)
    pairs = rep.get("aliased_pairs")
    if pairs is not None and pairs < min_aliased_pairs:
        raise AssertionError(
            f"{what}: only {pairs} input->output aliased buffers "
            f"(expected >= {min_aliased_pairs}) — a donated carry leaf "
            "is being copied instead of updated in place "
            f"(report: {rep})")
    alias_bytes = rep.get("alias_bytes")
    if min_alias_bytes and alias_bytes is not None \
            and alias_bytes < min_alias_bytes:
        raise AssertionError(
            f"{what}: donation saves {alias_bytes} bytes, expected >= "
            f"{min_alias_bytes} — the large carry buffers (replay "
            f"ring / learner state) are not aliased (report: {rep})")
    return rep
