"""Pre-flight sizing gate for on-device runs (VERDICT round-3 ask #1b).

All three tunnel-wedging incidents on this box (2026-07-30/31, recorded
in ``.claude/skills/verify/SKILL.md``) share one root cause: a device
job was started whose wall time exceeded the budget that would
eventually kill it — an external ``timeout`` SIGTERM (incidents #1, #2)
or the bench's own internal watchdog (incident #3) — and the kill landed
mid-device-op, wedging the pool-side tunnel grant for hours. A cleanup
handler cannot save a process that is *blocked inside* a device RPC, so
the only real protection is refusing to START jobs that could need
killing. This module predicts the wall time of a run from analytic
FLOPs (``utils/flops.py``), the measured per-env-step bandwidth cost,
and the measured ~65-70 ms tunnel dispatch constant, then refuses
configs whose prediction approaches the caller's kill budget — plus two
hard envelope rules distilled from the incidents:

* sizes **proven oversized** by a measured failure are refused outright
  (2048 lanes x batch 1024 timed out the 450 s watchdog on v5e and
  wedged the tunnel — incident #3);
* sizes **more than 2x any proven-safe size** are refused as unproven
  (the incident-#3 rule: 1024 lanes succeeded, 2048 killed the window).

Both refusals honor an explicit override (``BENCH_ALLOW_UNPROVEN=1``)
so a deliberately-risked probe is still possible — LAST in a window,
never while a driver capture is owed.

Calibration anchors (measured, ``docs/tpu_runs/`` 2026-07-31, v5e):

* fused-loop per-iteration wall: 1.00 ms @ 512 lanes, 1.80 ms @ 1024
  lanes (510k / 569k env-steps/s => ~1.8 us/env-step); the gate charges
  a conservative 3 us/env-step.
* tunnel dispatch constant: 62-70 ms/call (recovered ``dispatch_s`` in
  ``sampler_bench_marginal.jsonl``); the gate charges 80 ms/dispatch.
* compile: the fused program builds in ~60-90 s on this box; the gate
  budgets 150 s for bench.py's two compiles (fused chunk + the
  standalone MFU-census step).
* learner achieved compute: the lowest measured learner MFU is 1.6 %
  of the 197 TFLOP/s bf16 peak (qrdqn); the gate assumes 3 TFLOP/s
  achieved so FLOPs-heavy configs are charged honestly.
"""
from __future__ import annotations

import dataclasses
import os
from typing import Optional

from dist_dqn_tpu.utils import flops as flops_util

# Measured anchors (module docstring) with conservative safety margins.
DISPATCH_S = 0.080          # per host->device call round-trip (measured 62-70 ms)
ENV_STEP_S = 3e-6           # per env-step wall cost in the fused loop (measured ~1.8 us)
ACHIEVED_FLOPS = 3e12       # learner FLOP/s actually achieved (lowest measured: 3.2e12)
COMPILE_BUDGET_S = 150.0    # fused chunk + census step, first build
BUDGET_FRACTION = 0.6       # predicted time must fit in this fraction of the kill budget

# Envelope rules (v5e, incident #3). "Proven safe" = the largest sizes
# that completed a measured run on this box's chip; update when a larger
# size completes cleanly. ring=200_000: the atari preset's full ring
# trained clean under merged-row flat storage (2026-08-01, rc=0).
# ring_dedup: frame-dedup rings carry 1/stack the bytes per slot, but
# every slot-COUNT-scaled cost (PER priority plane, cumsum/stratified
# sampler, gather index math) is unchanged — so dedup rings get their
# OWN measured transition-count anchor (the 1M-slot dedup Breakout
# window trained clean for 3000 s, docs/tpu_runs/20260801_2300_dedup/
# breakout_c51_1M_window*.jsonl), not the stacked bound divided by the
# stack (ADVICE r5: dividing admitted 4x more slots than proven).
# Bytes stay separately gated by predict_fused_hbm_bytes, which already
# models dedup storage.
PROVEN_SAFE = {"num_envs": 1024, "batch_size": 512, "ring": 200_000,
               "ring_dedup": 1_048_576}
# Measured failures: configs at or beyond these sizes died mid-window.
KNOWN_BAD = {"num_envs": 2048}

OVERRIDE_ENV = "BENCH_ALLOW_UNPROVEN"

# HBM model (v5e, calibrated on the two 2026-08-01 compile OOMs and the
# successful flat-200k run). XLA's layout padding on the ring buffer:
# tiled multi-dim u8 pads ~1.6x (84x84 at (8,128) tiles); the 2-D
# merged-row flat layout pads <2%. The compiler's accounting kept ~2
# copies of the ring live in both OOMs (donation alias not elided at
# the failure point), so the gate charges ring x2 plus a measured
# ~1.5G program residue (CNN params/activations/env lanes).
HBM_CAPACITY_BYTES = 15.75e9
HBM_REFUSE_BYTES = 15.0e9
RING_PAD_TILED = 1.6
RING_PAD_FLAT = 1.02
FLAT_AUTO_BYTES = float(2 << 30)   # mirror train_loop's auto rule
PROGRAM_RESIDUE_BYTES = 1.5e9


def predict_fused_hbm_bytes(*, ring: int, pixel_obs: bool = True,
                            obs_elems: int = 84 * 84 * 4,
                            obs_itemsize: int = 1,
                            store_final_obs: bool = False,
                            flat_storage: Optional[bool] = None,
                            frame_dedup_stack: int = 0) -> float:
    """Conservative HBM footprint of a fused-loop device program.

    ``ring`` is the TOTAL capacity in transitions (the config knob, not
    per-lane slots). The flat/tiled padding factor mirrors
    train_loop.py's ``replay.flat_storage`` auto rule so the prediction
    matches what the program will actually allocate.
    ``frame_dedup_stack`` > 0 models ``replay.frame_dedup``: each stored
    transition holds one frame instead of the whole stack.
    """
    if not pixel_obs:
        return PROGRAM_RESIDUE_BYTES
    if frame_dedup_stack:
        obs_elems //= frame_dedup_stack
    logical = float(ring) * obs_elems * obs_itemsize
    if store_final_obs:
        logical *= 2
    flat = (flat_storage if flat_storage is not None
            else bool(frame_dedup_stack) or logical > FLAT_AUTO_BYTES)
    padded = logical * (RING_PAD_FLAT if flat else RING_PAD_TILED)
    return padded * 2 + PROGRAM_RESIDUE_BYTES


@dataclasses.dataclass(frozen=True)
class SizingVerdict:
    ok: bool
    predicted_s: float
    budget_s: float
    reason: str

    def as_fields(self) -> dict:
        return {"sizing_predicted_s": round(self.predicted_s, 1),
                "sizing_budget_s": round(self.budget_s, 1)}


def _override_active() -> bool:
    return os.environ.get(OVERRIDE_ENV) == "1"


def grad_step_flops_estimate(batch_size: int, num_actions: int = 6,
                             pixel_obs: bool = True) -> float:
    """Analytic FLOPs of one grad step, for sizing only (pre-compile, so
    no XLA census is available). fwd+bwd ~ 3x forward, plus the target
    forward = 4x; non-pixel nets are MLPs too small to matter."""
    if not pixel_obs:
        return 0.0
    return 4.0 * flops_util.nature_cnn_fwd_flops(batch_size,
                                                 num_actions=num_actions)


def predict_fused_seconds(*, num_envs: int, batch_size: int,
                          train_every: int, chunk_iters: int,
                          num_chunks: int, num_evals: int = 0,
                          eval_iters: int = 0, pixel_obs: bool = True,
                          num_actions: int = 6,
                          compile_s: float = COMPILE_BUDGET_S) -> float:
    """Conservative wall-time prediction for a fused-loop device run.

    Terms: compile budget + per-chunk dispatch + env-step bandwidth cost
    + learner FLOPs at the conservative achieved rate + eval episodes
    (each eval is one dispatch plus its own env-step cost).
    """
    env_steps = float(num_chunks) * chunk_iters * num_envs
    grad_steps = float(num_chunks) * chunk_iters / max(train_every, 1)
    flops = grad_steps * grad_step_flops_estimate(batch_size, num_actions,
                                                  pixel_obs)
    eval_s = num_evals * (DISPATCH_S + eval_iters * ENV_STEP_S)
    return (compile_s
            + num_chunks * DISPATCH_S
            + env_steps * ENV_STEP_S
            + flops / ACHIEVED_FLOPS
            + eval_s)


def check_envelope(*, num_envs: int, batch_size: int,
                   ring: Optional[int] = None,
                   pixel_obs: bool = True,
                   frame_dedup_stack: int = 0) -> Optional[str]:
    """Hard size rules from measured incidents; None when inside the
    envelope, else the refusal reason. Override: BENCH_ALLOW_UNPROVEN=1.

    The envelope is calibrated on the pixel (84x84x4) configs where all
    three incidents happened; vector-obs runs are orders of magnitude
    smaller per lane/slot and rely on the time model alone."""
    if _override_active() or not pixel_obs:
        return None
    if num_envs >= KNOWN_BAD["num_envs"]:
        return (f"num_envs={num_envs} is PROVEN OVERSIZED on this chip "
                f"(>= {KNOWN_BAD['num_envs']} timed out the watchdog and "
                f"wedged the tunnel, incident #3); set {OVERRIDE_ENV}=1 "
                "to deliberately risk it (last in a window, never while "
                "a driver capture is owed)")
    sized = {"num_envs": num_envs, "batch_size": batch_size}
    if ring is not None:
        # Transition COUNTS are what slot-scaled device costs (priority
        # plane, samplers, index math) follow, so the bound stays in
        # counts — dedup rings against their own measured anchor
        # (PROVEN_SAFE["ring_dedup"], the clean 1M-slot dedup window),
        # never the stacked bound divided by the stack. The bytes side
        # of dedup is a separate allowance: predict_fused_hbm_bytes
        # models the 1/stack storage and gates it against HBM.
        sized["ring_dedup" if frame_dedup_stack else "ring"] = ring
    for key, value in sized.items():
        if value > 2 * PROVEN_SAFE[key]:
            return (f"{key}={value} is more than 2x the proven-safe "
                    f"{PROVEN_SAFE[key]} (incident-#3 rule: unproven "
                    f"sizes wedge windows); set {OVERRIDE_ENV}=1 to "
                    "deliberately risk it")
    return None


def gate_fused(*, budget_s: float, num_envs: int, batch_size: int,
               train_every: int, chunk_iters: int, num_chunks: int,
               ring: Optional[int] = None, num_evals: int = 0,
               eval_iters: int = 0, pixel_obs: bool = True,
               num_actions: int = 6,
               compile_s: float = COMPILE_BUDGET_S,
               frame_dedup_stack: int = 0) -> SizingVerdict:
    """Combined envelope + time-prediction gate for a fused device run.

    ``budget_s`` is whatever will kill the process (internal watchdog,
    external ``timeout``); the run must be predicted to finish in
    ``BUDGET_FRACTION`` of it or it is refused before any device work.
    """
    predicted = predict_fused_seconds(
        num_envs=num_envs, batch_size=batch_size, train_every=train_every,
        chunk_iters=chunk_iters, num_chunks=num_chunks, num_evals=num_evals,
        eval_iters=eval_iters, pixel_obs=pixel_obs, num_actions=num_actions,
        compile_s=compile_s)
    envelope = check_envelope(num_envs=num_envs, batch_size=batch_size,
                              ring=ring, pixel_obs=pixel_obs,
                              frame_dedup_stack=frame_dedup_stack)
    if envelope is not None:
        return SizingVerdict(False, predicted, budget_s, envelope)
    if ring is not None and not _override_active():
        hbm = predict_fused_hbm_bytes(ring=ring, pixel_obs=pixel_obs,
                                      frame_dedup_stack=frame_dedup_stack)
        if hbm > HBM_REFUSE_BYTES:
            return SizingVerdict(
                False, predicted, budget_s,
                f"predicted HBM {hbm / 1e9:.1f}G exceeds the "
                f"{HBM_REFUSE_BYTES / 1e9:.1f}G gate (v5e has "
                f"{HBM_CAPACITY_BYTES / 1e9:.2f}G): the ring is too "
                "large for the chip even in the merged-row flat layout "
                "— shrink replay.capacity. (An HBM compile OOM exits "
                "cleanly, but costs a window its compile minutes; "
                f"{OVERRIDE_ENV}=1 to deliberately risk it)")
    limit = BUDGET_FRACTION * budget_s
    if predicted > limit:
        return SizingVerdict(
            False, predicted, budget_s,
            f"predicted {predicted:.0f}s exceeds {BUDGET_FRACTION:.0%} of "
            f"the {budget_s:.0f}s kill budget — shrink the run or raise "
            "the budget; starting a job that will be killed mid-device-op "
            "is how the tunnel wedges (incidents #1-#3)")
    return SizingVerdict(True, predicted, budget_s, "ok")
