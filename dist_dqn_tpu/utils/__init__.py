from dist_dqn_tpu.utils.metrics import RateTracker, MetricLogger  # noqa: F401
