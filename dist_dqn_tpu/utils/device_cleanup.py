"""Device-release hygiene for SIGTERM/exit: don't wedge the shared tunnel.

Round 1 lost half its TPU evidence to one event: a SIGTERM'd device-side
run left the remote (axon) tunnel's pool grant stuck, after which every
``jax.devices()`` on this box hung for hours (BASELINE.md "Measurement
note"). The runtime had no cleanup path at all.

``install()`` registers a SIGTERM handler + atexit hook that drops every
live device buffer, clears JAX's compiled/program caches and asks the
backends to shut down before the process dies, so a politely-terminated
run releases its device grant instead of orphaning it.

Honest limits: a handler only runs when the main thread is executing
Python bytecode — a process SIGTERM'd while *blocked inside* an
uninterruptible device RPC cannot run it (SIGKILL never can). This makes
the polite-kill path safe; un-wedging after a hard kill remains a
pool-operator action (documented in .claude/skills/verify/SKILL.md).

SIGINT is deliberately left alone: Ctrl-C should stay a KeyboardInterrupt
(clean Python unwind through ``finally`` blocks), and the atexit hook
still runs device cleanup on that path.
"""
from __future__ import annotations

import atexit
import os
import signal
import threading

_installed = False
_lock = threading.Lock()


def _release_devices(log_fn=None) -> None:
    """Best-effort device release; every step tolerates a dead backend."""
    import jax

    try:
        for arr in jax.live_arrays():
            try:
                arr.delete()
            except Exception:  # noqa: BLE001 — deleted/donated already
                pass
    except Exception:  # noqa: BLE001
        pass
    try:
        jax.clear_caches()
    except Exception:  # noqa: BLE001
        pass
    try:
        # Tears down backend clients (and with them any pool grants the
        # client protocol releases on close). Present in current jax;
        # guarded because it is not a stable API.
        jax.clear_backends()
    except Exception:  # noqa: BLE001
        pass
    if log_fn is not None:
        try:
            log_fn("# device buffers released")
        except Exception:  # noqa: BLE001
            pass
    # The SIGTERM path ends in os._exit, which discards buffered stdio —
    # flush here so the cleanup notice (and any buffered JSON log lines)
    # survive on block-buffered stdout.
    try:
        import sys

        sys.stdout.flush()
        sys.stderr.flush()
    except Exception:  # noqa: BLE001
        pass


def release(log_fn=None) -> None:
    """Public best-effort device release for watchdog/timeout paths.

    A measurement watchdog that must abandon a stuck run should call this
    (bounded by its own timer — on a truly wedged tunnel the release
    itself can hang) BEFORE its hard exit, so a live-but-slow run gets
    its grant released instead of orphaned (incident #3: the raw
    ``os._exit`` of a watchdog is exactly as mid-device-op as a SIGTERM).
    """
    _release_devices(log_fn)


def install(log_fn=None) -> None:
    """Idempotently register the SIGTERM handler + atexit release hook.

    Call once near the top of any entry point that will touch an
    accelerator (train CLI, apex service, benches).
    """
    global _installed
    with _lock:
        if _installed:
            return
        _installed = True

    atexit.register(_release_devices, log_fn)

    prev = signal.getsignal(signal.SIGTERM)

    def on_term(signum, frame):
        _release_devices(log_fn)
        # Chain a pre-existing Python-level handler; otherwise exit with
        # the conventional fatal-signal status (atexit will not run —
        # cleanup already did).
        if callable(prev) and prev not in (signal.SIG_IGN, signal.SIG_DFL):
            prev(signum, frame)
        else:
            os._exit(128 + signum)

    try:
        signal.signal(signal.SIGTERM, on_term)
    except ValueError:
        # Not the main thread (e.g. installed from a worker): atexit-only.
        pass
