"""Checkpoint/resume via orbax (SURVEY.md §5).

Failure model of the actor/learner architecture: actors are stateless
workers (they re-pull params after a restart), replay refills from live
experience, so the *learner state* — params, target params, optimizer
moments, step counters — is the recovery point. By DEFAULT checkpoints
hold the learner pytree plus the host-side training cursor (env
frames), not the replay ring.

The replay trade-off, quantified (VERDICT round-3 next #7): a 65k-slot
84x84x4 pixel ring is ~1.8 GB vs ~7 MB of Nature-CNN learner state —
~260x the checkpoint bytes. Refill on resume costs
``min_fill / steady-rate`` env steps of training delay: at the fused
loop's measured 569k steps/s/chip that is 4096/569k ~= **7 ms**; even
a full 65k-slot ring re-reaches capacity in ~0.12 s (the apex host
shard's 20k min_fill at the 1-core dev box's ~13k steps/s host rate:
~1.5 s; at a pod's per-host rates, sub-second). What refill does NOT
recover is the ring's *contents* — a resumed run trains on freshly
generated experience, so it is statistically equivalent, not
bit-equal. Runs that need bit-exact resume (debugging, preemption-
heavy pods where distribution continuity matters) opt into
``train(..., checkpoint_replay=True)`` / ``--checkpoint-replay``,
which checkpoints the WHOLE fused carry (ring + env states + rng) at
ring-sized save cost; ``tests/test_checkpoint.py`` pins the bit-equal
resume property. The apex runtime's same flag
(``ApexRuntimeConfig.checkpoint_replay``) snapshots the host replay
shard beside the learner checkpoint (``replay/host.py state_dict``) —
warm-buffer, statistically-continuous resume; the async service is not
bit-replayable by design.

Orbax handles the pytree IO (async-capable, atomic renames, works with
sharded jax.Arrays on a mesh — global arrays are saved/restored with their
shardings, so a pod checkpoint restores onto the same mesh layout).
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional, Tuple

import jax
import numpy as np
import orbax.checkpoint as ocp

from dist_dqn_tpu import chaos
from dist_dqn_tpu.types import PyTree


@dataclasses.dataclass
class TrainCheckpointer:
    """Periodic learner-state checkpoints with retention + resume.

    Usage:
      ckpt = TrainCheckpointer(dir, save_every_frames=100_000)
      start = ckpt.restore_latest(learner)   # (frames, learner) or None
      ...
      ckpt.maybe_save(frames, learner)       # inside the training loop
    """

    directory: str
    save_every_frames: int = 100_000
    max_to_keep: int = 3

    def __post_init__(self):
        self._mgr = ocp.CheckpointManager(
            self.directory,
            options=ocp.CheckpointManagerOptions(
                max_to_keep=self.max_to_keep, create=True),
        )
        self._next_save = 0
        self._meta_mgr = None  # lazy; only eval's restore_params needs it
        self._pytree_mgr = None  # lazy twin for params-only restores

    def maybe_save(self, frames: int, learner: PyTree) -> bool:
        """Save when the frame cursor crosses the next save boundary."""
        if frames < self._next_save:
            return False
        self.save(frames, learner)
        self._next_save = frames + self.save_every_frames
        return True

    def save(self, frames: int, learner: PyTree) -> None:
        """Save + stamp the atomic ``LATEST`` pointer (ISSUE 7).

        The pointer (step + param checksum + manifest hash) is written
        only after the save LANDED, so any reader that trusts it — the
        serving ModelStore watcher, evaluate's restores — addresses a
        complete checkpoint. The stamp rides a small background thread
        that blocks on ``wait_until_finished`` so the training loop
        keeps orbax's async-save overlap (ring-sized --checkpoint-replay
        carries would otherwise stall the loop for the full write);
        ``wait()``/the next ``save`` join it. A crash between commit
        and stamp leaves a stale pointer — ``latest_step`` guards by
        also consulting the orbax listing.

        Orbax surfaces an async save's failure exactly ONCE, from the
        first ``wait_until_finished`` — which is now the stamp thread's.
        The thread therefore captures any failure and the next join
        point (``save``/``wait``/``close``) re-raises it on the caller's
        thread, so a failed commit still fails the run instead of dying
        silently in a daemon thread.
        """
        import errno
        import threading

        self._join_pointer_stamp()
        # Chaos seam (ISSUE 8): "fail" is a disk-full save (the caller
        # must surface it, not train on silently); "crash_before_stamp"
        # commits the orbax step but never stamps LATEST — exactly the
        # crash window latest_step()'s listing fallback exists for.
        ev = chaos.fire("checkpoint.save")
        if ev is not None and ev.fault == "fail":
            raise OSError(errno.ENOSPC,
                          "chaos: injected disk-full on checkpoint save")
        self._mgr.save(frames, args=ocp.args.StandardSave(learner))
        # Checksum on the caller's thread: orbax has already snapshotted
        # the tree, and device-backed arrays stay off the side thread.
        checksum = _pointer_checksum(learner)
        if ev is not None and ev.fault == "crash_before_stamp":
            self._mgr.wait_until_finished()
            return

        def _stamp():
            try:
                self._mgr.wait_until_finished()
                write_latest_pointer(self.directory, frames,
                                     param_checksum=checksum)
                # A completed save + stamp proves recovery from any
                # earlier injected save/stamp fault.
                chaos.mark_recovered("checkpoint.save")
            except BaseException as e:  # re-raised at the next join
                self._ptr_error = e

        self._ptr_thread = threading.Thread(
            target=_stamp, name="checkpoint-latest-pointer", daemon=True)
        self._ptr_thread.start()

    def _join_pointer_stamp(self) -> None:
        t = getattr(self, "_ptr_thread", None)
        if t is not None:
            t.join()
            self._ptr_thread = None
        err = getattr(self, "_ptr_error", None)
        if err is not None:
            self._ptr_error = None  # surfaced once, like orbax's own
            raise err

    def wait(self) -> None:
        """Block until any async save landed (call before process exit)."""
        self._join_pointer_stamp()
        self._mgr.wait_until_finished()

    def all_steps(self) -> Tuple[int, ...]:
        """Retained checkpoint steps (frame cursors), oldest first."""
        return tuple(sorted(self._mgr.all_steps()))

    def delete(self, step: int) -> None:
        """Remove one retained step (ISSUE 12): a committed orbax step
        whose sidecar proved torn/unreadable is NOT a usable checkpoint
        — the resume path deletes it so the run can fall back to the
        previous step AND later re-save at the same frame cursor
        without orbax's StepAlreadyExists refusal."""
        self._join_pointer_stamp()
        self._mgr.delete(int(step))

    def latest_step(self) -> Optional[int]:
        """Newest COMPLETE checkpoint step: the max of the ``LATEST``
        pointer (when present and its step dir still exists) and orbax's
        directory listing. The pointer is what makes an in-progress save
        invisible (a complete-by-construction step id); the listing
        guards against a pointer left stale by a crash between a save's
        commit and its stamp — preferring a stale pointer outright would
        silently resume/serve older params than the newest complete
        checkpoint.
        """
        import os

        steps = []
        ptr = read_latest_pointer(self.directory)
        if ptr is not None:
            step = int(ptr["step"])
            if os.path.isdir(os.path.join(self.directory, str(step))):
                steps.append(step)
        mgr_step = self._mgr.latest_step()
        if mgr_step is not None:
            steps.append(int(mgr_step))
        return max(steps) if steps else None

    def restore_latest(self, example: PyTree, step: Optional[int] = None
                       ) -> Optional[Tuple[int, PyTree]]:
        """Restore the newest checkpoint (or a specific retained ``step``
        from ``all_steps()``) as (frames, learner), or None.

        ``example`` is a live learner pytree of the target structure; its
        shapes/dtypes/shardings template the restore, so restoring onto a
        different mesh layout re-shards on load.
        """
        # The save schedule advances only on the latest-resume path: an
        # explicitly requested OLD step (the eval surfaces walk
        # all_steps()) must not regress _next_save and re-save over
        # newer retained steps (ADVICE round 3).
        advance_schedule = step is None
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        abstract = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                np.shape(x), x.dtype,
                sharding=getattr(x, "sharding", None)),
            example)
        try:
            restored = self._mgr.restore(
                step, args=ocp.args.StandardRestore(abstract))
        except ValueError as e:
            # Orbax's structure-mismatch error lists raw pytree paths;
            # the usual cause is a config drift, so say that first — but
            # only for actual structure mismatches; any other restore
            # ValueError (corruption, sharding mapping, ...) passes
            # through untouched.
            msg = str(e)
            if not ("structures do not match" in msg
                    or "User-provided restore item" in msg):
                raise
            raise ValueError(
                "checkpoint does not match the current config's learner "
                "structure — it was saved with a different network/"
                "optimizer architecture. Rebuild with the same --config "
                "and --set overrides used at save time.\n\nOriginal "
                f"error:\n{e}") from e
        if advance_schedule:
            self._next_save = step + self.save_every_frames
        return int(step), restored

    def restore_params(self, example_params: PyTree,
                       step: Optional[int] = None,
                       prefix: Tuple[str, ...] = (),
                       member: Optional[int] = None
                       ) -> Optional[Tuple[int, PyTree]]:
        """Restore ONLY the policy parameters of a checkpoint.

        Deploy surfaces (evaluate) need the params to match the live
        network — the true requirement — but ``restore_latest`` also
        demands the optimizer/counter structure match, coupling eval
        invocations to training-only knobs (an lr schedule adds a count
        leaf to opt_state, so an eval without the exact training
        ``--set`` flags would fail its restore). This surface templates
        just the ``(*prefix, "params")`` subtree from the live example
        and partial-restores it; optimizer contents never constrain
        eval, and carry-kind checkpoints (``prefix=("learner",)``) no
        longer pay a ring-sized template either. Read-only: never
        advances the save schedule.

        Population checkpoints (ISSUE 20) hold an [M]-stacked params
        tree; ``member=k`` templates the stacked shape from the solo
        ``example_params``, restores the stack and returns member k's
        slice — so evaluate.py and the serving ModelStore serve any
        single member of a population run without knowing how to train
        one. Direction mismatches fail with the actual cause: a member
        request against a solo directory, or a member-less restore of a
        stacked directory (its leaves would come back [M]-leading and
        shape-mismatch the live net downstream).
        """
        if step is None:
            step = self.latest_step()
        if step is None:
            return None
        pop_size = read_population_size(self.directory)
        if member is not None:
            if pop_size is None:
                raise ValueError(
                    f"member={member} requested but {self.directory!r} "
                    "is not a population checkpoint (no POPULATION "
                    "width marker) — drop the member selector")
            if not 0 <= member < pop_size:
                raise ValueError(
                    f"member={member} is out of range for a population-"
                    f"{pop_size} checkpoint (members are 0-based)")
        elif pop_size is not None:
            raise ValueError(
                f"{self.directory!r} holds a population-{pop_size} "
                "[M]-stacked tree — pass member=k (evaluate.py "
                "--member k) to extract one policy")
        default_dev = jax.local_devices()[0]
        stack = (pop_size,) if member is not None else ()
        live_abs = jax.tree.map(
            lambda x: jax.ShapeDtypeStruct(
                stack + tuple(np.shape(x)), x.dtype,
                sharding=getattr(x, "sharding", None)
                or jax.sharding.SingleDeviceSharding(default_dev)),
            example_params)
        # Partial restore takes the INTERSECTION silently, so a network
        # drift in either direction (template leaves missing on disk, OR
        # on-disk heads the live net lacks) must be caught up front by
        # comparing the params subtree against the on-disk metadata —
        # otherwise a mismatched eval runs with wrong/unrestored params
        # instead of erroring.
        self._check_params_match(step, live_abs, prefix)
        rargs = ocp.checkpoint_utils.construct_restore_args(live_abs)
        item: Any = live_abs
        for key in reversed(prefix + ("params",)):
            item = {key: item}
            rargs = {key: rargs}
        restored = self._pytree_restore_mgr().restore(
            step, args=self._partial_restore_args(item, rargs))
        out = restored
        for key in prefix + ("params",):
            out = out[key]
        bad = [str(p) for p, leaf
               in jax.tree_util.tree_flatten_with_path(out)[0]
               if not hasattr(leaf, "addressable_data")
               and isinstance(leaf, jax.ShapeDtypeStruct)]
        if bad:  # defense in depth behind _check_params_match
            raise ValueError(
                f"checkpoint restore left {len(bad)} parameter leaves "
                f"unrestored (first: {bad[0]}) — network architecture "
                "drift between save and eval.")
        if member is not None:
            out = jax.tree.map(lambda x: x[member], out)
        return int(step), out

    def _pytree_restore_mgr(self):
        """Manager for params-only (PyTreeRestore) reads. The main
        manager registers its handlers from the save/StandardRestore
        args it has seen; on orbax 0.7.x its composite handler then
        REJECTS a PyTreeRestoreArgs restore outright ("does not match
        any registered handler"), so the partial restore needs its own
        manager with the PyTree handler registered explicitly — cached,
        like the metadata manager."""
        if self._pytree_mgr is None:
            self._pytree_mgr = ocp.CheckpointManager(
                self.directory,
                item_handlers=ocp.PyTreeCheckpointHandler())
        return self._pytree_mgr

    @staticmethod
    def _partial_restore_args(item, rargs):
        """Version-adaptive partial-restore args: orbax >= 0.11 spells
        it ``partial_restore=True``; 0.7.x (this container) only has
        the legacy transforms API, where an EMPTY ``transforms`` dict
        with an item tree that is a subset of the saved tree restores
        exactly that subset (verified against 0.7.0 — the deprecation
        warning it logs is the API's own, not a misuse)."""
        import inspect

        params = inspect.signature(
            ocp.args.PyTreeRestore.__init__).parameters
        if "partial_restore" in params:
            return ocp.args.PyTreeRestore(
                item, restore_args=rargs, partial_restore=True)
        return ocp.args.PyTreeRestore(
            item, restore_args=rargs, transforms={})

    def _check_params_match(self, step: int, live_abs: PyTree,
                            prefix: Tuple[str, ...]) -> None:
        """Raise the config-drift error unless the on-disk params
        subtree matches ``live_abs`` in structure, shape and dtype."""
        if self._meta_mgr is None:
            # The main manager has no handler registry (restore args
            # pick its handlers), so item_metadata on it returns None;
            # cache one metadata-capable manager for the whole walk.
            self._meta_mgr = ocp.CheckpointManager(
                self.directory,
                item_handlers=ocp.StandardCheckpointHandler())
        meta = self._meta_mgr.item_metadata(step)
        try:
            for key in prefix + ("params",):
                meta = meta[key]
        except (KeyError, TypeError) as e:
            raise ValueError(
                f"checkpoint at step {step} has no "
                f"{'/'.join(prefix + ('params',))} subtree — wrong "
                "checkpoint kind or directory") from e
        meta = jax.tree.map(lambda m: m, meta)  # plain containers
        live_paths = {
            tuple(str(k) for k in p): (tuple(leaf.shape), leaf.dtype)
            for p, leaf in jax.tree_util.tree_flatten_with_path(
                live_abs)[0]}
        disk_paths = {
            tuple(str(k) for k in p): (tuple(m.shape),
                                       np.dtype(m.dtype))
            for p, m in jax.tree_util.tree_flatten_with_path(meta)[0]}
        if live_paths != disk_paths:
            only_live = sorted(set(live_paths) - set(disk_paths))[:3]
            only_disk = sorted(set(disk_paths) - set(live_paths))[:3]
            shape_drift = sorted(
                k for k in set(live_paths) & set(disk_paths)
                if live_paths[k] != disk_paths[k])[:3]
            raise ValueError(
                "checkpoint parameters do not match the current config's "
                "network structure — it was saved with a different "
                "network architecture. Rebuild with the same --config "
                "and --set overrides used at save time.\n"
                f"param leaves only in the live net: {only_live}\n"
                f"only in the checkpoint: {only_disk}\n"
                f"shape/dtype drift: {shape_drift}")

    def close(self) -> None:
        try:
            # Re-raises a captured stamp/async-save failure — keep it
            # loud, but never at the cost of leaking the managers.
            self._join_pointer_stamp()
            self._mgr.wait_until_finished()
        finally:
            self._mgr.close()
            if self._meta_mgr is not None:
                self._meta_mgr.close()
                self._meta_mgr = None
            if self._pytree_mgr is not None:
                self._pytree_mgr.close()
                self._pytree_mgr = None


class CheckpointMissingError(FileNotFoundError):
    """The requested checkpoint (dir or step) is absent. A distinct type
    so bounded-retry launchers (evaluate/serving --wait-for-checkpoint)
    and --all-steps walks can catch EXACTLY this condition without
    swallowing unrelated FileNotFoundErrors (missing ROM/asset) from
    the work itself (ADVICE round 3)."""


def wait_for_checkpoint(fn, wait_s: float, stop=None):
    """Run ``fn()``, bounded-retrying :class:`CheckpointMissingError`
    for up to ``wait_s`` seconds — the launched-alongside-training
    startup window shared by evaluate.py and the serving CLI. A 0
    budget keeps fail-fast single-attempt behavior; any other error
    stays loud on the first attempt. ``stop`` (a ``threading.Event``)
    aborts the wait early by re-raising the pending
    CheckpointMissingError — how the serving CLI's SIGTERM handler
    stays honored during a long startup wait instead of being ignored
    until the budget runs out."""
    import time

    deadline = time.monotonic() + max(wait_s, 0.0)
    while True:
        try:
            return fn()
        except CheckpointMissingError as e:
            remaining = deadline - time.monotonic()
            if remaining <= 0 or (stop is not None and stop.is_set()):
                raise
            print(f"# waiting for checkpoint ({e}); "
                  f"{remaining:.0f}s left", flush=True)
            nap = min(2.0, remaining)
            if stop is not None:
                if stop.wait(nap):
                    raise
            else:
                time.sleep(nap)


_LATEST_FILE = "LATEST"


def _pointer_checksum(tree: PyTree):
    """Cheap params digest for the ``LATEST`` pointer: the float64 fold
    of the policy-params subtree (the SAME rule as the loops'
    ``param_checksum`` pin anchors), or None when the saved tree has no
    recognizable params (custom pytrees). Carry-kind trees digest their
    nested learner's params — never the ring."""
    obj = tree
    if isinstance(obj, dict) and "learner" in obj:
        obj = obj["learner"]
    obj = getattr(obj, "learner", obj)
    params = getattr(obj, "params", None)
    if params is None and isinstance(obj, dict):
        params = obj.get("params")
    if params is None:
        return None
    try:
        return float(sum(
            np.float64(np.sum(np.asarray(jax.device_get(leaf),
                                         np.float64)))
            for leaf in jax.tree.leaves(params)))
    except Exception:
        # Provenance only — a params tree the host cannot materialize
        # (e.g. non-fully-addressable global arrays on a pod) must not
        # break the save; the pointer just carries no digest.
        return None


def write_latest_pointer(directory: str, step: int,
                         param_checksum=None) -> None:
    """Atomically (tmp + rename) stamp ``<directory>/LATEST`` with the
    newest COMPLETE checkpoint step, its param checksum and the run's
    manifest config hash — so readers (serving ModelStore watcher,
    evaluate) address the newest checkpoint without globbing step dirs
    and racing an in-progress save (ISSUE 7 satellite)."""
    import json
    import os
    import time

    from dist_dqn_tpu.telemetry.manifest import get_run_manifest

    man = get_run_manifest()
    payload = {
        "step": int(step),
        "param_checksum": param_checksum,
        "manifest_hash": man.get("config_hash") if man else None,
        "saved_unix": time.time(),
    }
    path = os.path.join(directory, _LATEST_FILE)
    ev = chaos.fire("latest.write")
    if ev is not None and ev.fault == "torn":
        # A torn stamp: half a JSON object lands as the final file
        # (crash mid-write on a filesystem without atomic rename
        # semantics). read_latest_pointer must reject it and every
        # reader must fall back to the orbax listing.
        with open(path, "w") as fh:
            fh.write(json.dumps(payload)[: max(4, len(str(step)))])
        return
    # Per-process tmp name: on multihost runs every process stamps the
    # shared dir after its save; a fixed tmp would let writers truncate
    # each other mid-write and rename a torn JSON into place. Distinct
    # tmps keep each os.replace atomic (last writer wins whole-file).
    tmp = f"{path}.tmp.{os.getpid()}"
    with open(tmp, "w") as fh:
        json.dump(payload, fh, sort_keys=True)
    os.replace(tmp, path)
    # A committed, well-formed stamp proves recovery from an earlier
    # injected torn write.
    chaos.mark_recovered("latest.write")


def checkpoint_present(directory: str) -> bool:
    """Cheap committed-checkpoint presence probe: the ``LATEST`` pointer
    or any committed digit-named step dir. No orbax manager (which would
    mkdir a typo'd path), no restore — the gate --wait-for-checkpoint
    loops poll so a retry never pays an env/network build just to find
    the directory still empty. In-progress orbax saves live under
    ``*.orbax-checkpoint-tmp-*`` names, so a digit-named dir is a
    committed step."""
    import os

    if not os.path.isdir(directory):
        return False
    if read_latest_pointer(directory) is not None:
        return True
    try:
        entries = os.listdir(directory)
    except OSError:
        return False
    return any(e.isdigit() and os.path.isdir(os.path.join(directory, e))
               for e in entries)


def read_latest_pointer(directory: str):
    """The parsed ``LATEST`` pointer dict, or None (absent — pre-pointer
    directory — or torn/corrupt, in which case readers fall back to the
    orbax directory listing)."""
    import json
    import os

    try:
        with open(os.path.join(directory, _LATEST_FILE)) as fh:
            payload = json.load(fh)
    except (OSError, ValueError):
        return None
    if not isinstance(payload, dict) or "step" not in payload:
        return None
    return payload


_KIND_FILE = "CHECKPOINT_KIND"


def record_checkpoint_kind(directory: str, kind: str) -> None:
    """Stamp what a checkpoint directory's items contain — ``learner``
    (the default recovery point), ``carry`` (--checkpoint-replay's
    whole fused carry) or ``host_loop`` (the host-replay runtime's
    whole-state {learner, carry} + npz sidecar, ISSUE 8). Restore
    paths read this to template correctly and to say THE ACTUAL CAUSE
    when the flavors mismatch, instead of orbax's structure error
    being rewrapped as a config drift."""
    import os

    path = os.path.join(directory, _KIND_FILE)
    existing = read_checkpoint_kind(directory)
    if existing is not None and existing != kind:
        raise ValueError(
            f"checkpoint directory {directory!r} holds {existing!r} "
            f"checkpoints but this run would write {kind!r} — the "
            "--checkpoint-replay flag differs from the run that created "
            "the directory. Resume with the same flag, or use a fresh "
            "--checkpoint-dir.")
    if existing is None:
        with open(path, "w") as fh:
            fh.write(kind)


def read_checkpoint_kind(directory: str):
    """The recorded kind, or None (pre-marker directories: learner-only
    by construction, since the marker landed with --checkpoint-replay)."""
    import os

    try:
        with open(os.path.join(directory, _KIND_FILE)) as fh:
            return fh.read().strip() or None
    except OSError:
        return None


_POPULATION_FILE = "POPULATION"


def record_population_size(directory: str, size: int) -> None:
    """Stamp a population run's member-axis width M (ISSUE 20). The
    stacked tree's leading [M] axis is checkpoint STRUCTURE: resuming a
    population-M' directory at a different --population would fail as
    an opaque orbax shape mismatch, so — like the kind marker above —
    the width is pinned up front and a mismatch says the actual cause
    (callers count it under dqn_checkpoint_refused_resumes_total with
    reason="population")."""
    import os

    existing = read_population_size(directory)
    if existing is not None and existing != size:
        raise ValueError(
            f"checkpoint directory {directory!r} holds a population-"
            f"{existing} stacked tree but this run trains --population "
            f"{size} — the member axis is part of the checkpoint "
            "structure. Resume with the same --population, use a fresh "
            "--checkpoint-dir, or extract single members with "
            "restore_params(member=k) / evaluate.py --member.")
    if existing is None:
        os.makedirs(directory, exist_ok=True)
        with open(os.path.join(directory, _POPULATION_FILE), "w") as fh:
            fh.write(str(int(size)))


def read_population_size(directory: str):
    """The recorded member width M, or None (solo directories — every
    pre-population checkpoint by construction)."""
    import os

    try:
        with open(os.path.join(directory, _POPULATION_FILE)) as fh:
            text = fh.read().strip()
    except OSError:
        return None
    return int(text) if text else None


def list_checkpoint_steps(directory: str) -> Tuple[int, ...]:
    """Retained checkpoint steps under ``directory``, oldest first,
    without keeping a manager open. Read-only surface: a missing
    directory raises instead of being created (the manager itself
    mkdirs, so guard before constructing it)."""
    import os

    if not os.path.isdir(directory):
        raise FileNotFoundError(
            f"no checkpoint found under {directory!r}")
    ckpt = TrainCheckpointer(directory)
    try:
        return ckpt.all_steps()
    finally:
        ckpt.close()


def atomic_savez(path: str, **arrays) -> None:
    """np.savez to ``path`` atomically (tmp + rename): a crash mid-write
    leaves the previous file, never a torn npz. The one shared writer
    for replay/sidecar snapshots — the host-replay SIDECAR save is the
    deliberate exception (it splices the ``sidecar.write`` chaos seam
    between its tmp write and the rename)."""
    import os

    import numpy as np

    tmp = f"{path}.tmp"
    with open(tmp, "wb") as fh:
        np.savez(fh, **arrays)
    os.replace(tmp, path)


def save_pytree(path: str, tree: PyTree) -> None:
    """One-shot pytree save (e.g. the --export-params deploy artifact).

    The checkpointer saves asynchronously; close (which blocks on the
    outstanding save) before returning so a CLI process can exit
    immediately after — a dropped instance races interpreter shutdown
    and loses the write."""
    with ocp.StandardCheckpointer() as ckptr:
        ckptr.save(path, tree, force=True)


def restore_pytree(path: str, example: PyTree) -> Any:
    abstract = jax.tree.map(
        lambda x: jax.ShapeDtypeStruct(np.shape(x), x.dtype,
                                       sharding=getattr(x, "sharding", None)),
        example)
    return ocp.StandardCheckpointer().restore(path, abstract)
