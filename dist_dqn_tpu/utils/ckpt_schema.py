"""Versioned checkpoint-sidecar schema (ISSUE 12 satellite).

The host-replay runtime's whole-state checkpoints are an orbax pytree
plus an npz SIDECAR holding everything orbax does not: the ring
window(s), the loop cursors, the PER sampler state and any deferred
priority write-backs. Resume correctness therefore depends on the
sidecar's FIELD SET — a renamed or dropped field would deserialize into
silence, not an error, and surface at 3am as a wrong resume.

This module is the pin. It names every sidecar field (scalars
explicitly, per-shard/per-entry families as regex patterns), carries a
``SIDECAR_VERSION`` the writer stamps into every sidecar, and keeps an
append-only ``SIDECAR_HISTORY`` of ``version -> sha256-fingerprint``
exactly like the wire codec's ``WIRE_HISTORY`` (ingest/codec.py):

* ``scripts/check_ckpt_schema.py`` (tier-1 via
  tests/test_ckpt_schema_lint.py) recomputes the fingerprint and fails
  CI when the field set changed without a version bump + history entry;
* the writer calls :func:`validate_sidecar` on every save, so a code
  path emitting a key this module does not name fails AT SAVE TIME;
* the resume path refuses a sidecar whose stamped version differs from
  the reader's, naming both — resume-format drift is one loud error at
  restore, never a silently-wrong training run.

stdlib + numpy only (the lint imports this without jax).
"""
from __future__ import annotations

import hashlib
import json
import re
from typing import Dict, Iterable, Tuple

#: Bump on ANY change to the field set below, and append the new
#: (version, digest) pair to SIDECAR_HISTORY — scripts/check_ckpt_schema.py
#: prints the expected digest on mismatch.
SIDECAR_VERSION = 4

#: Scalar fields present in every host_loop sidecar.
SIDECAR_SCALAR_FIELDS: Tuple[str, ...] = (
    "sidecar_version",   # this schema's version stamp
    "env_steps",         # frame cursor at the save boundary
    "grad_steps",        # grad-step cursor
    "sample_k",          # per-index batch-RNG stream cursor
    "train_debt_iters",  # train-event cadence remainder
    "next_chunk",        # first chunk body the resumed run executes
    "chunk_iters",       # loop shape pin (cursors are in chunk units)
    "dp",                # mesh width pin (per-shard layout is positional)
    "per",               # prioritized-sampling pin (uniform <-> PER refuse)
    "prio_writeback_batch",  # PER flush-cadence pin (a changed batch
                         # would flush restored pending rows on a
                         # different schedule — silent divergence)
    "wb_count",          # deferred priority write-back entries serialized
    "has_stats",         # episode-stat scalars of the dispatched chunk ride
    "has_pending",       # serial path: next chunk's records ride along
    "sharded_collect",   # v2 (ISSUE 15): collect-carry placement pin —
                         # sharded runs keep per-shard carries in the
                         # sidecar (carry{s}_leaf{i}), single-collect
                         # runs keep the one carry in the orbax tree;
                         # a mismatch cannot restore either way
    "per_sampler_kind",  # v3 (ISSUE 18): PER backend pin — 0 = host
                         # sum-tree, 1 = device priority plane. The
                         # mass shadow restores either way, but draw
                         # timing/fp-reduction order differ, so a
                         # resume that silently swapped backends would
                         # break the bit-identical-resume contract;
                         # refuse loudly instead (reason=sampler_kind)
    "population",        # v4 (ISSUE 20): member-axis width pin — the
                         # host-replay runtime has no stacked-member
                         # plane yet so its writer always stamps 1; a
                         # sidecar stamped differently (a future
                         # population-capable writer) cannot resume
                         # into this loop's solo state shapes — refuse
                         # loudly instead (reason=population)
)

#: Conditional scalars: present only when their ``has_*`` flag is set.
SIDECAR_CONDITIONAL_FIELDS: Tuple[str, ...] = (
    "stats_cr",          # completed-return accumulator (has_stats)
    "stats_cc",          # completed-count accumulator (has_stats)
)

#: Array-family patterns: one entry per shard / pending record field /
#: deferred write-back entry. ``ring_*`` carries the HostTimeRing (dp=1)
#: or ShardedHostReplay (dp>1: ring_num_shards + ring_shard{i}_{field},
#: with PER sampler state as ring_shard{i}_per_{field}) snapshot;
#: ``per_*`` the dp=1 sampler snapshot; ``wb{s}_*`` the deferred
#: priority write-backs of shard s; ``pending_*`` the serial path's
#: un-appended next-chunk records.
SIDECAR_PATTERNS: Tuple[str, ...] = (
    r"^ring_[a-z_]+$",
    r"^ring_num_shards$",
    r"^ring_shard\d+_[a-z_]+$",
    r"^ring_shard\d+_per_[a-z_]+$",
    r"^per_[a-z_]+$",
    r"^wb\d+_leaf$",
    r"^wb\d+_slot_gen$",
    r"^wb_prios$",
    r"^pending_[a-z_]+$",
    # v2 (ISSUE 15, sharded collect): per-shard collect carries —
    # carry{s}_leaf{i} is leaf i of shard s's CollectCarry, flattened
    # against the freshly-initialized carry's treedef — and the serial
    # path's per-shard pending records (pending{s}_{field}).
    r"^carry\d+_leaf\d+$",
    r"^pending\d+_[a-z_]+$",
)


def sidecar_digest() -> str:
    """Canonical fingerprint of the field set a resume must agree on."""
    spec = {
        "scalars": list(SIDECAR_SCALAR_FIELDS),
        "conditionals": list(SIDECAR_CONDITIONAL_FIELDS),
        "patterns": list(SIDECAR_PATTERNS),
    }
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


#: Append-only: every released sidecar version maps to the fingerprint
#: of its field set. Rewriting an entry (instead of appending) is a
#: lint failure — history is how a version number stays meaningful.
SIDECAR_HISTORY: Dict[int, str] = {
    1: "948b5e00114da529",
    2: "0e038b7fe0331a3d",
    3: "8ef0d7a524f3d7d3",
    4: "a21f0ff7cab3aeb5",
}

_COMPILED = None


def _patterns():
    global _COMPILED
    if _COMPILED is None:
        _COMPILED = [re.compile(p) for p in SIDECAR_PATTERNS]
    return _COMPILED


def validate_sidecar(keys: Iterable[str]) -> None:
    """Raise unless ``keys`` (the dict about to be written) is exactly
    the schema: every required scalar present, every key named by the
    schema. Called by the WRITER on every save — a new code path
    emitting an unnamed key fails here, at save time, with the
    bump-the-schema instruction, instead of becoming a silently-ignored
    field at restore time."""
    keys = set(keys)
    missing = [f for f in SIDECAR_SCALAR_FIELDS if f not in keys]
    if missing:
        raise ValueError(
            f"checkpoint sidecar is missing required fields {missing} — "
            "the writer and utils/ckpt_schema.py disagree; update the "
            "schema (bump SIDECAR_VERSION + append SIDECAR_HISTORY) or "
            "fix the writer")
    known = set(SIDECAR_SCALAR_FIELDS) | set(SIDECAR_CONDITIONAL_FIELDS)
    unknown = sorted(
        k for k in keys
        if k not in known and not any(p.match(k) for p in _patterns()))
    if unknown:
        raise ValueError(
            f"checkpoint sidecar carries fields the schema does not "
            f"name: {unknown} — add them to utils/ckpt_schema.py, bump "
            "SIDECAR_VERSION and append the new digest to "
            "SIDECAR_HISTORY (scripts/check_ckpt_schema.py prints it)")
