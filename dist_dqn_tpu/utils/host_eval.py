"""Greedy-episode rollouts on HOST envs — the one eval protocol shared by
in-training eval (actors/service.py) and standalone checkpoint eval
(evaluate.py), so the two surfaces cannot drift on carry-reset or
truncation accounting.
"""
from __future__ import annotations

from typing import Optional, Tuple

import numpy as np


def run_greedy_episodes(env, act, params, rng, *, episodes: int,
                        recurrent_carry=None, epsilon: float = 0.001,
                        max_steps: int = 10_000
                        ) -> Tuple[np.ndarray, int, "object"]:
    """Play one episode per vectorized env lane with a (near-)greedy
    policy; returns (per-episode returns [episodes], episodes still
    alive at the step cap, advanced rng).

    ``act`` is the jitted actor step: ``act(params, obs, k, eps) ->
    actions`` for feed-forward nets, or — when ``recurrent_carry`` is
    given — ``act(params, carry, obs, k, eps) -> (carry, actions, ...)``
    (extra outputs such as Q planes are ignored). The recurrent carry is
    zeroed on each lane's episode end, matching training-side acting.
    """
    import jax
    import jax.numpy as jnp

    carry = recurrent_carry
    obs = env.reset()
    returns = np.zeros((episodes,), np.float64)
    alive = np.ones((episodes,), bool)
    eps = jnp.float32(epsilon)
    for _ in range(max_steps):
        rng, k = jax.random.split(rng)
        if carry is not None:
            out = act(params, carry, jnp.asarray(obs), k, eps)
            carry, actions = out[0], out[1]
        else:
            actions = act(params, jnp.asarray(obs), k, eps)
        obs, _, reward, term, trunc = env.step(np.asarray(actions))
        returns += np.asarray(reward, np.float64) * alive
        done = np.logical_or(term, trunc)
        if carry is not None and done.any():
            keep = jnp.asarray(~done, jnp.float32)[:, None]
            carry = (carry[0] * keep, carry[1] * keep)
        alive &= ~done
        if not alive.any():
            break
    return returns, int(alive.sum()), rng
