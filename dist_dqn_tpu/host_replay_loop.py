"""Hybrid fused loop with HOST-DRAM replay (BASELINE.json:5's north-star
phrase — "replay buffer shards across TPU-VM host DRAM" — applied to the
single-chip fused path, VERDICT round-4 next #2).

The all-on-device loop (train_loop.py) is the throughput king, but its
replay window lives in HBM: ~200k stacked / ~1M deduped pixel
transitions on a 16 GB v5e. This loop splits the program at the replay
boundary instead, and since ISSUE 3 runs the split as a THREE-STAGE
SOFTWARE PIPELINE rather than a serial chunk loop:

  device: [act -> env.step] x chunk_iters  (one jitted scan, no replay)
     |  chunk g+1 is dispatched BEFORE chunk g's train event, so its
     |  device compute overlaps chunk g's evacuation and training
     |  (collect therefore acts on params one train event stale — in
     |  BOTH the pipelined and the serial reference path, so the two
     |  stay bit-identical; Podracer-style off-policy staleness)
  d2h:   chunk records leave as --evac-slices streamed time slices
     |  (replay/staging.py StreamedEvacuator): one split dispatch, all
     |  host copies started async, slice k's ring append overlapping
     |  slice k+1's transfer — drained by a BACKGROUND EVACUATION
     |  WORKER so the main thread keeps dispatching
  host:  HostTimeRing in DRAM — the window is DRAM-sized (hundreds of
     |  GB => hundreds of millions of pixel transitions); slice appends
     |  publish atomically under the ring's generation fence, and the
     |  train event fences on the chunk's completion handle before
     |  sampling, so a batch never sees a half-appended slice
  device: train_step (donated state), exactly the learner the fused
          loop runs; sampled batches H2D double-buffered as before

Throughput model: the link, not HBM, prices the window. Per env step
the D2H cost is one stored frame; per grad step the H2D cost is one
batch (2 x batch x obs bytes). On a TPU-VM host link (~10 GB/s) that
admits ~1.4M deduped env-steps/s of collection — above the fused
loop's own rate; on this dev box the axon tunnel (~25 MB/s measured)
is the honest bound. The round-5 chip measurement put the SERIAL chunk
loop at 488 steps/s, 91% D2H-bound — the device idle for the whole
evacuation, the host idle for the whole collect. The pipeline takes the
serial sum collect + evac + train to ~max(evac, collect + train): the
per-chunk rows carry the overlap accounting (``evac_s``,
``evac_fence_wait_s``, ``evac_overlap_frac``, ``device_idle_est_s``)
so the win is measured per run, not asserted. ``pipeline=False``
(train.py ``--no-pipeline``) keeps the monolithic blocking evacuation
as the numerically pinned A/B reference, same discipline as PR 2's
``fused_ingest=False``.
"""
from __future__ import annotations

import json
import math
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dist_dqn_tpu import loop_common
from dist_dqn_tpu.agents.dqn import make_actor_step, make_learner
from dist_dqn_tpu.config import ExperimentConfig
from dist_dqn_tpu.envs.base import JaxEnv
from dist_dqn_tpu.replay.host_ring import HostTimeRing
from dist_dqn_tpu.types import PyTree, Transition

Array = jnp.ndarray


class CollectCarry(NamedTuple):
    env_state: PyTree
    obs: PyTree
    rng: Array
    iteration: Array
    ep_return: Array


class _ScanCarry(NamedTuple):
    """Chunk-internal scan carry: the persistent CollectCarry fields plus
    the chunk-local episode accumulators. The accumulators are RETURNED
    as separate chunk outputs rather than carried across chunks, so the
    pipelined loop can hold and fetch them (one fused device_get) after
    the carry itself has been donated into the next chunk's dispatch."""
    env_state: PyTree
    obs: PyTree
    rng: Array
    iteration: Array
    ep_return: Array
    completed_return: Array
    completed_count: Array


def make_collect_chunk(cfg: ExperimentConfig, env: JaxEnv, net,
                       frame_stack: int):
    """(init, collect): a device chunk of act -> step that RETURNS its
    transitions (time-major [C, B, ...]) plus the chunk's episode stats
    instead of writing a ring."""
    B = cfg.actor.num_envs
    act = make_actor_step(net)
    epsilon, _ = loop_common.make_schedules(cfg, B, 1)
    slice_newest = ((lambda o: o[..., -1:]) if frame_stack
                    else (lambda o: o))

    def init(rng: Array) -> CollectCarry:
        k_env, k_run = jax.random.split(rng)
        env_state, obs = env.v_reset(k_env, B)
        obs = jax.tree.map(jnp.copy, obs)
        return CollectCarry(env_state=env_state, obs=obs, rng=k_run,
                            iteration=jnp.int32(0),
                            ep_return=jnp.zeros((B,), jnp.float32))

    def collect(carry: CollectCarry, params, num_iters: int):
        def one_iteration(sc: _ScanCarry, _):
            rng, k_act = jax.random.split(sc.rng)
            eps = epsilon(sc.iteration)
            actions = act(params, sc.obs, k_act, eps)
            env_state, out = env.v_step(sc.env_state, actions)
            record = dict(obs=slice_newest(sc.obs), action=actions,
                          reward=out.reward, terminated=out.terminated,
                          truncated=out.truncated)
            done = jnp.logical_or(out.terminated, out.truncated)
            ep_return, completed_return, completed_count = \
                loop_common.episode_stats_update(sc, out.reward, done)
            return _ScanCarry(env_state=env_state, obs=out.obs, rng=rng,
                              iteration=sc.iteration + 1,
                              ep_return=ep_return,
                              completed_return=completed_return,
                              completed_count=completed_count), record

        zero = jnp.float32(0.0)
        sc = _ScanCarry(*carry, completed_return=zero,
                        completed_count=zero)
        sc, records = jax.lax.scan(one_iteration, sc, None,
                                   length=num_iters)
        carry = CollectCarry(env_state=sc.env_state, obs=sc.obs,
                             rng=sc.rng, iteration=sc.iteration,
                             ep_return=sc.ep_return)
        stats = (sc.completed_return, sc.completed_count)
        return carry, records, stats

    return init, collect


def run_host_replay(cfg: ExperimentConfig, total_env_steps: int,
                    chunk_iters: int = 200, log_fn=print,
                    env: Optional[JaxEnv] = None,
                    double_buffer: bool = True,
                    pipeline: bool = True,
                    evac_slices: int = 4):
    """Run the hybrid loop; returns a summary dict.

    Cadence matches the fused loop: one train event every
    ``cfg.train_every`` env iterations, ``cfg.updates_per_train`` grad
    steps each, batches sampled uniformly from the host ring.

    ``pipeline`` selects the three-stage software pipeline (streamed
    sub-chunk evacuation drained by a background worker, trains fenced
    on the chunk's publication handle); False is the serial reference —
    one monolithic blocking ``device_get`` + one monolithic
    ``add_chunk``, device idle throughout. Both paths share the same
    collect-ahead schedule (chunk g+1 dispatched with the params as
    they stand BEFORE chunk g's train event), so they are numerically
    IDENTICAL — tests/test_host_replay_pipeline.py pins it.

    ``double_buffer`` stages batch g+1's sample+H2D while step g trains
    (replay/staging.py); False is the serial H2D reference —
    numerically identical, tests/test_ingest_fastpath.py pins it.
    """
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.telemetry import collectors as tmc, get_registry
    from dist_dqn_tpu.telemetry import flight as tm_flight
    from dist_dqn_tpu.telemetry import watchdog as tm_watchdog

    # Honest-unsupported-surface gates (ADVICE r5): this loop builds the
    # FEED-FORWARD actor/learner and samples the ring uniformly. A
    # recurrent config would silently train the wrong program; a PER
    # config silently loses its prioritization — say so.
    if cfg.network.lstm_size > 0:
        raise ValueError(
            "host-replay runs the feed-forward collect/train split; "
            "recurrent (R2D2, network.lstm_size>0) configs need the "
            "sequence learner — use the apex runtime or the fused loop")
    if cfg.replay.prioritized:
        log_fn("# prioritized replay not supported by host-replay; "
               "sampling uniformly (cfg.replay.prioritized ignored)")
    if evac_slices < 1:
        raise ValueError(f"--evac-slices must be >= 1, got {evac_slices}")

    if env is None:
        env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    B = cfg.actor.num_envs
    obs_shape = tuple(env.observation_shape)
    stack = (cfg.replay.frame_dedup
             and getattr(env, "frame_stack", 0)) or 0
    if cfg.replay.frame_dedup and stack < 2:
        raise ValueError(
            "replay.frame_dedup=True but this env declares no rolling "
            "frame stack (envs/base.py JaxEnv.frame_stack)")
    stored_shape = obs_shape[:-1] + (1,) if stack else obs_shape

    # Floor covers the n-step window AND the dedup rebuild context —
    # a smaller ring would be permanently unsampleable (can_sample
    # needs size > n_step + stack - 1).
    num_slots = max(cfg.replay.capacity // B,
                    cfg.learner.n_step + max(stack - 1, 0) + 2)
    # Fail BEFORE the compile, naming the knobs: a chunk larger than the
    # ring would only surface in HostTimeRing.add_chunk after the first
    # device chunk (ADVICE r5 — wasted compile, error points nowhere).
    if chunk_iters > num_slots:
        raise ValueError(
            f"--chunk-iters {chunk_iters} exceeds the host ring's "
            f"{num_slots} slots (replay.capacity={cfg.replay.capacity} "
            f"/ num_envs={B}); lower --chunk-iters or raise "
            "replay.capacity (one chunk == the whole window would make "
            "the ring a FIFO of the last chunk — keep chunk_iters well "
            "below the slot count)")

    init_collect, collect = make_collect_chunk(cfg, env, net, stack)
    collect_jit = jax.jit(collect, static_argnums=2, donate_argnums=0)
    init_learner, train_step = make_learner(net, cfg.learner)
    train_jit = jax.jit(train_step, donate_argnums=0)

    ring = HostTimeRing(num_slots, B, stored_shape,
                        np.dtype(env.observation_dtype), frame_stack=stack)

    rng = jax.random.PRNGKey(cfg.seed)
    k_carry, k_learn = jax.random.split(rng)
    carry = init_collect(k_carry)
    obs_example = jax.tree.map(lambda x: x[0], carry.obs)
    state = init_learner(k_learn, obs_example)
    host_rng = np.random.default_rng(cfg.seed)

    def sample_host() -> Transition:
        hb = ring.sample(host_rng, cfg.learner.batch_size,
                         cfg.learner.n_step, cfg.learner.gamma)
        return Transition(obs=hb.obs, action=hb.action, reward=hb.reward,
                          discount=hb.discount, next_obs=hb.next_obs)

    def put_batch(hb: Transition) -> Transition:
        return jax.tree.map(jax.device_put, hb)

    def ring_append(tree, lo, hi):
        ring.add_chunk(tree["obs"], tree["action"], tree["reward"],
                       tree["terminated"], tree["truncated"])

    # Double-buffered H2D (replay/staging.py): batch g+1 is gathered
    # into reusable pinned-host staging buffers and its upload
    # dispatched while step g trains.
    stager = None
    if double_buffer:
        from dist_dqn_tpu.replay.staging import DoubleBufferedStager
        stager = DoubleBufferedStager(depth=2, name="host_replay")

    # Streamed D2H + background worker (the pipeline's stages 2 and 3).
    evacuator = worker = None
    if pipeline:
        from dist_dqn_tpu.replay.staging import (EvacuationWorker,
                                                 StreamedEvacuator)
        evacuator = StreamedEvacuator(num_slices=evac_slices,
                                      name="host_replay")
        worker = EvacuationWorker(evacuator, ring_append,
                                  name="host_replay")

    # Crash forensics (ISSUE 4): per-stage heartbeats (the evacuation
    # stage's heartbeat lives inside EvacuationWorker as
    # "evac.host_replay") + per-chunk flight events; the divergence
    # sentinel sees every train event's loss and the end-of-run param
    # checksum. All null-safe no-ops until the CLI arms them
    # (--forensics-dir / --no-flight-recorder, train.py). Startup grace
    # covers the first-chunk jit compile; a compile outliving it is the
    # wedged-tunnel hang and trips with its stack on record.
    fr = tm_flight.get_flight()
    hb_collect = tm_watchdog.heartbeat(
        "host_replay.collect",
        startup_grace_s=tm_watchdog.STARTUP_GRACE_S)
    hb_train = tm_watchdog.heartbeat(
        "host_replay.train", startup_grace_s=tm_watchdog.STARTUP_GRACE_S)

    reg = get_registry()
    _labels = {"loop": "host_replay"}
    g_overlap = reg.gauge(tmc.HOST_REPLAY_OVERLAP,
                          "share of the last chunk's evacuation hidden "
                          "off the training critical path", _labels)
    h_fence = reg.histogram(tmc.HOST_REPLAY_FENCE_WAIT_SECONDS,
                            "main-thread wait on the chunk publication "
                            "fence (evacuation on the critical path)",
                            _labels)
    c_d2h = reg.counter(tmc.HOST_REPLAY_D2H_BYTES,
                        "bytes evacuated device->host by the replay "
                        "pipeline", _labels)

    # Train-event cadence carries its remainder across chunks so the
    # average exactly matches the fused loop's one-event-per-train_every
    # iterations (chunk_iters need not divide train_every).
    updates_per_train = max(cfg.updates_per_train, 1)
    train_debt_iters = 0
    weights = jnp.ones((cfg.learner.batch_size,), jnp.float32)

    num_chunks = max(0, math.ceil(total_env_steps / (chunk_iters * B)))
    env_steps = 0
    grad_steps = 0
    d2h_bytes_total = 0
    fence_wait_total = 0.0
    overlap_fracs = []
    history = []
    metrics = None
    t_start = time.perf_counter()
    try:
        records = stats = handle = None
        if num_chunks:
            # Chunk 0: prologue dispatch + evacuation submit.
            carry, records, stats = collect_jit(carry, state.params,
                                                chunk_iters)
            if pipeline:
                handle = worker.submit(records)
                records = None
        for g in range(num_chunks):
            t0 = time.perf_counter()
            next_records = next_stats = None
            if pipeline:
                # Stage 1 — look-ahead dispatch: chunk g+1's device
                # compute starts now and overlaps chunk g's evacuation
                # tail + training. Its collect uses the params BEFORE
                # chunk g's train event (one event stale — the price of
                # the overlap; the serial path below dispatches at the
                # same point in the data-dependency order, so the two
                # paths stay bit-identical).
                if g + 1 < num_chunks:
                    carry, next_records, next_stats = collect_jit(
                        carry, state.params, chunk_iters)
                hb_collect.beat()
                t_dispatch = time.perf_counter()
                # Stage 2 — fence on chunk g's evacuation (submitted
                # last iteration / at the prologue): its last slice
                # must be published before the train event may sample.
                # The wait is the portion of the evacuation left on
                # the critical path; in steady state the worker
                # finished it while the device ran chunk g-1's trains
                # tail and chunk g's collect.
                handle.wait()
                t_fence = time.perf_counter()
                fence_wait_s = t_fence - t_dispatch
                evac_s = handle.stats["evac_s"]
                d2h_bytes = handle.stats["bytes"]
                overlap = max(0.0, min(1.0, 1.0 - fence_wait_s
                                       / max(evac_s, 1e-9)))
                t_evac_parts = None
            else:
                # Serial reference: one monolithic blocking fetch, one
                # monolithic append, device idle throughout (the
                # round-5 measured shape), THEN the look-ahead dispatch
                # — same pre-train params as the pipelined path, with
                # zero evacuation overlap.
                host = {k: np.asarray(jax.device_get(v))
                        for k, v in records.items()}
                t_mono_fetch = time.perf_counter()
                ring.add_chunk(host["obs"], host["action"], host["reward"],
                               host["terminated"], host["truncated"])
                t_fence = time.perf_counter()
                fence_wait_s = evac_s = t_fence - t0
                d2h_bytes = int(sum(v.nbytes for v in host.values()))
                c_d2h.inc(d2h_bytes)
                overlap = 0.0
                t_evac_parts = (t_mono_fetch - t0, t_fence - t_mono_fetch)
                del host
                if g + 1 < num_chunks:
                    carry, next_records, next_stats = collect_jit(
                        carry, state.params, chunk_iters)
                hb_collect.beat()
            records = next_records
            fr.record("fence", "host_replay.chunk", chunk=g,
                      fence_wait_s=round(fence_wait_s, 4),
                      evac_s=round(evac_s, 4), d2h_bytes=d2h_bytes)
            env_steps += chunk_iters * B
            d2h_bytes_total += d2h_bytes
            fence_wait_total += fence_wait_s
            overlap_fracs.append(overlap)
            # Both paths record the overlap instruments (a serial run's
            # flat-zero overlap series is the dashboard A/B baseline),
            # and the row's ring occupancy is snapshotted HERE — after
            # the fence, before chunk g+1's background appends can
            # advance it — so pipelined and serial rows report the same
            # deterministic post-chunk-g state.
            g_overlap.set(overlap)
            h_fence.observe(fence_wait_s)
            ring_transitions = ring.size * B

            # Stage 3 — train event for chunk g (samples the window
            # INCLUDING chunk g, exactly as the serial path does).
            did = 0
            if (ring.can_sample(cfg.learner.n_step)
                    and ring.size * B >= cfg.replay.min_fill):
                train_debt_iters += chunk_iters
                events = train_debt_iters // max(cfg.train_every, 1)
                train_debt_iters -= events * max(cfg.train_every, 1)
                grads_this_chunk = events * updates_per_train
                if grads_this_chunk:
                    if stager is not None:
                        # Double-buffered: batch g+1's gather + H2D
                        # upload overlap step g's device time.
                        stager.stage(sample_host())
                        for i in range(grads_this_chunk):
                            batch, _ = stager.pop()
                            state, metrics = train_jit(state, batch,
                                                       weights)
                            if i + 1 < grads_this_chunk:
                                stager.stage(sample_host())
                    else:
                        # Serial H2D reference (--no-double-buffer):
                        # sample -> upload -> train, one at a time.
                        batch = put_batch(sample_host())
                        for i in range(grads_this_chunk):
                            state, metrics = train_jit(state, batch,
                                                       weights)
                            if i + 1 < grads_this_chunk:
                                batch = put_batch(sample_host())
                    did = grads_this_chunk
                    grad_steps += did
            # Chunk g+1's evacuation: every sample for chunk g's event
            # has been drawn above, so chunk g+1's slices may publish
            # from here on without changing what those samples saw —
            # submit now, and its transfers overlap chunk g's train
            # execution and chunk g+2's collect.
            if pipeline and records is not None:
                handle = worker.submit(records)
                records = None
            if did:
                jax.block_until_ready(state.params)
            hb_train.beat()
            t_train = time.perf_counter()
            fr.record("train", "host_replay.train_event", chunk=g,
                      grad_steps=did)

            # Fused episode-stat fetch (ISSUE 3 satellite): ONE
            # device_get for both scalars, and its wall accounted in
            # the row instead of hiding between t_train and the log.
            cr, cc = jax.device_get(stats)
            stats = next_stats
            t_stats = time.perf_counter()
            ep = float(cr) / max(float(cc), 1.0)

            row = {
                "env_frames": env_steps, "grad_steps": grad_steps,
                "episode_return": round(ep, 3),
                "env_steps_per_sec": round(
                    chunk_iters * B / max(t_train - t0, 1e-9), 1),
                # Whole-loop rate (ISSUE 3 satellite): includes stat
                # fetches and logging, so it reconciles with the
                # end-of-run summary rate; the per-chunk rate above
                # excludes them by construction.
                "env_steps_per_sec_loop": round(
                    env_steps / max(t_stats - t_start, 1e-9), 1),
                "chunk_train_s": round(t_train - t_fence, 4),
                "chunk_stats_fetch_s": round(t_stats - t_train, 4),
                "evac_s": round(evac_s, 4),
                "evac_fence_wait_s": round(fence_wait_s, 4),
                "evac_overlap_frac": round(overlap, 4),
                # Upper bound on device idle attributable to
                # evacuation: the fence wait (pipelined — the device
                # may still be running collect g+1 under it) or the
                # whole evacuation (serial — nothing is dispatched).
                "device_idle_est_s": round(fence_wait_s, 4),
                "d2h_bytes": d2h_bytes,
                "ring_transitions": ring_transitions,
                "ring_gb": round(ring.nbytes / 1e9, 3),
            }
            if t_evac_parts is not None:
                row["chunk_collect_fetch_s"] = round(t_evac_parts[0], 4)
                row["chunk_ring_s"] = round(t_evac_parts[1], 4)
            if stager is not None:
                row["h2d_staged_bytes"] = stager.bytes_staged
            if did:
                loss_val = float(jax.device_get(metrics["loss"]))
                row["loss"] = round(loss_val, 4)
                # Divergence sentinel (ISSUE 4): a NaN/Inf loss dumps a
                # forensics bundle instead of training on silently.
                tm_watchdog.observe_divergence(loss=loss_val,
                                               step=grad_steps)
            history.append(row)
            log_fn(json.dumps(row))
    finally:
        if worker is not None:
            worker.close()
        hb_collect.close()
        hb_train.close()

    wall = time.perf_counter() - t_start
    # Pin anchor for the pipelined-vs-serial equivalence test: a cheap
    # whole-params digest (float64 fold of float32 leaves, deterministic
    # on one host).
    param_checksum = float(sum(
        np.float64(np.sum(np.asarray(leaf, np.float64)))
        for leaf in jax.tree.leaves(jax.device_get(state.params))))
    # The checksum doubles as the sentinel's divergence signal: NaN/Inf
    # parameters at run end produce a bundle even when no per-chunk loss
    # was sampled (e.g. a run that never reached min_fill). Finiteness
    # only — the sentinel's explosion tracking compares consecutive
    # observations of ONE run's stream, and this is a once-per-run value
    # (two runs in one process would cross-compare).
    if not math.isfinite(param_checksum):
        tm_watchdog.observe_divergence(param_checksum=param_checksum,
                                       step=grad_steps)
    n = max(len(overlap_fracs), 1)
    return {
        "env_steps": env_steps, "grad_steps": grad_steps,
        "wall_s": round(wall, 1),
        "env_steps_per_sec": round(env_steps / wall, 1),
        "ring_transitions": ring.size * B,
        "ring_gb": round(ring.nbytes / 1e9, 3),
        "window_transitions_max": num_slots * B,
        "pipeline": pipeline,
        "evac_slices": (evacuator.num_slices if evacuator is not None
                        else 0),
        "d2h_bytes_total": d2h_bytes_total,
        "evac_fence_wait_s_total": round(fence_wait_total, 4),
        "evac_overlap_frac_mean": round(sum(overlap_fracs) / n, 4),
        "param_checksum": param_checksum,
        "double_buffer": stager is not None,
        "h2d_staged_bytes": (stager.bytes_staged if stager is not None
                             else 0),
        "history": history,
    }
