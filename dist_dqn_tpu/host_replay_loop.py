"""Hybrid fused loop with HOST-DRAM replay (BASELINE.json:5's north-star
phrase — "replay buffer shards across TPU-VM host DRAM" — applied to the
single-chip fused path, VERDICT round-4 next #2).

The all-on-device loop (train_loop.py) is the throughput king, but its
replay window lives in HBM: ~200k stacked / ~1M deduped pixel
transitions on a 16 GB v5e. This loop splits the program at the replay
boundary instead, and since ISSUE 3 runs the split as a THREE-STAGE
SOFTWARE PIPELINE rather than a serial chunk loop:

  device: [act -> env.step] x chunk_iters  (one jitted scan, no replay)
     |  chunk g+1 is dispatched BEFORE chunk g's train event, so its
     |  device compute overlaps chunk g's evacuation and training
     |  (collect therefore acts on params one train event stale — in
     |  BOTH the pipelined and the serial reference path, so the two
     |  stay bit-identical; Podracer-style off-policy staleness)
  d2h:   chunk records leave as --evac-slices streamed time slices
     |  (replay/staging.py StreamedEvacuator): one split dispatch, all
     |  host copies started async, slice k's ring append overlapping
     |  slice k+1's transfer — drained by a BACKGROUND EVACUATION
     |  WORKER so the main thread keeps dispatching
  host:  HostTimeRing in DRAM — the window is DRAM-sized (hundreds of
     |  GB => hundreds of millions of pixel transitions); slice appends
     |  publish atomically under the ring's generation fence, and the
     |  train event fences on the chunk's completion handle before
     |  sampling, so a batch never sees a half-appended slice
  device: train_step (donated state), exactly the learner the fused
          loop runs; sampled batches H2D double-buffered as before

Since ISSUE 5 the H2D side is pipelined too: a SamplePrefetcher thread
(replay/staging.py — the H2D twin of the EvacuationWorker) runs
sample -> gather -> pin -> upload ahead of the learner, so train steps
pop device-resident batches instead of paying host-side sampling on
the critical path; batch k's RNG is a per-index stream split from the
seed, so the prefetched and serial paths draw bit-identical batches
(``prefetch=False`` / --no-prefetch is the pinned serial reference).
Sampling is also PRIORITIZED now (cfg.replay.prioritized / --per): a
NativeSumTree shard over the ring's slots, kept in lockstep with the
ring by the evacuation worker's appends (new chunks seeded at max
priority, under the generation fence), stratified draws + IS weights,
and TD-error write-backs batched into one vectorized tree update per
``prio_writeback_batch`` train steps (PR 2's semantics: chronological
last-wins + per-slot expected-generation drop).

Throughput model: the link, not HBM, prices the window. Per env step
the D2H cost is one stored frame; per grad step the H2D cost is one
batch (2 x batch x obs bytes). On a TPU-VM host link (~10 GB/s) that
admits ~1.4M deduped env-steps/s of collection — above the fused
loop's own rate; on this dev box the axon tunnel (~25 MB/s measured)
is the honest bound. The round-5 chip measurement put the SERIAL chunk
loop at 488 steps/s, 91% D2H-bound — the device idle for the whole
evacuation, the host idle for the whole collect. The pipeline takes the
serial sum collect + evac + train to ~max(evac, collect + train): the
per-chunk rows carry the overlap accounting (``evac_s``,
``evac_fence_wait_s``, ``evac_overlap_frac``, ``device_idle_est_s``)
so the win is measured per run, not asserted. ``pipeline=False``
(train.py ``--no-pipeline``) keeps the monolithic blocking evacuation
as the numerically pinned A/B reference, same discipline as PR 2's
``fused_ingest=False``.
"""
from __future__ import annotations

import json
import math
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dist_dqn_tpu import chaos, loop_common
from dist_dqn_tpu.agents.dqn import make_actor_step, make_learner
from dist_dqn_tpu.config import ExperimentConfig
from dist_dqn_tpu.envs.base import JaxEnv
from dist_dqn_tpu.replay.host_ring import HostTimeRing
from dist_dqn_tpu.types import PyTree, Transition

Array = jnp.ndarray


class CollectCarry(NamedTuple):
    env_state: PyTree
    obs: PyTree
    rng: Array
    iteration: Array
    ep_return: Array


class _UniformTag(NamedTuple):
    """Uniform-mode sample bookkeeping: just the ring generation the
    batch was drawn against (the prefetcher's staleness handshake)."""

    generation: int


class _ScanCarry(NamedTuple):
    """Chunk-internal scan carry: the persistent CollectCarry fields plus
    the chunk-local episode accumulators. The accumulators are RETURNED
    as separate chunk outputs rather than carried across chunks, so the
    pipelined loop can hold and fetch them (one fused device_get) after
    the carry itself has been donated into the next chunk's dispatch."""
    env_state: PyTree
    obs: PyTree
    rng: Array
    iteration: Array
    ep_return: Array
    completed_return: Array
    completed_count: Array


def make_collect_chunk(cfg: ExperimentConfig, env: JaxEnv, net,
                       frame_stack: int, lanes: Optional[int] = None,
                       num_shards: int = 1):
    """(init, collect): a device chunk of act -> step that RETURNS its
    transitions (time-major [C, B, ...]) plus the chunk's episode stats
    instead of writing a ring.

    ``lanes``/``num_shards`` (ISSUE 15, sharded collect): build the
    PER-SHARD variant — a chunk program over ``lanes`` env lanes (one
    dp shard's lane block) whose epsilon schedule decays in per-shard
    iteration units (``make_schedules`` divides the decay horizon by
    ``lanes * num_shards``), so N shard programs together walk exactly
    the schedule the whole-B single program walks at the same global
    frame count. Defaults build the whole-B program unchanged."""
    B = cfg.actor.num_envs if lanes is None else int(lanes)
    act = make_actor_step(net)
    epsilon, _ = loop_common.make_schedules(cfg, B, num_shards)
    slice_newest = ((lambda o: o[..., -1:]) if frame_stack
                    else (lambda o: o))

    def init(rng: Array) -> CollectCarry:
        k_env, k_run = jax.random.split(rng)
        env_state, obs = env.v_reset(k_env, B)
        obs = jax.tree.map(jnp.copy, obs)
        return CollectCarry(env_state=env_state, obs=obs, rng=k_run,
                            iteration=jnp.int32(0),
                            ep_return=jnp.zeros((B,), jnp.float32))

    def collect(carry: CollectCarry, params, num_iters: int):
        def one_iteration(sc: _ScanCarry, _):
            rng, k_act = jax.random.split(sc.rng)
            eps = epsilon(sc.iteration)
            actions = act(params, sc.obs, k_act, eps)
            env_state, out = env.v_step(sc.env_state, actions)
            record = dict(obs=slice_newest(sc.obs), action=actions,
                          reward=out.reward, terminated=out.terminated,
                          truncated=out.truncated)
            done = jnp.logical_or(out.terminated, out.truncated)
            ep_return, completed_return, completed_count = \
                loop_common.episode_stats_update(sc, out.reward, done)
            return _ScanCarry(env_state=env_state, obs=out.obs, rng=rng,
                              iteration=sc.iteration + 1,
                              ep_return=ep_return,
                              completed_return=completed_return,
                              completed_count=completed_count), record

        zero = jnp.float32(0.0)
        sc = _ScanCarry(*carry, completed_return=zero,
                        completed_count=zero)
        sc, records = jax.lax.scan(one_iteration, sc, None,
                                   length=num_iters)
        carry = CollectCarry(env_state=sc.env_state, obs=sc.obs,
                             rng=sc.rng, iteration=sc.iteration,
                             ep_return=sc.ep_return)
        stats = (sc.completed_return, sc.completed_count)
        return carry, records, stats

    return init, collect


class _MultiEvacHandle:
    """Fan-in completion handle over per-shard evacuation jobs (dp > 1):
    the train event fences when EVERY shard's lane block is published.
    ``evac_s`` reports the slowest shard (the critical-path wall);
    bytes/slices aggregate."""

    def __init__(self, handles):
        self.handles = handles

    def wait(self, timeout=None) -> bool:
        ok = True
        for h in self.handles:
            ok = h.wait(timeout) and ok
        return ok

    @property
    def done(self) -> bool:
        return all(h.done for h in self.handles)

    @property
    def stats(self) -> dict:
        return {
            "evac_s": max(h.stats["evac_s"] for h in self.handles),
            "bytes": sum(h.stats["bytes"] for h in self.handles),
            "slices": sum(h.stats["slices"] for h in self.handles),
        }

    @property
    def per_shard(self) -> list:
        """[shard] -> that shard's own drained stats (ISSUE 15): the
        per-shard byte-conservation evidence and straggler wall."""
        return [h.stats for h in self.handles]


class _ResumedEvacHandle:
    """Completion-handle stand-in installed on resume: the chunk it
    fences was already appended to the ring INSIDE the checkpoint, so
    the fence is a no-op and the evacuation accounting reads zero."""

    stats = {"evac_s": 0.0, "bytes": 0, "slices": 0}
    per_shard = ()
    done = True

    def wait(self, timeout=None) -> bool:
        return True


def run_host_replay(cfg: ExperimentConfig, total_env_steps: int,
                    chunk_iters: int = 200, log_fn=print,
                    env: Optional[JaxEnv] = None,
                    double_buffer: bool = True,
                    pipeline: bool = True,
                    evac_slices: int = 4,
                    prefetch: bool = True,
                    prefetch_depth: int = 2,
                    prioritized: Optional[bool] = None,
                    prio_writeback_batch: int = 8,
                    checkpoint_dir: Optional[str] = None,
                    save_every_frames: int = 0,
                    mesh_devices: int = 1,
                    sharded_collect: Optional[bool] = None,
                    device_sampling: bool = False,
                    profile_dir: Optional[str] = None):
    """Run the hybrid loop; returns a summary dict.

    Cadence matches the fused loop: one train event every
    ``cfg.train_every`` env iterations, ``cfg.updates_per_train *
    cfg.replay.updates_per_chunk`` grad steps each (the ISSUE 6 replay
    ratio — the prefetcher simply draws that many batches per event),
    batches sampled from the host ring at the pow2-bucketed
    ``replay.train_batch`` width — uniformly, or by sum-tree priority
    when ``prioritized`` (default: ``cfg.replay.prioritized``) is set.

    ``pipeline`` selects the three-stage software pipeline (streamed
    sub-chunk evacuation drained by a background worker, trains fenced
    on the chunk's publication handle); False is the serial reference —
    one monolithic blocking ``device_get`` + one monolithic
    ``add_chunk``, device idle throughout. Both paths share the same
    collect-ahead schedule (chunk g+1 dispatched with the params as
    they stand BEFORE chunk g's train event), so they are numerically
    IDENTICAL — tests/test_host_replay_pipeline.py pins it.

    ``prefetch`` moves the whole sample -> gather -> stage chain onto a
    background SamplePrefetcher thread (replay/staging.py); False keeps
    the sample-in-loop path as the serial reference. Batch RNG streams
    are split from ``cfg.seed`` per batch INDEX, so the two paths draw
    bit-identical batches in uniform mode — the ISSUE 5 equivalence
    pin. PER mode is the one deliberate exception to bit-level
    reproducibility under prefetch: batch k+1's sum-tree draw races
    the batched |TD| write-backs of steps <= k on the fence lock, so
    WHICH priorities a draw sees is timing-dependent (every
    interleaving is a valid PER schedule — write-backs already lag by
    up to ``prio_writeback_batch`` steps by design; ``--no-prefetch``
    PER remains run-to-run deterministic for debugging).
    ``prefetch_depth`` bounds how many device-resident batches may
    be staged ahead. With ``prefetch`` the legacy ``double_buffer``
    knob is moot (the prefetcher owns its own stager); without it,
    ``double_buffer=False`` is the fully serial H2D reference —
    numerically identical, tests/test_ingest_fastpath.py pins it.

    ``prio_writeback_batch`` batches that many train steps' |TD|
    write-backs into one vectorized sum-tree update (PER only; 1 =
    per-step flush), mirroring the apex service's knob.

    ``checkpoint_dir`` (ISSUE 8; sharded + PER since ISSUE 12) enables
    WHOLE-STATE checkpoint/resume every ``save_every_frames`` env
    frames (0 = default cadence: ``max(cfg.eval_every_steps, one
    chunk)`` — each save copies the whole ring window, so the default
    never pays that per chunk): learner state + collect carry (orbax)
    plus the host ring window(s), pending chunk, episode stats and
    every loop cursor (versioned sidecar npz — utils/ckpt_schema.py).
    Saves land at a QUIESCED end-of-chunk boundary (every shard's
    in-flight evacuation is fenced first — idempotent, the next
    chunk's body re-fences for free), so a run killed at chunk k and
    resumed continues BIT-IDENTICALLY to an uninterrupted one — the
    resume pins in tests/test_chaos.py (dp=1 uniform) and
    tests/test_sharded_checkpoint.py (dp>1, PER) hold against mid-run
    kills. At dp > 1 the sidecar carries one ring snapshot PER SHARD
    plus the mesh width; PER mode snapshots each shard's
    RingPrioritySampler (shadow mass, exact sum-tree heap, running
    max, deferred write-backs) so a resumed run's priorities are
    exact, not max-seeded. The sidecar pins ``sidecar_version`` /
    ``chunk_iters`` / ``dp`` / ``per`` and refuses a mismatched resume
    loudly (counted in dqn_checkpoint_refused_resumes_total); a torn
    sidecar falls back to the newest intact step, deleting the
    unusable one. PER + prefetch resume keeps PER's documented
    timing-dependence (above); ``--no-prefetch`` PER resume is
    bit-identical.

    ``mesh_devices`` (ISSUE 10 tentpole) runs the runtime DATA-PARALLEL
    over a ``dp`` mesh of that many devices (0 = all): env lanes split
    into ``dp`` lane blocks, each block's transitions evacuate through
    that shard's own EvacuationWorker into its own host ring
    (replay/sharded.py ShardedHostReplay), each shard's own
    SamplePrefetcher feeds its LOCAL chip, and the train step runs
    under ``shard_map`` with params replicated, batch rows sharded over
    ``dp`` and ONE pmean gradient allreduce per update (the same specs
    the fused and apex learners use — parallel/learner.py).

    Since ISSUE 15 COLLECT is data-parallel too: each dp shard runs its
    OWN collect program over its own ``B/dp`` env-lane block, with its
    own donated ``CollectCarry`` and its own per-shard RNG stream, ON
    ITS OWN DEVICE — the transitions are born on the device whose
    evacuation worker feeds the shard's ring, so no lane-block split
    dispatch and no cross-shard scatter exist anywhere on the path.
    All shard dispatches share ONE params snapshot per chunk (a single
    replicated copy/bf16-cast program; each device materializes its
    replica locally and the shard collects consume zero-copy per-device
    views — parallel/learner.py replicated_device_views), so the bf16
    actor split still costs one cast per chunk, not one per shard. The
    collect-ahead schedule, heartbeats
    (``host_replay.collect.s{N}``), generation fences and evacuation
    workers are all per-shard. ``mesh_devices=1`` is the untouched
    pre-mesh program — bit-identical by construction (same code path);
    ``sharded_collect=True`` at ``mesh_devices=1`` forces the sharded
    machinery through a 1-shard mesh instead — the mechanism pin
    (tests/test_sharded_collect.py holds it bit-identical to the
    single-collect program).
    """
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.telemetry import collectors as tmc, get_registry
    from dist_dqn_tpu.telemetry import flight as tm_flight
    from dist_dqn_tpu.telemetry import watchdog as tm_watchdog

    # Honest-unsupported-surface gate (ADVICE r5): this loop builds the
    # FEED-FORWARD actor/learner; a recurrent config would silently
    # train the wrong program — say so.
    if cfg.network.lstm_size > 0:
        raise ValueError(
            "host-replay runs the feed-forward collect/train split; "
            "recurrent (R2D2, network.lstm_size>0) configs need the "
            "sequence learner — use the apex runtime or the fused loop")
    if evac_slices < 1:
        raise ValueError(f"--evac-slices must be >= 1, got {evac_slices}")
    if prio_writeback_batch < 1:
        raise ValueError("prio_writeback_batch must be >= 1, got "
                         f"{prio_writeback_batch}")
    per_enabled = (cfg.replay.prioritized if prioritized is None
                   else prioritized)
    if device_sampling and not per_enabled:
        raise ValueError(
            "--device-sampling without --per has nothing to sample on "
            "device: the priority planes hold p^alpha mass (uniform "
            "draws never touch a tree). Add --per or drop "
            "--device-sampling")
    dp = len(jax.devices()) if mesh_devices == 0 else int(mesh_devices)
    if dp < 1:
        raise ValueError(f"mesh_devices must be >= 0, got {mesh_devices}")
    if dp > len(jax.devices()):
        raise ValueError(f"--mesh-devices {dp} requested but only "
                         f"{len(jax.devices())} devices are available")
    if sharded_collect is False and dp > 1:
        raise ValueError(
            "--mesh-devices > 1 always runs the sharded collect path "
            "(ISSUE 15 removed the single-device lane-scatter collect); "
            "sharded_collect=False is only meaningful at mesh width 1")
    # mesh_mode routes the WHOLE sharded machinery (per-shard collect +
    # rings + pipelines + shard_map train). dp > 1 implies it;
    # sharded_collect=True forces it through a 1-shard mesh — the
    # dp=1 mechanism-equivalence pin's knob.
    mesh_mode = dp > 1 or bool(sharded_collect)

    if env is None:
        env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    B = cfg.actor.num_envs
    obs_shape = tuple(env.observation_shape)
    stack = (cfg.replay.frame_dedup
             and getattr(env, "frame_stack", 0)) or 0
    if cfg.replay.frame_dedup and stack < 2:
        raise ValueError(
            "replay.frame_dedup=True but this env declares no rolling "
            "frame stack (envs/base.py JaxEnv.frame_stack)")
    stored_shape = obs_shape[:-1] + (1,) if stack else obs_shape

    # Floor covers the n-step window AND the dedup rebuild context —
    # a smaller ring would be permanently unsampleable (can_sample
    # needs size > n_step + stack - 1).
    num_slots = max(cfg.replay.capacity // B,
                    cfg.learner.n_step + max(stack - 1, 0) + 2)
    # Fail BEFORE the compile, naming the knobs: a chunk larger than the
    # ring would only surface in HostTimeRing.add_chunk after the first
    # device chunk (ADVICE r5 — wasted compile, error points nowhere).
    if chunk_iters > num_slots:
        raise ValueError(
            f"--chunk-iters {chunk_iters} exceeds the host ring's "
            f"{num_slots} slots (replay.capacity={cfg.replay.capacity} "
            f"/ num_envs={B}); lower --chunk-iters or raise "
            "replay.capacity (one chunk == the whole window would make "
            "the ring a FIFO of the last chunk — keep chunk_iters well "
            "below the slot count)")

    if dp > 1 and B % dp:
        raise ValueError(
            f"actor.num_envs={B} not divisible by --mesh-devices {dp}: "
            "each dp shard owns one env-lane block of the collect chunk")

    if mesh_mode:
        # Per-shard collect program (ISSUE 15): one chunk body over a
        # B/dp lane block; ONE jit, dispatched once per shard on that
        # shard's own device (jit re-specializes per device placement,
        # so the mesh pays dp compiles of the same small program).
        init_collect, collect = make_collect_chunk(
            cfg, env, net, stack, lanes=B // dp, num_shards=dp)
    else:
        init_collect, collect = make_collect_chunk(cfg, env, net, stack)
    collect_jit = jax.jit(collect, static_argnums=2, donate_argnums=0)
    init_learner, train_step = make_learner(
        net, cfg.learner, axis_name="dp" if mesh_mode else None)
    # Chip-time attribution (ISSUE 19): both hot programs register in
    # the process ProgramRegistry; cost is harvested at the first
    # dispatch (trace-only lowering against the live args — no second
    # XLA compile) and device-seconds at the fences the loop already
    # holds. The collect program is deliberately left without device
    # time in pipeline mode — it overlaps evac+train by design and
    # fencing it would be a new hot-path sync.
    from dist_dqn_tpu.telemetry import devtime as _devtime
    _prog_collect = _devtime.register_program(
        "host_replay.collect", loop="host_replay", role="collect")
    _prog_train = _devtime.register_program(
        "host_replay.train_step", loop="host_replay", role="train")
    mesh = mesh_devs = weights_sharding = None
    if not mesh_mode:
        train_jit = jax.jit(train_step, donate_argnums=0)
    else:
        from jax.sharding import NamedSharding, PartitionSpec as P

        from dist_dqn_tpu.parallel import make_mesh
        from dist_dqn_tpu.parallel.learner import (make_sharded_train_step,
                                                   train_step_specs)
        mesh = make_mesh(devices=jax.devices()[:dp])
        mesh_devs = list(mesh.devices.flat)
        data_specs, metric_specs = train_step_specs("dp")
        # Donates the replicated learner state (inside the helper) — the
        # same aliasing contract the single-chip audit pins.
        train_jit = make_sharded_train_step(train_step, mesh,
                                            data_specs, metric_specs)
        weights_sharding = NamedSharding(mesh, P("dp"))
        repl_sharding = NamedSharding(mesh, P())

    def _train_dispatch(state, batch, w):
        """Every train-step launch goes through here so the registry
        sees one dispatch count per grad step and the cost analysis is
        harvested exactly once, at the first launch (when real args
        exist). The mesh train step is a shard_map wrapper without
        .lower — attach_cost degrades to flops=None there, one shot."""
        if not _prog_train.cost_attached:
            _prog_train.attach_cost(
                lambda: train_jit.lower(state, batch, w))
        _prog_train.count_dispatch()
        return train_jit(state, batch, w)

    # Replay-ratio engine (ISSUE 6): multiplies the grad steps each
    # train event runs — the SamplePrefetcher simply draws that many
    # batches ahead, so the ratio rides the existing sample pipeline.
    replay_ratio = loop_common.resolve_replay_ratio(cfg)
    # Wide bucketed train batches (ISSUE 6): resolved through the same
    # pow2 rule as the fused loop; default = learner.batch_size exactly.
    train_batch = loop_common.resolve_train_batch(cfg)
    if dp > 1 and train_batch % dp:
        raise ValueError(
            f"train batch {train_batch} not divisible by --mesh-devices "
            f"{dp}: each dp shard draws and uploads an equal row block "
            "(widen replay.train_batch or change the mesh size)")
    # Actor-dtype split (ISSUE 6): collect already acts on chunk-stale
    # params by construction (the collect-ahead schedule), so the bf16
    # snapshot costs ONE extra cast dispatch per chunk and no extra
    # staleness. Learner masters stay fp32 untouched.
    _cast_actor, _actor_split = loop_common.make_actor_param_cast(
        cfg.network.actor_dtype)
    cast_jit = jax.jit(_cast_actor) if _actor_split else None

    if not mesh_mode:
        def collect_params(state):
            return cast_jit(state.params) if _actor_split \
                else state.params
    else:
        from dist_dqn_tpu.parallel.learner import replicated_device_views

        # ONE collect-params snapshot per chunk, shared by every shard
        # dispatch (ISSUE 15): a single replicated mesh program — each
        # device casts/copies its own replica locally, replacing PR
        # 10's per-chunk host mirror (one D2H + re-upload) with zero
        # host traffic and exactly one cast even at dp shards. The
        # copy (never an alias of the live params) is what lets the
        # donated train step overwrite its state while the async shard
        # collects are still reading the snapshot.
        # donation: the snapshot must COPY (the learner still owns the
        # params the train step donates); devtime: one cast per chunk.
        @jax.jit
        def snapshot_collect_params(params):
            params = _cast_actor(params) if _actor_split else params
            return jax.tree.map(jnp.copy, params)

        def collect_params_views(state):
            """[shard] -> shard s's zero-copy device view of this
            chunk's one shared snapshot."""
            return replicated_device_views(
                snapshot_collect_params(state.params), mesh_devs)

    if not mesh_mode:
        ring = HostTimeRing(num_slots, B, stored_shape,
                            np.dtype(env.observation_dtype),
                            frame_stack=stack)
        store = None
    else:
        from dist_dqn_tpu.replay.sharded import ShardedHostReplay
        store = ShardedHostReplay(dp, num_slots, B // dp, stored_shape,
                                  np.dtype(env.observation_dtype),
                                  frame_stack=stack)
        ring = None

    rng = jax.random.PRNGKey(cfg.seed)
    k_carry, k_learn = jax.random.split(rng)
    carries = None
    if not mesh_mode:
        carry = init_collect(k_carry)
        obs_example = jax.tree.map(lambda x: x[0], carry.obs)
    else:
        # Per-shard collect carries, each committed to its own device
        # (ISSUE 15). Shard s acts on its own RNG stream; ONE shard
        # keeps the seed's undivided stream, which is what makes the
        # 1-shard sharded-collect path bit-identical to the
        # single-collect program (the dp=1 mechanism pin,
        # tests/test_sharded_collect.py).
        shard_keys = ([k_carry] if dp == 1
                      else list(jax.random.split(k_carry, dp)))
        carries = [jax.device_put(init_collect(shard_keys[s]),
                                  mesh_devs[s]) for s in range(dp)]
        obs_example = jax.tree.map(lambda x: x[0], carries[0].obs)
        carry = None
    state = init_learner(k_learn, obs_example)
    if mesh_mode:
        # Replicate the learner once onto the mesh; the donated sharded
        # train step then updates the replicas in place.
        state = jax.device_put(state, repl_sharding)

    # Prioritized sampling (ISSUE 5): a sum-tree shard over the ring's
    # slots, kept in lockstep with every append (main thread or
    # evacuation worker) through the ring's publish hook — under the
    # same generation fence the samplers hold. dp > 1 attaches ONE
    # sum-tree per shard ring (per-shard fences, per-shard flushes).
    per_sampler = per_samplers = None
    if per_enabled and not mesh_mode:
        if device_sampling:
            from dist_dqn_tpu.replay.host_ring import \
                RingDevicePrioritySampler
            per_sampler = RingDevicePrioritySampler(
                ring, n_step=cfg.learner.n_step,
                alpha=cfg.replay.priority_exponent,
                beta=cfg.replay.importance_exponent,
                eps=cfg.replay.priority_eps,
                device=jax.devices()[0], seed=cfg.seed)
            log_fn("# host-replay sampler: prioritized device plane "
                   f"({jax.devices()[0].platform}, "
                   f"alpha={cfg.replay.priority_exponent}, "
                   f"beta={cfg.replay.importance_exponent}, "
                   f"prio_writeback_batch={prio_writeback_batch})")
        else:
            from dist_dqn_tpu.replay.host_ring import RingPrioritySampler
            per_sampler = RingPrioritySampler(
                ring, n_step=cfg.learner.n_step,
                alpha=cfg.replay.priority_exponent,
                beta=cfg.replay.importance_exponent,
                eps=cfg.replay.priority_eps)
            log_fn("# host-replay sampler: prioritized sum-tree "
                   f"({type(per_sampler.tree).__name__}, "
                   f"alpha={cfg.replay.priority_exponent}, "
                   f"beta={cfg.replay.importance_exponent}, "
                   f"prio_writeback_batch={prio_writeback_batch})")
    elif per_enabled:
        per_samplers = store.attach_priority_samplers(
            n_step=cfg.learner.n_step,
            alpha=cfg.replay.priority_exponent,
            beta=cfg.replay.importance_exponent,
            eps=cfg.replay.priority_eps,
            device_sampling=device_sampling,
            devices=mesh_devs, seed=cfg.seed)
        kind = ("device plane" if device_sampling
                else f"sum-tree ({type(per_samplers[0].tree).__name__})")
        log_fn(f"# host-replay sampler: prioritized {kind} x {dp} "
               f"shards (alpha={cfg.replay.priority_exponent}, "
               f"beta={cfg.replay.importance_exponent}, "
               f"prio_writeback_batch={prio_writeback_batch})")
    else:
        log_fn("# host-replay sampler: uniform"
               + (f" x {dp} shards" if dp > 1 else ""))

    def _batch_rng(k: int, shard: Optional[int] = None
                   ) -> np.random.Generator:
        # Per-batch-index RNG streams split from the seed: batch k's
        # content is a pure function of (k, ring window), never of
        # which thread drew it or when — the property that makes the
        # prefetched and serial paths bit-identical. dp shards extend
        # the spawn key with the shard id: stream (k, s) is shard s's
        # slice of train batch k, identical whether a prefetcher thread
        # or the serial reference draws it.
        key = (k,) if shard is None else (k, shard)
        return np.random.default_rng(
            np.random.SeedSequence(cfg.seed, spawn_key=key))

    def sample_host(k: int):
        """Batch k's host-side sample+gather -> (host pytree, aux)."""
        rng_k = _batch_rng(k)
        if per_sampler is not None:
            hb, aux = per_sampler.sample(rng_k, train_batch,
                                         cfg.learner.gamma)
            tr = Transition(obs=hb.obs, action=hb.action,
                            reward=hb.reward, discount=hb.discount,
                            next_obs=hb.next_obs)
            # IS weights travel WITH the batch through the staging
            # pipeline, so the upload and the bookkeeping stay one unit.
            return (tr, aux.weights), aux
        hs = ring.sample(rng_k, train_batch,
                         cfg.learner.n_step, cfg.learner.gamma)
        hb = hs.batch
        tr = Transition(obs=hb.obs, action=hb.action, reward=hb.reward,
                        discount=hb.discount, next_obs=hb.next_obs)
        return tr, _UniformTag(generation=hs.generation)

    def put_batch(tree):
        return jax.tree.map(jax.device_put, tree)

    def ring_append(tree, lo, hi):
        ring.add_chunk(tree["obs"], tree["action"], tree["reward"],
                       tree["terminated"], tree["truncated"])

    # -- mesh plumbing (ISSUE 10): per-shard sample/upload/assemble ------
    shard_samples = shard_puts = assemble_tree = None
    if mesh_mode:
        lb_shard = train_batch // dp

        def make_shard_sample(s: int):
            ring_s = store.rings[s]
            sampler_s = (per_samplers[s] if per_samplers is not None
                         else None)

            def sample_shard(k: int):
                """Shard s's row block of train batch k. A 1-shard
                mesh keeps the undivided (k,) stream — the dp=1
                mechanism pin's draws are the single-ring draws."""
                rng_k = _batch_rng(k, s if dp > 1 else None)
                if sampler_s is not None:
                    hb, aux = sampler_s.sample(rng_k, lb_shard,
                                               cfg.learner.gamma)
                    tr = Transition(obs=hb.obs, action=hb.action,
                                    reward=hb.reward,
                                    discount=hb.discount,
                                    next_obs=hb.next_obs)
                    return (tr, aux.weights), aux
                hs = ring_s.sample(rng_k, lb_shard, cfg.learner.n_step,
                                   cfg.learner.gamma)
                hb = hs.batch
                tr = Transition(obs=hb.obs, action=hb.action,
                                reward=hb.reward, discount=hb.discount,
                                next_obs=hb.next_obs)
                return tr, _UniformTag(generation=hs.generation)

            return sample_shard

        def _make_shard_put(dev):
            def put(tree):
                # Fresh copy per upload: the staging slot buffers are
                # REUSED while an earlier upload may still alias their
                # pages on CPU PJRT (the ISSUE 5 alias bug) — a per-call
                # copy makes each upload's source immutable for its
                # whole lifetime, and lands the rows on shard s's OWN
                # device so assembly below is zero-copy.
                return jax.tree.map(
                    lambda x: jax.device_put(np.array(x, copy=True),
                                             dev), tree)

            return put

        shard_samples = [make_shard_sample(s) for s in range(dp)]
        shard_puts = [_make_shard_put(mesh_devs[s]) for s in range(dp)]

        def _assemble(*leaves):
            shape = ((sum(lf.shape[0] for lf in leaves),)
                     + tuple(leaves[0].shape[1:]))
            return jax.make_array_from_single_device_arrays(
                shape, weights_sharding, list(leaves))

        def assemble_tree(trees):
            """N per-shard device trees (shard s committed to mesh
            device s) -> one global row-sharded tree, no data motion."""
            return jax.tree.map(lambda *ls: _assemble(*ls), *trees)

    # Sample-side pipeline (ISSUE 5): a background prefetcher runs
    # sample -> gather -> stage ahead of the learner. Without it, the
    # legacy main-thread double-buffered stager (ISSUE 2) or the fully
    # serial put_batch path serve as the pinned references. dp > 1 runs
    # ONE prefetcher per shard, staging onto that shard's local chip.
    prefetcher = stager = prefetchers = None
    if prefetch and mesh_mode:
        from dist_dqn_tpu.replay.staging import SamplePrefetcher
        prefetchers = [
            SamplePrefetcher(shard_samples[s], depth=prefetch_depth,
                             name=f"host_replay_s{s}",
                             wait_generation=store.rings[s]
                             .wait_generation,
                             device_put=shard_puts[s])
            for s in range(dp)
        ]
    elif prefetch:
        from dist_dqn_tpu.replay.staging import SamplePrefetcher
        prefetcher = SamplePrefetcher(sample_host, depth=prefetch_depth,
                                      name="host_replay",
                                      wait_generation=ring.wait_generation)
    elif double_buffer and not mesh_mode:
        from dist_dqn_tpu.replay.staging import DoubleBufferedStager
        stager = DoubleBufferedStager(depth=2, name="host_replay")
    elif double_buffer:
        # Never degrade a requested reference path silently (the
        # train.py ignored-flag discipline): the legacy main-thread
        # stager is single-chip only — the dp serial path samples and
        # uploads per shard on the critical path instead.
        log_fn("# --no-prefetch with --mesh-devices > 1 runs the fully "
               "serial per-shard reference (sample -> per-device upload "
               "-> assemble); the double-buffered stager is single-chip "
               "only — ignored")

    # Streamed D2H + background worker (the pipeline's stages 2 and 3).
    # Mesh mode: one evacuator/worker pair PER SHARD. Since ISSUE 15
    # each shard's records are BORN on that shard's own device (its own
    # collect program), so a worker's whole stream — split dispatch,
    # async host copies, ring appends — runs against its own device and
    # its own generation fence: the lane-block scatter program PR 10
    # dispatched on device 0 no longer exists.
    evacuator = worker = workers = None
    if pipeline and mesh_mode:
        from dist_dqn_tpu.replay.staging import (EvacuationWorker,
                                                 StreamedEvacuator)

        def _make_append(s: int):
            def append(tree, lo, hi):
                store.add_chunk(s, tree["obs"], tree["action"],
                                tree["reward"], tree["terminated"],
                                tree["truncated"])

            return append

        workers = [
            EvacuationWorker(
                StreamedEvacuator(num_slices=evac_slices,
                                  name=f"host_replay_s{s}", shard=s),
                _make_append(s), name=f"host_replay_s{s}", shard=s)
            for s in range(dp)
        ]
    elif pipeline:
        from dist_dqn_tpu.replay.staging import (EvacuationWorker,
                                                 StreamedEvacuator)
        evacuator = StreamedEvacuator(num_slices=evac_slices,
                                      name="host_replay")
        worker = EvacuationWorker(evacuator, ring_append,
                                  name="host_replay")

    def submit_evac(records):
        """Queue one chunk's evacuation; returns the completion handle
        the next train event fences on. Mesh mode takes the per-shard
        records LIST — shard s's block goes straight to shard s's
        worker, no split dispatch in between."""
        if not mesh_mode:
            return worker.submit(records)
        return _MultiEvacHandle([w.submit(r)
                                 for w, r in zip(workers, records)])

    # Crash forensics (ISSUE 4): per-stage heartbeats (the evacuation
    # stage's heartbeat lives inside EvacuationWorker as
    # "evac.host_replay") + per-chunk flight events; the divergence
    # sentinel sees every train event's loss and the end-of-run param
    # checksum. All null-safe no-ops until the CLI arms them
    # (--forensics-dir / --no-flight-recorder, train.py). Startup grace
    # covers the first-chunk jit compile; a compile outliving it is the
    # wedged-tunnel hang and trips with its stack on record.
    fr = tm_flight.get_flight()
    # Collect heartbeats are per-shard in mesh mode (ISSUE 15): stage
    # host_replay.collect.s{N} — a wedged shard dispatch names ITS
    # shard in the forensics bundle instead of hiding behind one
    # aggregate stage.
    if mesh_mode:
        hb_collects = [tm_watchdog.heartbeat(
            f"host_replay.collect.s{s}",
            startup_grace_s=tm_watchdog.STARTUP_GRACE_S)
            for s in range(dp)]
    else:
        hb_collects = [tm_watchdog.heartbeat(
            "host_replay.collect",
            startup_grace_s=tm_watchdog.STARTUP_GRACE_S)]
    hb_train = tm_watchdog.heartbeat(
        "host_replay.train", startup_grace_s=tm_watchdog.STARTUP_GRACE_S)

    def _beat_collect():
        for hb in hb_collects:
            hb.beat()

    reg = get_registry()
    _labels = {"loop": "host_replay"}
    g_overlap = reg.gauge(tmc.HOST_REPLAY_OVERLAP,
                          "share of the last chunk's evacuation hidden "
                          "off the training critical path", _labels)
    h_fence = reg.histogram(tmc.HOST_REPLAY_FENCE_WAIT_SECONDS,
                            "main-thread wait on the chunk publication "
                            "fence (evacuation on the critical path)",
                            _labels)
    c_d2h = reg.counter(tmc.HOST_REPLAY_D2H_BYTES,
                        "bytes evacuated device->host by the replay "
                        "pipeline", _labels)
    # Learner-utilization config surface (ISSUE 6): which replay ratio /
    # batch width / actor dtype produced this process's learner numbers.
    reg.gauge(tmc.LEARNER_REPLAY_RATIO,
              "grad sub-steps per train event", _labels).set(replay_ratio)
    reg.gauge(tmc.LEARNER_TRAIN_BATCH,
              "effective (bucketed) train batch width",
              _labels).set(train_batch)
    reg.gauge(tmc.LEARNER_ACTOR_DTYPE_INFO,
              "1 for the active actor inference dtype",
              {**_labels, "dtype": cfg.network.actor_dtype
               or "float32"}).set(1)
    g_grad_rate = reg.gauge(tmc.LEARNER_GRAD_RATE,
                            "grad steps per second (whole loop)",
                            _labels)
    # Utilization ledger (ISSUE 19): per-chunk wall decomposed into
    # device-busy (train section minus its host-blocked share) and the
    # named idle buckets — evac_fence is the publication-fence wait,
    # prefetch_wait/sample the sample-side blocking, everything else
    # (dispatch enqueues, stat fetches, logging) lands in `other`.
    _ledger = _devtime.UtilizationLedger("host_replay", reg)
    # Sharded-collect surface (ISSUE 15): the lane block each shard's
    # own collect acts over, and the per-shard dispatch enqueue wall
    # (async dispatch — growth means that shard's device queue is full,
    # the dqn_mesh_chunk_dispatch_seconds semantic). The per-shard evac
    # gauges live with the workers (replay/staging.py).
    h_collect_disp = c_shard_d2h = None
    collect_dispatch_s_total = 0.0
    if mesh_mode:
        reg.gauge(tmc.HOST_REPLAY_COLLECT_LANE_BLOCK,
                  "env lanes per shard collect dispatch",
                  _labels).set(B // dp)
        h_collect_disp = [reg.histogram(
            tmc.HOST_REPLAY_COLLECT_SECONDS,
            "per-shard collect dispatch enqueue wall",
            {**_labels, "shard": str(s)}) for s in range(dp)]
        # Serial (--no-pipeline) path's half of the per-shard byte
        # family; the pipelined half lives with each shard's
        # StreamedEvacuator (same name+labels => same series).
        c_shard_d2h = [reg.counter(
            tmc.HOST_REPLAY_SHARD_D2H_BYTES,
            "bytes evacuated from this shard's own device into its "
            "own ring (zero cross-shard lane scatter)",
            {**_labels, "shard": str(s)}) for s in range(dp)]

        def dispatch_collect(state):
            """Per-shard collect dispatches (ISSUE 15 tentpole): one
            shared params snapshot, then shard s's donated carry +
            lane block dispatched on ITS OWN device. Dispatches are
            async, so all dp devices collect concurrently; the
            records land where their evac worker and ring live, and
            no byte ever crosses a shard boundary."""
            nonlocal collect_dispatch_s_total
            views = collect_params_views(state)
            recs, sts = [], []
            stalled = False
            for s in range(dp):
                # Chaos seam (ISSUE 15): per-shard crash/stall at the
                # dispatch site. Stall recovery = the completed
                # dispatch pass below; crash recovery = the next
                # process's resume (anchored beside
                # host_replay.chunk's).
                cev = chaos.fire("host_replay.collect")
                if cev is not None:
                    if cev.fault == "crash":
                        raise chaos.ChaosInjectedError(
                            "host_replay.collect", cev.fault)
                    chaos.sleep_for(cev)
                    stalled = True
                if not _prog_collect.cost_attached:
                    _c, _v = carries[s], views[s]
                    _prog_collect.attach_cost(
                        lambda: collect_jit.lower(_c, _v, chunk_iters))
                t_d = time.perf_counter()
                carries[s], r, st = collect_jit(carries[s], views[s],
                                                chunk_iters)
                dt = time.perf_counter() - t_d
                _prog_collect.count_dispatch()
                h_collect_disp[s].observe(dt)
                collect_dispatch_s_total += dt
                hb_collects[s].beat()
                recs.append(r)
                sts.append(st)
            if stalled:
                chaos.mark_recovered("host_replay.collect")
            return recs, sts

    # Train-event cadence carries its remainder across chunks so the
    # average exactly matches the fused loop's one-event-per-train_every
    # iterations (chunk_iters need not divide train_every).
    updates_per_train = max(cfg.updates_per_train, 1) * replay_ratio
    train_debt_iters = 0
    if not mesh_mode:
        weights = jnp.ones((train_batch,), jnp.float32)
    else:
        weights = jax.device_put(np.ones((train_batch,), np.float32),
                                 weights_sharding)

    # Batched priority write-backs (ISSUE 5, PER only): each train
    # step's |TD| plane stays a device array in this pending list (its
    # dispatch is long retired by flush time, so the np.asarray there
    # costs a copy, not a sync) and lands in the sum-tree as ONE
    # vectorized set per prio_writeback_batch steps. Chronological
    # order + the per-slot generation guard preserve last-write-wins.
    # dp > 1: aux is the LIST of per-shard PerSamples and the flush is
    # per shard — the global priority rows materialize in shard-block
    # order (shard s owns rows [s*lb, (s+1)*lb) of every batch), each
    # shard's rows applied as its own vectorized set under its own fence.
    wb_pending = []
    is_w_sum, is_w_count, is_w_min = 0.0, 0, 1.0

    def _wb_add(aux, metrics):
        nonlocal is_w_sum, is_w_count, is_w_min
        if per_sampler is None and per_samplers is None:
            return
        wb_pending.append((aux, metrics["priorities"]))
        for a in (aux if mesh_mode else (aux,)):
            is_w_sum += float(a.weights.sum())
            is_w_count += int(a.weights.shape[0])
            is_w_min = min(is_w_min, float(a.weights.min()))
        if len(wb_pending) >= prio_writeback_batch:
            _wb_flush()

    def _wb_flush():
        if (per_sampler is None and per_samplers is None) \
                or not wb_pending:
            return
        pending, wb_pending[:] = wb_pending[:], []
        if not mesh_mode:
            leaf = np.concatenate([a.leaf for a, _ in pending])
            prios = np.concatenate([np.asarray(p, np.float64)
                                    for _, p in pending])
            gens = np.concatenate([a.slot_gen for a, _ in pending])
            per_sampler.update_priorities(leaf, prios, expected_gen=gens)
            return
        lb = train_batch // dp
        prios_np = [np.asarray(p, np.float64) for _, p in pending]
        for s in range(dp):
            leaf = np.concatenate([aux[s].leaf for aux, _ in pending])
            pr = np.concatenate([p[s * lb:(s + 1) * lb]
                                 for p in prios_np])
            gens = np.concatenate([aux[s].slot_gen
                                   for aux, _ in pending])
            per_samplers[s].update_priorities(leaf, pr,
                                              expected_gen=gens)

    num_chunks = max(0, math.ceil(total_env_steps / (chunk_iters * B)))
    env_steps = 0
    grad_steps = 0
    sample_k = 0          # global batch index — the RNG-stream cursor

    # -- whole-state checkpoint/resume (ISSUE 8; sharded + PER: ISSUE 12) --
    ckpt = None
    next_save = float("inf")
    start_chunk = 0
    resumed = False
    resume_stats = resume_pending = None
    h_ckpt_save = c_ckpt_bytes = None
    if checkpoint_dir:
        import os

        from dist_dqn_tpu.utils import ckpt_schema
        from dist_dqn_tpu.utils.checkpoint import (TrainCheckpointer,
                                                   record_checkpoint_kind)
        # Checkpoint telemetry (ISSUE 12 satellite): save wall/bytes/
        # shard count, successful resumes, and every refused resume by
        # reason — docs/observability.md "Checkpoint/resume metrics".
        h_ckpt_save = reg.histogram(
            tmc.CHECKPOINT_SAVE_SECONDS,
            "whole quiesced checkpoint save wall (fence + sidecar + "
            "orbax commit)", _labels)
        c_ckpt_bytes = reg.counter(
            tmc.CHECKPOINT_BYTES,
            "checkpoint bytes written (sidecar + learner/carry tree)",
            _labels)
        reg.gauge(tmc.CHECKPOINT_SHARDS_SAVED,
                  "replay shards carried by each whole-state save",
                  _labels).set(dp)

        def _count_refused(reason: str) -> None:
            reg.counter(tmc.CHECKPOINT_REFUSED,
                        "resume attempts refused at the sidecar pins",
                        {**_labels, "reason": reason}).inc()

        def _refuse_resume(reason: str, msg: str):
            _count_refused(reason)
            raise ValueError(msg)

        # Default cadence mirrors the fused loop's eval-period rhythm,
        # never finer than one chunk: each save copies the WHOLE ring
        # window (DRAM-sized at real configs) into the sidecar, so a
        # per-chunk default would put a multi-GB memcpy + npz write on
        # every chunk boundary.
        save_period = save_every_frames or max(cfg.eval_every_steps,
                                               chunk_iters * B)
        ckpt = TrainCheckpointer(checkpoint_dir,
                                 save_every_frames=save_period)
        record_checkpoint_kind(checkpoint_dir, "host_loop")
        next_save = save_period

        def _sidecar_path(step: int) -> str:
            return os.path.join(checkpoint_dir, f"host_loop_{step}.npz")

        # Mesh mode keeps the per-shard collect carries in the SIDECAR
        # (flattened leaves, schema v2) — the orbax tree carries only
        # the learner; the single-collect path keeps its one carry in
        # orbax exactly as before (ISSUE 15).
        example_tree = ({"learner": state} if mesh_mode
                        else {"learner": state, "carry": carry})
        # Newest step whose sidecar READS wins: an orbax step whose
        # sidecar is torn or missing is not a checkpoint — delete it
        # loudly and fall back to the next older one, instead of
        # failing the resume outright (the sidecar.write:torn game-day
        # invariant, scripts/chaos_run.py sharded_ckpt_crash).
        side = step = None
        fell_back = False
        import zipfile
        for cand in sorted(ckpt.all_steps(), reverse=True):
            try:
                with np.load(_sidecar_path(cand)) as f:
                    side = {k: f[k] for k in f.files}
                step = cand
                break
            # Only the CONTENT-level failures a truncated npz actually
            # produces (zip/header/pickle/key errors) plus an absent
            # file count as torn. A transient I/O OSError (stale NFS
            # handle, mount race) propagates instead — deleting a
            # committed step on a transient read error would destroy
            # valid training state.
            except (FileNotFoundError, ValueError, EOFError, KeyError,
                    zipfile.BadZipFile) as e:
                fell_back = True
                _count_refused("torn_sidecar")
                log_fn(f"# checkpoint step {cand}: sidecar unreadable "
                       f"({type(e).__name__}: {e}) — deleting the "
                       "unusable step and falling back to the previous "
                       "one")
                ckpt.delete(cand)
                try:
                    os.remove(_sidecar_path(cand))
                except OSError:
                    pass
        if side is not None:
            ver = int(side.get("sidecar_version", 0))
            if ver != ckpt_schema.SIDECAR_VERSION:
                _refuse_resume(
                    "sidecar_version",
                    f"checkpoint at {checkpoint_dir!r} carries sidecar "
                    f"schema v{ver}, this build reads "
                    f"v{ckpt_schema.SIDECAR_VERSION} — resume with a "
                    "matching build (utils/ckpt_schema.py documents the "
                    "history), or start a fresh --checkpoint-dir")
            if int(side["chunk_iters"]) != chunk_iters:
                # next_chunk/env_steps cursors are in chunk units; a
                # different --chunk-iters would silently misinterpret
                # them and break the bit-identical resume contract.
                _refuse_resume(
                    "chunk_iters",
                    f"checkpoint at {checkpoint_dir!r} was written with "
                    f"--chunk-iters {int(side['chunk_iters'])}, this "
                    f"run uses {chunk_iters} — resume with the same "
                    "loop shape (the ring/env config is already "
                    "validated by the snapshot shapes)")
            if int(side["dp"]) != dp:
                # Lane blocks are positional (shard s owns env lanes
                # [s*L, (s+1)*L)), so a changed mesh width cannot
                # restore the striped window bit-identically. The apex
                # ITEM store migrates across shard counts; this lane
                # store refuses.
                _refuse_resume(
                    "dp",
                    f"checkpoint at {checkpoint_dir!r} was written at "
                    f"--mesh-devices {int(side['dp'])}, this run uses "
                    f"{dp} — resume with the same mesh width "
                    "(re-sharding a lane-striped host-replay window is "
                    "not supported; docs/fault_tolerance.md 'resuming "
                    "a sharded run')")
            if bool(side["sharded_collect"]) != mesh_mode:
                # The collect carries live in different places per mode
                # (per-shard sidecar leaves vs the orbax tree), so a
                # mode flip cannot restore either representation.
                _refuse_resume(
                    "sharded_collect",
                    f"checkpoint at {checkpoint_dir!r} was written "
                    f"with sharded_collect="
                    f"{bool(side['sharded_collect'])}, this run "
                    f"resolves sharded_collect={mesh_mode} — resume "
                    "with the same collect mode (the collect carries "
                    "are stored per mode)")
            if per_enabled and \
                    int(side["prio_writeback_batch"]) \
                    != prio_writeback_batch:
                # The restored pending write-back entries flush when
                # the list crosses prio_writeback_batch: a different
                # cadence would apply |TD| updates on a different
                # schedule than the killed run — silent divergence
                # from the bit-identical contract.
                _refuse_resume(
                    "prio_writeback_batch",
                    f"checkpoint at {checkpoint_dir!r} was written "
                    f"with prio_writeback_batch="
                    f"{int(side['prio_writeback_batch'])}, this run "
                    f"uses {prio_writeback_batch} — resume with the "
                    "same PER write-back cadence")
            if int(side.get("population", 1)) != 1:
                # v4 (ISSUE 20): this loop has no stacked-member plane —
                # a population sidecar's state shapes carry a leading
                # [M] axis its solo restore templates cannot absorb.
                _refuse_resume(
                    "population",
                    f"checkpoint at {checkpoint_dir!r} was written with "
                    f"population={int(side['population'])} stacked "
                    "members, but --runtime host-replay trains a single "
                    "policy — the member axis is checkpoint structure. "
                    "Resume it under the fused --population runtime, or "
                    "start a fresh --checkpoint-dir")
            if bool(side["per"]) != per_enabled:
                _refuse_resume(
                    "per",
                    f"checkpoint at {checkpoint_dir!r} was written with "
                    f"prioritized={bool(side['per'])}, this run "
                    f"configures prioritized={per_enabled} — a uniform "
                    "snapshot cannot honestly seed a sum-tree (and vice "
                    "versa); resume with the same sampler, or start a "
                    "fresh --checkpoint-dir")
            if per_enabled and \
                    int(side["per_sampler_kind"]) != int(device_sampling):
                # The mass shadow would restore either way, but draw
                # timing and fp reduction order differ between the host
                # tree and the device plane — a silent backend swap
                # breaks the bit-identical-resume contract (ISSUE 18).
                _kinds = {0: "host sum-tree", 1: "device plane"}
                _refuse_resume(
                    "sampler_kind",
                    f"checkpoint at {checkpoint_dir!r} was written with "
                    f"the {_kinds[int(side['per_sampler_kind'])]} PER "
                    f"backend, this run configures the "
                    f"{_kinds[int(device_sampling)]} — resume with the "
                    "same --device-sampling setting, or start a fresh "
                    "--checkpoint-dir")
            _, tree = ckpt.restore_latest(example_tree, step=step)
            state = tree["learner"]
            if not mesh_mode:
                carry = tree["carry"]
            else:
                # Per-shard collect carries from the sidecar (ISSUE
                # 15): flattened leaves keyed carry{s}_leaf{i},
                # re-built against the freshly-initialized carries'
                # treedef (same cfg/env => same structure), committed
                # back to each shard's own device.
                cdef = jax.tree.structure(carries[0])
                n_leaves = len(jax.tree.leaves(carries[0]))
                carries = [
                    jax.device_put(
                        jax.tree.unflatten(
                            cdef, [side[f"carry{s}_leaf{i}"]
                                   for i in range(n_leaves)]),
                        mesh_devs[s])
                    for s in range(dp)]
            ring_side = {k[len("ring_"):]: v for k, v in side.items()
                         if k.startswith("ring_")}
            if not mesh_mode:
                ring.load_state_dict(ring_side)
                if per_sampler is not None:
                    # Exact priority state (ISSUE 12): shadow mass,
                    # running max AND the sum-tree heap (incl. native
                    # delta drift) — resumed draws see the killed run's
                    # priorities, not max-priority amnesia.
                    per_sampler.load_state_dict(
                        {k[len("per_"):]: v for k, v in side.items()
                         if k.startswith("per_")})
            else:
                # N per-shard rings (+ per-shard PER sampler state when
                # attached), shard count pinned inside.
                store.load_state_dict(ring_side)
            env_steps = int(side["env_steps"])
            grad_steps = int(side["grad_steps"])
            # Resume lineage baseline (ISSUE 16): post-restore appends
            # stamp the resumed version, not 0.
            if mesh_mode:
                store.current_params_version = grad_steps
            else:
                ring.current_params_version = grad_steps
            sample_k = int(side["sample_k"])
            if prefetcher is not None:
                # Per-index batch RNG: the prefetcher must continue the
                # killed run's index sequence, not restart at 0.
                prefetcher.seek(sample_k)
            if prefetchers is not None:
                # dp > 1: every shard's prefetcher shares the one batch
                # cursor (stream (k, s) is shard s's slice of batch k).
                for p in prefetchers:
                    p.seek(sample_k)
            train_debt_iters = int(side["train_debt_iters"])
            start_chunk = int(side["next_chunk"])
            next_save = env_steps + save_period
            resumed = True
            # Deferred-but-unflushed PER write-backs ride the sidecar
            # verbatim: flushing early at save time would apply |TD|
            # updates sooner than the uninterrupted run does, breaking
            # the bit-identical pin — so the pending list is restored
            # as-is and flushes on the killed run's schedule.
            from dist_dqn_tpu.replay.host_ring import PerSample
            for j in range(int(side.get("wb_count", 0))):
                prios_j = np.asarray(side["wb_prios"][j], np.float64)

                def _wb_aux(s: int) -> "PerSample":
                    leaf = np.asarray(side[f"wb{s}_leaf"][j], np.int64)
                    return PerSample(
                        leaf=leaf,
                        t_idx=np.zeros_like(leaf, np.int32),
                        b_idx=np.zeros_like(leaf, np.int32),
                        slot_gen=np.asarray(side[f"wb{s}_slot_gen"][j],
                                            np.int64),
                        weights=np.zeros(leaf.shape[0], np.float32),
                        generation=0)

                aux = (_wb_aux(0) if not mesh_mode
                       else [_wb_aux(s) for s in range(dp)])
                wb_pending.append((aux, prios_j))
            if bool(side["has_stats"]):
                # Episode-stat scalars of the already-dispatched next
                # chunk: host floats; jax.device_get at the loop's
                # fetch point is a no-op on them. Mesh mode stores one
                # (cr, cc) pair per shard as [dp] arrays.
                if not mesh_mode:
                    resume_stats = (np.float32(side["stats_cr"]),
                                    np.float32(side["stats_cc"]))
                else:
                    resume_stats = [
                        (np.float32(side["stats_cr"][s]),
                         np.float32(side["stats_cc"][s]))
                        for s in range(dp)]
            if bool(side["has_pending"]):
                # Serial path: the next chunk's collected records were
                # materialized into the checkpoint; the body's
                # monolithic fetch reads host arrays identically. Mesh
                # mode stores one record dict per shard
                # (pending{s}_{field}).
                if not mesh_mode:
                    resume_pending = {
                        k[len("pending_"):]: v for k, v in side.items()
                        if k.startswith("pending_")}
                else:
                    import re as _re
                    _pat = _re.compile(r"^pending(\d+)_([a-z_]+)$")
                    resume_pending = [dict() for _ in range(dp)]
                    for k, v in side.items():
                        m = _pat.match(k)
                        if m is not None:
                            resume_pending[int(m.group(1))][
                                m.group(2)] = v
            log_fn(json.dumps({"resumed_at_frames": env_steps,
                               "resumed_at_chunk": start_chunk,
                               "resumed_dp": dp,
                               "resumed_per": per_enabled}))
            reg.counter(tmc.CHECKPOINT_RESUMES,
                        "successful whole-state resumes",
                        _labels).inc()
            # Resuming from the checkpoint IS the recovery proof for an
            # injected mid-run crash (in-process chaos replay); a
            # resume that fell back past an injected torn sidecar
            # proves that seam recovered too.
            chaos.mark_recovered("host_replay.chunk")
            # ...and for a crash injected at a shard's collect dispatch
            # (ISSUE 15): the resumed process restores that shard's
            # carry from the sidecar, which is the surviving path.
            chaos.mark_recovered("host_replay.collect")
            if fell_back:
                chaos.mark_recovered("sidecar.write")

    d2h_bytes_total = 0
    d2h_bytes_by_shard = [0] * dp if mesh_mode else None
    fence_wait_total = 0.0
    sample_s_total = 0.0
    prefetch_wait_s_total = 0.0
    overlap_fracs = []
    history = []
    metrics = None
    t_start = time.perf_counter()
    records = stats = handle = None
    # The restored step already exists on disk: the save guard below
    # must treat it as saved, or resuming a COMPLETED run would re-save
    # its final step (orbax raises StepAlreadyExists) instead of
    # passing straight to the summary.
    last_saved = env_steps if resumed else -1

    def _save_checkpoint(g: int) -> None:
        """Quiesced whole-state save at the end of chunk ``g``'s body.
        Every shard's in-flight evacuation is fenced first (idempotent —
        the next body re-waits for free) so each ring snapshot is the
        complete window; the serial path's un-appended next-chunk
        records, the dispatched episode-stat scalars AND any deferred
        PER write-backs are materialized INTO the checkpoint instead of
        being perturbed — reads only, so the continuing run stays
        bit-identical to an unsaved one."""
        nonlocal last_saved
        if env_steps <= last_saved:
            return
        t_save = time.perf_counter()
        if pipeline and handle is not None:
            handle.wait()
        if not mesh_mode:
            side = {f"ring_{k}": v for k, v in ring.state_dict().items()}
            if per_sampler is not None:
                side.update({f"per_{k}": v for k, v in
                             per_sampler.state_dict().items()})
        else:
            # ShardedHostReplay snapshot: per-shard rings + (when PER)
            # per-shard sampler state, each under its own fence.
            side = {f"ring_{k}": v for k, v in store.state_dict().items()}
            # Per-shard collect carries (ISSUE 15, schema v2): shard
            # s's donated carry, flattened to leaves — the orbax tree
            # carries only the learner in mesh mode.
            for s in range(dp):
                for i, leaf in enumerate(
                        jax.tree.leaves(jax.device_get(carries[s]))):
                    side[f"carry{s}_leaf{i}"] = np.asarray(leaf)
        side.update(
            sidecar_version=np.int64(ckpt_schema.SIDECAR_VERSION),
            env_steps=np.int64(env_steps),
            grad_steps=np.int64(grad_steps),
            sample_k=np.int64(sample_k),
            train_debt_iters=np.int64(train_debt_iters),
            next_chunk=np.int64(g + 1),
            chunk_iters=np.int64(chunk_iters),
            dp=np.int64(dp),
            per=np.bool_(per_enabled),
            per_sampler_kind=np.int64(int(device_sampling)),
            # v4 (ISSUE 20): member-axis width pin — this loop always
            # trains ONE policy; the restore path refuses any other M.
            population=np.int64(1),
            sharded_collect=np.bool_(mesh_mode),
            prio_writeback_batch=np.int64(prio_writeback_batch),
            wb_count=np.int64(len(wb_pending)),
            has_stats=np.bool_(stats is not None),
            has_pending=np.bool_(records is not None))
        if wb_pending:
            # Deferred |TD| write-backs ride along verbatim (see the
            # restore path's comment: an early flush would break the
            # bit-identical pin).
            if not mesh_mode:
                side["wb0_leaf"] = np.stack(
                    [a.leaf for a, _ in wb_pending])
                side["wb0_slot_gen"] = np.stack(
                    [a.slot_gen for a, _ in wb_pending])
            else:
                for s in range(dp):
                    side[f"wb{s}_leaf"] = np.stack(
                        [aux[s].leaf for aux, _ in wb_pending])
                    side[f"wb{s}_slot_gen"] = np.stack(
                        [aux[s].slot_gen for aux, _ in wb_pending])
            side["wb_prios"] = np.stack(
                [np.asarray(p, np.float64) for _, p in wb_pending])
        if stats is not None:
            if not mesh_mode:
                s_cr, s_cc = jax.device_get(stats)
                side.update(stats_cr=np.float32(s_cr),
                            stats_cc=np.float32(s_cc))
            else:
                got = jax.device_get(stats)
                side.update(
                    stats_cr=np.asarray([g_[0] for g_ in got],
                                        np.float32),
                    stats_cc=np.asarray([g_[1] for g_ in got],
                                        np.float32))
        if records is not None:
            if not mesh_mode:
                side.update({f"pending_{k}":
                             np.asarray(jax.device_get(v))
                             for k, v in records.items()})
            else:
                for s, rec in enumerate(records):
                    side.update({f"pending{s}_{k}":
                                 np.asarray(jax.device_get(v))
                                 for k, v in rec.items()})
        # Schema gate (ISSUE 12 satellite): a code path emitting a
        # field utils/ckpt_schema.py does not name fails HERE, at save
        # time, instead of becoming a silently-unread key at restore.
        ckpt_schema.validate_sidecar(side.keys())
        # Sidecar BEFORE the orbax commit (atomic tmp+rename): any
        # committed step implies its sidecar exists, so a crash between
        # the two leaves the previous step as the resume point.
        path = _sidecar_path(env_steps)
        tmp = path + ".tmp"
        with open(tmp, "wb") as fh:
            np.savez(fh, **side)
        # Chaos seam (ISSUE 12): "torn" lands a truncated sidecar at
        # the FINAL path (crash mid-write on a filesystem without
        # atomic-rename semantics) while the orbax commit proceeds —
        # the resume path must detect the unreadable sidecar, delete
        # the unusable step and fall back to the previous one.
        cev = chaos.fire("sidecar.write")
        if cev is not None and cev.fault == "torn":
            with open(tmp, "rb") as fh:
                blob = fh.read()
            with open(path, "wb") as fh:
                fh.write(blob[: max(16, len(blob) // 7)])
            os.remove(tmp)
        else:
            os.replace(tmp, path)
        orbax_tree = ({"learner": state} if mesh_mode
                      else {"learner": state, "carry": carry})
        ckpt.save(env_steps, orbax_tree)
        ckpt.wait()
        last_saved = env_steps
        # Prune sidecars in lockstep with orbax's max_to_keep: each one
        # holds a full ring-window copy, so orphans from pruned steps
        # would leak window-sized files every save period.
        import glob as _glob
        keep = set(ckpt.all_steps())
        for old in _glob.glob(os.path.join(checkpoint_dir,
                                           "host_loop_*.npz")):
            try:
                step = int(os.path.basename(old)[len("host_loop_"):-4])
            except ValueError:
                continue
            if step not in keep:
                os.remove(old)
        wall = time.perf_counter() - t_save
        h_ckpt_save.observe(wall)
        c_ckpt_bytes.inc(
            os.path.getsize(path)
            + int(sum(getattr(leaf, "nbytes", 0) for leaf in
                      jax.tree.leaves(orbax_tree))))
        fr.record("checkpoint", "host_replay.save", frames=env_steps,
                  wall_s=round(wall, 3), shards=dp)
        log_fn(json.dumps({"host_replay_checkpoint": env_steps,
                           "save_s": round(wall, 3),
                           "shards_saved": dp}))

    if ckpt is not None:
        # Emergency checkpoint on watchdog abort (ISSUE 8; all shards
        # since ISSUE 12): the quiesced whole-state save needs
        # main-thread fencing, so the abort path saves a side snapshot
        # instead — the learner tree PLUS every replay shard's ring
        # (and PER sampler) state, each taken under its own generation
        # fence, so the data is per-shard consistent even while the
        # main thread is wedged. Honest limits: the loop cursors are
        # NOT quiesced, so this is a redeploy/forensics artifact, not
        # a bit-identical resume point (docs/fault_tolerance.md) — the
        # emergency sidecar deliberately does NOT carry the resume
        # schema's cursor fields.
        from dist_dqn_tpu.utils.checkpoint import save_pytree

        _emerg_state = {"state": state}

        def _emergency_save():
            import os as _os
            save_pytree(_os.path.join(checkpoint_dir, "emergency_learner"),
                        {"learner": _emerg_state["state"]})
            if not mesh_mode:
                # One fence hold for ring + sampler (RLock): appends
                # may still be in flight on the abort path, and a
                # publish between the two snapshots would tear sampler
                # mass against ring state.
                with ring._fence:
                    eside = {f"ring_{k}": v
                             for k, v in ring.state_dict().items()}
                    if per_sampler is not None:
                        eside.update({f"per_{k}": v for k, v in
                                      per_sampler.state_dict().items()})
            else:
                eside = {f"ring_{k}": v
                         for k, v in store.state_dict().items()}
            eside.update(dp=np.int64(dp), per=np.bool_(per_enabled),
                         env_steps=np.int64(env_steps))
            from dist_dqn_tpu.utils.checkpoint import atomic_savez
            atomic_savez(_os.path.join(checkpoint_dir,
                                       "emergency_sidecar.npz"),
                         **eside)

        tm_watchdog.register_emergency_hook("host_replay.checkpoint",
                                            _emergency_save)

    def _dispatch_chunk():
        """One chunk's collect: the single program (dp=1) or the
        per-shard dispatch pass (mesh mode). Returns (records, stats)
        — per-shard LISTS in mesh mode."""
        nonlocal carry
        if mesh_mode:
            return dispatch_collect(state)
        if not _prog_collect.cost_attached:
            _c, _p = carry, collect_params(state)
            _prog_collect.attach_cost(
                lambda: collect_jit.lower(_c, _p, chunk_iters))
        carry, r, st = collect_jit(carry, collect_params(state),
                                   chunk_iters)
        _prog_collect.count_dispatch()
        return r, st

    # --profile-dir (ISSUE 19 satellite): same contract as the fused
    # loop — trace the first post-warmup chunk (chunk 1; a run that is
    # all one chunk traces that one) into the given directory.
    _tracer = _devtime.maybe_trace_first_chunk(profile_dir)
    _profile_chunk = (min(start_chunk + 1, num_chunks - 1)
                      if profile_dir else -1)
    try:
        if num_chunks and not resumed:
            # Chunk 0: prologue dispatch + evacuation submit.
            records, stats = _dispatch_chunk()
            if pipeline:
                handle = submit_evac(records)
                records = None
        elif resumed and start_chunk < num_chunks \
                and resume_stats is None and resume_pending is None:
            # EXTENSION resume (found by driving the CLI, ISSUE 12): the
            # checkpoint is a FINAL save — no chunk was in flight — and
            # this run's --total-env-steps reaches past it. Run the
            # prologue dispatch against the restored carry/ring, exactly
            # like a fresh start. Honest contract: extension is a
            # supported CONTINUATION, not the bit-identical-resume pin —
            # an uninterrupted longer run would have dispatched this
            # chunk one train event earlier (the collect-ahead
            # schedule), so params at the boundary differ by one
            # staleness event.
            records, stats = _dispatch_chunk()
            if pipeline:
                handle = submit_evac(records)
                records = None
        elif resumed:
            # Re-establish the loop invariants at the top of body
            # ``start_chunk`` exactly as the killed run held them: the
            # fenced chunk is already inside the checkpointed ring
            # (pipeline) or rides along as pending records (serial).
            stats = resume_stats
            if pipeline:
                handle = _ResumedEvacHandle()
            else:
                records = resume_pending
        for g in range(start_chunk, num_chunks):
            if g == _profile_chunk:
                _tracer.start()
            t0 = time.perf_counter()
            next_records = next_stats = None
            if pipeline:
                # Stage 1 — look-ahead dispatch: chunk g+1's device
                # compute starts now and overlaps chunk g's evacuation
                # tail + training. Its collect uses the params BEFORE
                # chunk g's train event (one event stale — the price of
                # the overlap; the serial path below dispatches at the
                # same point in the data-dependency order, so the two
                # paths stay bit-identical).
                if g + 1 < num_chunks:
                    next_records, next_stats = _dispatch_chunk()
                _beat_collect()
                t_dispatch = time.perf_counter()
                # Stage 2 — fence on chunk g's evacuation (submitted
                # last iteration / at the prologue): its last slice
                # must be published before the train event may sample.
                # The wait is the portion of the evacuation left on
                # the critical path; in steady state the worker
                # finished it while the device ran chunk g-1's trains
                # tail and chunk g's collect.
                handle.wait()
                t_fence = time.perf_counter()
                fence_wait_s = t_fence - t_dispatch
                evac_s = handle.stats["evac_s"]
                d2h_bytes = handle.stats["bytes"]
                if mesh_mode:
                    # Per-shard conservation accounting (ISSUE 15):
                    # what each shard's own device evacuated this chunk
                    # (the worker already counted it into the {shard}
                    # telemetry families).
                    for s, st in enumerate(handle.per_shard):
                        d2h_bytes_by_shard[s] += st["bytes"]
                overlap = max(0.0, min(1.0, 1.0 - fence_wait_s
                                       / max(evac_s, 1e-9)))
                t_evac_parts = None
            else:
                # Serial reference: one monolithic blocking fetch (per
                # shard in mesh mode — each shard's records come off
                # its OWN device, no lane re-split), one monolithic
                # append, device idle throughout (the round-5 measured
                # shape), THEN the look-ahead dispatch — same pre-train
                # params as the pipelined path, with zero evacuation
                # overlap.
                if not mesh_mode:
                    host = {k: np.asarray(jax.device_get(v))
                            for k, v in records.items()}
                    t_mono_fetch = time.perf_counter()
                    ring.add_chunk(host["obs"], host["action"],
                                   host["reward"], host["terminated"],
                                   host["truncated"])
                    t_fence = time.perf_counter()
                    d2h_bytes = int(sum(v.nbytes
                                        for v in host.values()))
                    del host
                else:
                    hosts = [{k: np.asarray(jax.device_get(v))
                              for k, v in rec.items()}
                             for rec in records]
                    t_mono_fetch = time.perf_counter()
                    for s, host in enumerate(hosts):
                        store.add_chunk(s, host["obs"], host["action"],
                                        host["reward"],
                                        host["terminated"],
                                        host["truncated"])
                        b_s = int(sum(v.nbytes for v in host.values()))
                        d2h_bytes_by_shard[s] += b_s
                        c_shard_d2h[s].inc(b_s)
                    t_fence = time.perf_counter()
                    d2h_bytes = int(sum(
                        v.nbytes for host in hosts
                        for v in host.values()))
                    del hosts
                fence_wait_s = evac_s = t_fence - t0
                c_d2h.inc(d2h_bytes)
                overlap = 0.0
                t_evac_parts = (t_mono_fetch - t0, t_fence - t_mono_fetch)
                if g + 1 < num_chunks:
                    next_records, next_stats = _dispatch_chunk()
                _beat_collect()
            records = next_records
            fr.record("fence", "host_replay.chunk", chunk=g,
                      fence_wait_s=round(fence_wait_s, 4),
                      evac_s=round(evac_s, 4), d2h_bytes=d2h_bytes)
            env_steps += chunk_iters * B
            d2h_bytes_total += d2h_bytes
            fence_wait_total += fence_wait_s
            overlap_fracs.append(overlap)
            # Both paths record the overlap instruments (a serial run's
            # flat-zero overlap series is the dashboard A/B baseline),
            # and the row's ring occupancy is snapshotted HERE — after
            # the fence, before chunk g+1's background appends can
            # advance it — so pipelined and serial rows report the same
            # deterministic post-chunk-g state.
            g_overlap.set(overlap)
            h_fence.observe(fence_wait_s)
            ring_transitions = (store.size if mesh_mode
                                else ring.size) * B

            # Stage 3 — train event for chunk g (samples the window
            # INCLUDING chunk g, exactly as the serial path does).
            did = 0
            ev_sample_s = ev_wait_s = 0.0
            ev_depth_sum = ev_stale = 0
            sampleable = (store.can_sample(cfg.learner.n_step)
                          if mesh_mode
                          else ring.can_sample(cfg.learner.n_step))
            if sampleable and ring_transitions >= cfg.replay.min_fill:
                train_debt_iters += chunk_iters
                events = train_debt_iters // max(cfg.train_every, 1)
                train_debt_iters -= events * max(cfg.train_every, 1)
                grads_this_chunk = events * updates_per_train
                if grads_this_chunk and mesh_mode:
                    # Data-parallel train event (ISSUE 10): each shard's
                    # pipeline delivers its OWN row block onto its local
                    # chip; assembly stitches the blocks into one global
                    # row-sharded batch and the shard_map'd step runs
                    # one pmean gradient allreduce per update. Per-shard
                    # fences: every shard's ring published chunk g
                    # (fenced above), so each shard's generation is
                    # stable across the event.
                    fence_gens = store.generation
                    lb = train_batch // dp
                    if prefetchers is not None:
                        s0 = [(p.sample_s_total, p.wait_s_total,
                               p.stale_total) for p in prefetchers]
                        for s, p in enumerate(prefetchers):
                            p.request(grads_this_chunk, fence_gens[s])
                        for i in range(grads_this_chunk):
                            parts, w_parts, auxes = [], [], []
                            for s, p in enumerate(prefetchers):
                                dev, aux = p.pop(fence_gens[s])
                                ev_depth_sum += len(p)
                                if per_samplers is not None:
                                    tr, w_s = dev
                                    parts.append(tr)
                                    w_parts.append(w_s)
                                else:
                                    parts.append(dev)
                                auxes.append(aux)
                            batch = assemble_tree(parts)
                            w = (assemble_tree(w_parts)
                                 if per_samplers is not None else weights)
                            state, metrics = _train_dispatch(state, batch, w)
                            _wb_add(auxes, metrics)
                        for s, p in enumerate(prefetchers):
                            ev_sample_s += p.sample_s_total - s0[s][0]
                            ev_wait_s += p.wait_s_total - s0[s][1]
                            ev_stale += p.stale_total - s0[s][2]
                        sample_k = prefetchers[0].next_k
                    else:
                        # Serial dp reference (--no-prefetch): identical
                        # per-(k, shard) RNG streams, so it draws the
                        # SAME batches the prefetched path does.
                        for i in range(grads_this_chunk):
                            t_s = time.perf_counter()
                            parts, w_parts, auxes = [], [], []
                            for s in range(dp):
                                host, aux = shard_samples[s](sample_k)
                                if per_samplers is not None:
                                    tr, w_s = host
                                    parts.append(shard_puts[s](tr))
                                    w_parts.append(shard_puts[s](w_s))
                                else:
                                    parts.append(shard_puts[s](host))
                                auxes.append(aux)
                            ev_sample_s += time.perf_counter() - t_s
                            sample_k += 1
                            batch = assemble_tree(parts)
                            w = (assemble_tree(w_parts)
                                 if per_samplers is not None else weights)
                            state, metrics = _train_dispatch(state, batch, w)
                            _wb_add(auxes, metrics)
                    did = grads_this_chunk
                    grad_steps += did
                    # Lineage baseline (ISSUE 16): appends from here on
                    # are born at this params version, and staleness at
                    # sample time is measured against it.
                    store.current_params_version = grad_steps
                    sample_s_total += ev_sample_s
                    prefetch_wait_s_total += ev_wait_s
                elif grads_this_chunk:
                    # The window every one of this event's batches must
                    # see: chunk g is published (fenced above) and
                    # chunk g+1's appends are gated until the event's
                    # last sample is drawn, so the generation is stable
                    # across the event.
                    fence_gen = ring.generation

                    def _unpack(dev):
                        # PER stages (batch, IS weights) as one tree;
                        # uniform reuses the constant device ones.
                        return dev if per_sampler is not None \
                            else (dev, weights)

                    if prefetcher is not None:
                        # Sample-ahead: the prefetcher thread samples/
                        # gathers/uploads batch i+1.. while batch i
                        # trains; pops verify the generation tag.
                        s0 = (prefetcher.sample_s_total,
                              prefetcher.wait_s_total,
                              prefetcher.stale_total)
                        prefetcher.request(grads_this_chunk, fence_gen)
                        for i in range(grads_this_chunk):
                            dev, aux = prefetcher.pop(fence_gen)
                            ev_depth_sum += len(prefetcher)
                            batch, w = _unpack(dev)
                            state, metrics = _train_dispatch(state, batch, w)
                            _wb_add(aux, metrics)
                        ev_sample_s = prefetcher.sample_s_total - s0[0]
                        ev_wait_s = prefetcher.wait_s_total - s0[1]
                        ev_stale = prefetcher.stale_total - s0[2]
                        sample_k = prefetcher.next_k
                    elif stager is not None:
                        # Serial reference with main-thread double
                        # buffering (--no-prefetch): batch i+1's gather
                        # + upload still overlap step i's device time,
                        # but the sample itself stays on this thread.
                        t_s = time.perf_counter()
                        host, aux = sample_host(sample_k)
                        stager.stage(host, aux=aux)
                        ev_sample_s += time.perf_counter() - t_s
                        sample_k += 1
                        for i in range(grads_this_chunk):
                            dev, aux = stager.pop()
                            batch, w = _unpack(dev)
                            state, metrics = _train_dispatch(state, batch, w)
                            _wb_add(aux, metrics)
                            if i + 1 < grads_this_chunk:
                                t_s = time.perf_counter()
                                host, nxt = sample_host(sample_k)
                                stager.stage(host, aux=nxt)
                                ev_sample_s += time.perf_counter() - t_s
                                sample_k += 1
                    else:
                        # Fully serial H2D reference
                        # (--no-prefetch --no-double-buffer):
                        # sample -> upload -> train, one at a time.
                        t_s = time.perf_counter()
                        host, aux = sample_host(sample_k)
                        dev = put_batch(host)
                        ev_sample_s += time.perf_counter() - t_s
                        sample_k += 1
                        for i in range(grads_this_chunk):
                            batch, w = _unpack(dev)
                            state, metrics = _train_dispatch(state, batch, w)
                            _wb_add(aux, metrics)
                            if i + 1 < grads_this_chunk:
                                t_s = time.perf_counter()
                                host, aux = sample_host(sample_k)
                                dev = put_batch(host)
                                ev_sample_s += \
                                    time.perf_counter() - t_s
                                sample_k += 1
                    did = grads_this_chunk
                    grad_steps += did
                    # Lineage baseline (ISSUE 16): see the mesh branch.
                    ring.current_params_version = grad_steps
                    sample_s_total += ev_sample_s
                    prefetch_wait_s_total += ev_wait_s
            # Chunk g+1's evacuation: every sample for chunk g's event
            # has been drawn above, so chunk g+1's slices may publish
            # from here on without changing what those samples saw —
            # submit now, and its transfers overlap chunk g's train
            # execution and chunk g+2's collect.
            if pipeline and records is not None:
                handle = submit_evac(records)
                records = None
            if did:
                jax.block_until_ready(state.params)
            if ckpt is not None:
                _emerg_state["state"] = state
            hb_train.beat()
            t_train = time.perf_counter()
            fr.record("train", "host_replay.train_event", chunk=g,
                      grad_steps=did)

            # Fused episode-stat fetch (ISSUE 3 satellite): ONE
            # device_get for both scalars, and its wall accounted in
            # the row instead of hiding between t_train and the log.
            # Mesh mode fetches every shard's pair in the one call and
            # sums — the global stats are the sum over lane blocks.
            if not mesh_mode:
                cr, cc = jax.device_get(stats)
            else:
                got = jax.device_get(stats)
                cr = sum(float(g_[0]) for g_ in got)
                cc = sum(float(g_[1]) for g_ in got)
            stats = next_stats
            t_stats = time.perf_counter()
            ep = float(cr) / max(float(cc), 1.0)

            # Chip-time attribution (ISSUE 19), all from timestamps the
            # loop already took. The train section (t_fence -> t_train)
            # ends at a real fence (block_until_ready above), so minus
            # its host-blocked share it is the chunk's measured train
            # device time; `sample` only blocks when no prefetcher runs
            # (with one, the blocking share is prefetch_wait).
            _prefetching = (prefetcher is not None
                            or prefetchers is not None)
            sample_blocked = 0.0 if _prefetching else ev_sample_s
            train_busy = max((t_train - t_fence) - sample_blocked
                             - ev_wait_s, 0.0)
            if did:
                _prog_train.add_device_seconds(train_busy)
            if not pipeline and t_evac_parts is not None:
                # Serial reference: the monolithic blocking fetch waits
                # out the collect program — the one place its device
                # time is fenced and attributable.
                _prog_collect.add_device_seconds(t_evac_parts[0])
            chip = _ledger.observe_chunk(
                t_stats - t0, train_busy, sample=sample_blocked,
                evac_fence=fence_wait_s, prefetch_wait=ev_wait_s)
            _devtime.set_learner_mfu("host_replay", reg=reg)
            _devtime.sweep_device_memory(reg)

            row = {
                "env_frames": env_steps, "grad_steps": grad_steps,
                "episode_return": round(ep, 3),
                "env_steps_per_sec": round(
                    chunk_iters * B / max(t_train - t0, 1e-9), 1),
                # Whole-loop rate (ISSUE 3 satellite): includes stat
                # fetches and logging, so it reconciles with the
                # end-of-run summary rate; the per-chunk rate above
                # excludes them by construction.
                "env_steps_per_sec_loop": round(
                    env_steps / max(t_stats - t_start, 1e-9), 1),
                "chunk_train_s": round(t_train - t_fence, 4),
                "chunk_stats_fetch_s": round(t_stats - t_train, 4),
                "evac_s": round(evac_s, 4),
                "evac_fence_wait_s": round(fence_wait_s, 4),
                "evac_overlap_frac": round(overlap, 4),
                # Upper bound on device idle attributable to
                # evacuation: the fence wait (pipelined — the device
                # may still be running collect g+1 under it) or the
                # whole evacuation (serial — nothing is dispatched).
                "device_idle_est_s": round(fence_wait_s, 4),
                "d2h_bytes": d2h_bytes,
                "ring_transitions": ring_transitions,
                "ring_gb": round((store.nbytes if mesh_mode
                                  else ring.nbytes) / 1e9, 3),
                # Sample-side overlap accounting (ISSUE 5): sample_s is
                # the host sampling wall this chunk (on the critical
                # path when prefetch is off, overlapped when on);
                # prefetch_wait_s is the share still blocking the main
                # thread; prefetch_depth the mean batches staged ahead
                # at pop time; stale_batches the generation-fence drops.
                "sample_s": round(ev_sample_s, 4),
                # Ledger view of this chunk (ISSUE 19): measured device-
                # busy and the derived unattributed host residual; the
                # cumulative per-cause series is
                # dqn_chip_idle_seconds_total{loop="host_replay"}.
                "chip_busy_s": round(chip["busy"], 4),
                "idle_other_s": round(chip["other"], 4),
                "prefetch_wait_s": round(ev_wait_s, 4),
                "prefetch_depth": round(ev_depth_sum / (did * dp), 2)
                if did else 0.0,
                "stale_batches": ev_stale,
            }
            if t_evac_parts is not None:
                row["chunk_collect_fetch_s"] = round(t_evac_parts[0], 4)
                row["chunk_ring_s"] = round(t_evac_parts[1], 4)
            if prefetchers is not None:
                row["h2d_staged_bytes"] = sum(p.bytes_staged
                                              for p in prefetchers)
            elif prefetcher is not None:
                row["h2d_staged_bytes"] = prefetcher.bytes_staged
            elif stager is not None:
                row["h2d_staged_bytes"] = stager.bytes_staged
            if did:
                loss_val = float(jax.device_get(metrics["loss"]))
                row["loss"] = round(loss_val, 4)
                # Divergence sentinel (ISSUE 4): a NaN/Inf loss dumps a
                # forensics bundle instead of training on silently.
                tm_watchdog.observe_divergence(loss=loss_val,
                                               step=grad_steps)
            history.append(row)
            log_fn(json.dumps(row))
            if g == _profile_chunk and _tracer.stop():
                log_fn(json.dumps({"profile_trace": profile_dir}))
            if ckpt is not None and env_steps >= next_save:
                next_save = env_steps + save_period
                _save_checkpoint(g)
            # Chaos seam (ISSUE 8): the deliberate mid-run kill the
            # resume-bit-identical pin uses — fired AFTER the save so
            # "killed at chunk k" means "with a checkpoint at k".
            cev = chaos.fire("host_replay.chunk")
            if cev is not None and cev.fault == "crash":
                raise chaos.ChaosInjectedError("host_replay.chunk",
                                               cev.fault)
        if ckpt is not None and num_chunks:
            # Final whole-state save: resuming a completed run is a
            # no-op pass straight to the summary.
            _save_checkpoint(num_chunks - 1)
    finally:
        if worker is not None:
            worker.close()
        if workers is not None:
            for w in workers:
                w.close()
        if prefetcher is not None:
            prefetcher.close()
        if prefetchers is not None:
            for p in prefetchers:
                p.close()
        if ckpt is not None:
            tm_watchdog.unregister_emergency_hook("host_replay.checkpoint")
            try:
                ckpt.close()
            except Exception as e:  # noqa: BLE001 — surfaced already
                log_fn(f"# host-replay checkpoint close failed: "
                       f"{type(e).__name__}: {e}")
        for hb in hb_collects:
            hb.close()
        hb_train.close()

    # Apply any accumulated-but-unflushed |TD| write-backs before the
    # summary counts them (the PER twin of the apex barrier flush).
    _wb_flush()
    wall = time.perf_counter() - t_start
    # Pin anchor for the pipelined-vs-serial equivalence test: a cheap
    # whole-params digest (float64 fold of float32 leaves, deterministic
    # on one host).
    param_checksum = float(sum(
        np.float64(np.sum(np.asarray(leaf, np.float64)))
        for leaf in jax.tree.leaves(jax.device_get(state.params))))
    # The checksum doubles as the sentinel's divergence signal: NaN/Inf
    # parameters at run end produce a bundle even when no per-chunk loss
    # was sampled (e.g. a run that never reached min_fill). Finiteness
    # only — the sentinel's explosion tracking compares consecutive
    # observations of ONE run's stream, and this is a once-per-run value
    # (two runs in one process would cross-compare).
    if not math.isfinite(param_checksum):
        tm_watchdog.observe_divergence(param_checksum=param_checksum,
                                       step=grad_steps)
    n = max(len(overlap_fracs), 1)
    g_grad_rate.set(grad_steps / wall)
    _prefetch_on = prefetcher is not None or prefetchers is not None
    _samplers = ([per_sampler] if per_sampler is not None
                 else per_samplers if per_samplers is not None else [])
    return {
        "env_steps": env_steps, "grad_steps": grad_steps,
        "wall_s": round(wall, 1),
        "env_steps_per_sec": round(env_steps / wall, 1),
        "grad_steps_per_sec": round(grad_steps / wall, 1),
        # n-chip scale-out provenance (ISSUE 10): the dp mesh width this
        # run's aggregate rates were produced over (1 = single chip).
        "dp_size": dp,
        # Learner-utilization config provenance (ISSUE 6): the knobs
        # that shaped this run's grad-step numbers.
        "replay_ratio": replay_ratio,
        "train_batch": train_batch,
        "actor_dtype": cfg.network.actor_dtype or "float32",
        # Sharded-collect provenance + per-shard conservation evidence
        # (ISSUE 15): in mesh mode each entry of d2h_bytes_by_shard is
        # the bytes shard s's OWN device evacuated, and
        # ring_bytes_by_shard the bytes appended into shard s's ring —
        # elementwise equality is the zero-cross-shard-scatter proof
        # scaling_bench's collect arm asserts.
        "sharded_collect": mesh_mode,
        "collect_lane_block": (B // dp) if mesh_mode else B,
        "collect_dispatch_s_total": round(collect_dispatch_s_total, 4),
        "d2h_bytes_by_shard": d2h_bytes_by_shard,
        "ring_bytes_by_shard": (list(store.bytes_by_shard)
                                if mesh_mode else None),
        "ring_transitions": (store.size if mesh_mode
                             else ring.size) * B,
        "ring_gb": round((store.nbytes if mesh_mode else ring.nbytes)
                         / 1e9, 3),
        "window_transitions_max": num_slots * B,
        "pipeline": pipeline,
        "evac_slices": (evac_slices if (evacuator is not None
                                        or workers is not None) else 0),
        "d2h_bytes_total": d2h_bytes_total,
        "evac_fence_wait_s_total": round(fence_wait_total, 4),
        "evac_overlap_frac_mean": round(sum(overlap_fracs) / n, 4),
        "param_checksum": param_checksum,
        "double_buffer": stager is not None or _prefetch_on,
        "h2d_staged_bytes": (
            sum(p.bytes_staged for p in prefetchers)
            if prefetchers is not None
            else prefetcher.bytes_staged if prefetcher is not None
            else stager.bytes_staged if stager is not None else 0),
        # Sample-side pipeline summary (ISSUE 5).
        "prefetch": _prefetch_on,
        "prefetch_depth": prefetch_depth if _prefetch_on else 0,
        "prioritized": bool(_samplers),
        # PER backend provenance (ISSUE 18): which priority-mass
        # backend drew this run's batches — scaling_bench's collect arm
        # records it beside the dp width.
        "sampler": ("device" if (_samplers and device_sampling)
                    else "tree" if _samplers else "uniform"),
        "sample_s_total": round(sample_s_total, 4),
        "prefetch_wait_s_total": round(prefetch_wait_s_total, 4),
        "stale_batches": (
            sum(p.stale_total for p in prefetchers)
            if prefetchers is not None
            else prefetcher.stale_total if prefetcher is not None else 0),
        "prio_writeback_flushes": sum(s.writeback_flushes
                                      for s in _samplers),
        "prio_writeback_rows": sum(s.writeback_rows for s in _samplers),
        "prio_writeback_dropped": sum(s.writeback_dropped
                                      for s in _samplers),
        "is_weight_mean": round(is_w_sum / is_w_count, 6)
        if is_w_count else 1.0,
        "is_weight_min": round(is_w_min, 6) if is_w_count else 1.0,
        # Chip-time attribution (ISSUE 19): cumulative ledger buckets
        # and the per-program registry rows this run produced — what
        # scaling_bench re-emits as its `programs` block.
        "chip_time": _ledger.snapshot(),
        "programs": _devtime.programs_snapshot("host_replay"),
        "history": history,
    }
