"""Hybrid fused loop with HOST-DRAM replay (BASELINE.json:5's north-star
phrase — "replay buffer shards across TPU-VM host DRAM" — applied to the
single-chip fused path, VERDICT round-4 next #2).

The all-on-device loop (train_loop.py) is the throughput king, but its
replay window lives in HBM: ~200k stacked / ~1M deduped pixel
transitions on a 16 GB v5e. This loop splits the program at the replay
boundary instead:

  device: [act -> env.step] x chunk_iters   (one jitted scan, no replay)
     |  one D2H stream of the chunk's new transitions (frames stored
     |  once; with frame_dedup a step costs 7 KB, not 28 KB)
  host:  HostTimeRing in DRAM — the window is DRAM-sized (hundreds of
     |  GB => hundreds of millions of pixel transitions)
     |  sampled batches, H2D, double-buffered against the device
  device: train_step (donated state), exactly the learner the fused
          loop runs

Throughput model: the link, not HBM, prices the window. Per env step
the D2H cost is one stored frame; per grad step the H2D cost is one
batch (2 x batch x obs bytes). On a TPU-VM host link (~10 GB/s) that
admits ~1.4M deduped env-steps/s of collection — above the fused
loop's own rate; on this dev box the axon tunnel (~25 MB/s measured)
is the honest bound and the bench reports the byte streams so the
attribution is visible. Chunk collection and training are dispatched
back-to-back, so device idle per chunk is bounded by the host-side
ring ops, not the transfers' latency sum.
"""
from __future__ import annotations

import json
import time
from typing import NamedTuple, Optional

import jax
import jax.numpy as jnp
import numpy as np

from dist_dqn_tpu import loop_common
from dist_dqn_tpu.agents.dqn import make_actor_step, make_learner
from dist_dqn_tpu.config import ExperimentConfig
from dist_dqn_tpu.envs.base import JaxEnv
from dist_dqn_tpu.replay.host_ring import HostTimeRing
from dist_dqn_tpu.types import PyTree, Transition

Array = jnp.ndarray


class CollectCarry(NamedTuple):
    env_state: PyTree
    obs: PyTree
    rng: Array
    iteration: Array
    ep_return: Array
    completed_return: Array
    completed_count: Array


def make_collect_chunk(cfg: ExperimentConfig, env: JaxEnv, net,
                       frame_stack: int):
    """(init, collect): a device chunk of act -> step that RETURNS its
    transitions (time-major [C, B, ...]) instead of writing a ring."""
    B = cfg.actor.num_envs
    act = make_actor_step(net)
    epsilon, _ = loop_common.make_schedules(cfg, B, 1)
    slice_newest = ((lambda o: o[..., -1:]) if frame_stack
                    else (lambda o: o))

    def init(rng: Array) -> CollectCarry:
        k_env, k_run = jax.random.split(rng)
        env_state, obs = env.v_reset(k_env, B)
        obs = jax.tree.map(jnp.copy, obs)
        zero = jnp.float32(0.0)
        return CollectCarry(env_state=env_state, obs=obs, rng=k_run,
                            iteration=jnp.int32(0),
                            ep_return=jnp.zeros((B,), jnp.float32),
                            completed_return=zero, completed_count=zero)

    def collect(carry: CollectCarry, params, num_iters: int):
        def one_iteration(carry: CollectCarry, _):
            rng, k_act = jax.random.split(carry.rng)
            eps = epsilon(carry.iteration)
            actions = act(params, carry.obs, k_act, eps)
            env_state, out = env.v_step(carry.env_state, actions)
            record = dict(obs=slice_newest(carry.obs), action=actions,
                          reward=out.reward, terminated=out.terminated,
                          truncated=out.truncated)
            done = jnp.logical_or(out.terminated, out.truncated)
            ep_return, completed_return, completed_count = \
                loop_common.episode_stats_update(carry, out.reward, done)
            return CollectCarry(env_state=env_state, obs=out.obs, rng=rng,
                                iteration=carry.iteration + 1,
                                ep_return=ep_return,
                                completed_return=completed_return,
                                completed_count=completed_count), record

        carry = carry._replace(completed_return=jnp.float32(0.0),
                               completed_count=jnp.float32(0.0))
        carry, records = jax.lax.scan(one_iteration, carry, None,
                                      length=num_iters)
        return carry, records

    return init, collect


def run_host_replay(cfg: ExperimentConfig, total_env_steps: int,
                    chunk_iters: int = 200, log_fn=print,
                    env: Optional[JaxEnv] = None,
                    double_buffer: bool = True):
    """Run the hybrid loop; returns a summary dict.

    Cadence matches the fused loop: one train event every
    ``cfg.train_every`` env iterations, ``cfg.updates_per_train`` grad
    steps each, batches sampled uniformly from the host ring.
    ``double_buffer`` stages batch g+1's sample+H2D while step g trains
    (replay/staging.py); False is the serial reference path —
    numerically identical, tests/test_ingest_fastpath.py pins it.
    """
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network

    # Honest-unsupported-surface gates (ADVICE r5): this loop builds the
    # FEED-FORWARD actor/learner and samples the ring uniformly. A
    # recurrent config would silently train the wrong program; a PER
    # config silently loses its prioritization — say so.
    if cfg.network.lstm_size > 0:
        raise ValueError(
            "host-replay runs the feed-forward collect/train split; "
            "recurrent (R2D2, network.lstm_size>0) configs need the "
            "sequence learner — use the apex runtime or the fused loop")
    if cfg.replay.prioritized:
        log_fn("# prioritized replay not supported by host-replay; "
               "sampling uniformly (cfg.replay.prioritized ignored)")

    if env is None:
        env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    B = cfg.actor.num_envs
    obs_shape = tuple(env.observation_shape)
    stack = (cfg.replay.frame_dedup
             and getattr(env, "frame_stack", 0)) or 0
    if cfg.replay.frame_dedup and stack < 2:
        raise ValueError(
            "replay.frame_dedup=True but this env declares no rolling "
            "frame stack (envs/base.py JaxEnv.frame_stack)")
    stored_shape = obs_shape[:-1] + (1,) if stack else obs_shape

    init_collect, collect = make_collect_chunk(cfg, env, net, stack)
    collect_jit = jax.jit(collect, static_argnums=2, donate_argnums=0)
    init_learner, train_step = make_learner(net, cfg.learner)
    train_jit = jax.jit(train_step, donate_argnums=0)

    # Floor covers the n-step window AND the dedup rebuild context —
    # a smaller ring would be permanently unsampleable (can_sample
    # needs size > n_step + stack - 1).
    num_slots = max(cfg.replay.capacity // B,
                    cfg.learner.n_step + max(stack - 1, 0) + 2)
    # Fail BEFORE the compile, naming the knobs: a chunk larger than the
    # ring would only surface in HostTimeRing.add_chunk after the first
    # device chunk (ADVICE r5 — wasted compile, error points nowhere).
    if chunk_iters > num_slots:
        raise ValueError(
            f"--chunk-iters {chunk_iters} exceeds the host ring's "
            f"{num_slots} slots (replay.capacity={cfg.replay.capacity} "
            f"/ num_envs={B}); lower --chunk-iters or raise "
            "replay.capacity (one chunk == the whole window would make "
            "the ring a FIFO of the last chunk — keep chunk_iters well "
            "below the slot count)")
    ring = HostTimeRing(num_slots, B, stored_shape,
                        np.dtype(env.observation_dtype), frame_stack=stack)

    rng = jax.random.PRNGKey(cfg.seed)
    k_carry, k_learn = jax.random.split(rng)
    carry = init_collect(k_carry)
    obs_example = jax.tree.map(lambda x: x[0], carry.obs)
    state = init_learner(k_learn, obs_example)
    host_rng = np.random.default_rng(cfg.seed)

    def sample_host() -> Transition:
        hb = ring.sample(host_rng, cfg.learner.batch_size,
                         cfg.learner.n_step, cfg.learner.gamma)
        return Transition(obs=hb.obs, action=hb.action, reward=hb.reward,
                          discount=hb.discount, next_obs=hb.next_obs)

    def put_batch(hb: Transition) -> Transition:
        return jax.tree.map(jax.device_put, hb)

    # Double-buffered H2D (the module docstring's promise, made real in
    # replay/staging.py): batch g+1 is gathered into reusable pinned-host
    # staging buffers and its upload dispatched while step g trains.
    stager = None
    if double_buffer:
        from dist_dqn_tpu.replay.staging import DoubleBufferedStager
        stager = DoubleBufferedStager(depth=2, name="host_replay")

    # Train-event cadence carries its remainder across chunks so the
    # average exactly matches the fused loop's one-event-per-train_every
    # iterations (chunk_iters need not divide train_every).
    updates_per_train = max(cfg.updates_per_train, 1)
    train_debt_iters = 0
    weights = jnp.ones((cfg.learner.batch_size,), jnp.float32)

    env_steps = 0
    grad_steps = 0
    history = []
    t_start = time.perf_counter()
    while env_steps < total_env_steps:
        t0 = time.perf_counter()
        carry, records = collect_jit(carry, state.params, chunk_iters)
        # One D2H stream for the chunk (frames stored once).
        host = {k: np.asarray(jax.device_get(v))
                for k, v in records.items()}
        t_fetch = time.perf_counter()
        ring.add_chunk(host["obs"], host["action"], host["reward"],
                       host["terminated"], host["truncated"])
        env_steps += chunk_iters * B
        t_ring = time.perf_counter()

        did = 0
        if (ring.can_sample(cfg.learner.n_step)
                and ring.size * B >= cfg.replay.min_fill):
            train_debt_iters += chunk_iters
            events = train_debt_iters // max(cfg.train_every, 1)
            train_debt_iters -= events * max(cfg.train_every, 1)
            grads_this_chunk = events * updates_per_train
            if grads_this_chunk:
                if stager is not None:
                    # Double-buffered: batch g+1's gather + H2D upload
                    # overlap step g's device time; the train dispatch
                    # never waits on the link between steps.
                    stager.stage(sample_host())
                    for g in range(grads_this_chunk):
                        batch, _ = stager.pop()
                        state, metrics = train_jit(state, batch, weights)
                        if g + 1 < grads_this_chunk:
                            stager.stage(sample_host())
                else:
                    # Serial reference path (train.py --no-double-buffer,
                    # tests): sample -> upload -> train, one at a time.
                    batch = put_batch(sample_host())
                    for g in range(grads_this_chunk):
                        state, metrics = train_jit(state, batch, weights)
                        if g + 1 < grads_this_chunk:
                            batch = put_batch(sample_host())
                jax.block_until_ready(state.params)
                did = grads_this_chunk
                grad_steps += did
        t_train = time.perf_counter()

        ep = float(jax.device_get(carry.completed_return)) / max(
            float(jax.device_get(carry.completed_count)), 1.0)
        row = {
            "env_frames": env_steps, "grad_steps": grad_steps,
            "episode_return": round(ep, 3),
            "env_steps_per_sec": round(
                chunk_iters * B / max(t_train - t0, 1e-9), 1),
            "chunk_collect_fetch_s": round(t_fetch - t0, 4),
            "chunk_ring_s": round(t_ring - t_fetch, 4),
            "chunk_train_s": round(t_train - t_ring, 4),
            "d2h_bytes": int(sum(v.nbytes for v in host.values())),
            "ring_transitions": ring.size * B,
            "ring_gb": round(ring.nbytes / 1e9, 3),
        }
        if stager is not None:
            row["h2d_staged_bytes"] = stager.bytes_staged
        if did:
            row["loss"] = round(float(jax.device_get(metrics["loss"])), 4)
        history.append(row)
        log_fn(json.dumps(row))

    wall = time.perf_counter() - t_start
    return {
        "env_steps": env_steps, "grad_steps": grad_steps,
        "wall_s": round(wall, 1),
        "env_steps_per_sec": round(env_steps / wall, 1),
        "ring_transitions": ring.size * B,
        "ring_gb": round(ring.nbytes / 1e9, 3),
        "window_transitions_max": num_slots * B,
        "double_buffer": stager is not None,
        "h2d_staged_bytes": (stager.bytes_staged if stager is not None
                             else 0),
        "history": history,
    }
