"""ModelStore: resident policy params with checkpoint hot-reload.

One entry per tenant policy id. Each entry pins a training run's
checkpoint directory (utils/checkpoint.py) and holds the newest restored
params as an immutable :class:`PolicySnapshot`. A watcher thread polls
each directory's atomic ``LATEST`` pointer (written by
``TrainCheckpointer.save`` — no step-dir globbing, no in-progress-save
race), restores new steps OFF the serving path, and swaps the snapshot
reference under the store lock. The act path only ever does
``store.snapshot(policy_id)`` — one lock acquire, one reference read —
so a reload never blocks acting on restore I/O, and because the batcher
resolves one snapshot per dispatched batch, a swap can never produce a
mixed-version batch (the hot-reload pin in tests/test_serving.py).

Restores go through ``TrainCheckpointer.restore_params`` — the same
params-only partial restore evaluate.py deploys with, so optimizer
structure never constrains serving and carry-kind (--checkpoint-replay)
run dirs serve without a ring-sized template.
"""
from __future__ import annotations

import threading
from typing import Dict, List, Optional

from dist_dqn_tpu.serving.types import PolicySnapshot, UnknownPolicyError
from dist_dqn_tpu.telemetry import collectors as tmc
from dist_dqn_tpu.telemetry import get_registry


class _PolicyEntry:
    """One policy id's checkpoint binding + current snapshot."""

    def __init__(self, policy_id: str, checkpoint_dir: str, ckpt, prefix,
                 epsilon: float, member: Optional[int] = None):
        self.policy_id = policy_id
        self.checkpoint_dir = checkpoint_dir
        self.ckpt = ckpt                      # open TrainCheckpointer
        self.prefix = prefix
        self.epsilon = epsilon
        self.member = member                  # population member slice
        self.snapshot: Optional[PolicySnapshot] = None


class ModelStore:
    """Resident policies + the hot-reload watcher.

    ``example_params`` is a live params pytree of the serving network —
    the restore template every policy's checkpoints must match (all
    tenants share one network architecture; one jitted act program
    serves them all).
    """

    def __init__(self, example_params, poll_interval_s: float = 10.0,
                 log_fn=print):
        self.example_params = example_params
        self.poll_interval_s = float(poll_interval_s)
        self.log = log_fn
        self._entries: Dict[str, _PolicyEntry] = {}
        self._lock = threading.Lock()
        self._stop = threading.Event()
        self._thread: Optional[threading.Thread] = None
        reg = get_registry()
        self._tm_reloads: Dict[str, object] = {}
        self._tm_version: Dict[str, object] = {}
        self._reg = reg

    # -- registration -------------------------------------------------------
    def add_policy(self, policy_id: str, checkpoint_dir: str,
                   epsilon: float = 0.0,
                   member: Optional[int] = None) -> PolicySnapshot:
        """Register a tenant and BLOCKING-restore its newest checkpoint
        (startup path — the serving loop is not live yet). Raises the
        distinct CheckpointMissingError when the directory is absent or
        holds no complete checkpoint yet — the retryable
        launched-beside-training shape the CLI's --wait-for-checkpoint
        waits on (unrelated startup failures stay loud).

        ``member`` serves one policy out of a --population run's
        [M]-stacked checkpoint (ISSUE 20): every restore — startup and
        hot-reload alike — extracts member k's slice, so M tenants can
        bind M members of the same run directory and hot-reload
        independently off one stacked save."""
        import os

        from dist_dqn_tpu.utils.checkpoint import (CheckpointMissingError,
                                                   TrainCheckpointer,
                                                   read_checkpoint_kind)

        if not os.path.isdir(checkpoint_dir):
            raise CheckpointMissingError(
                f"policy {policy_id!r}: no checkpoint found under "
                f"{checkpoint_dir!r}")
        # carry (fused --checkpoint-replay) and host_loop (host-replay
        # whole-state, ISSUE 8) checkpoints nest the learner one level
        # down; plain learner checkpoints hold params at the top.
        prefix = (("learner",)
                  if read_checkpoint_kind(checkpoint_dir)
                  in ("carry", "host_loop")
                  else ())
        ckpt = TrainCheckpointer(checkpoint_dir)
        entry = _PolicyEntry(policy_id, checkpoint_dir, ckpt, prefix,
                             epsilon, member=member)
        try:
            snap = self._restore(entry, step=None, version=1)
        except BaseException:
            ckpt.close()
            raise
        if snap is None:
            ckpt.close()
            raise CheckpointMissingError(
                f"policy {policy_id!r}: no checkpoint found under "
                f"{checkpoint_dir!r}")
        entry.snapshot = snap
        with self._lock:
            self._entries[policy_id] = entry
        return snap

    # -- act-path read ------------------------------------------------------
    def snapshot(self, policy_id: str) -> PolicySnapshot:
        """The policy's current immutable snapshot — one lock acquire,
        one reference read; never any I/O."""
        with self._lock:
            entry = self._entries.get(policy_id)
            if entry is None or entry.snapshot is None:
                raise UnknownPolicyError(
                    f"unknown policy {policy_id!r} (resident: "
                    f"{sorted(self._entries)})")
            return entry.snapshot

    def policies(self) -> Dict[str, Dict]:
        """{policy_id: header dict} for /v1/policies."""
        with self._lock:
            return {
                pid: {"version": e.snapshot.version,
                      "step": e.snapshot.step,
                      "epsilon": e.snapshot.epsilon,
                      "param_checksum": e.snapshot.param_checksum,
                      "checkpoint_dir": e.checkpoint_dir,
                      **({"member": e.member}
                         if e.member is not None else {})}
                for pid, e in self._entries.items()
                if e.snapshot is not None
            }

    # -- hot reload ---------------------------------------------------------
    def _newest_step(self, entry: _PolicyEntry) -> Optional[int]:
        """The directory's newest complete step, LATEST-pointer first
        (utils/checkpoint.py ``latest_step`` — pointer when present,
        orbax listing fallback for pre-pointer directories)."""
        try:
            return entry.ckpt.latest_step()
        except Exception as e:
            self.log(f"# serving: poll of {entry.checkpoint_dir!r} "
                     f"failed ({type(e).__name__}: {e})")
            return None

    def _restore(self, entry: _PolicyEntry, step: Optional[int],
                 version: int) -> Optional[PolicySnapshot]:
        """Restore ``step`` (None = newest) into a fresh snapshot.
        Blocking I/O — called at startup and from the watcher thread,
        NEVER from the act path."""
        from dist_dqn_tpu import chaos
        from dist_dqn_tpu.utils.checkpoint import read_latest_pointer

        # Chaos seam (ISSUE 8): slow_reload holds the restore mid-flight
        # (reload-during-load — the act path must keep serving the
        # resident snapshot, version headers must never tear); fail
        # exercises poll_once's keep-resident-and-retry contract.
        ev = chaos.fire("serving.reload")
        if ev is not None:
            if ev.fault == "fail":
                raise chaos.ChaosInjectedError("serving.reload", ev.fault)
            chaos.sleep_for(ev)
        restored = entry.ckpt.restore_params(self.example_params,
                                             step=step,
                                             prefix=entry.prefix,
                                             member=entry.member)
        if restored is None:
            return None
        got_step, params = restored
        ptr = read_latest_pointer(entry.checkpoint_dir)
        # Population entries serve a member SLICE; the pointer's digest
        # covers the whole stacked tree, so it would mislabel the slice.
        checksum = (ptr.get("param_checksum")
                    if ptr and int(ptr.get("step", -1)) == got_step
                    and entry.member is None
                    else None)
        return PolicySnapshot(
            policy_id=entry.policy_id, params=params, version=version,
            step=got_step, param_checksum=checksum,
            epsilon=entry.epsilon)

    def poll_once(self) -> List[str]:
        """One watcher pass: reload every policy whose directory has a
        newer complete step than its resident snapshot. Returns the
        policy ids swapped (test surface; the watcher thread just calls
        this on its interval)."""
        with self._lock:
            entries = list(self._entries.values())
        reloaded = []
        for entry in entries:
            current = entry.snapshot
            newest = self._newest_step(entry)
            if current is None or newest is None or newest <= current.step:
                continue
            try:
                snap = self._restore(entry, step=newest,
                                     version=current.version + 1)
            except Exception as e:
                # A torn/mismatched checkpoint must not take serving
                # down — keep the resident version, log, retry next poll.
                self.log(f"# serving: hot-reload of {entry.policy_id!r} "
                         f"step {newest} failed ({type(e).__name__}: {e})"
                         "; keeping resident version")
                continue
            if snap is None:
                continue
            with self._lock:
                entry.snapshot = snap  # THE atomic swap
            from dist_dqn_tpu import chaos
            chaos.mark_recovered("serving.reload")
            reloaded.append(entry.policy_id)
            self._reload_counter(entry.policy_id).inc()
            self._version_gauge(entry.policy_id).set(snap.version)
            self.log(f'{{"serving_reload": "{entry.policy_id}", '
                     f'"step": {snap.step}, "version": {snap.version}}}')
        return reloaded

    def _reload_counter(self, policy_id: str):
        c = self._tm_reloads.get(policy_id)
        if c is None:
            c = self._reg.counter(
                tmc.SERVING_RELOADS,
                "checkpoint hot-reload swaps", {"policy": policy_id})
            self._tm_reloads[policy_id] = c
        return c

    def _version_gauge(self, policy_id: str):
        g = self._tm_version.get(policy_id)
        if g is None:
            g = self._reg.gauge(
                tmc.SERVING_POLICY_VERSION,
                "resident snapshot version", {"policy": policy_id})
            self._tm_version[policy_id] = g
        return g

    # -- watcher lifecycle --------------------------------------------------
    def start(self) -> None:
        """Start the hot-reload watcher thread (idempotent)."""
        if self._thread is not None:
            return
        # Snapshot under the lock (lock-discipline fix, ISSUE 13):
        # add_policy mutates _entries under the lock from whatever
        # thread registers late tenants, and iterating the live dict
        # here raced that with "dictionary changed size during
        # iteration" — the same copy-then-walk poll_once uses.
        with self._lock:
            entries = list(self._entries.values())
        for entry in entries:
            if entry.snapshot is not None:
                self._version_gauge(entry.policy_id).set(
                    entry.snapshot.version)
        self._thread = threading.Thread(
            target=self._run, name="serving-ckpt-watcher", daemon=True)
        self._thread.start()

    def _run(self) -> None:
        while not self._stop.wait(self.poll_interval_s):
            try:
                self.poll_once()
            except Exception as e:  # the watcher must survive any poll
                self.log(f"# serving: watcher pass failed "
                         f"({type(e).__name__}: {e})")

    def close(self) -> None:
        self._stop.set()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        with self._lock:
            entries = list(self._entries.values())
            self._entries.clear()
        for entry in entries:
            try:
                entry.ckpt.close()
            except Exception:
                pass
