"""Shared request/response/error types for the serving tier (ISSUE 7).

Kept free of jax imports so clients (serving/client.py, the load
generator) can import them from processes that never touch a device.
"""
from __future__ import annotations

import dataclasses
from typing import Any, Optional

import numpy as np


class ServingError(Exception):
    """Base for every serving-surface failure."""


class UnknownPolicyError(ServingError):
    """The request named a policy id with no resident checkpoint
    (HTTP 404)."""


class QueueFullError(ServingError):
    """The bounded admission queue shed this request (HTTP 429).

    ``retry_after_s`` is the server's drain estimate — echoed as the
    ``Retry-After`` header so closed-loop clients back off instead of
    hammering a saturated batcher."""

    def __init__(self, msg: str, retry_after_s: float):
        super().__init__(msg)
        self.retry_after_s = float(retry_after_s)


class ServerClosedError(ServingError):
    """The server shut down while the request was queued/in flight."""


@dataclasses.dataclass(frozen=True)
class PolicySnapshot:
    """One resident, immutable (params, version) pair.

    The micro-batcher resolves EXACTLY ONE snapshot per dispatched
    batch, so every row of a batch — and therefore every response split
    from it — acts on the same params and echoes the same version
    header. Hot-reload builds a NEW snapshot off the serving path and
    swaps the reference atomically; a swap can never tear a batch.
    """

    policy_id: str
    params: Any            # device pytree (read-only once resident)
    version: int           # bumps on every hot-reload swap, starts at 1
    step: int              # the checkpoint's frame cursor
    param_checksum: Optional[float]  # LATEST-pointer digest (provenance)
    epsilon: float         # tenant default exploration (0 = greedy)


@dataclasses.dataclass(frozen=True)
class ActResult:
    """One served act request: actions plus the provenance header."""

    actions: np.ndarray    # [rows] int32
    policy_id: str
    version: int
    step: int
    fanin_requests: int    # concurrent requests coalesced into the batch
    fanin_rows: int        # real (unpadded) rows of the dispatched batch
    latency_s: float       # admission -> response split
