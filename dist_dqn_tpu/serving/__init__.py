"""Production policy-inference service (ISSUE 7, ROADMAP item 3).

The acting tier of the north star: a standalone low-latency policy
server over trained checkpoints —

  * :class:`~dist_dqn_tpu.serving.batcher.MicroBatcher` — dynamic
    micro-batching of concurrent act requests into pow2-bucketed jitted
    dispatches (the ingest fast path's bucket rule) with a max-wait
    deadline bounding p99 at low load;
  * :class:`~dist_dqn_tpu.serving.model_store.ModelStore` — resident
    multi-tenant checkpoints with hot-reload off the serving path and
    atomic snapshot swaps (version header echoed per response);
  * :class:`~dist_dqn_tpu.serving.router.Router` — per-request policy
    routing + per-tenant epsilon/greedy knobs;
  * :class:`~dist_dqn_tpu.serving.server.PolicyServer` — the HTTP
    surface with SLO-backed /healthz and queue-full shedding
    (429 + Retry-After);
  * :class:`~dist_dqn_tpu.serving.client.ServingClient` — the jax-free
    blocking client the load generator drives.

CLI: ``python -m dist_dqn_tpu.serving --config cartpole
--checkpoint-dir RUNDIR`` (docs/serving.md). Load generator:
``benchmarks/serving_bench.py``.
"""
from dist_dqn_tpu.serving.batcher import MicroBatcher, SloTracker  # noqa: F401
from dist_dqn_tpu.serving.client import ServingClient  # noqa: F401
from dist_dqn_tpu.serving.model_store import ModelStore  # noqa: F401
from dist_dqn_tpu.serving.router import Router  # noqa: F401
from dist_dqn_tpu.serving.server import PolicyServer, build_server  # noqa: F401
from dist_dqn_tpu.serving.types import (ActResult,  # noqa: F401
                                        PolicySnapshot, QueueFullError,
                                        ServerClosedError, ServingError,
                                        UnknownPolicyError)
