"""Multi-tenant request routing: policy id -> resident snapshot + the
effective act knobs.

Several checkpoints stay resident in one server (ModelStore); every act
request names a policy id (default ``"default"``) and is routed to that
policy's current snapshot. Exploration resolves per request:
``greedy=True`` forces epsilon 0, an explicit request ``epsilon`` wins
otherwise, and the tenant's configured default (per --policy-epsilon)
is the fallback — so one server can serve a greedy product surface and
an exploring shadow tenant off the same checkpoints.
"""
from __future__ import annotations

from typing import Dict, Optional, Tuple

from dist_dqn_tpu.serving.model_store import ModelStore
from dist_dqn_tpu.serving.types import PolicySnapshot

DEFAULT_POLICY = "default"


class Router:
    def __init__(self, store: ModelStore):
        self.store = store

    def resolve(self, policy_id: Optional[str],
                epsilon: Optional[float] = None,
                greedy: bool = False) -> Tuple[PolicySnapshot, float]:
        """(snapshot, effective epsilon) for one request. Raises
        UnknownPolicyError for an unregistered id and ValueError for an
        out-of-range epsilon — both BEFORE the request is admitted to
        the batch queue, so malformed requests never consume queue
        slots or ride a dispatched batch."""
        snap = self.store.snapshot(policy_id or DEFAULT_POLICY)
        if greedy:
            eps = 0.0
        elif epsilon is not None:
            eps = float(epsilon)
            if not 0.0 <= eps <= 1.0:
                raise ValueError(
                    f"epsilon must be in [0, 1], got {eps}")
        else:
            eps = snap.epsilon
        return snap, eps

    def policies(self) -> Dict[str, Dict]:
        return self.store.policies()
