"""Dynamic micro-batcher + SLO tracker for the serving tier (ISSUE 7).

Concurrent act requests admit into ONE bounded queue; a single dispatch
thread coalesces the queue head into one jitted device call through the
shared pow2 packing (actors/act_dispatch.py — the ingest fast path's
bucket rule, so serving compiles O(log max-fan-in) act variants, not one
per burst size). Two latencies bound p99:

  * at load, a batch dispatches as soon as ``max_rows`` real rows are
    queued — fan-in amortizes the dispatch constant;
  * at low load, the HEAD request's age bounds the wait: once it has
    queued ``max_wait_s`` the batch goes out with whatever coalesced,
    so an idle server answers a lone request in ~max_wait + one
    dispatch, not "whenever a batch fills".

Backpressure is explicit: past ``queue_limit`` queued requests,
admission fails with :class:`QueueFullError` carrying a drain-estimate
``retry_after_s`` (HTTP 429 + ``Retry-After``) instead of letting the
queue — and every queued request's latency — grow without bound.

Version atomicity: the dispatch thread resolves EXACTLY ONE
:class:`PolicySnapshot` per batch, so a hot-reload swap lands between
batches, never inside one — every response in a batch echoes the same
version header (pinned by tests/test_serving.py under concurrent
reload).
"""
from __future__ import annotations

import threading
import time
from collections import deque
from typing import Callable, Dict, List, Optional, Tuple

import numpy as np

from dist_dqn_tpu import chaos
from dist_dqn_tpu.actors.act_dispatch import (bucket_rows, pack_act_rows,
                                              split_rows)
from dist_dqn_tpu.serving.router import Router
from dist_dqn_tpu.serving.types import (ActResult, QueueFullError,
                                        ServerClosedError, ServingError)
from dist_dqn_tpu.telemetry import collectors as tmc
from dist_dqn_tpu.telemetry import devtime as _devtime
from dist_dqn_tpu.telemetry import get_registry
from dist_dqn_tpu.telemetry import watchdog as tm_watchdog

#: Heartbeat stage the dispatch thread beats (docs/observability.md
#: stage table); swept once a watchdog is armed (--forensics-dir).
BATCHER_STAGE = "serving.batcher"


class SloTracker:
    """Rolling-window p99 latency + queue-depth SLOs feeding /healthz.

    ``probe()`` is registered as a watchdog health probe
    (telemetry/watchdog.py ``register_health_probe``), so a breach flips
    EVERY /healthz surface of the process — the serving endpoint and the
    telemetry endpoint agree. Thresholds of 0 disarm a dimension.
    Breaches count once per healthy->breached transition, not per
    scrape.
    """

    def __init__(self, p99_latency_s: float = 0.0, queue_depth: int = 0,
                 window: int = 512, min_samples: int = 20,
                 window_s: float = 60.0):
        self.p99_latency_s = float(p99_latency_s)
        self.queue_depth = int(queue_depth)
        self.min_samples = int(min_samples)
        # Samples age out after window_s even with no new traffic: a
        # breached replica that a load balancer drained would otherwise
        # hold 503 forever (count-only windows decay only on requests).
        self.window_s = float(window_s)
        self._lat = deque(maxlen=window)   # (monotonic t, latency_s)
        self._lock = threading.Lock()
        self._depth_fn: Optional[Callable[[], int]] = None
        self._breached = set()
        reg = get_registry()
        self._tm_breaches = {
            slo: reg.counter(tmc.SERVING_SLO_BREACHES,
                             "healthy->breached SLO transitions",
                             {"slo": slo})
            for slo in ("p99_latency", "queue_depth")
        }

    def attach_queue_depth(self, fn: Callable[[], int]) -> None:
        self._depth_fn = fn

    def observe(self, latency_s: float) -> None:
        with self._lock:
            self._lat.append((time.monotonic(), latency_s))

    def p99(self) -> Optional[float]:
        cutoff = time.monotonic() - self.window_s
        with self._lock:
            lat = [l for t, l in self._lat if t >= cutoff]
        if len(lat) < self.min_samples:
            return None
        return float(np.percentile(np.asarray(lat), 99))

    def reset(self) -> None:
        with self._lock:
            self._lat.clear()
            self._breached.clear()

    def probe(self) -> Optional[Dict]:
        """None while inside SLO; a detail dict (-> 503) otherwise."""
        detail = {}
        if self.p99_latency_s > 0:
            p99 = self.p99()
            if p99 is not None and p99 > self.p99_latency_s:
                detail["p99_latency_s"] = round(p99, 6)
                detail["slo_p99_latency_s"] = self.p99_latency_s
        if self.queue_depth > 0 and self._depth_fn is not None:
            depth = self._depth_fn()
            if depth > self.queue_depth:
                detail["queue_depth"] = depth
                detail["slo_queue_depth"] = self.queue_depth
        with self._lock:
            now_breached = set()
            if "p99_latency_s" in detail:
                now_breached.add("p99_latency")
            if "queue_depth" in detail:
                now_breached.add("queue_depth")
            for slo in now_breached - self._breached:
                self._tm_breaches[slo].inc()
            self._breached = now_breached
        return detail or None


class _Pending:
    __slots__ = ("policy_id", "obs", "epsilon", "t_enqueue", "event",
                 "result", "error", "abandoned")

    def __init__(self, policy_id: str, obs: np.ndarray, epsilon: float):
        self.policy_id = policy_id
        self.obs = obs
        self.epsilon = epsilon
        self.t_enqueue = time.perf_counter()
        self.event = threading.Event()
        self.result: Optional[ActResult] = None
        self.error: Optional[BaseException] = None
        self.abandoned = False           # client timed out and left


class MicroBatcher:
    """The admission queue + dispatch thread.

    ``act_fn(params, obs, rng, eps) -> actions`` is the jitted
    epsilon-greedy act (agents/dqn.py ``make_actor_step`` — the same
    program evaluate.py and the Ape-X ingest path act with, which is
    what makes the serving equivalence pin possible).

    ``batching=False`` is the A/B arm benchmarks/serving_bench.py
    measures against: one serialized dispatch per request, no
    coalescing (still pow2-padded — only the fan-in differs).
    """

    def __init__(self, act_fn, router: Router, *, rng,
                 max_rows: int = 256, max_wait_s: float = 0.002,
                 queue_limit: int = 256, batching: bool = True,
                 obs_spec: Optional[Tuple] = None,
                 slo: Optional[SloTracker] = None, log_fn=print):
        import jax

        self._jax = jax
        self.act_fn = act_fn
        self.router = router
        self.max_rows = bucket_rows(int(max_rows))  # cap is itself pow2
        self.max_wait_s = float(max_wait_s)
        self.queue_limit = int(queue_limit)
        self.batching = bool(batching)
        self.slo = slo
        self.log = log_fn
        self._obs_spec = obs_spec        # (row shape, dtype); first-
        self._rng = rng                  # request learned when None
        self._queue: deque = deque()
        self._cond = threading.Condition()
        self._serial_lock = threading.Lock()
        self._stopped = False
        self._draining = False
        self._dispatching = 0   # batches currently inside _dispatch
        # Drain-rate EWMA for the shed signal's retry-after estimate.
        self._ewma_batch_s = self.max_wait_s + 0.005
        self._ewma_fanin = 1.0
        reg = get_registry()
        self._tm_requests: Dict[str, object] = {}
        self._reg = reg
        self._tm_shed = reg.counter(
            tmc.SERVING_SHED, "requests shed by the bounded queue")
        self._tm_depth = reg.gauge(
            tmc.SERVING_QUEUE_DEPTH, "act requests awaiting dispatch")
        self._tm_latency = reg.histogram(
            tmc.SERVING_LATENCY, "request admission -> response split")
        self._tm_fanin = reg.histogram(
            tmc.SERVING_BATCH_FANIN,
            "real (unpadded) rows per dispatched act program",
            buckets=tmc.FANIN_BUCKETS)
        self._tm_dispatches = reg.counter(
            tmc.SERVING_DISPATCHES, "act programs dispatched")
        # Chip-time attribution (ISSUE 19): the coalesced act dispatch
        # is the serving tier's device program; the np.asarray fence in
        # _dispatch_inner is one the path already holds, so the
        # device-seconds sample costs no new sync. Cost attaches at the
        # first live dispatch — the first-seen pow2 bucket's census
        # (all buckets share this record).
        self._prog_act = _devtime.register_program(
            "serving.act", loop="serving", role="act")
        if slo is not None:
            slo.attach_queue_depth(self.queue_depth)
        self._thread: Optional[threading.Thread] = None
        if self.batching:
            self._thread = threading.Thread(
                target=self._worker, name="serving-batcher", daemon=True)
            self._thread.start()

    def warmup(self) -> int:
        """Pre-compile the whole pow2 bucket ladder (one dummy dispatch
        per bucket up to ``max_rows``) so no live request ever pays a
        jit compile on the serving path — measured ~1s PER BUCKET on a
        CPU dev box, which without this line lands on whichever unlucky
        requests first reach each fan-in. Called at server startup,
        before the port is announced. Returns the bucket count."""
        import jax.numpy as jnp

        if self._obs_spec is None:
            return 0
        shape, dtype = self._obs_spec
        policies = self.router.policies()
        if not policies:
            return 0
        snap = self.router.store.snapshot(next(iter(policies)))
        n, buckets = 1, 0
        while n <= self.max_rows:
            obs = np.zeros((n,) + tuple(shape), dtype)
            eps = np.zeros((n,), np.float32)
            self._rng, k = self._jax.random.split(self._rng)
            np.asarray(self.act_fn(snap.params, jnp.asarray(obs), k,
                                   jnp.asarray(eps)))
            buckets += 1
            n *= 2
        return buckets

    # -- admission ----------------------------------------------------------
    def queue_depth(self) -> int:
        # Monitoring read for the SLO probe/metrics: the depth is stale
        # the instant it returns, and taking _cond here would make
        # every /healthz scrape contend with the dispatch hot path.
        # lock: len() on a deque is one atomic op under the GIL.
        return len(self._queue)

    def _validate_obs(self, obs) -> np.ndarray:
        obs = np.asarray(obs)
        if obs.ndim < 1 or obs.shape[0] < 1:
            raise ValueError("obs must be a [rows, ...] batch with at "
                             "least one row")
        if obs.shape[0] > self.max_rows:
            raise ValueError(
                f"request rows {obs.shape[0]} exceed max_batch_rows "
                f"{self.max_rows}; split the request")
        if self._obs_spec is None:
            self._obs_spec = (obs.shape[1:], obs.dtype)
        elif (obs.shape[1:] != self._obs_spec[0]
              or obs.dtype != self._obs_spec[1]):
            raise ValueError(
                f"obs rows {obs.shape[1:]}/{obs.dtype} do not match the "
                f"serving spec {self._obs_spec[0]}/{self._obs_spec[1]}")
        return obs

    def _request_counter(self, policy_id: str):
        c = self._tm_requests.get(policy_id)
        if c is None:
            c = self._reg.counter(
                tmc.SERVING_REQUESTS,
                "act requests served by a dispatched program",
                {"policy": policy_id})
            self._tm_requests[policy_id] = c
        return c

    def submit(self, obs, policy_id: Optional[str] = None,
               epsilon: Optional[float] = None, greedy: bool = False,
               timeout_s: float = 30.0) -> ActResult:
        """Admit one request and block until its batch answered.
        Called from HTTP handler threads (and directly by tests/bench).
        """
        obs = self._validate_obs(obs)
        # lock: advisory fast-path read — the authoritative _draining
        # check re-runs under _cond below, atomically with the enqueue.
        if self._draining:
            # Graceful drain (ISSUE 8): already-admitted requests
            # complete; NEW admissions are refused up front (503) so
            # the in-flight queue can only shrink. (Early fast-path
            # refusal; the authoritative check is re-taken under the
            # admission lock below, atomically with the enqueue, so a
            # begin_drain + wait_idle pair can never miss a request
            # admitted in between.)
            raise ServerClosedError("server draining for shutdown")
        # Route BEFORE admission: unknown policy / bad epsilon must not
        # consume a queue slot or ride a dispatched batch.
        snap, eps = self.router.resolve(policy_id, epsilon, greedy)
        pending = _Pending(snap.policy_id, obs, eps)
        if not self.batching:
            with self._cond:
                if self._stopped or self._draining:
                    raise ServerClosedError("server shutting down")
                # Claim atomically with the drain check (the batching
                # path's queue-append twin): from this instant
                # wait_idle counts the request as in-flight, so a
                # begin_drain + wait_idle pair can never close the
                # server under a serial request that already passed
                # the check.
                self._dispatching += 1
            # Serialized dispatches compound: N concurrent handlers
            # wait N x dispatch-wall on this lock, so honor timeout_s
            # here like the batching path does (the dispatch itself is
            # one bounded device call).
            if not self._serial_lock.acquire(timeout=timeout_s):
                with self._cond:
                    self._dispatching -= 1
                    self._cond.notify_all()
                raise ServingError(
                    f"request timed out after {timeout_s}s waiting for "
                    "the serial dispatch lock")
            try:
                self._dispatch([pending], claimed=True)
            finally:
                self._serial_lock.release()
            if pending.error is not None:
                raise pending.error
            return pending.result
        with self._cond:
            if self._stopped:
                raise ServerClosedError("server shutting down")
            if self._draining:
                raise ServerClosedError("server draining for shutdown")
            if len(self._queue) >= self.queue_limit:
                self._tm_shed.inc()
                raise QueueFullError(
                    f"admission queue full ({self.queue_limit} requests "
                    "pending)", retry_after_s=self._retry_after())
            self._queue.append(pending)
            self._tm_depth.set(len(self._queue))
            self._cond.notify_all()
        if not pending.event.wait(timeout_s):
            # Withdraw a timed-out request: still queued -> remove it
            # (no wasted dispatch, frees its backpressure slot); already
            # packed into an in-flight batch -> mark it abandoned so its
            # client-gone latency is not fed to the SLO window after the
            # caller got its error.
            with self._cond:
                pending.abandoned = True
                try:
                    self._queue.remove(pending)
                except ValueError:
                    pass
                else:
                    self._tm_depth.set(len(self._queue))
            raise ServingError(
                f"request timed out after {timeout_s}s in the serving "
                "pipeline")
        if pending.error is not None:
            raise pending.error
        return pending.result

    def _retry_after(self) -> float:
        """Drain estimate for a shed request: the full queue's batches
        at the recent per-batch wall."""
        batches = max(1.0, self.queue_limit / max(self._ewma_fanin, 1.0))
        return max(0.05, batches * self._ewma_batch_s)

    # -- dispatch thread ----------------------------------------------------
    def _worker(self) -> None:
        hb = tm_watchdog.heartbeat(BATCHER_STAGE)
        try:
            while True:
                batch = self._take_batch(hb)
                if batch is None:
                    break
                if not batch:
                    # The head this cycle waited on was withdrawn by a
                    # client timeout and the next head is another
                    # policy's — nothing assembled; take again.
                    continue
                self._dispatch(batch, claimed=True)
                hb.beat()
        finally:
            hb.close()
            self._fail_queue(ServerClosedError("server shut down"))

    # lock: called only from _take_batch with self._cond already held —
    # a cross-function hold the lexical race analysis cannot see.
    def _head_run_rows(self) -> int:
        """Rows queued for the head request's policy (stops at the
        first other-policy request — batches never mix params)."""
        rows, policy = 0, self._queue[0].policy_id
        for p in self._queue:
            if p.policy_id != policy:
                break
            rows += p.obs.shape[0]
            if rows >= self.max_rows:
                break
        return rows

    def _take_batch(self, hb) -> Optional[List[_Pending]]:
        with self._cond:
            while True:
                while not self._queue:
                    if self._stopped:
                        return None
                    self._cond.wait(0.1)
                    hb.beat()
                head = self._queue[0]
                deadline = head.t_enqueue + self.max_wait_s
                drained = False
                while (self._head_run_rows() < self.max_rows
                       and not self._stopped):
                    remaining = deadline - time.perf_counter()
                    if remaining <= 0:
                        break
                    self._cond.wait(min(remaining, 0.05))
                    hb.beat()
                    if not self._queue:
                        # The head was withdrawn mid-wait (client
                        # timeout) and the queue drained; restart the
                        # wait iteratively — recursing here let a
                        # withdraw-storm grow the stack without bound.
                        drained = True
                        break
                if not drained:
                    break
            batch, rows = [], 0
            while self._queue:
                nxt = self._queue[0]
                if nxt.policy_id != head.policy_id:
                    break
                r = nxt.obs.shape[0]
                if batch and rows + r > self.max_rows:
                    break
                self._queue.popleft()
                batch.append(nxt)
                rows += r
                if rows >= self.max_rows:
                    break
            self._tm_depth.set(len(self._queue))
            if batch:
                # Claim under THIS lock hold: from wait_idle's view the
                # batch moves queue -> in-flight atomically.
                self._dispatching += 1
            return batch

    def _dispatch(self, batch: List[_Pending],
                  claimed: bool = False) -> None:
        """``claimed``: the worker path already counted this batch in
        ``_dispatching`` under the SAME lock hold that popped it from
        the queue — otherwise wait_idle could observe the instant
        between the pop and this increment and report an idle batcher
        while admitted requests still await dispatch."""
        if not claimed:
            with self._cond:
                self._dispatching += 1
        try:
            self._dispatch_inner(batch)
        finally:
            with self._cond:
                self._dispatching -= 1
                self._cond.notify_all()

    def _dispatch_inner(self, batch: List[_Pending]) -> None:
        import jax.numpy as jnp

        t0 = time.perf_counter()
        try:
            # Chaos seam (ISSUE 8): slow_model exercises the SLO/
            # backpressure degradation path (p99 breach -> 503, queue
            # growth -> 429) under a genuinely slow dispatch; exception
            # exercises the fan-out of a dispatch failure to every
            # rider as a structured 500, not a connection reset.
            ev = chaos.fire("serving.dispatch")
            if ev is not None:
                if ev.fault == "exception":
                    raise chaos.ChaosInjectedError("serving.dispatch",
                                                   ev.fault)
                chaos.sleep_for(ev)
            # ONE snapshot per batch: every row acts on the same params
            # and every response echoes the same version header — the
            # hot-reload atomicity contract.
            snap = self.router.store.snapshot(batch[0].policy_id)
            obs_cat, eps, rows, total = pack_act_rows(
                [p.obs for p in batch], [p.epsilon for p in batch])
            self._rng, k = self._jax.random.split(self._rng)
            if not self._prog_act.cost_attached:
                self._prog_act.attach_cost(
                    lambda: self.act_fn.lower(
                        snap.params, jnp.asarray(obs_cat), k,
                        jnp.asarray(eps)))
            actions = self.act_fn(snap.params, jnp.asarray(obs_cat), k,
                                  jnp.asarray(eps))
            acts_np = np.asarray(actions, np.int32)
        except BaseException as e:  # noqa: BLE001 — fanned back out
            for p in batch:
                p.error = e
                p.event.set()
            return
        # A completed dispatch proves recovery from an injected slow/
        # failed one (the chaos recovery metric's serving anchor).
        chaos.mark_recovered("serving.dispatch")
        self._tm_dispatches.inc()
        self._prog_act.count_dispatch()
        self._prog_act.add_device_seconds(time.perf_counter() - t0)
        # Counted at DISPATCH, not admission: docs derive the mean
        # request fan-in as requests_total / dispatches_total, so a
        # request shed at admission or withdrawn by a client timeout
        # while still queued must not skew the ratio — only requests
        # that actually rode a dispatched program count.
        self._request_counter(snap.policy_id).inc(len(batch))
        self._tm_fanin.observe(float(total))
        wall = time.perf_counter() - t0
        self._ewma_batch_s += 0.2 * (wall - self._ewma_batch_s)
        self._ewma_fanin += 0.2 * (len(batch) - self._ewma_fanin)
        now = time.perf_counter()
        for p, acts in zip(batch, split_rows(acts_np, rows)):
            latency = now - p.t_enqueue
            if not p.abandoned:
                self._tm_latency.observe(latency)
                if self.slo is not None:
                    self.slo.observe(latency)
            p.result = ActResult(
                actions=acts, policy_id=snap.policy_id,
                version=snap.version, step=snap.step,
                fanin_requests=len(batch), fanin_rows=total,
                latency_s=latency)
            p.event.set()

    def _fail_queue(self, err: BaseException) -> None:
        with self._cond:
            stuck = list(self._queue)
            self._queue.clear()
            self._tm_depth.set(0)
        for p in stuck:
            p.error = err
            p.event.set()

    def begin_drain(self) -> None:
        """Stop admitting; keep dispatching what is already queued.
        Step one of the SIGTERM graceful-drain contract (ISSUE 8):
        after this, ``submit`` answers ServerClosedError (503) while
        every request admitted before the drain still gets its real
        response."""
        with self._cond:
            self._draining = True
            self._cond.notify_all()

    def wait_idle(self, timeout_s: float) -> bool:
        """Block until the queue is empty and no dispatch is in flight
        (True), or ``timeout_s`` elapsed (False). Meaningful after
        ``begin_drain`` — an admitting batcher may never go idle."""
        deadline = time.monotonic() + max(timeout_s, 0.0)
        with self._cond:
            while self._queue or self._dispatching:
                remaining = deadline - time.monotonic()
                if remaining <= 0:
                    return False
                self._cond.wait(min(remaining, 0.1))
            return True

    def close(self) -> None:
        with self._cond:
            self._stopped = True
            self._cond.notify_all()
        if self._thread is not None:
            self._thread.join(timeout=5)
            self._thread = None
        self._fail_queue(ServerClosedError("server shut down"))
