"""Standalone policy-inference service CLI:
``python -m dist_dqn_tpu.serving --config cartpole --checkpoint-dir d``.

Serves greedy (or per-tenant epsilon) actions from one or more training
runs' checkpoints over HTTP with dynamic micro-batching, checkpoint
hot-reload and SLO-backed backpressure — see docs/serving.md for the
API, header semantics and load-generator usage.
"""
from __future__ import annotations

import argparse
import json
import signal
import threading

from dist_dqn_tpu.config import CONFIGS, apply_overrides


def _parse_kv(pairs, what, cast=str):
    out = {}
    for raw in pairs:
        if "=" not in raw:
            raise ValueError(f"{what} expects NAME=VALUE, got {raw!r}")
        name, value = raw.split("=", 1)
        out[name] = cast(value)
    return out


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", choices=sorted(CONFIGS), required=True)
    parser.add_argument("--checkpoint-dir", default=None,
                        help="shorthand for --policy default=DIR")
    parser.add_argument("--policy", action="append", default=[],
                        metavar="NAME=DIR",
                        help="make checkpoint directory DIR resident as "
                             "tenant NAME (repeatable; all tenants share "
                             "the config's network architecture)")
    parser.add_argument("--policy-epsilon", action="append", default=[],
                        metavar="NAME=EPS",
                        help="per-tenant exploration epsilon (default: "
                             "--epsilon)")
    parser.add_argument("--epsilon", type=float, default=0.0,
                        help="default tenant epsilon (0 = greedy serving)")
    parser.add_argument("--host", default="127.0.0.1",
                        help="bind address for the act endpoint (loopback "
                             "by default — the surface is unauthenticated)")
    parser.add_argument("--port", type=int, default=0,
                        help="act endpoint port (0 = ephemeral, reported "
                             "as a serving_port log line)")
    parser.add_argument("--max-batch-rows", type=int, default=256,
                        help="row cap per dispatched act program (rounded "
                             "up to a power of two — the bucket ladder "
                             "tops out here)")
    parser.add_argument("--max-wait-ms", type=float, default=2.0,
                        help="micro-batch coalescing deadline: the queue "
                             "head never waits longer than this for "
                             "fan-in (bounds p99 at low load)")
    parser.add_argument("--queue-limit", type=int, default=256,
                        help="bounded admission queue: requests past this "
                             "are shed with 429 + Retry-After")
    parser.add_argument("--no-batching", action="store_true",
                        help="serialize one dispatch per request (the "
                             "A/B baseline serving_bench measures "
                             "against)")
    parser.add_argument("--slo-p99-ms", type=float, default=0.0,
                        help="flip /healthz to 503 while the rolling p99 "
                             "request latency exceeds this (0 disables)")
    parser.add_argument("--slo-queue-depth", type=int, default=0,
                        help="flip /healthz to 503 while the admission "
                             "queue is deeper than this (0 disables)")
    parser.add_argument("--drain-timeout-s", type=float, default=5.0,
                        help="graceful-shutdown budget: on SIGTERM/"
                             "SIGINT the server stops accepting (new "
                             "requests answer 503), completes every "
                             "already-admitted request within this "
                             "window, then exits 0; stragglers past it "
                             "are failed at teardown")
    parser.add_argument("--poll-interval-s", type=float, default=10.0,
                        help="checkpoint hot-reload watcher period (reads "
                             "the run dir's atomic LATEST pointer)")
    parser.add_argument("--wait-for-checkpoint", type=float, default=0.0,
                        metavar="SECONDS",
                        help="at startup, retry an empty/absent "
                             "checkpoint directory for up to this long "
                             "instead of failing — for servers launched "
                             "alongside a fresh training run")
    parser.add_argument("--host-env", default=None,
                        help="probe this HOST env for the network's "
                             "action count/obs shape instead of the "
                             "config's JAX stand-in env (apex-trained "
                             "checkpoints, e.g. CartPole-v1, ale:Pong)")
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform (e.g. cpu)")
    parser.add_argument("--set", dest="overrides", action="append",
                        metavar="PATH=VALUE", default=[],
                        help="override config fields by dotted path (must "
                             "match how the checkpoints were trained)")
    parser.add_argument("--telemetry-port", type=int, default=None,
                        help="ALSO serve the registry on a separate "
                             "telemetry endpoint (the act server already "
                             "exposes /metrics + /healthz)")
    parser.add_argument("--telemetry-host", default="127.0.0.1",
                        help="bind address for --telemetry-port")
    parser.add_argument("--telemetry-snapshot", default=None,
                        help="dump a registry JSON snapshot here at exit")
    parser.add_argument("--fleet-dir", default=None,
                        help="fleet registry directory (ISSUE 16): "
                             "announce this replica's metrics endpoint "
                             "(the telemetry server when started, else "
                             "the act server — it serves /metrics + "
                             "/healthz too) to the run's aggregator; "
                             "defaults to $DQN_FLEET_DIR")
    parser.add_argument("--forensics-dir", default=None,
                        help="arm the stall watchdog (serving.batcher "
                             "heartbeat) + forensics bundles, as on the "
                             "train CLI")
    parser.add_argument("--watchdog-deadline-s", type=float, default=120.0)
    args = parser.parse_args()

    if args.telemetry_snapshot:
        from dist_dqn_tpu.telemetry import install_snapshot_dump
        install_snapshot_dump(args.telemetry_snapshot)
    if args.forensics_dir:
        from dist_dqn_tpu.telemetry import watchdog as _wd
        _wd.install_watchdog(forensics_dir=args.forensics_dir,
                             deadline_s=args.watchdog_deadline_s)
        _wd.install_sentinel(forensics_dir=args.forensics_dir)
    if args.platform:
        import jax
        jax.config.update("jax_platforms", args.platform)
    try:
        cfg = apply_overrides(CONFIGS[args.config], args.overrides)
        policies = _parse_kv(args.policy, "--policy")
        policy_epsilon = _parse_kv(args.policy_epsilon, "--policy-epsilon",
                                   cast=float)
    except ValueError as e:
        parser.error(str(e))
    if args.checkpoint_dir:
        policies.setdefault("default", args.checkpoint_dir)
    if not policies:
        parser.error("pass --checkpoint-dir DIR or --policy NAME=DIR")
    unknown = sorted(set(policy_epsilon) - set(policies))
    if unknown:
        parser.error(f"--policy-epsilon for unregistered policies: "
                     f"{unknown}")

    # Handlers BEFORE the (multi-second) jax import + warmup/build: a
    # TERM landing mid-bucket-ladder-compile must still produce the
    # graceful close-and-rc-0 exit the CLI contract promises, not a
    # default-disposition kill that skips server.close().
    stop = threading.Event()
    for sig in (signal.SIGINT, signal.SIGTERM):
        signal.signal(sig, lambda *_: stop.set())

    # Chaos (ISSUE 8): serving game days (reload-under-load, slow
    # dispatch) arm their fault plan via DQN_CHAOS_PLAN like the
    # training CLIs and spawned workers do.
    from dist_dqn_tpu import chaos
    chaos.maybe_install_from_env()

    from dist_dqn_tpu.serving.server import build_server

    # Serving-side counterpart of evaluate.py's --wait-for-checkpoint:
    # a server launched beside a fresh training run retries the
    # missing-checkpoint startup window instead of crash-looping. The
    # shared helper retries ONLY the distinct CheckpointMissingError —
    # an unrelated startup failure (missing ROM/asset, bad config)
    # stays loud on the first attempt.
    from dist_dqn_tpu.utils.checkpoint import (CheckpointMissingError,
                                               wait_for_checkpoint)

    try:
        server = wait_for_checkpoint(
            lambda: build_server(
                cfg, policies, host_env=args.host_env,
                policy_epsilon=policy_epsilon, epsilon=args.epsilon,
                host=args.host, port=args.port,
                max_rows=args.max_batch_rows,
                max_wait_ms=args.max_wait_ms,
                queue_limit=args.queue_limit,
                batching=not args.no_batching,
                slo_p99_ms=args.slo_p99_ms,
                slo_queue_depth=args.slo_queue_depth,
                poll_interval_s=args.poll_interval_s, seed=args.seed),
            args.wait_for_checkpoint, stop=stop)
    except CheckpointMissingError:
        if stop.is_set():
            # TERM'd while still waiting for the first checkpoint:
            # graceful rc-0 exit, same contract as a TERM while serving.
            print("# serving: terminated during checkpoint wait",
                  flush=True)
            return
        raise

    telemetry_server = None
    if args.telemetry_port is not None:
        from dist_dqn_tpu import telemetry
        telemetry_server = telemetry.start_server(args.telemetry_port,
                                                  host=args.telemetry_host)
        print(json.dumps({"telemetry_port": telemetry_server.port}))
    # Fleet registry (ISSUE 16): a replica is a fleet member like any
    # actor — the descriptor points at whichever endpoint scrapes.
    import os as _os
    if args.fleet_dir:
        _os.environ["DQN_FLEET_DIR"] = args.fleet_dir
    from dist_dqn_tpu.telemetry import fleet as _fleet
    if telemetry_server is not None:
        _fleet.register_endpoint("serving", telemetry_server.port,
                                 host=args.telemetry_host)
    else:
        _fleet.register_endpoint("serving", server.port, host=server.host)
    print(json.dumps({
        "serving_port": server.port, "serving_host": server.host,
        "policies": {pid: {"version": hdr["version"], "step": hdr["step"]}
                     for pid, hdr in server.router.policies().items()},
        "batching": not args.no_batching,
        "max_batch_rows": server.batcher.max_rows,
    }), flush=True)

    try:
        while not stop.wait(1.0):
            pass
    finally:
        # Graceful drain (ISSUE 8): complete what was admitted, refuse
        # what was not, exit 0 — in-flight requests no longer race the
        # teardown.
        drained = server.drain(args.drain_timeout_s)
        print(json.dumps({"serving_drained": bool(drained),
                          "drain_timeout_s": args.drain_timeout_s}),
              flush=True)
        if telemetry_server is not None:
            telemetry_server.close()


if __name__ == "__main__":
    main()
