"""Minimal blocking client for the serving tier — the test/bench-side
counterpart of serving/server.py.

One persistent keep-alive HTTP connection per instance (NOT
thread-safe; the closed-loop load generator gives each client thread
its own instance, which is exactly the per-user-connection shape the
bench wants to model). jax-free: only numpy + the transport array
codec, so load generators run from processes that never touch a device.
"""
from __future__ import annotations

import http.client
import json
from typing import Optional, Tuple

import numpy as np

from dist_dqn_tpu.actors.transport import decode_arrays, encode_arrays
from dist_dqn_tpu.serving.types import (ActResult, QueueFullError,
                                        ServingError, UnknownPolicyError)


class ServingClient:
    def __init__(self, address: str, timeout_s: float = 30.0):
        """``address`` is ``host:port`` (PolicyServer.address)."""
        import socket

        host, port = address.rsplit(":", 1)
        self._conn = http.client.HTTPConnection(host, int(port),
                                                timeout=timeout_s)
        # Requests are two small writes (headers, body): disable Nagle
        # or the body stalls against the server's delayed ACK (the
        # server handler disables it for responses symmetrically).
        self._conn.connect()
        self._conn.sock.setsockopt(socket.IPPROTO_TCP, socket.TCP_NODELAY,
                                   1)

    def act(self, obs: np.ndarray, policy: Optional[str] = None,
            epsilon: Optional[float] = None,
            greedy: bool = False) -> ActResult:
        """POST one act request; returns the ActResult (actions +
        version header). Raises the typed serving errors on 404/429/5xx
        so closed-loop callers can branch on shed-vs-fail."""
        meta = {"greedy": greedy}
        if policy is not None:
            meta["policy"] = policy
        if epsilon is not None:
            meta["epsilon"] = float(epsilon)
        body = encode_arrays({"obs": np.asarray(obs)}, meta=meta)
        self._conn.request(
            "POST", "/v1/act", body=body,
            headers={"Content-Type": "application/octet-stream"})
        resp = self._conn.getresponse()
        payload = resp.read()
        if resp.status == 200:
            arrays, rmeta = decode_arrays(payload)
            return ActResult(
                actions=arrays["action"], policy_id=rmeta["policy"],
                version=int(rmeta["version"]), step=int(rmeta["step"]),
                fanin_requests=int(rmeta["fanin_requests"]),
                fanin_rows=int(rmeta["fanin_rows"]),
                latency_s=float(rmeta["latency_s"]))
        detail = _error_detail(payload)
        if resp.status == 404:
            raise UnknownPolicyError(detail)
        if resp.status == 429:
            # The JSON body carries the precise float estimate; the
            # Retry-After header is RFC delay-seconds (integer) for
            # generic clients and proxies.
            try:
                retry = float(json.loads(payload.decode())["retry_after_s"])
            except Exception:
                retry = float(resp.getheader("Retry-After") or 0.05)
            raise QueueFullError(detail, retry_after_s=retry)
        raise ServingError(f"HTTP {resp.status}: {detail}")

    def policies(self) -> dict:
        return json.loads(self._get("/v1/policies")[1])

    def healthz(self) -> Tuple[int, bytes]:
        """(status, body) — 200 ok / 503 + breach JSON."""
        return self._get("/healthz")

    def _get(self, path: str) -> Tuple[int, bytes]:
        self._conn.request("GET", path)
        resp = self._conn.getresponse()
        return resp.status, resp.read()

    def close(self) -> None:
        self._conn.close()


def _error_detail(payload: bytes) -> str:
    try:
        return json.loads(payload.decode())["error"]
    except Exception:
        return payload.decode(errors="replace")
