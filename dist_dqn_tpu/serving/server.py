"""The policy-inference HTTP server: transport + assembly of the
serving pieces (ISSUE 7).

One process owns the device and runs

  * the :class:`ModelStore` (resident checkpoints + hot-reload watcher),
  * the :class:`Router` (multi-tenant policy/epsilon resolution),
  * the :class:`MicroBatcher` (ONE dispatch thread coalescing
    concurrent requests into pow2-bucketed jitted act calls),
  * a stdlib ``ThreadingHTTPServer`` front end (same posture as the
    telemetry endpoint: handler threads are request-scoped and block in
    ``batcher.submit`` — the accelerator only ever sees the batcher
    thread).

Wire format: the actors/transport.py array codec (``encode_arrays`` /
``decode_arrays``) — bit-exact observation/action transfer with the
optional CRC the transport already has, no JSON float round-trips on
the act path. ``POST /v1/act`` takes ``{"obs": [rows, ...]}`` with meta
``{"policy", "epsilon", "greedy"}`` and answers ``{"action": [rows]}``
with the provenance header (policy, version, step, fan-in, latency)
echoed in meta. ``/healthz`` is the SAME body the telemetry endpoint
serves (telemetry/server.py ``healthz_body``), so a stalled batcher
heartbeat, a divergence trip, or a serving SLO breach (p99 latency /
queue depth, via a registered health probe) flips every probe surface
of the process to 503 at once. Shed admissions answer 429 with a
``Retry-After`` drain estimate.
"""
from __future__ import annotations

import itertools
import json
import math
import threading
from http.server import BaseHTTPRequestHandler, ThreadingHTTPServer
from typing import Dict, Optional

from dist_dqn_tpu.actors.transport import decode_arrays, encode_arrays
from dist_dqn_tpu.serving.batcher import MicroBatcher, SloTracker
from dist_dqn_tpu.serving.model_store import ModelStore
from dist_dqn_tpu.serving.router import Router
from dist_dqn_tpu.serving.types import (QueueFullError, ServingError,
                                        UnknownPolicyError)
from dist_dqn_tpu.telemetry import watchdog as tm_watchdog
from dist_dqn_tpu.telemetry.exposition import (CONTENT_TYPE,
                                               render_prometheus, snapshot)
from dist_dqn_tpu.telemetry.registry import get_registry
from dist_dqn_tpu.telemetry.server import healthz_body

#: /healthz probe-name prefix the SLO tracker registers under; each
#: PolicyServer instance appends a sequence number so two servers in
#: one process (tests, embedded benches) can't clobber or unregister
#: each other's probe.
SLO_PROBE = "serving_slo"
_SLO_PROBE_SEQ = itertools.count(1)

#: Maximum accepted request body; far above any sane obs batch, far
#: below a memory-exhaustion payload (the endpoint is unauthenticated-
#: loopback by default, same posture as the transport listener).
_MAX_BODY = 256 << 20


class PolicyServer:
    """Assembled serving stack. ``policies`` maps policy id ->
    checkpoint directory; every tenant shares the one network
    architecture ``net`` (and the one jitted act program)."""

    def __init__(self, net, example_params, obs_spec, *,
                 policies: Dict[str, str],
                 policy_epsilon: Optional[Dict[str, float]] = None,
                 epsilon: float = 0.0,
                 host: str = "127.0.0.1", port: int = 0,
                 max_rows: int = 256, max_wait_ms: float = 2.0,
                 queue_limit: int = 256, batching: bool = True,
                 slo_p99_ms: float = 0.0, slo_queue_depth: int = 0,
                 poll_interval_s: float = 10.0, seed: int = 0,
                 compile_warmup: bool = True, log_fn=print):
        import jax

        from dist_dqn_tpu.agents.dqn import make_actor_step

        if not policies:
            raise ValueError("at least one --policy NAME=DIR is required")
        policy_epsilon = policy_epsilon or {}
        self.log = log_fn
        self.store = ModelStore(example_params,
                                poll_interval_s=poll_interval_s,
                                log_fn=log_fn)
        try:
            for pid, ckpt_dir in policies.items():
                self.store.add_policy(
                    pid, ckpt_dir,
                    epsilon=policy_epsilon.get(pid, epsilon))
        except BaseException:
            # A later tenant failing must not leak the earlier tenants'
            # open checkpoint managers — the CLI's --wait-for-checkpoint
            # loop rebuilds the whole server each retry.
            self.store.close()
            raise
        self.router = Router(self.store)
        self.slo = None
        self._slo_probe = f"{SLO_PROBE}.{next(_SLO_PROBE_SEQ)}"
        self.batcher: Optional[MicroBatcher] = None
        try:
            if slo_p99_ms > 0 or slo_queue_depth > 0:
                self.slo = SloTracker(p99_latency_s=slo_p99_ms / 1000.0,
                                      queue_depth=slo_queue_depth)
                tm_watchdog.register_health_probe(self._slo_probe,
                                                  self.slo.probe)
            self.batcher = MicroBatcher(
                jax.jit(make_actor_step(net)), self.router,
                rng=jax.random.PRNGKey(seed), max_rows=max_rows,
                max_wait_s=max_wait_ms / 1000.0, queue_limit=queue_limit,
                batching=batching, obs_spec=obs_spec, slo=self.slo,
                log_fn=log_fn)
            if compile_warmup:
                # Compile the whole bucket ladder BEFORE the port
                # exists: a jit compile on the serving path would land
                # ~1s stalls on the first request to reach each fan-in
                # bucket.
                import time as _time
                t0 = _time.perf_counter()
                buckets = self.batcher.warmup()
                log_fn(f'{{"serving_warmup_buckets": {buckets}, '
                       f'"serving_warmup_s": '
                       f'{_time.perf_counter() - t0:.2f}}}')
            self.store.start()
            self._httpd = ThreadingHTTPServer((host, port),
                                              self._make_handler())
        except BaseException:
            # A failed tail (port already bound, warmup compile error)
            # runs after the process-global SLO probe is registered and
            # the dispatch thread exists; close() is never reached on a
            # failed build, so unwind here — the --wait-for-checkpoint
            # CLI loop rebuilds the whole server each retry.
            if self.slo is not None:
                tm_watchdog.unregister_health_probe(self._slo_probe)
            if self.batcher is not None:
                self.batcher.close()
            self.store.close()
            raise
        self._httpd.daemon_threads = True
        self.host, self.port = self._httpd.server_address[:2]
        self._thread = threading.Thread(target=self._httpd.serve_forever,
                                        name="serving-http", daemon=True)
        self._thread.start()

    @property
    def address(self) -> str:
        return f"{self.host}:{self.port}"

    # -- HTTP front end -----------------------------------------------------
    def _make_handler(self):
        server = self
        registry = get_registry()

        class Handler(BaseHTTPRequestHandler):
            protocol_version = "HTTP/1.1"  # keep-alive for closed loops
            # Act responses are two small writes (headers, body); with
            # Nagle on, the body can deadlock against the client's
            # delayed ACK for ~200ms — measured as a 10x closed-loop
            # throughput collapse before this line (the client sets
            # TCP_NODELAY on its side too, serving/client.py).
            disable_nagle_algorithm = True

            def _reply(self, status, body, ctype,
                       headers: Optional[Dict[str, str]] = None):
                self.send_response(status)
                self.send_header("Content-Type", ctype)
                self.send_header("Content-Length", str(len(body)))
                for k, v in (headers or {}).items():
                    self.send_header(k, v)
                self.end_headers()
                self.wfile.write(body)

            def _reply_json(self, status, payload,
                            headers: Optional[Dict[str, str]] = None):
                body = (json.dumps(payload, sort_keys=True) + "\n").encode()
                self._reply(status, body, "application/json", headers)

            def do_GET(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path == "/healthz":
                    status, body = healthz_body()
                    self._reply(status, body,
                                "text/plain" if status == 200
                                else "application/json")
                elif path == "/v1/policies":
                    self._reply_json(200, server.router.policies())
                elif path == "/metrics":
                    self._reply(200, render_prometheus(registry).encode(),
                                CONTENT_TYPE)
                elif path == "/metrics.json":
                    self._reply_json(200, snapshot(registry))
                else:
                    self.send_error(404)

            def do_POST(self):  # noqa: N802 — http.server API
                path = self.path.split("?", 1)[0]
                if path != "/v1/act":
                    self.send_error(404)
                    return
                try:
                    length = int(self.headers.get("Content-Length", 0))
                except ValueError:
                    length = 0
                if not 0 < length <= _MAX_BODY:
                    # The body was NOT read — leaving it in the stream
                    # would desync the next keep-alive request, so drop
                    # the connection after this reply.
                    self.close_connection = True
                    self._reply_json(400, {"error": "bad Content-Length"},
                                     headers={"Connection": "close"})
                    return
                try:
                    arrays, meta = decode_arrays(self.rfile.read(length))
                    obs = arrays["obs"]
                    eps = meta.get("epsilon")
                    epsilon = None if eps is None else float(eps)
                    greedy = bool(meta.get("greedy", False))
                except Exception as e:  # noqa: BLE001 — a corrupt body
                    # raises whatever the codec hit (struct.error,
                    # zlib.error, KeyError, ...); all of it is a client
                    # problem and must answer 400, not kill the
                    # keep-alive connection with a bare reset.
                    self._reply_json(
                        400, {"error": f"malformed act request: {e}"})
                    return
                try:
                    result = server.batcher.submit(
                        obs, policy_id=meta.get("policy"),
                        epsilon=epsilon, greedy=greedy)
                except UnknownPolicyError as e:
                    self._reply_json(404, {"error": str(e)})
                    return
                except QueueFullError as e:
                    # Header is RFC 9110 delay-seconds (an INTEGER —
                    # generic clients/proxies int-parse it); the JSON
                    # body keeps the precise float for our client.
                    self._reply_json(
                        429, {"error": str(e),
                              "retry_after_s": e.retry_after_s},
                        headers={"Retry-After":
                                 str(max(1, math.ceil(e.retry_after_s)))})
                    return
                except ValueError as e:
                    self._reply_json(400, {"error": str(e)})
                    return
                except ServingError as e:
                    self._reply_json(503, {"error": str(e)})
                    return
                except Exception as e:  # noqa: BLE001 — dispatch fans
                    # arbitrary failures (XLA runtime errors included)
                    # back to every submit() in the batch; answer a
                    # structured 500 rather than resetting the
                    # keep-alive connection mid-protocol.
                    self._reply_json(
                        500, {"error": f"{type(e).__name__}: {e}"})
                    return
                body = encode_arrays(
                    {"action": result.actions},
                    meta={"policy": result.policy_id,
                          "version": result.version,
                          "step": result.step,
                          "fanin_requests": result.fanin_requests,
                          "fanin_rows": result.fanin_rows,
                          "latency_s": round(result.latency_s, 6)})
                self._reply(200, body, "application/octet-stream")

            def log_message(self, fmt, *args):
                pass  # request logging would swamp the JSON-line stream

        return Handler

    def drain(self, timeout_s: float = 5.0) -> bool:
        """Graceful drain (ISSUE 8 satellite): stop ADMITTING (new
        submits answer 503 ServerClosedError), let every already-
        admitted request complete within ``timeout_s``, then tear
        down. Returns True when the queue drained fully; False when
        the timeout expired and the stragglers were failed by
        ``close`` — either way the server is closed on return. Before
        this existed a SIGTERM raced in-flight requests against the
        teardown: the batcher's fail-queue answered them with errors
        mid-protocol."""
        self.batcher.begin_drain()
        drained = self.batcher.wait_idle(timeout_s)
        # One beat for handler threads to WRITE the final responses
        # the dispatch just completed — wait_idle proves dispatch
        # completion, not that the bytes left the socket.
        import time as _time
        _time.sleep(0.05)
        self.close()
        return drained

    def close(self) -> None:
        if self.slo is not None:
            tm_watchdog.unregister_health_probe(self._slo_probe)
        try:
            self._httpd.shutdown()
            self._httpd.server_close()
        except OSError:
            pass
        self.batcher.close()
        self.store.close()


def build_server(cfg, policies: Dict[str, str], *,
                 host_env: Optional[str] = None, **kw) -> PolicyServer:
    """Build a :class:`PolicyServer` from an experiment config: the
    network/obs-spec come from the config's JAX env (the evaluate.py
    surface) or, with ``host_env``, from probing a host env — the shape
    source for checkpoints trained by the apex runtime (whose non-pixel
    envs swap in the MLP torso exactly like the train CLI does)."""
    import dataclasses

    import jax
    import jax.numpy as jnp

    from dist_dqn_tpu.agents.dqn import make_learner
    from dist_dqn_tpu.models import build_network

    if cfg.network.lstm_size:
        raise ValueError(
            "the serving tier is feed-forward only for now — recurrent "
            "(R2D2) policies need per-caller carry state, which the "
            "stateless act protocol does not carry yet")
    if host_env:
        from dist_dqn_tpu.envs.gym_adapter import is_pixel_env, make_host_env
        if not is_pixel_env(host_env):
            cfg = dataclasses.replace(
                cfg, network=dataclasses.replace(
                    cfg.network, torso="mlp", compute_dtype="float32"))
        probe = make_host_env(host_env, 1)
        num_actions = probe.num_actions
        obs0 = probe.reset()
        obs_shape, obs_dtype = obs0.shape[1:], obs0.dtype
        del probe
    else:
        from dist_dqn_tpu.envs import make_jax_env
        env = make_jax_env(cfg.env_name)
        num_actions = env.num_actions
        obs_shape = tuple(env.observation_shape)
        obs_dtype = env.observation_dtype
    net = build_network(cfg.network, num_actions)
    init, _ = make_learner(net, cfg.learner)
    example = init(jax.random.PRNGKey(0),
                   jnp.zeros(obs_shape, obs_dtype))
    return PolicyServer(net, example.params, (obs_shape, obs_dtype),
                        policies=policies, **kw)
