"""Standalone checkpoint evaluation:
``python -m dist_dqn_tpu.evaluate --config cartpole --checkpoint-dir d``.

The deploy-side half of the checkpoint story (SURVEY.md §5): load the
newest learner checkpoint a training run (either runtime) saved with
``--checkpoint-dir`` and run greedy episodes on the config's env, without
any training machinery in the loop. Prints one JSON line with the mean
undiscounted return.
"""
from __future__ import annotations

import argparse
import json
import os

import jax

from dist_dqn_tpu.config import CONFIGS, ExperimentConfig


def evaluate_checkpoint(cfg: ExperimentConfig, checkpoint_dir: str,
                        episodes: int = 10, seed: int = 0,
                        epsilon: float = 0.001) -> dict:
    """Restore the newest checkpoint and play greedy episodes.

    Returns {"eval_return": mean, "frames": checkpoint cursor, ...}.
    Raises FileNotFoundError if the directory holds no checkpoint.
    """
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.utils.checkpoint import TrainCheckpointer

    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    rng = jax.random.PRNGKey(seed)
    rng, k_init, k_eval = jax.random.split(rng, 3)

    if cfg.network.lstm_size:
        from dist_dqn_tpu.agents.r2d2 import make_r2d2_learner
        from dist_dqn_tpu.r2d2_loop import make_r2d2_evaluator
        init, _ = make_r2d2_learner(net, cfg.learner, cfg.replay)
        evaluator = make_r2d2_evaluator(cfg, env, net,
                                        num_episodes=episodes,
                                        epsilon=epsilon)
    else:
        from dist_dqn_tpu.agents.dqn import make_learner
        from dist_dqn_tpu.train_loop import make_evaluator
        init, _ = make_learner(net, cfg.learner)
        evaluator = make_evaluator(cfg, env, net, num_episodes=episodes,
                                   epsilon=epsilon)

    obs_example = jax.numpy.zeros(env.observation_shape,
                                  env.observation_dtype)
    example = init(k_init, obs_example)
    # Read-only surface: never create the directory on a typo'd path, and
    # release the orbax manager after the one restore.
    if not os.path.isdir(checkpoint_dir):
        raise FileNotFoundError(
            f"no checkpoint found under {checkpoint_dir!r}")
    ckpt = TrainCheckpointer(checkpoint_dir)
    try:
        restored = ckpt.restore_latest(example)
    finally:
        ckpt.close()
    if restored is None:
        raise FileNotFoundError(
            f"no checkpoint found under {checkpoint_dir!r}")
    frames, learner = restored
    mean_return = float(jax.jit(evaluator)(learner.params, k_eval))
    return {"eval_return": mean_return, "frames": frames,
            "episodes": episodes, "config": cfg.name}


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", choices=sorted(CONFIGS), required=True)
    parser.add_argument("--checkpoint-dir", required=True)
    parser.add_argument("--episodes", type=int, default=10)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform (e.g. cpu)")
    args = parser.parse_args()
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    print(json.dumps(evaluate_checkpoint(
        CONFIGS[args.config], args.checkpoint_dir,
        episodes=args.episodes, seed=args.seed)))


if __name__ == "__main__":
    main()
