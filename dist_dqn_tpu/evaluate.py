"""Standalone checkpoint evaluation:
``python -m dist_dqn_tpu.evaluate --config cartpole --checkpoint-dir d``.

The deploy-side half of the checkpoint story (SURVEY.md §5): load the
newest learner checkpoint a training run (either runtime) saved with
``--checkpoint-dir`` and run greedy episodes on the config's env, without
any training machinery in the loop. Prints one JSON line with the mean
undiscounted return.
"""
from __future__ import annotations

import argparse
import json
import os

import jax

from dist_dqn_tpu.config import CONFIGS, ExperimentConfig, apply_overrides
from dist_dqn_tpu.utils.checkpoint import CheckpointMissingError


def _ckpt_prefix(checkpoint_dir: str):
    """Where the params live inside this directory's checkpoints:
    learner-kind saves the learner at the root; --checkpoint-replay
    (carry-kind) and the host-replay whole-state checkpoints
    (host_loop-kind, ISSUE 8) nest it one level down."""
    from dist_dqn_tpu.utils.checkpoint import read_checkpoint_kind

    return (("learner",)
            if read_checkpoint_kind(checkpoint_dir) in ("carry",
                                                        "host_loop")
            else ())


def _restore_latest(checkpoint_dir: str, example_params, step=None,
                    member=None):
    """(frames, params) from the newest checkpoint (or a specific
    retained ``step``). Read-only surface: never create the directory on
    a typo'd path, and release the orbax manager after the one restore.

    Eval needs only the policy parameters, so this partial-restores the
    params subtree (utils/checkpoint.py restore_params): the training
    run's optimizer structure (e.g. lr-schedule state) never constrains
    an eval invocation, and carry-kind (--checkpoint-replay) runs are
    evaluable without a ring-sized carry template. ``member`` selects
    one policy out of a --population run's [M]-stacked tree (ISSUE 20);
    restore_params refuses the solo/stacked direction mismatches with
    the actual cause.
    """
    from dist_dqn_tpu.utils.checkpoint import TrainCheckpointer

    if not os.path.isdir(checkpoint_dir):
        raise CheckpointMissingError(
            f"no checkpoint found under {checkpoint_dir!r}")
    prefix = _ckpt_prefix(checkpoint_dir)
    ckpt = TrainCheckpointer(checkpoint_dir)
    try:
        restored = ckpt.restore_params(example_params, step=step,
                                       prefix=prefix, member=member)
    except FileNotFoundError as e:
        # Convert to the skippable type ONLY when the requested step is
        # genuinely gone from the retained set (live retention race) —
        # a corrupt-but-present step (interrupted save) must propagate
        # loudly, not be mislabeled as deleted.
        if step is not None and step not in ckpt.all_steps():
            raise CheckpointMissingError(str(e)) from e
        raise
    finally:
        ckpt.close()
    if restored is None:
        raise CheckpointMissingError(
            f"no checkpoint found under {checkpoint_dir!r}")
    return restored


def _build_eval(cfg: ExperimentConfig, episodes: int, epsilon: float,
                seed: int):
    """(example learner pytree, jitted evaluator, eval key) for the
    config's JAX env — shared by the single-point and curve surfaces so
    the compiled evaluator is built exactly once either way."""
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network

    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    rng = jax.random.PRNGKey(seed)
    rng, k_init, k_eval = jax.random.split(rng, 3)

    if cfg.network.lstm_size:
        from dist_dqn_tpu.agents.r2d2 import make_r2d2_learner
        from dist_dqn_tpu.r2d2_loop import make_r2d2_evaluator
        init, _ = make_r2d2_learner(net, cfg.learner, cfg.replay)
        evaluator = make_r2d2_evaluator(cfg, env, net,
                                        num_episodes=episodes,
                                        epsilon=epsilon)
    else:
        from dist_dqn_tpu.agents.dqn import make_learner
        from dist_dqn_tpu.train_loop import make_evaluator
        init, _ = make_learner(net, cfg.learner)
        evaluator = make_evaluator(cfg, env, net, num_episodes=episodes,
                                   epsilon=epsilon)

    obs_example = jax.numpy.zeros(env.observation_shape,
                                  env.observation_dtype)
    example = init(k_init, obs_example)
    return example, jax.jit(evaluator), k_eval


def evaluate_checkpoint(cfg: ExperimentConfig, checkpoint_dir: str,
                        episodes: int = 10, seed: int = 0,
                        epsilon: float = 0.001, step: int = None,
                        export_params: str = None,
                        member: int = None) -> dict:
    """Restore the newest checkpoint (or retained ``step``) and play
    greedy episodes.

    ``export_params`` additionally writes the restored policy parameters
    as a standalone pytree checkpoint (utils/checkpoint.save_pytree) —
    the deploy artifact: a few MB of params with no optimizer state,
    loadable anywhere via ``restore_pytree(path, example_params)``
    without the training run's directory or flags.

    Returns {"eval_return": mean, "frames": checkpoint cursor, ...}.
    Raises FileNotFoundError if the directory holds no checkpoint.
    """
    example, evaluator, k_eval = _build_eval(cfg, episodes, epsilon, seed)
    frames, params = _restore_latest(checkpoint_dir, example.params,
                                     step=step, member=member)
    mean_return = float(evaluator(params, k_eval))
    out = {"eval_return": mean_return, "frames": frames,
           "episodes": episodes, "config": cfg.name}
    if member is not None:
        out["member"] = member
    if export_params:
        from dist_dqn_tpu.utils.checkpoint import save_pytree

        save_pytree(os.path.abspath(export_params), params)
        out["exported_params"] = os.path.abspath(export_params)
    return out


def _skip_row(step: int) -> dict:
    """The one shape both --all-steps modes emit for a checkpoint that a
    live training run's retention deleted mid-walk."""
    return {"frames": step,
            "skipped": "checkpoint deleted during walk (live retention)"}


def evaluate_checkpoint_curve(cfg: ExperimentConfig, checkpoint_dir: str,
                              episodes: int = 10, seed: int = 0,
                              epsilon: float = 0.001,
                              log_fn=None, member: int = None) -> list:
    """Evaluate EVERY retained checkpoint step (oldest first) — the
    learning curve of a run directory. One env/net/evaluator build and
    one compile serve all steps; one checkpoint manager restores each
    into the same example pytree. Identical eval rng per step, so curve
    points differ only by the restored parameters. Steps garbage-
    collected mid-walk by a live training run's retention are skipped
    with a log line rather than aborting the walk.
    """
    from dist_dqn_tpu.utils.checkpoint import TrainCheckpointer

    if not os.path.isdir(checkpoint_dir):
        raise FileNotFoundError(
            f"no checkpoint found under {checkpoint_dir!r}")
    rows = []
    prefix = _ckpt_prefix(checkpoint_dir)
    ckpt = TrainCheckpointer(checkpoint_dir)
    try:
        steps = ckpt.all_steps()
        if not steps:
            # The dir exists but holds no complete step yet — the
            # live-run-before-first-save shape, distinct from a missing
            # dir so --wait-for-checkpoint can retry it (still a
            # FileNotFoundError subclass for fail-fast callers).
            raise CheckpointMissingError(
                f"no checkpoint found under {checkpoint_dir!r}")
        # Build (env, net, jitted evaluator) only once a step list
        # exists — an empty dir errors without paying the build.
        example, evaluator, k_eval = _build_eval(cfg, episodes, epsilon,
                                                 seed)
        for step in steps:
            try:
                frames, params = ckpt.restore_params(
                    example.params, step=step, prefix=prefix,
                    member=member)
            except FileNotFoundError:
                # Narrow scope: only the restore is guarded, so an
                # unrelated FileNotFoundError cannot be mislabeled.
                if log_fn:
                    log_fn(_skip_row(step))
                continue
            row = {"eval_return": float(evaluator(params, k_eval)),
                   "frames": frames, "episodes": episodes,
                   "config": cfg.name}
            if member is not None:
                row["member"] = member
            rows.append(row)
            if log_fn:
                log_fn(row)
    finally:
        ckpt.close()
    return rows


def evaluate_checkpoint_host(cfg: ExperimentConfig, checkpoint_dir: str,
                             host_env: str, episodes: int = 10,
                             seed: int = 0, epsilon: float = 0.001,
                             max_steps: int = 20_000,
                             step: int = None, member: int = None) -> dict:
    """Greedy checkpoint episodes on a HOST env (real ALE / DM-Control /
    gymnasium) — the deploy-side counterpart of an Ape-X split training
    run, which steps host envs the JAX stand-ins only approximate.

    The network is built with the HOST env's action count (an ale:
    checkpoint trained on Breakout has 4 heads, not the stand-in's 6),
    one vectorized env instance per episode, whole-game episodes and RAW
    (unclipped) game scores (``for_eval=True``: episodic-life and reward
    clipping are training devices, not scoring rules).
    """
    from dist_dqn_tpu.envs.gym_adapter import make_host_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.utils.host_eval import run_greedy_episodes

    env = make_host_env(host_env, episodes, seed=10_000 + seed,
                        for_eval=True)
    net = build_network(cfg.network, env.num_actions)
    obs = env.reset()
    recurrent = cfg.network.lstm_size > 0
    if recurrent:
        from dist_dqn_tpu.agents.r2d2 import (make_r2d2_learner,
                                              make_recurrent_actor_step)
        init, _ = make_r2d2_learner(net, cfg.learner, cfg.replay)
        act = jax.jit(make_recurrent_actor_step(net))
        carry = net.initial_state(episodes)
    else:
        from dist_dqn_tpu.agents.dqn import make_actor_step, make_learner
        init, _ = make_learner(net, cfg.learner)
        act = jax.jit(make_actor_step(net))

    rng = jax.random.PRNGKey(seed)
    rng, k_init = jax.random.split(rng)
    example = init(k_init, jax.numpy.asarray(obs[0]))
    frames, params = _restore_latest(checkpoint_dir, example.params,
                                     step=step, member=member)

    returns, truncated, _ = run_greedy_episodes(
        env, act, params, rng, episodes=episodes,
        recurrent_carry=carry if recurrent else None, epsilon=epsilon,
        max_steps=max_steps)
    out = {"eval_return": float(returns.mean()), "frames": frames,
           "episodes": episodes, "config": cfg.name, "host_env": host_env,
           "episodes_truncated": truncated}
    if member is not None:
        out["member"] = member
    return out


def _apply_risk_eta(cfg: ExperimentConfig, eta) -> ExperimentConfig:
    """Evaluate an IQN checkpoint under a different risk profile than it
    was trained with (the point of IQN's CVaR acting: one set of learned
    quantiles, a family of policies). Parameters are risk-agnostic, so
    any eta in (0, 1] restores cleanly."""
    import dataclasses

    if not cfg.network.iqn:
        raise ValueError(
            "--risk-cvar-eta only applies to IQN configs (the acting "
            f"fractions of {cfg.name!r} are not tau-conditioned)")
    return dataclasses.replace(
        cfg, network=dataclasses.replace(cfg.network, risk_cvar_eta=eta))


def main():
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--config", choices=sorted(CONFIGS), required=True)
    parser.add_argument("--checkpoint-dir", required=True)
    parser.add_argument("--episodes", type=int, default=10)
    parser.add_argument("--seed", type=int, default=0)
    parser.add_argument("--platform", default=None,
                        help="force a JAX platform (e.g. cpu)")
    parser.add_argument("--host-env", default=None,
                        help="evaluate on a HOST env (e.g. ale:Breakout, "
                             "CartPole-v1, dmc:reacher:easy) instead of "
                             "the config's JAX stand-in env")
    parser.add_argument("--risk-cvar-eta", type=float, default=None,
                        help="IQN configs only: act on the lower-eta CVaR "
                             "tail of the learned return distribution "
                             "instead of the trained profile (risk-averse "
                             "deploy-time policy from the same checkpoint)")
    parser.add_argument("--set", dest="overrides", action="append",
                        metavar="PATH=VALUE", default=[],
                        help="override config fields by dotted path (must "
                             "match how the checkpoint was trained, e.g. "
                             "--set network.dueling=true)")
    parser.add_argument("--member", type=int, default=None, metavar="K",
                        help="population checkpoints (ISSUE 20, "
                             "--population runs): evaluate member K of "
                             "the [M]-stacked tree (0-based). Required "
                             "for population directories — a member-less "
                             "restore of a stacked tree is refused with "
                             "the cause — and refused on solo "
                             "directories")
    parser.add_argument("--all-steps", action="store_true",
                        help="evaluate EVERY retained checkpoint step "
                             "(oldest first, one JSON line each) — a "
                             "learning curve from the run directory "
                             "instead of just the newest point")
    parser.add_argument("--export-params", default=None, metavar="PATH",
                        help="also write the restored policy parameters "
                             "as a standalone pytree checkpoint at PATH "
                             "(params only, no optimizer state — the "
                             "deploy artifact; JAX-env surface, newest/"
                             "single step)")
    parser.add_argument("--wait-for-checkpoint", type=float, default=0.0,
                        metavar="SECONDS",
                        help="retry a missing checkpoint (absent dir or "
                             "a live run dir that has not saved yet) for "
                             "up to this many seconds instead of failing "
                             "immediately — for evals launched alongside "
                             "training (default 0: fail fast as before)")
    parser.add_argument("--telemetry-port", type=int, default=None,
                        help="serve this process's telemetry registry "
                             "(/metrics, /metrics.json, /healthz, "
                             "/debug/*) on this port; 0 binds an "
                             "ephemeral port (reported as a "
                             "telemetry_port log line) — eval runs are "
                             "scrapable exactly like train runs "
                             "(docs/observability.md)")
    parser.add_argument("--telemetry-host", default="127.0.0.1",
                        help="bind address for --telemetry-port "
                             "(loopback by default; 0.0.0.0 exposes the "
                             "scrape surface outside the container/VM)")
    parser.add_argument("--telemetry-snapshot", default=None,
                        help="dump a JSON snapshot of the telemetry "
                             "registry to this path at exit (offline "
                             "runs; same data as /metrics.json)")
    parser.add_argument("--fleet-dir", default=None,
                        help="fleet registry directory (ISSUE 16): "
                             "announce this eval's telemetry endpoint "
                             "to the run's aggregator; defaults to "
                             "$DQN_FLEET_DIR")
    args = parser.parse_args()
    if args.export_params and (args.all_steps or args.host_env):
        parser.error("--export-params applies to the single-point JAX-env "
                     "surface (not --all-steps or --host-env)")
    # Telemetry surface parity with the train CLI (ISSUE 4 satellite):
    # eval processes populate the same registry (checkpoint restore
    # spans, env steps), so expose the same scrape/snapshot knobs.
    if args.telemetry_snapshot:
        from dist_dqn_tpu.telemetry import install_snapshot_dump

        install_snapshot_dump(args.telemetry_snapshot)
    if args.fleet_dir:
        import os as _os

        _os.environ["DQN_FLEET_DIR"] = args.fleet_dir
    if args.telemetry_port is not None:
        from dist_dqn_tpu import telemetry
        from dist_dqn_tpu.telemetry import fleet as _fleet

        _srv = telemetry.start_server(args.telemetry_port,
                                      host=args.telemetry_host)
        print(json.dumps({"telemetry_port": _srv.port}))
        _fleet.register_endpoint("eval", _srv.port,
                                 host=args.telemetry_host)
    if args.platform:
        jax.config.update("jax_platforms", args.platform)
    try:
        cfg = apply_overrides(CONFIGS[args.config], args.overrides)
    except ValueError as e:
        parser.error(str(e))
    if args.risk_cvar_eta is not None:
        cfg = _apply_risk_eta(cfg, args.risk_cvar_eta)

    def tag_and_print(out):
        if args.risk_cvar_eta is not None:
            out["risk_cvar_eta"] = args.risk_cvar_eta
        print(json.dumps(out), flush=True)

    def run_one(step=None):
        if args.host_env:
            out = evaluate_checkpoint_host(
                cfg, args.checkpoint_dir, args.host_env,
                episodes=args.episodes, seed=args.seed, step=step,
                member=args.member)
        else:
            out = evaluate_checkpoint(
                cfg, args.checkpoint_dir,
                episodes=args.episodes, seed=args.seed, step=step,
                export_params=args.export_params, member=args.member)
        tag_and_print(out)

    def dispatch():
        # Cheap presence gate BEFORE any env/network build: without it,
        # every --wait-for-checkpoint retry rebuilds the whole eval
        # stack (env + net + jit, seconds on CPU) just to find the dir
        # still empty — and the --all-steps listing paths raise plain
        # FileNotFoundError on an absent dir, which the retry loop
        # deliberately does not catch. One probe makes the absent-dir
        # and empty-live-dir shapes retryable on every mode.
        from dist_dqn_tpu.utils.checkpoint import checkpoint_present

        if not checkpoint_present(args.checkpoint_dir):
            raise CheckpointMissingError(
                f"no checkpoint found under {args.checkpoint_dir!r}")
        if args.all_steps and not args.host_env:
            # One build + one compile + one manager serve the whole curve.
            evaluate_checkpoint_curve(
                cfg, args.checkpoint_dir, episodes=args.episodes,
                seed=args.seed,
                log_fn=tag_and_print, member=args.member)
        elif args.all_steps:
            # Host envs: per-step restores through the single-point
            # surface (episode stepping dominates; no scan-evaluator
            # recompile).
            from dist_dqn_tpu.utils.checkpoint import list_checkpoint_steps

            steps = list_checkpoint_steps(args.checkpoint_dir)
            if not steps:
                # Existing-but-empty run dir: CheckpointMissingError so
                # --wait-for-checkpoint retries (a missing dir raised
                # FileNotFoundError from the listing already).
                raise CheckpointMissingError(
                    f"no checkpoint found under {args.checkpoint_dir!r}")
            for step in steps:
                # A step deleted mid-walk by a live run's retention
                # raises the DISTINCT CheckpointMissingError from the
                # restore — skip it and keep walking. Any other error
                # (missing ROM/asset, plain FileNotFoundError included)
                # propagates loudly; no per-step re-listing, no TOCTOU
                # window (ADVICE round 3).
                try:
                    run_one(step)
                except CheckpointMissingError:
                    tag_and_print(_skip_row(step))
        else:
            run_one()

    # --wait-for-checkpoint (ISSUE 7 satellite): an eval launched beside
    # a fresh training run sees the run dir before its first save lands
    # (the manager mkdirs at construction) — bounded retry instead of an
    # immediate crash. ONLY the distinct CheckpointMissingError retries
    # (utils/checkpoint.py wait_for_checkpoint, shared with the serving
    # CLI); any other failure (missing ROM/asset, corrupt step) stays
    # loud on the first attempt, and the default 0s budget keeps today's
    # fail-fast behavior.
    from dist_dqn_tpu.utils.checkpoint import wait_for_checkpoint

    wait_for_checkpoint(dispatch, args.wait_for_checkpoint)


if __name__ == "__main__":
    main()
