"""Population training plane (ISSUE 20): M vmap-stacked policies, one
program.

BENCH_r05 prices the fused learner at 96% chip-idle — one cartpole/atari
policy cannot fill a TPU. ROADMAP item 6's answer (after Podracer's
"one program, many policies", PAPERS.md, on the commodity-scale terms of
arXiv:2111.01264) is to train M policies — distinct seeds and
hyperparameter variants — as ONE jitted program: every carry leaf
(params, optimizer state, target params, replay ring, env vector, rng)
gains a leading member axis and ``jax.vmap`` advances all M members in
one dispatch per chunk, composing with the in-scan replay ratio and
pow2 train batches unchanged.

Member independence is a hard contract, not a best effort: member k of
an M-run must BIT-MATCH a solo run configured with member k's
hyperparameters and seeded with member k's stream (no cross-member
leakage through replay, RNG or the traced hyperparameters —
tests/test_population.py pins it). That is why

* per-member RNG streams spawn from ``--seed`` with the SeedSequence
  spawn-key discipline (PR 5): member k's base seed is
  ``SeedSequence(seed, spawn_key=(k,))`` — solo-reproducible by seeding
  a plain run with the same derived value;
* per-member epsilon decays through
  ``loop_common.make_member_epsilon`` — the op-for-op twin of the solo
  ``optax.linear_schedule`` with the constants as traced lanes;
* per-member learning rates ride the optimizer STATE
  (``agents.dqn.make_population_optimizer``) so the vmapped update
  applies bit-identically to the solo Adam at the same rate;
* per-member gamma threads into the n-step fold at sample time
  (``replay/device.py compute_n_step`` is pure jnp broadcasting).

The spec JSON (``--population-spec``) carries the per-member vectors:
an object with any of ``epsilon`` (exploration floor epsilon_end),
``lr``, ``gamma`` — each a length-M array. Members without an override
inherit the base config's value.
"""
from __future__ import annotations

import dataclasses
import json
from typing import List, Optional, Tuple

import jax
import jax.numpy as jnp
import numpy as np

from dist_dqn_tpu.config import ExperimentConfig, PopulationConfig
from dist_dqn_tpu.train_loop import MemberHP, make_fused_train

#: The spec's per-member vector keys, and the config field each one
#: overrides in a member's solo-equivalent run.
SPEC_KEYS = ("epsilon", "lr", "gamma")


@dataclasses.dataclass(frozen=True)
class PopulationSpec:
    """Validated per-member hyperparameter vectors (None = inherit)."""

    epsilon: Optional[Tuple[float, ...]] = None
    lr: Optional[Tuple[float, ...]] = None
    gamma: Optional[Tuple[float, ...]] = None


def parse_spec(text: str, size: int) -> PopulationSpec:
    """Parse + validate a ``--population-spec`` JSON document.

    Accepts an object whose keys are a subset of :data:`SPEC_KEYS`,
    each a length-``size`` array of numbers. Empty text means "no
    overrides". Raises ``ValueError`` with the offending key on any
    shape/range violation — at startup, not as a traced NaN later.
    """
    if not text or not text.strip():
        return PopulationSpec()
    try:
        raw = json.loads(text)
    except json.JSONDecodeError as e:
        raise ValueError(f"population spec is not valid JSON: {e}") from e
    if not isinstance(raw, dict):
        raise ValueError(
            f"population spec must be a JSON object of per-member "
            f"vectors {SPEC_KEYS}, got {type(raw).__name__}")
    unknown = sorted(set(raw) - set(SPEC_KEYS))
    if unknown:
        raise ValueError(
            f"population spec has unknown keys {unknown}; supported "
            f"per-member vectors: {list(SPEC_KEYS)}")
    out = {}
    for key in SPEC_KEYS:
        if key not in raw:
            continue
        vec = raw[key]
        if not isinstance(vec, (list, tuple)) or not all(
                isinstance(v, (int, float)) and not isinstance(v, bool)
                for v in vec):
            raise ValueError(
                f"population spec {key!r} must be an array of numbers")
        if len(vec) != size:
            raise ValueError(
                f"population spec {key!r} has {len(vec)} entries for "
                f"--population {size}; each vector must be length M")
        vals = tuple(float(v) for v in vec)
        if key == "epsilon" and not all(0.0 <= v <= 1.0 for v in vals):
            raise ValueError(
                "population spec 'epsilon' entries must be in [0, 1] "
                "(the per-member exploration floor epsilon_end)")
        if key == "lr" and not all(v > 0.0 for v in vals):
            raise ValueError(
                "population spec 'lr' entries must be > 0")
        if key == "gamma" and not all(0.0 < v <= 1.0 for v in vals):
            raise ValueError(
                "population spec 'gamma' entries must be in (0, 1]")
        out[key] = vals
    return PopulationSpec(**out)


def resolve_spec(cfg: ExperimentConfig) -> PopulationSpec:
    """The config's spec, parsed against its own ``population.size``."""
    spec = parse_spec(cfg.population.spec_json, cfg.population.size)
    if spec.lr is not None and cfg.learner.lr_schedule != "constant":
        raise ValueError(
            "population spec 'lr' requires learner.lr_schedule="
            "'constant' (agents/dqn.py make_population_optimizer: the "
            "anneal horizon is not a stackable member axis)")
    return spec


def member_seeds(seed: int, size: int) -> List[int]:
    """Member k's base seed: ``SeedSequence(seed, spawn_key=(k,))`` —
    the PR 5 stream discipline. A solo run seeded with ``seeds[k]``
    consumes exactly member k's key stream."""
    return [int(np.random.SeedSequence(seed, spawn_key=(k,))
                .generate_state(1)[0]) for k in range(size)]


def member_config(cfg: ExperimentConfig, spec: PopulationSpec,
                  k: int) -> ExperimentConfig:
    """Member k's solo-equivalent config: the base config with member
    k's spec overrides applied statically and the population section
    reset — the reference program of the member-independence pin."""
    actor, learner = cfg.actor, cfg.learner
    if spec.epsilon is not None:
        actor = dataclasses.replace(actor, epsilon_end=spec.epsilon[k])
    if spec.lr is not None:
        learner = dataclasses.replace(learner,
                                      learning_rate=spec.lr[k])
    if spec.gamma is not None:
        learner = dataclasses.replace(learner, gamma=spec.gamma[k])
    return dataclasses.replace(cfg, actor=actor, learner=learner,
                               population=PopulationConfig())


def member_hp(cfg: ExperimentConfig, spec: PopulationSpec) -> MemberHP:
    """The stacked [M] :class:`MemberHP` arrays the vmapped entry
    points consume. ``eps_delta`` folds epsilon_start - epsilon_end on
    the host in float64 and casts to f32 — the exact constant
    ``optax.linear_schedule`` embeds for the solo program, so member
    epsilon is bitwise the solo schedule."""
    M = cfg.population.size
    eps_end = (spec.epsilon if spec.epsilon is not None
               else (cfg.actor.epsilon_end,) * M)
    lr = (spec.lr if spec.lr is not None
          else (cfg.learner.learning_rate,) * M)
    gamma = (spec.gamma if spec.gamma is not None
             else (cfg.learner.gamma,) * M)
    start = float(cfg.actor.epsilon_start)
    return MemberHP(
        eps_delta=jnp.asarray([np.float32(start - float(e))
                               for e in eps_end], jnp.float32),
        eps_end=jnp.asarray(eps_end, jnp.float32),
        gamma=jnp.asarray(gamma, jnp.float32),
        lr=jnp.asarray(lr, jnp.float32))


def extract_member(tree, k: int):
    """Member k's slice of an [M]-stacked pytree (params, carry, ...)."""
    return jax.tree.map(lambda x: x[k], tree)


def stacked_members(tree) -> int:
    """The member-axis width M of a stacked pytree."""
    return int(jax.tree.leaves(tree)[0].shape[0])


def make_population_train(cfg: ExperimentConfig, env, net):
    """(init_population, run_population_chunk) — the vmap-stacked twins
    of ``make_fused_train``'s (init, run_chunk).

    ``init_population(keys, hp)`` vmaps the per-member init over [M]
    base keys + the stacked :class:`MemberHP`;
    ``run_population_chunk(carries, hp, num_iters)`` advances all M
    members ONE dispatch per chunk (jit it with ``static_argnums=2,
    donate_argnums=0`` — the [M]-stacked carries update in place like
    the solo carry does). Each member's lane is the exact solo program:
    same replay ring, same key stream, same schedule arithmetic.
    """
    spec = resolve_spec(cfg)
    init_m, run_m = make_fused_train(cfg, env, net, member_hp=True,
                                     member_lr=spec.lr is not None)

    def init_population(keys, hp: MemberHP):
        return jax.vmap(init_m)(keys, hp)

    def run_population_chunk(carries, hp: MemberHP, num_iters: int):
        return jax.vmap(lambda c, h: run_m(c, h, num_iters))(carries, hp)

    return init_population, run_population_chunk
