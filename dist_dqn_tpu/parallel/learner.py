"""Multi-chip fused training: shard_map over the ICI mesh.

Composition of the per-device fused loop (train_loop.py) into the pod-scale
program the driver describes (BASELINE.json:5):

  * envs + replay shard over the ``dp`` mesh axis — each device rolls out
    its own env lanes and owns one replay shard in its HBM (the TPU-native
    reading of "replay shards across TPU-VM host DRAM"; the host-DRAM
    variant for external envs is replay/host.py + actors/),
  * learner state is replicated; gradients cross the ICI once per update
    via ``pmean`` inside the learner (agents/dqn.py) — the NCCL-allreduce
    replacement,
  * chunk metrics are psum-reduced so the host sees global numbers.

Everything below is spec plumbing: which TrainCarry leaves live on which
mesh axis. The actual math is unchanged single-device code — that's the
point of SPMD.
"""
from __future__ import annotations

from functools import partial
from typing import Dict

import jax
from jax.sharding import Mesh, PartitionSpec as P

from dist_dqn_tpu.agents.dqn import LearnerState
from dist_dqn_tpu.config import ExperimentConfig
from dist_dqn_tpu.envs.base import JaxEnv
from dist_dqn_tpu.replay.device import TimeRingState
from dist_dqn_tpu.replay.prioritized_device import PrioritizedRingState
from dist_dqn_tpu.train_loop import TrainCarry, make_fused_train


def _carry_specs(prioritized: bool, axis: str) -> TrainCarry:
    """Pytree-prefix PartitionSpecs for every TrainCarry field.

    Env-batched leaves shard their env axis; ring leaves are [slots, envs,
    ...] so they shard axis 1; learner state and scalar counters are
    replicated (kept consistent by pmean/psum inside the body).
    """
    shard0 = P(axis)            # leading env axis
    shard1 = P(None, axis)      # ring layout [T, B, ...]
    repl = P()
    ring_spec = TimeRingState(
        obs=shard1, action=shard1, reward=shard1, terminated=shard1,
        truncated=shard1, final_obs=shard1, pos=repl, size=repl)
    replay_spec = (PrioritizedRingState(ring=ring_spec, priorities=shard1,
                                        max_priority=repl)
                   if prioritized else ring_spec)
    learner_spec = LearnerState(params=repl, target_params=repl,
                                opt_state=repl, steps=repl, rng=repl)
    return TrainCarry(
        env_state=shard0, obs=shard0, replay=replay_spec,
        learner=learner_spec, rng=shard0, iteration=repl,
        ep_return=shard0, completed_return=repl, completed_count=repl,
        loss_sum=repl, train_count=repl)


def make_mesh_fused_train(cfg: ExperimentConfig, env: JaxEnv, net,
                          mesh: Mesh, axis: str = "dp"):
    """Returns (init, run) on GLOBAL arrays: ``init(key)`` builds the pod-
    wide carry; ``run(carry, num_iters)`` executes a fused chunk across the
    mesh and reports global metrics. Both are jit-compiled; the carry is
    donated so replay shards update in place in each device's HBM.
    """
    ndp = mesh.shape[axis]
    init_local, run_local = make_fused_train(cfg, env, net, axis_name=axis,
                                             num_shards=ndp)
    specs = _carry_specs(cfg.replay.prioritized, axis)

    init = jax.jit(
        jax.shard_map(init_local, mesh=mesh, in_specs=P(),
                      out_specs=specs, check_vma=False))

    @partial(jax.jit, static_argnums=1, donate_argnums=0)
    def run(carry: TrainCarry, num_iters: int):
        body = jax.shard_map(
            lambda c: run_local(c, num_iters), mesh=mesh,
            in_specs=(specs,), out_specs=(specs, P()), check_vma=False)
        return body(carry)

    return init, run


def global_metrics(metrics: Dict) -> Dict:
    """Device-get + float-cast a metrics dict for logging."""
    got = jax.device_get(metrics)
    return {k: float(v) for k, v in got.items()}
