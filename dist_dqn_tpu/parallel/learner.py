"""Multi-chip fused training: shard_map over the ICI mesh.

Composition of the per-device fused loops (train_loop.py, r2d2_loop.py) into
the pod-scale program the driver describes (BASELINE.json:5):

  * envs + replay shard over the ``dp`` mesh axis — each device rolls out
    its own env lanes and owns one replay shard in its HBM (the TPU-native
    reading of "replay shards across TPU-VM host DRAM"; the host-DRAM
    variant for external envs is replay/host.py + actors/),
  * learner state is replicated; gradients cross the ICI once per update
    via ``pmean`` inside the learner (agents/dqn.py, agents/r2d2.py) — the
    NCCL-allreduce replacement,
  * chunk metrics are psum-reduced so the host sees global numbers.

Everything below is spec plumbing: which carry leaves live on which mesh
axis. The actual math is unchanged single-device code — that's the point
of SPMD.
"""
from __future__ import annotations

import time
from functools import partial
from typing import Dict

import jax
from jax.sharding import Mesh, PartitionSpec as P

from dist_dqn_tpu.telemetry import get_registry
from dist_dqn_tpu.utils import compat

from dist_dqn_tpu.agents.dqn import LearnerState
from dist_dqn_tpu.config import ExperimentConfig
from dist_dqn_tpu.envs.base import JaxEnv
from dist_dqn_tpu.replay.device import TimeRingState
from dist_dqn_tpu.replay.prioritized_device import PrioritizedRingState
from dist_dqn_tpu.train_loop import TrainCarry, make_fused_train


def _ring_spec(axis: str) -> TimeRingState:
    """Ring leaves are [slots, envs, ...]: env axis 1 sharded."""
    shard1 = P(None, axis)
    repl = P()
    return TimeRingState(
        obs=shard1, action=shard1, reward=shard1, terminated=shard1,
        truncated=shard1, final_obs=shard1, pos=repl, size=repl)


def _learner_spec() -> LearnerState:
    repl = P()
    return LearnerState(params=repl, target_params=repl, opt_state=repl,
                        steps=repl, rng=repl)


def _carry_specs(prioritized: bool, axis: str) -> TrainCarry:
    """Pytree-prefix PartitionSpecs for every TrainCarry field.

    Env-batched leaves shard their env axis; learner state and scalar
    counters are replicated (kept consistent by pmean/psum inside the body).
    """
    shard0 = P(axis)            # leading env axis
    shard1 = P(None, axis)
    repl = P()
    ring_spec = _ring_spec(axis)
    replay_spec = (PrioritizedRingState(ring=ring_spec, priorities=shard1,
                                        max_priority=repl)
                   if prioritized else ring_spec)
    return TrainCarry(
        env_state=shard0, obs=shard0, replay=replay_spec,
        learner=_learner_spec(), rng=shard0, iteration=repl,
        ep_return=shard0, completed_return=repl, completed_count=repl,
        loss_sum=repl, train_count=repl)


def _r2d2_carry_specs(axis: str) -> "R2D2Carry":
    """R2D2 carry: same layout story plus the actor LSTM carry ([B, lstm] —
    env axis sharded) and the stored per-step recurrent-state planes
    ([T, B, lstm] — env axis 1 sharded)."""
    from dist_dqn_tpu.r2d2_loop import R2D2Carry
    from dist_dqn_tpu.replay.sequence_device import SequenceRingState

    shard0 = P(axis)
    shard1 = P(None, axis)
    repl = P()
    replay_spec = SequenceRingState(
        ring=_ring_spec(axis), state_c=shard1, state_h=shard1,
        priorities=shard1, max_priority=repl, writes=repl)
    return R2D2Carry(
        env_state=shard0, obs=shard0, actor_carry=(shard0, shard0),
        replay=replay_spec, learner=_learner_spec(), rng=shard0,
        iteration=repl, ep_return=shard0, completed_return=repl,
        completed_count=repl, loss_sum=repl, train_count=repl)


def _mesh_wrap(mesh: Mesh, specs, init_local, run_local):
    """Lift per-device (init, run_chunk) bodies to jit-compiled functions on
    GLOBAL arrays; the carry is donated so replay shards update in place in
    each device's HBM."""
    # donation: PRNG-key-only init (run() donates the carry); devtime:
    # one-shot, not hot-path. mesh-axis: dp specs via _carry_specs.
    init = jax.jit(
        compat.shard_map(init_local, mesh=mesh, in_specs=P(),
                         out_specs=specs, check_vma=False))

    @partial(jax.jit, static_argnums=1, donate_argnums=0)
    def run(carry, num_iters: int):
        # mesh-axis: specs name the dp axis (see _carry_specs).
        body = compat.shard_map(
            lambda c: run_local(c, num_iters), mesh=mesh,
            in_specs=(specs,), out_specs=(specs, P()), check_vma=False)
        return body(carry)

    # Mesh-chunk telemetry (ISSUE 1): dispatch count + host-side dispatch
    # latency. JAX dispatch is async, so this times the enqueue, not the
    # execution — a GROWING dispatch latency means the device queue is
    # full and the host is now rate-limited by the mesh program (the
    # chunk wall itself is measured by the caller, train.py).
    reg = get_registry()
    c_chunks = reg.counter("dqn_mesh_chunks_total",
                           "fused mesh chunks dispatched")
    h_dispatch = reg.histogram("dqn_mesh_chunk_dispatch_seconds",
                               "host-side mesh chunk enqueue latency")

    def run_instrumented(carry, num_iters: int):
        t0 = time.perf_counter()
        out = run(carry, num_iters)
        h_dispatch.observe(time.perf_counter() - t0)
        c_chunks.inc()
        return out

    return init, run_instrumented


def make_mesh_fused_train(cfg: ExperimentConfig, env: JaxEnv, net,
                          mesh: Mesh, axis: str = "dp"):
    """Returns (init, run) on GLOBAL arrays: ``init(key)`` builds the pod-
    wide carry; ``run(carry, num_iters)`` executes a fused chunk across the
    mesh and reports global metrics.

    The ISSUE 6 learner-utilization knobs ride the per-device body
    unchanged: the replay-ratio scan and the deferred PER flush run
    inside each shard's chunk (every device draws its own sub-step
    batches from its local replay shard; gradients still pmean once per
    sub-step), and the pow2-bucketed ``replay.train_batch`` resolves
    through ``loop_common.shard_sizes`` — so the per-shard width, not
    the global one, must divide evenly. The donated global carry keeps
    the same aliasing contract the single-chip audit pins
    (utils/donation.py): ``run`` donates argnum 0 below.
    """
    ndp = mesh.shape[axis]
    init_local, run_local = make_fused_train(cfg, env, net, axis_name=axis,
                                             num_shards=ndp)
    return _mesh_wrap(mesh, _carry_specs(cfg.replay.prioritized, axis),
                      init_local, run_local)


def make_mesh_r2d2_train(cfg: ExperimentConfig, env: JaxEnv, net,
                         mesh: Mesh, axis: str = "dp"):
    """R2D2 across the mesh: env lanes + sequence-replay shard per device,
    sequence learner pmean-allreduced — same contract as
    ``make_mesh_fused_train`` (BASELINE.json:5,10)."""
    from dist_dqn_tpu.r2d2_loop import make_r2d2_train

    ndp = mesh.shape[axis]
    init_local, run_local = make_r2d2_train(cfg, env, net, axis_name=axis,
                                            num_shards=ndp)
    return _mesh_wrap(mesh, _r2d2_carry_specs(axis), init_local, run_local)


def train_step_specs(axis: str, recurrent: bool = False):
    """(data_specs, metric_specs) for one data-parallel train step: batch
    leaves shard their row axis over ``axis``, IS weights shard with
    them, pmean-reduced scalars replicate, per-example priorities stay
    sharded. The ONE spec set every host-side data-parallel learner
    (apex service, host-replay runtime, multi-host wrapper) lifts the
    per-device step with — the specs cannot drift apart per runtime.
    """
    from dist_dqn_tpu.types import SequenceSample, Transition

    repl = P()
    if recurrent:
        # Time-major [L, S, ...] fields shard the sequence axis (1).
        data_specs = (SequenceSample(
            obs=P(None, axis), action=P(None, axis),
            reward=P(None, axis), done=P(None, axis),
            reset=P(None, axis), start_state=(P(axis), P(axis)),
            weights=P(axis), t_idx=P(axis), b_idx=P(axis)),)
        metric_specs = {"loss": repl, "raw_loss": repl,
                        "priorities": P(axis), "grad_norm": repl}
    else:
        data_specs = (jax.tree.map(
            lambda _: P(axis),
            Transition(obs=0, action=0, reward=0, discount=0,
                       next_obs=0)),
            P(axis))  # batch, weights
        metric_specs = {"loss": repl, "raw_loss": repl,
                        "priorities": P(axis), "grad_norm": repl,
                        "mean_q_target_gap": repl}
    return data_specs, metric_specs


def scan_train_step_specs(axis: str):
    """Specs for the replay-ratio SCAN dispatch (agents/dqn.py
    make_scan_train with ``flatten=False``): batches carry a leading
    sub-step axis N, so rows shard on axis 1 and the returned
    priorities keep [N, local_rows] shape per shard — the host reshapes
    the global [N, B] to the chronological [N*B] the batched write-back
    expects (a sharded flat concat would interleave by device block,
    not by sub-step)."""
    from dist_dqn_tpu.types import Transition

    repl = P()
    data_specs = (jax.tree.map(
        lambda _: P(None, axis),
        Transition(obs=0, action=0, reward=0, discount=0, next_obs=0)),
        P(None, axis))  # stacked batches, stacked weights
    metric_specs = {"loss": repl, "raw_loss": repl,
                    "priorities": P(None, axis), "grad_norm": repl,
                    "mean_q_target_gap": repl}
    return data_specs, metric_specs


def make_sharded_train_step(train_step, mesh: Mesh, data_specs,
                            metric_specs):
    """Lift a per-device train step (built with ``axis_name`` set, so the
    pmean grad allreduce lives INSIDE it — agents/) onto ``mesh``: batch
    leaves shard per ``data_specs``, learner state replicates, and the
    state is donated so replicas update in place. Shared by the apex
    service's local learner mesh and the host-replay dp runtime."""
    repl = P()

    def sharded(state, *data):
        state_spec = jax.tree.map(lambda _: repl, state,
                                  is_leaf=lambda x: x is None)
        # mesh-axis: data_specs/metric_specs name the axis
        # (train_step_specs / scan_train_step_specs).
        body = compat.shard_map(
            train_step, mesh=mesh,
            in_specs=(state_spec,) + tuple(data_specs),
            out_specs=(state_spec, metric_specs), check_vma=False)
        return body(state, *data)

    # devtime: registered by the callers that own the dispatch fence —
    # apex service `_attach_train_cost` / host-replay `_train_dispatch`.
    return jax.jit(sharded, donate_argnums=0)


def replicated_device_views(tree, devices):
    """Per-device single-device views of a mesh-REPLICATED pytree
    (ISSUE 15, sharded collect): every mesh device already holds a full
    replica of a ``P()``-sharded array, so handing shard ``s``'s
    collect program ``views[s]`` moves ZERO bytes — the Sebulba
    actor-side param refresh without the PR 10 host mirror (which paid
    one D2H per chunk and re-uploaded on dispatch). The caller owns
    lifetime: views alias the replica buffers, so snapshot (copy/cast)
    the tree first if a donated consumer will overwrite it."""

    def view(x, d):
        for sh in x.addressable_shards:
            if sh.device == d:
                return sh.data
        # Uncommitted (host-resident) leaf — e.g. a single-device test
        # tree that never replicated: a put is correct, just not free.
        return jax.device_put(x, d)

    return [jax.tree.map(lambda x, d=d: view(x, d), tree)
            for d in devices]


def global_metrics(metrics: Dict) -> Dict:
    """Device-get + float-cast a metrics dict for logging; mirrors each
    value into a ``dqn_mesh_<name>`` registry gauge on the way."""
    got = jax.device_get(metrics)
    out = {k: float(v) for k, v in got.items()}
    reg = get_registry()
    for k, v in out.items():
        reg.gauge(f"dqn_mesh_{k}", f"mesh chunk metric {k!r}").set(v)
    return out
