"""Multi-host runtime: ``jax.distributed`` over DCN.

The reference family scales learners across hosts with NCCL/MPI process
groups (BASELINE.json:5). The TPU-native equivalent is JAX's multi-process
runtime: every host runs the SAME program, ``jax.distributed.initialize``
wires the processes into one coordination service, and ``jax.devices()``
becomes the *global* accelerator list — so the existing mesh trainers
(parallel/learner.py) scale from multi-chip to multi-host without touching
the compiled program: gradient ``pmean``s ride ICI within a host slice and
DCN across hosts, exactly where XLA places them.

What this module adds around ``jax.distributed``:

  * platform-aware initialization (on CPU it selects the gloo collectives
    implementation so the same code paths are testable without a pod —
    SURVEY.md §4's portable-idiom rule);
  * main-process gating helpers for logging/checkpointing (every process
    computes, one reports);
  * ``host_replica`` — fetch a replicated global pytree as host numpy so
    per-process code (greedy eval, checkpoint writes) can use it without
    entering a global program.

Single-process runs never need this module; nothing here imports at
train-CLI startup unless ``--coordinator`` is passed.
"""
from __future__ import annotations

from typing import Optional

import jax
import numpy as np


def initialize(coordinator_address: str, num_processes: int,
               process_id: int,
               local_device_ids: Optional[list] = None) -> None:
    """Join this process into the multi-host runtime.

    Must run before the first JAX backend touch (any jnp op / jax.devices).
    ``coordinator_address`` is ``host:port`` of process 0 — reachable over
    DCN from every host. On the CPU platform the gloo cross-process
    collectives implementation is selected automatically (the pure-Python
    default cannot allreduce across processes).
    """
    # Cross-process collectives on the CPU platform need the gloo
    # implementation (the default cannot allreduce between processes).
    # Selected unconditionally: the setting only affects the CPU client,
    # so TPU/accelerator runs are untouched — and a CPU-only host that
    # never set jax_platforms still gets working collectives.
    jax.config.update("jax_cpu_collectives_implementation", "gloo")
    jax.distributed.initialize(
        coordinator_address=coordinator_address,
        num_processes=num_processes,
        process_id=process_id,
        local_device_ids=local_device_ids)


def is_main_process() -> bool:
    """True on the process that should log/checkpoint (process 0)."""
    return jax.process_index() == 0


def main_process_log(log_fn):
    """Wrap ``log_fn`` so only process 0 emits (others compute silently)."""
    if is_main_process():
        return log_fn
    return lambda *a, **k: None


def host_replica(tree):
    """Replicated global pytree -> host numpy copy (any process).

    Replicated arrays are addressable on every process, so this never
    triggers cross-host transfers; use it to hand params to process-local
    programs (greedy eval) or checkpoint writes.
    """
    return jax.tree.map(np.asarray, jax.device_get(tree))


def shutdown() -> None:
    """Leave the multi-host runtime (idempotent)."""
    try:
        jax.distributed.shutdown()
    except Exception:
        pass
