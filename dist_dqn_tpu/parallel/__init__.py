from dist_dqn_tpu.parallel.mesh import make_mesh  # noqa: F401
from dist_dqn_tpu.parallel.learner import make_mesh_fused_train  # noqa: F401
