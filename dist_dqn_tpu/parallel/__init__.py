from dist_dqn_tpu.parallel.mesh import make_mesh  # noqa: F401
from dist_dqn_tpu.parallel.learner import (  # noqa: F401
    make_mesh_fused_train, make_mesh_r2d2_train)
