"""Device-mesh construction helpers.

One place decides how chips become a ``jax.sharding.Mesh``: the fused
multi-chip trainer uses a 1-D ``dp`` learner axis (the only parallelism the
DQN workload needs — networks are Nature-CNN sized, SURVEY.md §2), but the
helper accepts arbitrary axis layouts so future shardings (e.g. an ``ep``
axis for population-based sweeps) reuse it.
"""
from __future__ import annotations

from typing import Optional, Sequence, Tuple

import jax
import numpy as np
from jax.sharding import Mesh


def make_mesh(axis_sizes: Optional[Sequence[int]] = None,
              axis_names: Tuple[str, ...] = ("dp",),
              devices=None) -> Mesh:
    """Build a mesh over the given (or all) devices.

    ``axis_sizes=None`` puts every device on the first axis. Multi-host note:
    ``jax.devices()`` is the *global* device list under a multi-host runtime,
    so the same call shapes the pod-wide mesh with ICI-contiguous ordering.
    """
    devices = list(jax.devices()) if devices is None else list(devices)
    if axis_sizes is None:
        axis_sizes = [len(devices)] + [1] * (len(axis_names) - 1)
    if int(np.prod(axis_sizes)) != len(devices):
        raise ValueError(f"axis sizes {axis_sizes} don't cover "
                         f"{len(devices)} devices")
    grid = np.asarray(devices, dtype=object).reshape(tuple(axis_sizes))
    return Mesh(grid, axis_names)
