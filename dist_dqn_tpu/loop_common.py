"""Scaffolding shared by the fused training loops (train_loop.py, r2d2_loop.py).

Both loops are the same Anakin-style SPMD program — per-device env lanes +
replay shard, pmean-allreduced learner — differing only in what the carry
threads (feed-forward vs LSTM state) and which replay/learner pair they
drive. The schedule construction, per-device rng handling and chunk-metric
reduction live here exactly once so a fix (e.g. to beta annealing or the
psum block) cannot silently diverge between the two.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from dist_dqn_tpu.config import ExperimentConfig

Array = jnp.ndarray


def resolve_train_batch(cfg: ExperimentConfig) -> int:
    """Effective train-event batch width (ISSUE 6).

    ``replay.train_batch == 0`` keeps ``learner.batch_size`` EXACTLY
    (the bit-identity contract for existing configs); > 0 widens the
    train batch to that many rows — sequences, on the R2D2 loops —
    rounded up to the next power of two by the SAME ``pad_pow2`` the
    ingest act bucketing uses (replay/host.py), so the two bucket
    policies cannot drift apart. Every runtime's learner resolves
    through here.
    """
    from dist_dqn_tpu.replay.host import pad_pow2

    if cfg.replay.train_batch <= 0:
        return cfg.learner.batch_size
    return pad_pow2(cfg.replay.train_batch)


def resolve_replay_ratio(cfg: ExperimentConfig) -> int:
    """Validated on-device replay ratio (``replay.updates_per_chunk``):
    grad sub-steps per train event, >= 1."""
    r = cfg.replay.updates_per_chunk
    if r < 1:
        raise ValueError(
            f"replay.updates_per_chunk must be >= 1, got {r}")
    return r


def make_actor_param_cast(actor_dtype: str):
    """(cast_fn, active) for the actor/learner dtype split (ISSUE 6).

    ``actor_dtype="float32"`` (default) returns an identity and
    ``active=False`` — acting reads the live learner params, exactly
    the pre-split program. "bfloat16" returns a tree-cast of float
    leaves (params only; integer leaves untouched) the loops apply ONCE
    per chunk, keeping the learner's fp32 masters untouched.
    """
    if actor_dtype in ("", "float32"):
        return (lambda params: params), False
    if actor_dtype != "bfloat16":
        raise ValueError(
            f"network.actor_dtype must be 'float32' or 'bfloat16', got "
            f"{actor_dtype!r}")
    dt = jnp.bfloat16

    def cast(params):
        return jax.tree.map(
            lambda x: x.astype(dt)
            if jnp.issubdtype(x.dtype, jnp.floating) else x, params)

    return cast, True


def shard_sizes(cfg: ExperimentConfig, num_shards: int) -> Tuple[int, int]:
    """Validate divisibility and return per-shard (num_envs,
    train_batch) — the batch side resolved through the ISSUE 6 bucket
    rule (``resolve_train_batch``; identical to learner.batch_size
    unless replay.train_batch widens it)."""
    train_batch = resolve_train_batch(cfg)
    for name, total in (("num_envs", cfg.actor.num_envs),
                        ("train_batch", train_batch)):
        if total % num_shards:
            raise ValueError(f"{name}={total} not divisible by "
                             f"num_shards={num_shards}")
    return (cfg.actor.num_envs // num_shards,
            train_batch // num_shards)


FLAT_AUTO_BYTES = 2 << 30


def resolve_flat_storage(rcfg, obs_shape, obs_dtype, num_slots: int, B: int,
                         store_final: bool = False,
                         prefer_flat: bool = False) -> bool:
    """Decide merged-row ("flat") obs storage for a device ring.

    XLA lays out multi-dim u8 ring buffers with (8,128) tiling on
    whichever dims it puts minormost, padding 84x84 to ~1.6x its logical
    bytes — and a [slots, B, flat] 3-D form to 2.0x (lanes transposed
    minormost and padded 64->128; both measured in the 2026-08-01 v5e
    compile OOMs). A 2-D merged-row buffer pads <1% but gathers ~3%
    slower at small rings (619k vs 602k env-steps/s at 16k slots). Auto
    rule (``replay.flat_storage=None``): flat only when the ring's
    logical bytes exceed FLAT_AUTO_BYTES, where memory dominates.
    Shared by both fused loops so the rule cannot diverge.
    """
    if rcfg.flat_storage is None:
        if prefer_flat and len(obs_shape) >= 2:
            # Frame-dedup rings store [.., H, W, 1] slices whose TILED
            # layout pads the size-1 minor dim catastrophically —
            # measured on v5e (2026-08-01): 208k env-steps/s tiled vs
            # 395k flat at the same 131k dedup ring. Flat is the dedup
            # default at any size.
            return True
        obs_bytes = num_slots * B * int(jnp.dtype(obs_dtype).itemsize)
        for d in obs_shape:
            obs_bytes *= d
        return (len(obs_shape) >= 2
                and obs_bytes * (2 if store_final else 1) > FLAT_AUTO_BYTES)
    return bool(rcfg.flat_storage) and len(obs_shape) >= 2


def flat_obs_codecs(flat_storage: bool, obs_shape):
    """Reshape helpers for merged-row ("flat") ring storage.

    ``flatten_batched``: [B, *obs_shape] leaves -> [B, prod] at the
    insert boundary (identity when tiled). ``unflatten_rows``:
    [..., prod] leaves -> [..., *obs_shape] after a gather —
    rank-agnostic, so the feed-forward [N, prod] batch and the R2D2
    [L, S, prod] sequence gather share it. Both loops must use these
    (not local reshapes) so the layout boundary cannot diverge.
    """
    obs_shape = tuple(obs_shape)

    def flatten_batched(tree):
        if not flat_storage:
            return tree
        return jax.tree.map(
            lambda x: x.reshape(x.shape[0], -1) if x.ndim >= 3 else x,
            tree)

    def unflatten_rows(tree):
        if not flat_storage:
            return tree
        return jax.tree.map(
            lambda x: x.reshape(x.shape[:-1] + obs_shape), tree)

    return flatten_batched, unflatten_rows


def ring_obs_example(obs_example, flat_storage: bool):
    """Per-env obs example as the ring will store it (flattened rows
    when flat). The unflatten codec reshapes every leaf to the env's
    single observation_shape; a multi-leaf obs tree would need per-leaf
    bookkeeping it doesn't do — no current env emits one, so fail
    loudly rather than mis-shape a future one."""
    if not flat_storage:
        return obs_example
    if len(jax.tree.leaves(obs_example)) != 1:
        raise ValueError(
            "replay.flat_storage supports single-array observations "
            f"only; this env's obs is a {type(obs_example).__name__} "
            "tree — set replay.flat_storage=False")
    return jax.tree.map(
        lambda x: x.reshape(-1) if x.ndim >= 2 else x, obs_example)


def resolve_frame_dedup(rcfg, env, obs_shape,
                        store_final: bool = False):
    """Validate + resolve ``replay.frame_dedup`` for a fused loop.

    Returns (stack, stored_shape, frame_shape, slice_newest): the
    declared rolling-stack depth (0 = dedup off), the per-step shape as
    STORED in the ring (single frame under dedup), the static frame
    shape the merge-rows gather reshapes to (None when off), and the
    insert-side obs slicer. Shared by train_loop and r2d2_loop so the
    contract checks cannot diverge."""
    obs_shape = tuple(obs_shape)
    stack = rcfg.frame_dedup and getattr(env, "frame_stack", 0) or 0
    if rcfg.frame_dedup:
        if stack < 2:
            raise ValueError(
                "replay.frame_dedup=True but this env does not declare a "
                "rolling frame stack (JaxEnv.frame_stack is "
                f"{getattr(env, 'frame_stack', 0)}); dedup storage "
                "cannot rebuild its observations")
        if stack != obs_shape[-1]:
            raise ValueError(
                f"env.frame_stack={stack} does not match the obs last "
                f"axis {obs_shape[-1]}")
        if store_final:
            raise ValueError(
                "replay.frame_dedup needs store_final_obs off (the "
                "final-obs buffer is not a rolling frame stream)")
    stored_shape = obs_shape[:-1] + (1,) if stack else obs_shape
    frame_shape = stored_shape if stack else None
    slice_newest = ((lambda o: o[..., -1:]) if stack else (lambda o: o))
    return stack, stored_shape, frame_shape, slice_newest


def make_schedules(cfg: ExperimentConfig, B: int, num_shards: int
                   ) -> Tuple[Callable, Callable]:
    """(epsilon(iteration), beta(iteration)): exploration decay and the PER
    importance exponent annealing beta0 -> 1 over the configured run, both
    in per-shard iteration units."""
    epsilon = optax.linear_schedule(
        cfg.actor.epsilon_start, cfg.actor.epsilon_end,
        max(cfg.actor.epsilon_decay_steps // (B * num_shards), 1))
    total_iters = max(cfg.total_env_steps // (B * num_shards), 1)
    beta0 = cfg.replay.importance_exponent

    def beta_at(iteration: Array) -> Array:
        frac = jnp.minimum(iteration.astype(jnp.float32) / total_iters, 1.0)
        return beta0 + (1.0 - beta0) * frac

    return epsilon, beta_at


def make_member_epsilon(cfg: ExperimentConfig, B: int, num_shards: int
                        ) -> Callable:
    """Per-member exploration decay for the population plane (ISSUE 20):
    ``eps_at(iteration, delta, end)`` with TRACED ``delta`` / ``end``
    scalars (member k's ``epsilon_start - epsilon_end`` and
    ``epsilon_end`` under ``jax.vmap``).

    Op-for-op the body of ``make_schedules``'s
    ``optax.linear_schedule`` (polynomial power=1): same int32 clip,
    same ``1 - count/steps`` promotion, same multiply-add — with the
    constants arriving as [M]-array lanes instead of trace-time
    literals, so member k's epsilon is bit-identical to a solo run
    configured with member k's ``epsilon_end`` (the member-independence
    pin). ``delta`` must be folded on the HOST in float64 then cast to
    f32, exactly as the schedule's Python-literal subtraction is
    (population.member_hp does this).
    """
    steps = max(cfg.actor.epsilon_decay_steps // (B * num_shards), 1)

    def eps_at(iteration: Array, delta: Array, end: Array) -> Array:
        count = jnp.clip(iteration, 0, steps)
        frac = 1 - count / steps
        return delta * frac + end

    return eps_at


def pallas_routing(enabled: bool) -> Tuple[bool, bool]:
    """(use_pallas, pallas_interpret) for the priority-sampling kernel.

    Pallas kernels compile only on real TPU backends; anywhere else the
    config flag falls back to the equivalent XLA sampler — the Python-level
    interpreter inside a scanned hot loop would look like a hang at real
    buffer sizes. DIST_DQN_PALLAS_INTERPRET=1 opts back in for tiny-size
    integration tests of the kernel routing.
    """
    import os

    import jax

    on_tpu = jax.default_backend() == "tpu"
    interpret = (not on_tpu
                 and os.environ.get("DIST_DQN_PALLAS_INTERPRET") == "1")
    return enabled and (on_tpu or interpret), interpret


def make_rng_splitter(spmd: bool) -> Callable:
    """split(carry_rng, n) -> (new_carry_rng, [n] keys); in SPMD mode the
    carry rng is a [1] key array (per-device stream) and stays that shape."""

    def split(carry_rng: Array, n: int):
        base = carry_rng[0] if spmd else carry_rng
        keys = jax.random.split(base, n + 1)
        new = keys[:1] if spmd else keys[0]
        return new, keys[1:]

    return split


def reduce_chunk_metrics(carry, axis_name: Optional[str], B: int,
                         num_shards: int) -> Tuple[Dict, Dict]:
    """Reduce the chunk accumulators carried by either loop into the global
    metrics dict; returns (metrics, zeroed accumulator replacements).

    In SPMD mode episode stats are psum-ed (global counts), loss/train
    counters pmean-ed (identical across devices anyway), and the returned
    replacements keep every accumulator leaf replicated for the next chunk.
    """
    completed_return = carry.completed_return
    completed_count = carry.completed_count
    loss_sum = carry.loss_sum
    train_count = carry.train_count
    zero = jnp.float32(0.0)
    replace = {}
    if axis_name is not None:
        completed_return = jax.lax.psum(completed_return, axis_name)
        completed_count = jax.lax.psum(completed_count, axis_name)
        loss_sum = jax.lax.pmean(loss_sum, axis_name)
        train_count = jax.lax.pmean(train_count, axis_name)
        replace = dict(completed_return=zero, completed_count=zero,
                       loss_sum=zero, train_count=zero)
    metrics = {
        "env_frames": carry.iteration * B * num_shards,
        "episode_return":
            completed_return / jnp.maximum(completed_count, 1.0),
        "episodes": completed_count,
        "loss": loss_sum / jnp.maximum(train_count, 1.0),
        "grad_steps_in_chunk": train_count,
    }
    return metrics, replace


def episode_stats_update(carry, reward: Array, done: Array):
    """Fold one step's rewards/dones into the per-env episode trackers.

    Returns (ep_return, completed_return, completed_count) updates.
    """
    ep_return = carry.ep_return + reward
    completed_return = carry.completed_return + jnp.sum(
        jnp.where(done, ep_return, 0.0))
    completed_count = carry.completed_count + jnp.sum(
        done.astype(jnp.float32))
    ep_return = jnp.where(done, 0.0, ep_return)
    return ep_return, completed_return, completed_count
