"""Scaffolding shared by the fused training loops (train_loop.py, r2d2_loop.py).

Both loops are the same Anakin-style SPMD program — per-device env lanes +
replay shard, pmean-allreduced learner — differing only in what the carry
threads (feed-forward vs LSTM state) and which replay/learner pair they
drive. The schedule construction, per-device rng handling and chunk-metric
reduction live here exactly once so a fix (e.g. to beta annealing or the
psum block) cannot silently diverge between the two.
"""
from __future__ import annotations

from typing import Callable, Dict, Optional, Tuple

import jax
import jax.numpy as jnp
import optax

from dist_dqn_tpu.config import ExperimentConfig

Array = jnp.ndarray


def shard_sizes(cfg: ExperimentConfig, num_shards: int) -> Tuple[int, int]:
    """Validate divisibility and return per-shard (num_envs, batch_size)."""
    for name, total in (("num_envs", cfg.actor.num_envs),
                        ("batch_size", cfg.learner.batch_size)):
        if total % num_shards:
            raise ValueError(f"{name}={total} not divisible by "
                             f"num_shards={num_shards}")
    return (cfg.actor.num_envs // num_shards,
            cfg.learner.batch_size // num_shards)


def make_schedules(cfg: ExperimentConfig, B: int, num_shards: int
                   ) -> Tuple[Callable, Callable]:
    """(epsilon(iteration), beta(iteration)): exploration decay and the PER
    importance exponent annealing beta0 -> 1 over the configured run, both
    in per-shard iteration units."""
    epsilon = optax.linear_schedule(
        cfg.actor.epsilon_start, cfg.actor.epsilon_end,
        max(cfg.actor.epsilon_decay_steps // (B * num_shards), 1))
    total_iters = max(cfg.total_env_steps // (B * num_shards), 1)
    beta0 = cfg.replay.importance_exponent

    def beta_at(iteration: Array) -> Array:
        frac = jnp.minimum(iteration.astype(jnp.float32) / total_iters, 1.0)
        return beta0 + (1.0 - beta0) * frac

    return epsilon, beta_at


def pallas_routing(enabled: bool) -> Tuple[bool, bool]:
    """(use_pallas, pallas_interpret) for the priority-sampling kernel.

    Pallas kernels compile only on real TPU backends; anywhere else the
    config flag falls back to the equivalent XLA sampler — the Python-level
    interpreter inside a scanned hot loop would look like a hang at real
    buffer sizes. DIST_DQN_PALLAS_INTERPRET=1 opts back in for tiny-size
    integration tests of the kernel routing.
    """
    import os

    import jax

    on_tpu = jax.default_backend() == "tpu"
    interpret = (not on_tpu
                 and os.environ.get("DIST_DQN_PALLAS_INTERPRET") == "1")
    return enabled and (on_tpu or interpret), interpret


def make_rng_splitter(spmd: bool) -> Callable:
    """split(carry_rng, n) -> (new_carry_rng, [n] keys); in SPMD mode the
    carry rng is a [1] key array (per-device stream) and stays that shape."""

    def split(carry_rng: Array, n: int):
        base = carry_rng[0] if spmd else carry_rng
        keys = jax.random.split(base, n + 1)
        new = keys[:1] if spmd else keys[0]
        return new, keys[1:]

    return split


def reduce_chunk_metrics(carry, axis_name: Optional[str], B: int,
                         num_shards: int) -> Tuple[Dict, Dict]:
    """Reduce the chunk accumulators carried by either loop into the global
    metrics dict; returns (metrics, zeroed accumulator replacements).

    In SPMD mode episode stats are psum-ed (global counts), loss/train
    counters pmean-ed (identical across devices anyway), and the returned
    replacements keep every accumulator leaf replicated for the next chunk.
    """
    completed_return = carry.completed_return
    completed_count = carry.completed_count
    loss_sum = carry.loss_sum
    train_count = carry.train_count
    zero = jnp.float32(0.0)
    replace = {}
    if axis_name is not None:
        completed_return = jax.lax.psum(completed_return, axis_name)
        completed_count = jax.lax.psum(completed_count, axis_name)
        loss_sum = jax.lax.pmean(loss_sum, axis_name)
        train_count = jax.lax.pmean(train_count, axis_name)
        replace = dict(completed_return=zero, completed_count=zero,
                       loss_sum=zero, train_count=zero)
    metrics = {
        "env_frames": carry.iteration * B * num_shards,
        "episode_return":
            completed_return / jnp.maximum(completed_count, 1.0),
        "episodes": completed_count,
        "loss": loss_sum / jnp.maximum(train_count, 1.0),
        "grad_steps_in_chunk": train_count,
    }
    return metrics, replace


def episode_stats_update(carry, reward: Array, done: Array):
    """Fold one step's rewards/dones into the per-env episode trackers.

    Returns (ep_return, completed_return, completed_count) updates.
    """
    ep_return = carry.ep_return + reward
    completed_return = carry.completed_return + jnp.sum(
        jnp.where(done, ep_return, 0.0))
    completed_count = carry.completed_count + jnp.sum(
        done.astype(jnp.float32))
    ep_return = jnp.where(done, 0.0, ep_return)
    return ep_return, completed_return, completed_count
