"""Pallas TPU kernel: stratified inverse-CDF priority sampling.

The driver mandates on-device priority sampling via Pallas (BASELINE.json:5).
The XLA path (replay/prioritized_device.py) materializes a [T*B] cumsum in
HBM and runs ``searchsorted`` — a log-depth gather chain that is latency-
bound on TPU. This kernel keeps the whole priority plane resident in VMEM
and replaces cumsum+search with TPU-native compute:

  * all prefix sums are TRIANGULAR-MATRIX MATMULS on the MXU (Mosaic has no
    cumsum primitive): within-chunk row CDFs are ``rs @ L``, chunk offsets
    are an exclusive prefix over per-chunk masses, in-row lane CDFs are
    ``rows @ L_B``;
  * each sample's ring row comes from chunked compare-and-count — [S, C]
    VPU tiles against all S stratified targets at once, instead of S
    binary searches;
  * the selected rows are gathered with a one-hot [S, C] x [C, B] MXU
    matmul — no dynamic indexing, no scalar loops.

The only loops are ``fori_loop``s over row chunks, so occupancy does not
depend on S or the priority distribution. VMEM budget: the plane (4 bytes
per slot; a 1M-transition per-device shard is 4 MB) plus O(S*C + C*C)
scratch.

Validity masking and the alpha exponent are applied by the caller (cheap
elementwise XLA ops; this keeps ring-position arithmetic out of the
kernel); zero-mass rows (invalid/padded) are never selected.

Measured on a v5e chip (round 3, checked-in ``benchmarks/sampler_bench.py
--amortize 500`` — two-point marginal: sample+priority-write-back scans of
K and 2K draws are timed in one jit each and the per-draw cost is
``(t_2K - t_K)/K``, which subtracts the ~65-70ms axon-tunnel dispatch
constant exactly): **5.7x faster than the XLA cumsum+searchsorted path at
the ~1M-cell Ape-X shard (45us vs 260us per draw), 2.2x at 131k, 1.6x at
16k cells.** The kernel's per-draw cost is nearly flat in shard size
(VMEM-resident, chunked MXU phases) while XLA's HBM cumsum scales with
it — so the advantage grows with the shard. Raw log:
``docs/tpu_runs/20260731_0100/sampler_bench_marginal.jsonl``. (The
round-1 ad-hoc "~3x, 1.0ms vs 3.1ms" and an interim "~1.6x" figure are
both superseded by this reproducible number.) The kernel is also more
accurate than the XLA f32 path (94% exact vs a float64 reference —
tests/test_pallas_sampler.py). ``ReplayConfig.pallas_sampler`` stays
opt-in per config: at small shards both paths cost tens of
microseconds inside the fused step, so the simpler XLA path is fine
below ~10^5 cells.
"""
from __future__ import annotations

import functools
from typing import Tuple

import jax
import jax.numpy as jnp
from jax.experimental import pallas as pl
from jax.experimental.pallas import tpu as pltpu

Array = jnp.ndarray

_CHUNK = 512  # rows per chunk ([S, _CHUNK] compare tiles, [C, C] triangulars)


def _tri(n: int, strict: bool) -> Array:
    """[n, n] lower-triangular ones: L[i, j] = 1 if i < j (strict) or
    i <= j, so ``row_vector @ L`` is an exclusive/inclusive prefix sum
    along lanes."""
    i = jax.lax.broadcasted_iota(jnp.int32, (n, n), 0)
    j = jax.lax.broadcasted_iota(jnp.int32, (n, n), 1)
    return ((i < j) if strict else (i <= j)).astype(jnp.float32)


def _sample_kernel(w_ref, u_ref, t_out, b_out, p_out, tot_out, rs_ref, *,
                   num_chunks: int, real_T: int):
    T, B = w_ref.shape
    S = u_ref.shape[0]
    C = T // num_chunks
    ones_b = jnp.ones((1, B), jnp.float32)
    tri_inc_c = _tri(C, strict=False)

    # Phase 1: per-chunk row masses (ones @ w contraction), stashed in
    # scratch so the count pass never re-reads the [T, B] plane; total mass
    # accumulates alongside.
    def mass_body(c, tot):
        w_c = w_ref[pl.ds(c * C, C), :]                   # [C, B]
        rs = jax.lax.dot_general(
            ones_b, w_c, (((1,), (1,)), ((), ())),
            preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)          # [1, C]
        rs_ref[pl.ds(c, 1), :] = rs
        return tot + jnp.sum(rs, axis=1, keepdims=True)

    total = jax.lax.fori_loop(0, num_chunks, mass_body,
                              jnp.zeros((1, 1), jnp.float32))
    tot_out[:] = total
    # Margin keeps every target strictly inside the CDF even when the
    # chunked prefix sums land an ulp below `total` (different reduction
    # orders): without it the top stratum can walk past the last nonzero
    # row onto zero-mass padding, whose ~0 selection probability would blow
    # up the importance weight.
    targets = u_ref[:] * total * (1.0 - 1e-5)             # [S, 1]

    # Phase 2: per-sample row index = #(row_cdf < target) and the CDF mass
    # strictly before that row (masked max). The chunk CDF offset rides the
    # loop carry (chunks are visited in order), so no cross-chunk prefix
    # array is ever materialized.
    def count_body(c, carry):
        counts, prev, off = carry
        rs = rs_ref[pl.ds(c, 1), :]                       # [1, C]
        cdf_row = off + jnp.dot(rs, tri_inc_c,
                                preferred_element_type=jnp.float32,
                                precision=jax.lax.Precision.HIGHEST)
        less = (cdf_row < targets).astype(jnp.float32)    # [S, C]
        counts = counts + jnp.sum(less, axis=1, keepdims=True)
        prev = jnp.maximum(prev, jnp.max(cdf_row * less, axis=1,
                                         keepdims=True))
        off = off + jnp.sum(rs, axis=1, keepdims=True)
        return counts, prev, off

    counts0 = jnp.zeros((S, 1), jnp.float32)
    counts, prev_cdf, _ = jax.lax.fori_loop(
        0, num_chunks, count_body,
        (counts0, counts0, jnp.zeros((1, 1), jnp.float32)))
    # Clamp into the REAL (unpadded) rows: padded rows carry zero mass.
    t_idx = jnp.minimum(counts, float(real_T - 1)).astype(jnp.int32)
    t_out[:] = t_idx

    # Phase 3: gather the S selected rows with a one-hot MXU matmul.
    def gather_body(c, rows):
        iota = jax.lax.broadcasted_iota(jnp.int32, (S, C), 1) + c * C
        onehot = (iota == t_idx).astype(jnp.float32)      # [S, C]
        w_c = w_ref[pl.ds(c * C, C), :]                   # [C, B]
        return rows + jnp.dot(onehot, w_c,
                              preferred_element_type=jnp.float32,
            precision=jax.lax.Precision.HIGHEST)

    rows = jax.lax.fori_loop(0, num_chunks, gather_body,
                             jnp.zeros((S, B), jnp.float32))

    # In-row lane pick: lane CDF via triangular matmul, compare-and-count.
    # The residual is clamped strictly inside the row's own mass so the
    # count always stops at a nonzero lane (the plateau-start argument:
    # the first lane whose cumulative mass reaches the residual must have
    # added mass), immune to cross-phase fp reduction-order differences.
    row_cum = jnp.dot(rows, _tri(B, strict=False),
                      preferred_element_type=jnp.float32,
                      precision=jax.lax.Precision.HIGHEST)  # [S, B]
    row_total = row_cum[:, B - 1:B]                       # [S, 1]
    residual = jnp.minimum(targets - prev_cdf,
                           row_total * (1.0 - 1e-6))      # [S, 1]
    b_counts = jnp.sum((row_cum < residual).astype(jnp.int32), axis=1,
                       keepdims=True)
    b_idx = jnp.minimum(b_counts, B - 1)                  # [S, 1]
    b_out[:] = b_idx
    b_iota = jax.lax.broadcasted_iota(jnp.int32, (S, B), 1)
    p_out[:] = jnp.sum(jnp.where(b_iota == b_idx, rows, 0.0), axis=1,
                       keepdims=True)


def stratified_sample(w: Array, rng: Array, batch_size: int,
                      use_pallas: bool = False, interpret: bool = False
                      ) -> Tuple[Array, Array, Array, Array]:
    """Stratified inverse-CDF draw from a [T, B] mass plane — the ONE
    implementation both replay samplers (transition and sequence) share.

    Returns (t_idx [S], b_idx [S], mass_sel [S], total []). Routing:
    ``use_pallas`` runs the VMEM kernel below; otherwise the portable XLA
    cumsum+searchsorted path.
    """
    u01 = (jnp.arange(batch_size, dtype=jnp.float32)
           + jax.random.uniform(rng, (batch_size,))) / batch_size
    return stratified_sample_at(w, u01, use_pallas=use_pallas,
                                interpret=interpret)


def stratified_sample_at(w: Array, u: Array, use_pallas: bool = False,
                         interpret: bool = False
                         ) -> Tuple[Array, Array, Array, Array]:
    """Inverse-CDF draw from a [T, B] mass plane at EXPLICIT uniforms
    ``u`` [S] in [0, 1) — the per-shard leg of a cross-shard stratified
    draw (replay/sharded.py): the coordinator lays ONE global ladder
    over the concatenated per-shard totals and hands each shard its
    local positions as fractions of its own mass, so draws land here in
    proportion to this plane's mass with exactly the single-plane P(i).
    Same (t_idx, b_idx, mass_sel, total) contract and Pallas/XLA
    routing as :func:`stratified_sample`.
    """
    if use_pallas:
        return pallas_stratified_sample(w, u, interpret=interpret)
    num_envs = w.shape[1]
    flat = w.reshape(-1)
    cdf = jnp.cumsum(flat)
    total = cdf[-1]
    idx = jnp.clip(jnp.searchsorted(cdf, u * total), 0, flat.shape[0] - 1)
    t_idx = (idx // num_envs).astype(jnp.int32)
    b_idx = (idx % num_envs).astype(jnp.int32)
    return t_idx, b_idx, flat[idx], total


SAMPLE_BLOCK = 32  # lanes per second-level block of the hierarchical draw


def stratified_sample_rows(w: Array, blk_sums: Array, u: Array
                           ) -> Tuple[Array, Array, Array, Array]:
    """Three-level XLA inverse-CDF draw at explicit uniforms ``u`` [S]:
    row pick by searchsorted over the [T] row-sum CDF (row sums reduced
    from ``blk_sums`` — a [T, NB] pass, not a plane pass), then block
    pick over the selected rows' [NB] block sums, then lane pick inside
    one ``SAMPLE_BLOCK``-wide sub-block — O(T + S*(NB + BLOCK)) work
    and O(S*(NB + BLOCK)) memory traffic against the flat path's O(T*B)
    cumsum, which is what lets the device priority planes beat the host
    sum-tree on aggregate draws/sec even on CPU
    (benchmarks/sampler_bench.py ``sharded`` arm).

    ``blk_sums`` [T, B // SAMPLE_BLOCK] must track the per-block
    partial sums of ``w``; the device sampler maintains it
    incrementally inside its write-back scatter (touched blocks only),
    so no draw ever re-reduces the plane. Each level's residual is
    clamped strictly inside the level's own mass (the kernel's
    plateau-start argument) — the levels reduce in different fp orders,
    so without the clamps a top-of-row target could walk one cell past
    the last written one. Same (t_idx, b_idx, mass_sel, total) contract
    as :func:`stratified_sample_at`.
    """
    T, B = w.shape
    NB = blk_sums.shape[1]
    BS = B // NB
    row_sums = blk_sums.sum(axis=1)
    cdf = jnp.cumsum(row_sums)
    total = cdf[-1]
    pos = u.astype(jnp.float32) * total
    t_idx = jnp.clip(jnp.searchsorted(cdf, pos), 0, T - 1)
    blk = blk_sums[t_idx]                                 # [S, NB]
    blk_cdf = jnp.cumsum(blk, axis=1)
    res = jnp.minimum(pos - (cdf[t_idx] - row_sums[t_idx]),
                      blk_cdf[:, -1] * (1.0 - 1e-6))[:, None]
    jb = jnp.minimum(
        jnp.sum((blk_cdf < res).astype(jnp.int32), axis=1, keepdims=True),
        NB - 1)                                           # [S, 1]
    res2 = res - (jnp.take_along_axis(blk_cdf, jb, axis=1)
                  - jnp.take_along_axis(blk, jb, axis=1))
    sub = w.reshape(T, NB, BS)[t_idx, jb[:, 0]]           # [S, BS]
    sub_cdf = jnp.cumsum(sub, axis=1)
    res2 = jnp.minimum(res2, sub_cdf[:, -1:] * (1.0 - 1e-6))
    b2 = jnp.minimum(
        jnp.sum((sub_cdf < res2).astype(jnp.int32), axis=1, keepdims=True),
        BS - 1)                                           # [S, 1]
    mass = jnp.take_along_axis(sub, b2, axis=1)[:, 0]
    b_idx = jb[:, 0] * BS + b2[:, 0]
    return t_idx.astype(jnp.int32), b_idx.astype(jnp.int32), mass, total


def importance_weights(mass_sel: Array, total: Array, n_valid: Array,
                       beta: Array) -> Array:
    """(N * P(i))^-beta, batch-max normalized; zero-mass selections
    (possible only through fp boundary pathology) get weight 0 instead of
    an enormous one that would crush the batch."""
    p_sel = jnp.maximum(mass_sel, 1e-12) / jnp.maximum(total, 1e-12)
    weights = (jnp.maximum(n_valid, 1.0) * p_sel) ** (-beta)
    weights = jnp.where(mass_sel > 0.0, weights, 0.0)
    return weights / jnp.maximum(jnp.max(weights), 1e-12)


@functools.partial(jax.jit, static_argnames=("interpret",))
def pallas_stratified_sample(w: Array, u: Array, interpret: bool = False
                             ) -> Tuple[Array, Array, Array, Array]:
    """Draw samples ~ w (a [T, B] non-negative mass plane) at stratified
    uniforms ``u`` [S] in [0, 1).

    Returns (t_idx [S], b_idx [S], p_sel [S], total []): ring rows, env
    lanes, the selected masses (for importance weights) and the total mass.
    ``interpret=True`` runs the kernel in the Pallas interpreter (CPU tests).
    """
    T, B = w.shape
    S = u.shape[0]
    # Pad rows to a chunk multiple; zero-mass padding is never selected.
    T_pad = ((T + _CHUNK - 1) // _CHUNK) * _CHUNK
    if T_pad != T:
        w = jnp.pad(w, ((0, T_pad - T), (0, 0)))
    num_chunks = T_pad // _CHUNK

    t_idx, b_idx, p_sel, total = pl.pallas_call(
        functools.partial(_sample_kernel, num_chunks=num_chunks, real_T=T),
        out_shape=(
            jax.ShapeDtypeStruct((S, 1), jnp.int32),
            jax.ShapeDtypeStruct((S, 1), jnp.int32),
            jax.ShapeDtypeStruct((S, 1), jnp.float32),
            jax.ShapeDtypeStruct((1, 1), jnp.float32),
        ),
        in_specs=[
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ],
        out_specs=(
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
            pl.BlockSpec(memory_space=pltpu.VMEM),
        ),
        scratch_shapes=[
            pltpu.VMEM((num_chunks, _CHUNK), jnp.float32),  # per-chunk rs
        ],
        interpret=interpret,
    )(w, u.reshape((S, 1)))
    return t_idx[:, 0], b_idx[:, 0], p_sel[:, 0], total[0, 0]
