from dist_dqn_tpu.ops import losses as losses  # noqa: F401
