"""TD-loss family: n-step, double-Q, distributional (C51), R2D2 rescaling.

All functions are pure and shape-polymorphic over leading batch dims, so the
same code runs under ``jit``, ``vmap``, ``scan`` and ``shard_map``. The driver
spec requires forward + TD-loss + backward to compile into a single XLA jit
(BASELINE.json:5) — these ops are the loss half of that program.
"""
from __future__ import annotations

import jax
import jax.numpy as jnp

Array = jnp.ndarray


def huber(x: Array, delta: float = 1.0) -> Array:
    """Huber loss elementwise; quadratic within ``delta``, linear outside.

    Computed in float32 regardless of the input dtype: the per-example
    values double as PER priorities and IS-weighted loss terms, and the
    ISSUE 6 actor/learner dtype split makes bf16-valued TD inputs a
    config choice rather than an impossibility — a bf16 priority plane
    would quantize the sum-tree mass. Today's heads already emit f32
    (models/qnets.py casts at the head), so the upcast is an identity
    there — bit-identical, no new program for existing configs.
    """
    abs_x = jnp.abs(x.astype(jnp.float32))
    quad = jnp.minimum(abs_x, delta)
    return 0.5 * quad * quad + delta * (abs_x - quad)


def n_step_from_rollout(rewards: Array, discounts: Array, n: int):
    """Fold a rollout into n-step returns and compound discounts.

    Args:
      rewards:   [..., T] per-step rewards r_t.
      discounts: [..., T] per-step discounts (gamma * (1 - terminated_t)).
      n: static n-step horizon (loop is unrolled at trace time).

    Returns:
      (returns, discounts): each [..., T - n + 1] where
        returns[t]   = sum_{k<n} (prod_{j<k} discounts[t+j]) * rewards[t+k]
        discounts[t] = prod_{k<n} discounts[t+k]
      so target_t = returns[t] + discounts[t] * bootstrap(obs[t+n]).
    """
    T = rewards.shape[-1]
    if n < 1 or n > T:
        raise ValueError(f"n_step={n} out of range for rollout length {T}")
    out = T - n + 1
    acc_r = jnp.zeros_like(rewards[..., :out])
    acc_d = jnp.ones_like(acc_r)
    for k in range(n):
        acc_r = acc_r + acc_d * rewards[..., k:k + out]
        acc_d = acc_d * discounts[..., k:k + out]
    return acc_r, acc_d


def double_q_bootstrap(q_next_online: Array, q_next_target: Array) -> Array:
    """Double-DQN bootstrap: argmax from online net, value from target net."""
    a_star = jnp.argmax(q_next_online, axis=-1)
    return jnp.take_along_axis(
        q_next_target, a_star[..., None], axis=-1)[..., 0]


def q_learning_error(
    q: Array,
    actions: Array,
    rewards: Array,
    discounts: Array,
    bootstrap_q: Array,
) -> Array:
    """TD error q(s,a) - (r + discount * bootstrap). Gradient flows into q only."""
    qa = jnp.take_along_axis(q, actions[..., None].astype(jnp.int32),
                             axis=-1)[..., 0]
    target = rewards + discounts * bootstrap_q
    return qa - jax.lax.stop_gradient(target)


# ---------------------------------------------------------------------------
# Munchausen-DQN (Vieillard et al., 2020): entropy-regularized soft
# bootstrap + clipped scaled log-policy reward bonus, on the scalar head.
# ---------------------------------------------------------------------------

def munchausen_soft_bootstrap(q_next_target: Array, tau: float) -> Array:
    """Soft state value from the target net: sum_a' pi(a'|s')(q(s',a') -
    tau log pi(a'|s')) with pi = softmax(q/tau).

    Computed in the numerically stable log-sum-exp form
    tau * logsumexp(q/tau) (the two are algebraically identical).
    Args: q_next_target [B, A]. Returns [B].
    """
    return tau * jax.scipy.special.logsumexp(q_next_target / tau, axis=-1)


def munchausen_bonus(q_obs_target: Array, actions: Array, alpha: float,
                     tau: float, clip_low: float) -> Array:
    """The Munchausen reward bonus alpha * clip(tau * log pi(a|s), l0, 0).

    pi = softmax(q/tau) from the TARGET net at the stored observation;
    the log-policy of the action the actor actually took is scaled and
    clipped below at ``clip_low`` (paper l0 = -1) to bound the penalty
    for very off-policy actions.
    Args: q_obs_target [B, A]; actions [B]. Returns [B].
    """
    log_pi = jax.nn.log_softmax(q_obs_target / tau, axis=-1)
    log_pi_a = jnp.take_along_axis(
        log_pi, actions[..., None].astype(jnp.int32), axis=-1)[..., 0]
    return alpha * jnp.clip(tau * log_pi_a, clip_low, 0.0)


# ---------------------------------------------------------------------------
# R2D2 value rescaling (BASELINE.json:10): h(x) = sign(x)(sqrt(|x|+1)-1)+eps*x
# ---------------------------------------------------------------------------

def value_rescale(x: Array, eps: float = 1e-3) -> Array:
    return jnp.sign(x) * (jnp.sqrt(jnp.abs(x) + 1.0) - 1.0) + eps * x


def inv_value_rescale(x: Array, eps: float = 1e-3) -> Array:
    """Exact inverse of ``value_rescale`` (closed form)."""
    inner = jnp.sqrt(1.0 + 4.0 * eps * (jnp.abs(x) + 1.0 + eps))
    return jnp.sign(x) * (jnp.square((inner - 1.0) / (2.0 * eps)) - 1.0)


# ---------------------------------------------------------------------------
# C51 / categorical distributional RL (BASELINE.json:11)
# ---------------------------------------------------------------------------

def categorical_projection(
    atoms: Array,
    next_probs: Array,
    rewards: Array,
    discounts: Array,
) -> Array:
    """Project the shifted/shrunk target distribution back onto ``atoms``.

    The Bellman update maps atom z_j to Tz_j = r + discount * z_j; the mass of
    each Tz_j is split linearly between its two neighbouring atoms.

    Args:
      atoms:      [M] support (uniformly spaced v_min..v_max).
      next_probs: [B, M] target-net distribution at the chosen next action.
      rewards:    [B] n-step returns.
      discounts:  [B] compound discounts (0 at terminal).

    Returns:
      [B, M] projected target distribution (rows sum to 1).
    """
    v_min, v_max = atoms[0], atoms[-1]
    m = atoms.shape[0]
    dz = (v_max - v_min) / (m - 1)

    tz = rewards[:, None] + discounts[:, None] * atoms[None, :]   # [B, M]
    tz = jnp.clip(tz, v_min, v_max)
    b = (tz - v_min) / dz                                         # in [0, M-1]
    # Scatter-free TPU formulation: the linear mass split IS a triangular
    # interpolation kernel — source atom i at fractional position b_i
    # contributes relu(1 - |b_i - j|) of its mass to output atom j (1 at
    # an exact landing, (1-frac)/frac to the floor/ceil neighbours). One
    # dense [B, M, M] elementwise weight + reduce replaces the two
    # .at[].add scatters, which XLA lowers poorly on TPU; at C51 sizes
    # the cube is tiny (B x 51 x 51). Elementwise multiply+sum rather
    # than einsum: a default-precision matmul would run the contraction
    # through the MXU with bf16-truncated inputs, breaking the rows-sum-
    # to-1 contract at ~1e-2; the VPU reduce stays full f32.
    j = jnp.arange(m, dtype=b.dtype)
    w = jnp.maximum(0.0, 1.0 - jnp.abs(b[:, :, None] - j[None, None, :]))
    return jnp.sum(next_probs[:, :, None] * w, axis=1)


def categorical_double_q_probs(
    logits_next_online: Array,
    logits_next_target: Array,
    atoms: Array,
) -> Array:
    """Pick next-greedy action by online expected value; return target probs.

    Args: logits [B, A, M]; atoms [M]. Returns probs [B, M].
    """
    probs_online = jax.nn.softmax(logits_next_online, axis=-1)
    q_online = jnp.sum(probs_online * atoms, axis=-1)             # [B, A]
    a_star = jnp.argmax(q_online, axis=-1)                        # [B]
    logits_t = jnp.take_along_axis(
        logits_next_target, a_star[:, None, None], axis=1)[:, 0]  # [B, M]
    return jax.nn.softmax(logits_t, axis=-1)


# ---------------------------------------------------------------------------
# QR-DQN / quantile-regression distributional RL (Dabney et al., 2018) —
# the second distributional family next to C51: the head predicts N
# quantile VALUES of the return distribution (no fixed support, no v_min/
# v_max), trained with the asymmetric quantile-Huber regression below.
# ---------------------------------------------------------------------------

def quantile_midpoints(num_quantiles: int, dtype=jnp.float32) -> Array:
    """tau-hat_i = (2i + 1) / 2N — the quantile targets of each output."""
    return (jnp.arange(num_quantiles, dtype=dtype) + 0.5) / num_quantiles


def quantile_double_q_select(theta_next_selector: Array,
                             theta_next_target: Array) -> Array:
    """Greedy action by the selector net's MEAN over quantiles; returns the
    target net's quantile values at that action.

    Args: theta [B, A, N]. Returns [B, N].
    """
    q_sel = jnp.mean(theta_next_selector, axis=-1)          # [B, A]
    a_star = jnp.argmax(q_sel, axis=-1)                     # [B]
    return jnp.take_along_axis(
        theta_next_target, a_star[:, None, None], axis=1)[:, 0]


def quantile_huber_td(theta_a: Array, target_theta: Array,
                      kappa: float = 1.0) -> Array:
    """Per-example quantile-Huber regression loss.

    Args:
      theta_a:      [B, N] predicted quantiles at the taken action.
      target_theta: [B, M] Bellman-target quantile samples; stop-gradded
                    HERE — no gradient ever flows into the target.
      kappa: Huber threshold.

    Returns: [B] losses — sum over predicted quantiles i of the mean over
    target samples j of |tau_i - 1{u_ij < 0}| * Huber_kappa(u_ij) / kappa,
    the Dabney et al. (2018) estimator. This is the fixed-midpoint
    special case of ``iqn_quantile_huber_td``.
    """
    n = theta_a.shape[-1]
    taus = jnp.broadcast_to(quantile_midpoints(n, theta_a.dtype)[None, :],
                            theta_a.shape)
    return iqn_quantile_huber_td(theta_a, taus, target_theta, kappa)


def iqn_quantile_huber_td(theta_a: Array, taus: Array, target_theta: Array,
                          kappa: float = 1.0) -> Array:
    """Per-example quantile-Huber loss at SAMPLED quantile fractions (IQN).

    Generalizes ``quantile_huber_td`` from the fixed QR-DQN midpoints to
    per-example sampled taus (Dabney et al., 2018b "Implicit Quantile
    Networks"): each predicted quantile value theta_a[b, i] is trained
    toward the taus[b, i] fraction of the target sample distribution.

    Args:
      theta_a:      [B, N] predicted quantile values at the taken action.
      taus:         [B, N] the quantile fractions those predictions were
                    conditioned on (in (0, 1)).
      target_theta: [B, M] Bellman-target quantile samples; stop-gradded
                    here — no gradient ever flows into the target.
      kappa: Huber threshold.

    Returns: [B] losses — sum over predicted quantiles i of the mean over
    target samples j of |tau_i - 1{u_ij < 0}| * Huber_kappa(u_ij) / kappa.
    Reduces exactly to ``quantile_huber_td`` when taus are the fixed
    midpoints (pinned by tests/test_iqn.py).
    """
    u = (jax.lax.stop_gradient(target_theta)[:, None, :]
         - theta_a[:, :, None])                              # [B, N, M]
    tau = jax.lax.stop_gradient(taus)[:, :, None]            # [B, N, 1]
    weight = jnp.abs(tau - (u < 0.0).astype(theta_a.dtype))
    return jnp.sum(jnp.mean(weight * huber(u, kappa) / kappa, axis=2),
                   axis=1)


def categorical_td_loss(
    logits: Array,
    actions: Array,
    target_probs: Array,
) -> Array:
    """Per-example cross-entropy between projected target and predicted dist.

    Args: logits [B, A, M]; actions [B]; target_probs [B, M] (stop-gradded).
    Returns: [B] losses. The per-example loss also serves as the Ape-X/Rainbow
    priority signal.
    """
    logits_a = jnp.take_along_axis(
        logits, actions[:, None, None].astype(jnp.int32), axis=1)[:, 0]
    log_p = jax.nn.log_softmax(logits_a, axis=-1)
    return -jnp.sum(jax.lax.stop_gradient(target_probs) * log_p, axis=-1)
