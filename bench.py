"""Headline benchmark: env-steps/sec/chip on the Atari-shaped pipeline.

Runs the fused on-device training loop (act -> PixelPong step -> replay ->
prioritized-style learner update cadence) on whatever single accelerator is
present and reports the driver's north-star metric (BASELINE.json:2,5):
env-steps/sec/chip against the 50k/sec/chip Ape-X target.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

BASELINE_ENV_STEPS_PER_SEC_PER_CHIP = 50_000.0  # BASELINE.json:5 target


def main():
    import jax

    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.train_loop import make_fused_train

    # BENCH_SMOKE=1 shrinks every dimension so the identical code path can be
    # smoke-tested on a CPU dev box; default sizes target a real TPU chip.
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    num_envs = 8 if smoke else 128
    chunk = 20 if smoke else 200
    measure_s = 2.0 if smoke else 15.0

    cfg = CONFIGS["atari"]
    # Bench sizing: enough parallel envs to saturate the chip's batch dims,
    # a replay ring bounded to fit HBM.
    cfg = dataclasses.replace(
        cfg,
        actor=dataclasses.replace(cfg.actor, num_envs=num_envs),
        replay=dataclasses.replace(cfg.replay,
                                   capacity=2_048 if smoke else 65_536,
                                   min_fill=128 if smoke else 4_096),
        learner=dataclasses.replace(cfg.learner,
                                    batch_size=32 if smoke else 256),
    )
    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    init, run_chunk = make_fused_train(cfg, env, net)
    run = jax.jit(run_chunk, static_argnums=1, donate_argnums=0)

    carry = init(jax.random.PRNGKey(0))
    carry, _ = run(carry, chunk)  # compile + warmup
    jax.block_until_ready(carry.learner.params)

    t0 = time.perf_counter()
    iters = 0
    while time.perf_counter() - t0 < measure_s:
        carry, metrics = run(carry, chunk)
        jax.block_until_ready(carry.learner.params)
        iters += chunk
    dt = time.perf_counter() - t0

    value = iters * num_envs / dt
    print(json.dumps({
        "metric": "env_steps_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "env-steps/sec/chip (synthetic 84x84 Atari-shaped pixel env,"
                " Nature CNN, fused on-device actor+learner)",
        "vs_baseline": round(value / BASELINE_ENV_STEPS_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
