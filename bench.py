"""Headline benchmark: env-steps/sec/chip on the Atari-shaped pipeline.

Runs the fused on-device training loop (act -> PixelPong step -> replay ->
learner update cadence) on whatever single accelerator is present and
reports the driver's north-star metric (BASELINE.json:2,5):
env-steps/sec/chip against the 50k/sec/chip Ape-X target.

Timing is fenced with ``device_get`` on a chunk metric: on the remote-
tunnel (axon) platform ``block_until_ready`` returns before execution
finishes, so only a host-materialized value proves the chunk ran.

Prints exactly ONE JSON line:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
import time

BASELINE_ENV_STEPS_PER_SEC_PER_CHIP = 50_000.0  # BASELINE.json:5 target


def main():
    import jax

    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.train_loop import make_fused_train

    # BENCH_SMOKE=1 shrinks every dimension so the identical code path can be
    # smoke-tested on a CPU dev box; default sizes target a real TPU chip
    # (512 env lanes saturate the v5e MXU on the Nature-CNN batch, measured
    # ~487k env-steps/sec/chip).
    smoke = os.environ.get("BENCH_SMOKE") == "1"
    num_envs = 8 if smoke else 512
    chunk = 20 if smoke else 200
    # ~25 chunks x 200 iters x 512 envs ~= 2.5M env steps: several seconds
    # of measured work, long enough to average out dispatch/clock jitter.
    measure_chunks = 2 if smoke else 25

    cfg = CONFIGS["atari"]
    cfg = dataclasses.replace(
        cfg,
        actor=dataclasses.replace(cfg.actor, num_envs=num_envs),
        # 65536 pixel slots ~= 1.8 GB of HBM for the obs ring: big enough to
        # exercise real sampling, small enough to leave the chip headroom
        # (a 131k ring was measurably slower on a 16 GB v5e).
        replay=dataclasses.replace(cfg.replay,
                                   capacity=2_048 if smoke else 65_536,
                                   min_fill=128 if smoke else 4_096),
        learner=dataclasses.replace(cfg.learner,
                                    batch_size=32 if smoke else 256),
    )
    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    init, run_chunk = make_fused_train(cfg, env, net)
    run = jax.jit(run_chunk, static_argnums=1, donate_argnums=0)

    def fence(metrics) -> float:
        return float(jax.device_get(metrics["loss"]))

    carry = init(jax.random.PRNGKey(0))
    for _ in range(2):  # compile + fill past min_fill into steady state
        carry, metrics = run(carry, chunk)
        fence(metrics)

    t0 = time.perf_counter()
    for _ in range(measure_chunks):
        carry, metrics = run(carry, chunk)
    fence(metrics)
    dt = time.perf_counter() - t0

    value = measure_chunks * chunk * num_envs / dt
    print(json.dumps({
        "metric": "env_steps_per_sec_per_chip",
        "value": round(value, 1),
        "unit": "env-steps/sec/chip (synthetic 84x84 Atari-shaped pixel env,"
                " Nature CNN, fused on-device actor+learner)",
        "vs_baseline": round(value / BASELINE_ENV_STEPS_PER_SEC_PER_CHIP, 3),
    }))


if __name__ == "__main__":
    sys.exit(main())
