"""Headline benchmark: env-steps/sec/chip on the Atari-shaped pipeline.

Runs the fused on-device training loop (act -> PixelPong step -> replay ->
learner update cadence) on whatever single accelerator is present and
reports the driver's north-star metric (BASELINE.json:2,5):
env-steps/sec/chip against the 50k/sec/chip Ape-X target, plus ``mfu`` —
the conventional definition: learner fwd+bwd+optimizer FLOPs over chip
bf16 peak, censused on a standalone compile of the train step (the same
program benchmarks/learner_bench.py times). The census deliberately does
NOT come from the fused chunk: XLA's cost analysis counts a ``lax.scan``
body ONCE regardless of trip count (verified on this box — identical
census for 5/20/40-iteration chunks), so a whole-chunk number would
undercount by ~the chunk length; the standalone train step has no scan.

Timing is fenced with ``device_get`` on a chunk metric: on the remote-
tunnel (axon) platform ``block_until_ready`` returns before execution
finishes, so only a host-materialized value proves the chunk ran.

Capture-proofing (VERDICT round 1, weak #2): this box's TPU tunnel can
wedge such that ANY backend touch hangs forever, and round 1's driver
capture died as a raw traceback. Every failure path here — backend-init
hang, mid-run hang, any exception — emits exactly ONE structured JSON
line (with an "error" field) before exiting nonzero, so a driver capture
is always parseable:
  {"metric": ..., "value": N, "unit": ..., "vs_baseline": N}
"""
from __future__ import annotations

import dataclasses
import json
import os
import sys
import threading
import time

BASELINE_ENV_STEPS_PER_SEC_PER_CHIP = 50_000.0  # BASELINE.json:5 target
METRIC = "env_steps_per_sec_per_chip"
UNIT = ("env-steps/sec/chip (synthetic 84x84 Atari-shaped pixel env,"
        " Nature CNN, fused on-device actor+learner)")

class ContractEmitter:
    """The emit-once BENCH contract: every exit path of a benchmark —
    success, backend hang, any exception — produces exactly ONE
    structured JSON line (first caller wins), so a driver capture is
    always parseable. Extracted from this file's capture-proofing
    (VERDICT round 1) for the satellite benchmarks that share the
    contract (benchmarks/serving_bench.py)."""

    def __init__(self, metric: str, unit: str):
        self.metric, self.unit = metric, unit
        self._lock = threading.Lock()
        self._emitted = False

    def emit_payload(self, payload: dict) -> None:
        with self._lock:
            if self._emitted:
                return
            self._emitted = True
            print(json.dumps(payload), flush=True)

    def error(self, stage: str, err: str) -> None:
        self.emit_payload({"metric": self.metric, "value": None,
                           "unit": self.unit, "vs_baseline": None,
                           "error": f"{stage}: {err}"})


_contract = ContractEmitter(METRIC, UNIT)


def _emit(payload: dict) -> None:
    """Print the single contract JSON line (first caller wins)."""
    _contract.emit_payload(payload)


def _emit_error(stage: str, err: str) -> None:
    _contract.error(stage, err)


def _env_float(name: str, default: float) -> float:
    """Parse a float env override; a malformed value must not be able to
    break the one-JSON-line contract, so it falls back to the default."""
    try:
        return float(os.environ.get(name, default))
    except ValueError:
        return default


def _env_int(name: str, default: int) -> int:
    """Int env override with the same never-break-the-contract fallback.
    Used by benchmarks/bench_sweep.py to explore lane/batch/ring variants
    without forking this file; defaults are the tuned headline config."""
    try:
        return int(os.environ.get(name, default))
    except ValueError:
        return default


def _watchdog(stage: str, seconds: float) -> threading.Timer:
    """Arm a timer that emits an error line and exits; caller cancels.

    The exit path (incident #3, VERDICT round 3): a raw ``os._exit``
    here is exactly as mid-device-op as a SIGKILL — it fires precisely
    when a device op is stuck, and on this box's axon tunnel that
    orphans the pool-side grant and wedges the tunnel for hours. So the
    watchdog now (1) emits the JSON contract line, (2) attempts a
    BOUNDED device release (``utils/device_cleanup.release`` on a
    daemon thread — on a truly wedged tunnel the release itself hangs,
    so it gets ``BENCH_CLEANUP_TIMEOUT_S`` seconds, default 60, not
    forever), then (3) hard-exits. A live-but-slow run gets its grant
    released; a genuinely wedged one is no worse off than before. The
    real protection remains the pre-flight sizing gate in ``main`` —
    never starting a run that could hit this timer.
    """

    def fire():
        _emit_error(stage, f"no progress within {seconds:.0f}s "
                           "(wedged TPU tunnel?)")
        sys.stdout.flush()
        try:
            from dist_dqn_tpu.utils.device_cleanup import release

            done = threading.Event()

            def _clean():
                release()
                done.set()

            cleaner = threading.Thread(target=_clean, daemon=True)
            cleaner.start()
            done.wait(_env_float("BENCH_CLEANUP_TIMEOUT_S", 60.0))
        except Exception:  # noqa: BLE001 — exit anyway
            pass
        sys.stdout.flush()
        os._exit(3)

    t = threading.Timer(seconds, fire)
    t.daemon = True
    t.start()
    return t


def _sizes(smoke: bool) -> dict:
    """The run-shaping knobs, readable before any device work (env
    overrides are how benchmarks/bench_sweep.py explores variants).
    train_every defaults to the atari preset's value so the benchmark
    cannot silently diverge from the config it claims to measure."""
    from dist_dqn_tpu.config import CONFIGS

    # Frame-dedup storage is the round-5 default (BENCH_FRAME_DEDUP=0
    # opts back to full-stack storage): single stored frames +
    # sample-time stack rebuild measured FASTER than stacked at matched
    # rings on v5e (637.0k vs 619.1k at 16k; 632.4k at 65k vs 572.5k
    # stacked) because the 4x smaller ring keeps gathers/inserts hot.
    # The default ring is sized per mode to the same HBM bytes: 65k
    # deduped == 16k stacked (~0.5 GB) — so the default headline also
    # carries a 4x bigger replay window than round 4's.
    frame_dedup = os.environ.get("BENCH_FRAME_DEDUP", "1") == "1"
    default_ring = 65_536 if frame_dedup else 16_384
    return {
        "num_envs": _env_int("BENCH_NUM_ENVS", 8 if smoke else 1024),
        "chunk": _env_int("BENCH_CHUNK", 20 if smoke else 200),
        "measure_chunks": _env_int("BENCH_MEASURE_CHUNKS", 2 if smoke else 25),
        "ring": _env_int("BENCH_RING", 2_048 if smoke else default_ring),
        "batch": _env_int("BENCH_BATCH", 32 if smoke else 512),
        "train_every": _env_int("BENCH_TRAIN_EVERY",
                                CONFIGS["atari"].train_every),
        # BENCH_PRIORITIZED=1 swaps the uniform ring for device PER
        # (ReplayConfig default alpha 0.6 / beta 0.4) — the Ape-X-shaped
        # fused program, measured beside the default Nature-DQN one.
        # Sampler routing follows production: XLA stratified-CDF by
        # default (the small-ring regime), the Pallas kernel with
        # BENCH_PALLAS_SAMPLER=1 (what the apex preset's 1M shard uses).
        "prioritized": os.environ.get("BENCH_PRIORITIZED") == "1",
        "pallas_sampler": os.environ.get("BENCH_PALLAS_SAMPLER") == "1",
        "frame_dedup": frame_dedup,
        # Learner-utilization knobs (ISSUE 6): grad sub-steps per train
        # event (scanned on device), pow2-bucketed train-batch widening
        # (0 = batch as-is), and the actor-inference dtype split. The
        # defaults reproduce the pre-knob program exactly; the BENCH
        # JSON always records all three next to mfu so the trajectory
        # knows WHICH configuration produced each number.
        "replay_ratio": _env_int("BENCH_REPLAY_RATIO", 1),
        "train_batch": _env_int("BENCH_TRAIN_BATCH", 0),
        "actor_dtype": os.environ.get("BENCH_ACTOR_DTYPE", "float32"),
    }


def main() -> int:
    smoke = os.environ.get("BENCH_SMOKE") == "1"

    guard = _watchdog("backend-init", _env_float("BENCH_BACKEND_TIMEOUT_S",
                                                 180.0))
    try:
        import jax

        if smoke:
            # The identical code path must smoke-test on any dev box without
            # touching (and possibly wedging on) the tunnel platform.
            jax.config.update("jax_platforms", "cpu")
        device = jax.devices()[0]
    except Exception as e:  # noqa: BLE001 — contract: never a raw traceback
        _emit_error("backend-init", repr(e))
        return 2
    finally:
        guard.cancel()

    total_budget = _env_float("BENCH_TOTAL_TIMEOUT_S", 900.0)
    if device.platform != "cpu":
        # Pre-flight sizing gate (VERDICT round-3 ask #1b): refuse any
        # config not predicted to finish comfortably inside the watchdog
        # budget, BEFORE touching the device — a run that hits the
        # watchdog dies mid-device-op and wedges the tunnel (incident
        # #3). CPU smoke runs are exempt (no tunnel to wedge).
        from dist_dqn_tpu.config import CONFIGS
        from dist_dqn_tpu.envs import make_jax_env
        from dist_dqn_tpu.utils.sizing import gate_fused

        s = _sizes(smoke)
        # Stack depth from the env's own declaration (train.py does the
        # same) so the gate's dedup divisor cannot drift from reality.
        bench_env = make_jax_env(CONFIGS["atari"].env_name)
        dedup_stack = (getattr(bench_env, "frame_stack", 0)
                       if s["frame_dedup"] else 0)
        verdict = gate_fused(
            budget_s=total_budget, num_envs=s["num_envs"],
            batch_size=s["batch"], train_every=s["train_every"],
            chunk_iters=s["chunk"], num_chunks=2 + s["measure_chunks"],
            ring=s["ring"],
            frame_dedup_stack=dedup_stack)
        if not verdict.ok:
            _emit({"metric": METRIC, "value": None, "unit": UNIT,
                   "vs_baseline": None, **verdict.as_fields(),
                   "error": f"sizing-gate: {verdict.reason}"})
            return 4

    guard = _watchdog("measurement", total_budget)
    try:
        from dist_dqn_tpu.utils.device_cleanup import install

        install()  # SIGTERM'd bench must release its device grant
        value, extras = _measure(jax, device, smoke)
    except Exception as e:  # noqa: BLE001
        _emit_error("measurement", repr(e))
        return 2
    finally:
        guard.cancel()

    _emit({"metric": METRIC, "value": round(value, 1), "unit": UNIT,
           "vs_baseline": round(value / BASELINE_ENV_STEPS_PER_SEC_PER_CHIP,
                                6), **extras})
    return 0


def _learner_step_flops(jax, cfg, env, net):
    """Op-census FLOPs of ONE learner grad step, lowered standalone.

    The fused chunk's census also counts env physics, acting and replay
    ops; the conventional MFU definition counts model fwd+bwd+optimizer
    only (ADVICE round 2) — so the ``mfu`` field is derived from this
    program, exactly the one benchmarks/learner_bench.py times. The
    census registers as ``fused.train_step`` in the chip-time
    ProgramRegistry (ISSUE 19) so the caller can derive the
    ``dqn_learner_mfu`` gauge the runtimes publish.
    """
    import numpy as np

    from dist_dqn_tpu.agents.dqn import make_learner
    from dist_dqn_tpu.types import Transition
    from dist_dqn_tpu.utils import flops as flops_util

    from dist_dqn_tpu import loop_common

    init, train_step = make_learner(net, cfg.learner)
    obs_shape = env.observation_shape
    obs_dtype = np.dtype(env.observation_dtype)
    state = init(jax.random.PRNGKey(0), jax.numpy.zeros(obs_shape, obs_dtype))
    # The census must price the step the fused program ACTUALLY runs:
    # the bucketed train width, not the nominal batch_size — otherwise
    # a BENCH_TRAIN_BATCH-widened row under-reports mfu by the ratio.
    B = loop_common.resolve_train_batch(cfg)
    r = np.random.default_rng(0)

    def obs():
        if obs_dtype == np.uint8:
            return jax.numpy.asarray(
                r.integers(0, 255, (B,) + obs_shape, np.uint8))
        return jax.numpy.asarray(r.normal(size=(B,) + obs_shape)
                                 .astype(obs_dtype))

    batch = Transition(
        obs=obs(),
        action=jax.numpy.asarray(r.integers(0, env.num_actions, B, np.int32)),
        reward=jax.numpy.asarray(r.normal(size=B).astype(np.float32)),
        discount=jax.numpy.full(B, cfg.learner.gamma ** cfg.learner.n_step,
                                jax.numpy.float32),
        next_obs=obs(),
    )
    from dist_dqn_tpu.telemetry import devtime as _devtime

    jitted = jax.jit(train_step, donate_argnums=0)
    prog = _devtime.register_program(  # cost census of `jitted` above
        "fused.train_step", loop="fused", role="train",
        cost=lambda: jitted.lower(state, batch,
                                  jax.numpy.ones(B, jax.numpy.float32)))
    return prog.flops


def _measure(jax, device, smoke: bool):
    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.train_loop import make_fused_train
    from dist_dqn_tpu.utils import flops as flops_util

    # BENCH_SMOKE=1 shrinks every dimension; default sizes target a real TPU
    # chip. The round-3 sweep (benchmarks/bench_sweep.py, fixed 0.125
    # examples/frame) measured 1024 lanes x batch 512 at 569,049
    # env-steps/sec/chip vs 510-525k for the round-1 512x256 default, so
    # 1024x512 is the default; 2048x1024 exceeded the 450s watchdog
    # (docs/tpu_runs/20260731_0316_sweep/).
    s = _sizes(smoke)
    num_envs = s["num_envs"]
    chunk = s["chunk"]
    # ~25 chunks x 200 iters x 1024 envs ~= 5M env steps: several seconds
    # of measured work, long enough to average out dispatch/clock jitter.
    measure_chunks = s["measure_chunks"]

    cfg = CONFIGS["atari"]
    cfg = dataclasses.replace(
        cfg,
        actor=dataclasses.replace(cfg.actor, num_envs=num_envs),
        # Round-5 default: a 65,536-transition FRAME-DEDUP ring — the
        # same ~0.5 GB of HBM as round 4's 16k stacked default with 4x
        # its replay window, and FASTER (632.4k vs 572.5k stacked at
        # 65k; 637.0k vs 619.1k at 16k — the smaller footprint keeps
        # gathers/inserts hot). Stacked ring-size axis for reference
        # (2026-08-01 v5e): 627k/619k/605k/572k/527k env-steps/s at
        # 8k/16k/32k/65k/131k slots (uniform sampling; no PER tree in
        # this program). Production configs size their rings for
        # learning (e.g. atari: 200k), not for this contract metric.
        replay=dataclasses.replace(
            cfg.replay,
            capacity=s["ring"],
            prioritized=s["prioritized"],
            pallas_sampler=s["pallas_sampler"],
            frame_dedup=s["frame_dedup"],
            updates_per_chunk=s["replay_ratio"],
            train_batch=s["train_batch"],
            min_fill=128 if smoke else 4_096),
        learner=dataclasses.replace(
            cfg.learner,
            batch_size=s["batch"]),
        network=dataclasses.replace(
            cfg.network,
            actor_dtype=s["actor_dtype"]),
        train_every=s["train_every"],
    )
    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    init, run_chunk = make_fused_train(cfg, env, net)
    run = jax.jit(run_chunk, static_argnums=1, donate_argnums=0)

    def fence(metrics) -> float:
        return float(jax.device_get(metrics["loss"]))

    carry = init(jax.random.PRNGKey(0))
    compiled = run.lower(carry, chunk).compile()
    # Chip-time attribution (ISSUE 19): the measured program registers
    # with its census so the BENCH row's `programs` block and the
    # registry-derived mfu come from the same plane the runtimes use.
    from dist_dqn_tpu.telemetry import devtime as _devtime
    _prog_chunk = _devtime.register_program(  # census of `run`'s chunk
        "fused.chunk", loop="fused", role="chunk", cost=compiled)
    for _ in range(2):  # warmup + fill past min_fill into steady state
        carry, metrics = compiled(carry)
        fence(metrics)

    t0 = time.perf_counter()
    for _ in range(measure_chunks):
        carry, metrics = compiled(carry)
    fence(metrics)
    dt = time.perf_counter() - t0
    _prog_chunk.count_dispatch(measure_chunks)
    _prog_chunk.add_device_seconds(dt)

    value = measure_chunks * chunk * num_envs / dt
    extras = {"platform": device.platform,
              "device_kind": getattr(device, "device_kind", "unknown")}
    # Telemetry snapshot (ISSUE 1): a perf regression in this line should
    # carry the pipeline internals, not just the headline number — record
    # the measured state into the process registry and embed its JSON
    # snapshot in the contract line's extras.
    from dist_dqn_tpu import telemetry
    from dist_dqn_tpu.telemetry import collectors as tmc

    reg = telemetry.get_registry()
    reg.gauge(tmc.ENV_RATE, "measured env-steps/sec").set(value)
    reg.counter(tmc.ENV_STEPS, "env steps in the measured window") \
        .inc(measure_chunks * chunk * num_envs)
    chunk_hist = reg.histogram("dqn_chunk_seconds", "fused chunk wall")
    chunk_hist.observe(dt / measure_chunks)
    _, ring_slots = tmc.observe_device_ring(carry.replay)
    # Experience lineage (ISSUE 16): reconstruct the measured window's
    # collect stamps (the timed loop cannot touch the host per chunk —
    # that would fence it) and age them exactly as train.py does, so
    # the BENCH row carries the fused loop's sample-age distribution.
    gsteps_chunk = float(jax.device_get(metrics["grad_steps_in_chunk"]))
    _lineage = tmc.FusedLineageTable()
    _per_chunk = dt / measure_chunks
    for i in range(measure_chunks):
        _lineage.on_chunk(gsteps_chunk * (i + 1),
                          max(1, ring_slots // chunk),
                          now=t0 + (i + 1) * _per_chunk)
    _age_h, _stale_h = tmc.lineage_histograms("fused")
    extras["sample_age_p50_s"] = round(
        tmc.histogram_quantile(_age_h, 0.5), 6)
    extras["sample_age_p99_s"] = round(
        tmc.histogram_quantile(_age_h, 0.99), 6)
    extras["staleness_versions_p99"] = round(
        tmc.histogram_quantile(_stale_h, 0.99), 2)
    gsteps = float(jax.device_get(metrics["grad_steps_in_chunk"]))
    if gsteps:
        reg.histogram(tmc.GRAD_LATENCY,
                      "per-grad-step share of the chunk wall") \
            .observe(dt / measure_chunks / gsteps)
    # Run manifest (ISSUE 4 satellite): BENCH rows self-describe their
    # provenance — git sha, jax/numpy versions, platform, the exact
    # measured config (hashed), argv, schema_version — the same block
    # train.py logs and forensics bundles embed (telemetry/manifest.py).
    extras["manifest"] = telemetry.build_manifest(cfg)
    if s["prioritized"]:
        extras["prioritized"] = True  # opt-in: default line unchanged
        extras["sampler"] = "pallas" if s["pallas_sampler"] else "xla"
    if s["frame_dedup"]:
        # ON by default since round 5: the default contract line carries
        # this field (value/unit/vs_baseline schema unchanged).
        extras["frame_dedup"] = True
    # Learner-utilization config provenance (ISSUE 6): ALWAYS next to
    # mfu, so every BENCH row names the replay ratio / effective train
    # batch / actor dtype that produced its utilization numbers.
    from dist_dqn_tpu import loop_common as _lc
    extras["replay_ratio"] = s["replay_ratio"]
    extras["train_batch"] = _lc.resolve_train_batch(cfg)
    extras["actor_dtype"] = s["actor_dtype"]
    # Conventional MFU: learner fwd+bwd+optimizer FLOPs only. Grad-step
    # count uses the last chunk's census — the cadence is deterministic in
    # steady state, so every measured chunk ran the same number (reading
    # each chunk's metric would insert a host fence into the timed loop).
    # The gauge itself is registry-derived (ISSUE 19): the train-step
    # census program gets the window's dispatches + wall and
    # set_learner_mfu does the same division every runtime publishes.
    grad_steps = float(jax.device_get(metrics["grad_steps_in_chunk"])) \
        * measure_chunks
    train_flops = _learner_step_flops(jax, cfg, env, net)
    _prog_train = _devtime.get_program_registry().get(
        "fused.train_step", "fused")
    if grad_steps:
        _prog_train.count_dispatch(grad_steps)
        _prog_train.add_device_seconds(dt)
    learner = flops_util.mfu_fields(train_flops, grad_steps, dt, device)
    if "model_flops_per_sec" in learner:
        extras["model_flops_per_sec"] = learner["model_flops_per_sec"]
        extras["learner_grad_steps_per_sec"] = round(grad_steps / dt, 2)
    mfu_val = _devtime.set_learner_mfu("fused", device=device, reg=reg)
    if mfu_val is not None:
        extras["mfu"] = round(mfu_val, 4)
    if grad_steps:
        reg.gauge(tmc.LEARNER_GRAD_RATE,
                  "grad steps per second (measured window)",
                  {"loop": "fused"}).set(grad_steps / dt)
    # Per-program chip-time census (ISSUE 19): flops/bytes/dispatches/
    # device-seconds + arithmetic intensity for every registered program.
    extras["programs"] = _devtime.programs_snapshot("fused")
    # Snapshot LAST so the embedded registry block carries the learner-
    # utilization gauges set above.
    extras["telemetry"] = telemetry.snapshot(reg)
    return value, extras


if __name__ == "__main__":
    sys.exit(main())
