#!/usr/bin/env python
"""Compatibility shim (ISSUE 13): the thread-hygiene lint now lives in
``dist_dqn_tpu/analysis/plugins/threads.py``, registered with
``scripts/dqnlint.py`` as the ``threads`` check. This entry point keeps
the original verdict contract — ``python scripts/check_threads.py``
prints ``check_threads: OK``/``FAIL`` with the same exit code — and
re-exports the historical module surface for external references.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dist_dqn_tpu.analysis.plugins.threads import (REQUIRED_KEYWORDS,  # noqa: F401,E402
                                                   SCAN_ROOTS,
                                                   _is_thread_call, scan)
from dist_dqn_tpu.analysis.runner import legacy_main  # noqa: E402


def main() -> int:
    """The historical module-level entry point."""
    return legacy_main("threads", "check_threads")


if __name__ == "__main__":
    sys.exit(main())
