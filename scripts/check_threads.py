#!/usr/bin/env python
"""Lint: every ``threading.Thread(...)`` in ``dist_dqn_tpu/`` must pass
explicit ``name=`` AND ``daemon=``.

ISSUE 4 added all-thread stack dumps to the forensics bundles and
``/debug/stacks`` (telemetry/watchdog.py ``format_stacks``): the stacks
are labeled by THREAD NAME, so an unnamed thread prints as ``Thread-7``
and the one dump you get from a wedged production run points nowhere.
Explicit ``daemon=`` is required for the same post-mortem reason — shut
down behavior must be a decision visible at the call site, not an
inherited default someone has to go look up.

AST-based (no regex false positives on comments/strings): flags any
``threading.Thread(...)`` or bare ``Thread(...)`` call whose keywords do
not include both ``name`` and ``daemon``. ``threading.Timer`` is out of
scope — its constructor takes neither.

Run from the repo root: ``python scripts/check_threads.py``. Wired into
tier-1 via tests/test_threads_lint.py (the sibling of the metric-
emission lint, scripts/check_metrics.py).
"""
from __future__ import annotations

import ast
import sys
from pathlib import Path

SCAN_ROOTS = ("dist_dqn_tpu",)
REQUIRED_KEYWORDS = ("name", "daemon")


def _is_thread_call(func: ast.expr) -> bool:
    if isinstance(func, ast.Attribute) and func.attr == "Thread":
        return isinstance(func.value, ast.Name) \
            and func.value.id == "threading"
    # ``from threading import Thread`` style — not current repo idiom,
    # but the lint must bite if it appears.
    return isinstance(func, ast.Name) and func.id == "Thread"


def scan(repo_root: Path):
    """[(relpath, lineno, missing keywords), ...] for violating sites."""
    failures = []
    for root in SCAN_ROOTS:
        base = repo_root / root
        files = ([base] if base.is_file()
                 else sorted(base.rglob("*.py")) if base.is_dir() else [])
        for f in files:
            rel = f.relative_to(repo_root).as_posix()
            try:
                tree = ast.parse(f.read_text())
            except SyntaxError as e:
                failures.append((rel, e.lineno or 0, ["<unparseable>"]))
                continue
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and _is_thread_call(node.func)):
                    continue
                kw = {k.arg for k in node.keywords}
                missing = [r for r in REQUIRED_KEYWORDS if r not in kw]
                if missing:
                    failures.append((rel, node.lineno, missing))
    return failures


def main() -> int:
    repo_root = Path(__file__).resolve().parent.parent
    failures = scan(repo_root)
    if failures:
        print("check_threads: FAIL", file=sys.stderr)
        for rel, lineno, missing in failures:
            wanted = ", ".join(f"{m}=" for m in missing)
            print(f"  {rel}:{lineno}: threading.Thread(...) without "
                  f"explicit {wanted} — unnamed/implicit threads make "
                  "forensics stack dumps unreadable "
                  "(docs/observability.md)", file=sys.stderr)
        return 1
    print("check_threads: OK (every Thread call site names itself and "
          "declares daemon-ness)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
