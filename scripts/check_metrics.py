#!/usr/bin/env python
"""Compatibility shim (ISSUE 13): the metric-emission + docs-drift lint
now lives in ``dist_dqn_tpu/analysis/plugins/metrics.py``, registered
with ``scripts/dqnlint.py`` as the ``metrics`` check. This entry point
keeps the original verdict contract — ``python scripts/check_metrics.py``
prints ``check_metrics: OK``/``FAIL`` with the same exit code — and
re-exports the historical module surface for external references.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dist_dqn_tpu.analysis.plugins.metrics import (ALLOWLIST,  # noqa: F401,E402
                                                   CONSTANT,
                                                   DOCS_ALLOWLIST,
                                                   PATTERN, REGISTRATION,
                                                   SCAN_ROOTS, check_docs,
                                                   scan, scan_metric_names)
from dist_dqn_tpu.analysis.runner import legacy_main  # noqa: E402


def main() -> int:
    """The historical module-level entry point."""
    return legacy_main("metrics", "check_metrics")


if __name__ == "__main__":
    sys.exit(main())
