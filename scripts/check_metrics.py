#!/usr/bin/env python
"""Lint: no NEW JSON-line metric emission bypassing the telemetry registry.

ISSUE 1 unified metrics behind ``dist_dqn_tpu/telemetry`` — new code
should record through the registry (and let MetricLogger / the /metrics
endpoint do the emitting), not grow more ad-hoc ``print(json.dumps(...))``
/ ``log_fn(json.dumps(...))`` call sites that scrapers can't see.

The legacy sites that existed when the registry landed are grandfathered
in the allowlist below (several are load-bearing CLI output contracts —
bench.py's single contract line, train.py's log rows). The lint fails
when a file GROWS new call sites or a new file starts emitting directly;
shrinking is always allowed (update the allowlist in the same PR).

Run from the repo root: ``python scripts/check_metrics.py``. Wired into
tier-1 via tests/test_metrics_lint.py.
"""
from __future__ import annotations

import re
import sys
from pathlib import Path

PATTERN = re.compile(r"(?:print|log_fn)\(json\.dumps")

#: file (repo-relative, posix) -> call sites grandfathered at ISSUE 1.
ALLOWLIST = {
    "bench.py": 1,
    "benchmarks/ale_learning.py": 2,
    "benchmarks/apex_feeder_bench.py": 1,
    "benchmarks/apex_split_bench.py": 2,
    "benchmarks/bench_sweep.py": 4,
    "benchmarks/cli_e2e.py": 3,
    "benchmarks/host_replay_bench.py": 1,
    "benchmarks/learner_bench.py": 3,
    "benchmarks/pong_learning.py": 4,
    "benchmarks/r2d2_pixel_learning.py": 1,
    "benchmarks/roofline_inscan.py": 1,
    "benchmarks/sampler_bench.py": 2,
    "benchmarks/tpu_battery.py": 5,
    "dist_dqn_tpu/actors/remote.py": 1,
    "dist_dqn_tpu/actors/service.py": 3,
    "dist_dqn_tpu/atari57.py": 7,
    # +1 at ISSUE 4: the telemetry_port announcement line (a CLI output
    # contract like train.py's, not a metric — the metrics themselves go
    # through the registry the flag exposes).
    "dist_dqn_tpu/evaluate.py": 2,
    "dist_dqn_tpu/host_replay_loop.py": 1,
    # +1 at ISSUE 4: the one-per-run {"manifest": ...} provenance line
    # (telemetry/manifest.py) — run identity, not a metric stream.
    "dist_dqn_tpu/train.py": 11,
    "dist_dqn_tpu/utils/metrics.py": 1,  # MetricLogger.flush itself
}

SCAN_ROOTS = ("dist_dqn_tpu", "benchmarks", "bench.py", "__graft_entry__.py")


def scan(repo_root: Path):
    counts = {}
    for root in SCAN_ROOTS:
        path = repo_root / root
        files = ([path] if path.is_file()
                 else sorted(path.rglob("*.py")) if path.is_dir() else [])
        for f in files:
            rel = f.relative_to(repo_root).as_posix()
            if rel.startswith("dist_dqn_tpu/telemetry/"):
                continue  # the registry itself is the sanctioned emitter
            n = len(PATTERN.findall(f.read_text()))
            if n:
                counts[rel] = n
    return counts


def main() -> int:
    repo_root = Path(__file__).resolve().parent.parent
    counts = scan(repo_root)
    failures = []
    for rel, n in sorted(counts.items()):
        allowed = ALLOWLIST.get(rel, 0)
        if n > allowed:
            failures.append(
                f"{rel}: {n} direct JSON-metric emission call sites "
                f"(allowlist: {allowed}). New metrics must go through "
                f"dist_dqn_tpu/telemetry (registry counters/gauges/"
                f"histograms); see docs/observability.md.")
    if failures:
        print("check_metrics: FAIL", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"check_metrics: OK ({sum(counts.values())} grandfathered "
          f"call sites in {len(counts)} files)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
