#!/usr/bin/env python
"""Lint: no NEW JSON-line metric emission bypassing the telemetry registry,
and no ``dqn_*`` metric family undocumented in docs/observability.md.

ISSUE 1 unified metrics behind ``dist_dqn_tpu/telemetry`` — new code
should record through the registry (and let MetricLogger / the /metrics
endpoint do the emitting), not grow more ad-hoc ``print(json.dumps(...))``
/ ``log_fn(json.dumps(...))`` call sites that scrapers can't see.

The legacy sites that existed when the registry landed are grandfathered
in the allowlist below (several are load-bearing CLI output contracts —
bench.py's single contract line, train.py's log rows). The lint fails
when a file GROWS new call sites or a new file starts emitting directly;
shrinking is always allowed (update the allowlist in the same PR).

ISSUE 5 added the docs-drift half: every ``dqn_*`` family name that
appears at a registry registration site (``.counter(/.gauge(/
.histogram(`` with a literal name) or as a canonical constant in
``telemetry/collectors.py`` must appear in docs/observability.md, so
the naming table can no longer silently lag the code. Names that are
deliberately undocumented live in DOCS_ALLOWLIST with a rationale;
dynamically composed names (f-strings) are out of scope by
construction.

Run from the repo root: ``python scripts/check_metrics.py``. Wired into
tier-1 via tests/test_metrics_lint.py.
"""
from __future__ import annotations

import re
import sys
from pathlib import Path

PATTERN = re.compile(r"(?:print|log_fn)\(json\.dumps")

#: Registry registration with a literal family name. ``\s`` spans
#: newlines, so multi-line calls are covered.
REGISTRATION = re.compile(
    r"\.(?:counter|gauge|histogram)\(\s*[\"'](dqn_[a-z0-9_]+)[\"']")
#: Canonical name constants in telemetry/collectors.py (including the
#: ``NAME = \`` + next-line-string spelling).
CONSTANT = re.compile(
    r"^[A-Z0-9_]+\s*=\s*(?:\\\s*)?[\"'](dqn_[a-z0-9_]+)[\"']", re.M)

#: dqn_* families allowed to be absent from docs/observability.md,
#: each with the reason it stays undocumented.
DOCS_ALLOWLIST = {
    # Internal plumbing of the span tracer: a scratch gauge the
    # MetricLogger uses to mirror counter-style extras; not a scrape
    # surface anyone should alert on (utils/trace.py).
    "dqn_trace_counter",
}

#: file (repo-relative, posix) -> call sites grandfathered at ISSUE 1.
ALLOWLIST = {
    "bench.py": 1,
    "benchmarks/ale_learning.py": 2,
    "benchmarks/apex_feeder_bench.py": 1,
    "benchmarks/apex_split_bench.py": 2,
    "benchmarks/bench_sweep.py": 4,
    "benchmarks/cli_e2e.py": 3,
    "benchmarks/host_replay_bench.py": 1,
    "benchmarks/learner_bench.py": 3,
    "benchmarks/pong_learning.py": 4,
    "benchmarks/r2d2_pixel_learning.py": 1,
    "benchmarks/roofline_inscan.py": 1,
    "benchmarks/sampler_bench.py": 2,
    # ISSUE 7: the per-arm BENCH row line (the contract line goes
    # through bench.ContractEmitter, counted under bench.py) — CLI
    # output contracts; the serving metrics themselves go through the
    # registry (dqn_serving_*).
    "benchmarks/serving_bench.py": 1,
    "benchmarks/tpu_battery.py": 5,
    "dist_dqn_tpu/actors/remote.py": 1,
    # +2 at ISSUE 8: the ingest_degraded alarm transitions (one line
    # per episode edge, state changes — the continuous signal is the
    # dqn_ingest_degraded gauge).
    "dist_dqn_tpu/actors/service.py": 5,
    # ISSUE 8: the one-per-episode transport shedding alarm (the
    # per-record stream is dqn_transport_tcp_shed_total).
    "dist_dqn_tpu/actors/transport.py": 1,
    "dist_dqn_tpu/atari57.py": 7,
    # +1 at ISSUE 4: the telemetry_port announcement line (a CLI output
    # contract like train.py's, not a metric — the metrics themselves go
    # through the registry the flag exposes).
    "dist_dqn_tpu/evaluate.py": 2,
    # +2 at ISSUE 8: the resumed_at_frames and per-save checkpoint
    # announcement lines (run-lifecycle output contracts, mirroring
    # train.py's resume line; the chaos/crash metrics go through the
    # registry).
    "dist_dqn_tpu/host_replay_loop.py": 3,
    # ISSUE 7: the serving CLI's startup announcements (serving_port +
    # optional telemetry_port) — output contracts like train.py's; act
    # metrics go through the registry. +1 at ISSUE 8: the shutdown
    # serving_drained line (graceful-drain outcome contract).
    "dist_dqn_tpu/serving/__main__.py": 3,
    # +1 at ISSUE 4: the one-per-run {"manifest": ...} provenance line
    # (telemetry/manifest.py) — run identity, not a metric stream.
    "dist_dqn_tpu/train.py": 11,
    "dist_dqn_tpu/utils/metrics.py": 1,  # MetricLogger.flush itself
}

SCAN_ROOTS = ("dist_dqn_tpu", "benchmarks", "bench.py", "__graft_entry__.py")


def scan(repo_root: Path):
    counts = {}
    for root in SCAN_ROOTS:
        path = repo_root / root
        files = ([path] if path.is_file()
                 else sorted(path.rglob("*.py")) if path.is_dir() else [])
        for f in files:
            rel = f.relative_to(repo_root).as_posix()
            if rel.startswith("dist_dqn_tpu/telemetry/"):
                continue  # the registry itself is the sanctioned emitter
            n = len(PATTERN.findall(f.read_text()))
            if n:
                counts[rel] = n
    return counts


def scan_metric_names(repo_root: Path):
    """Every dqn_* family name the package registers or canonicalizes."""
    names = set()
    pkg = repo_root / "dist_dqn_tpu"
    for f in sorted(pkg.rglob("*.py")):
        names.update(REGISTRATION.findall(f.read_text()))
    names.update(CONSTANT.findall(
        (pkg / "telemetry" / "collectors.py").read_text()))
    return names


def check_docs(repo_root: Path):
    """Names registered in code but absent from docs/observability.md
    (minus the rationale'd allowlist). Whole-name match: a family that
    is merely a prefix of a documented longer name (dqn_foo vs
    dqn_foo_seconds) still counts as undocumented."""
    doc = (repo_root / "docs" / "observability.md").read_text()
    return sorted(
        n for n in scan_metric_names(repo_root)
        if not re.search(rf"{re.escape(n)}(?![a-z0-9_])", doc)
        and n not in DOCS_ALLOWLIST)


def main() -> int:
    repo_root = Path(__file__).resolve().parent.parent
    counts = scan(repo_root)
    failures = []
    for rel, n in sorted(counts.items()):
        allowed = ALLOWLIST.get(rel, 0)
        if n > allowed:
            failures.append(
                f"{rel}: {n} direct JSON-metric emission call sites "
                f"(allowlist: {allowed}). New metrics must go through "
                f"dist_dqn_tpu/telemetry (registry counters/gauges/"
                f"histograms); see docs/observability.md.")
    undocumented = check_docs(repo_root)
    for name in undocumented:
        failures.append(
            f"{name}: registered in dist_dqn_tpu/ but missing from the "
            f"docs/observability.md naming table. Document the family "
            f"(or add it to DOCS_ALLOWLIST with a rationale).")
    if failures:
        print("check_metrics: FAIL", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"check_metrics: OK ({sum(counts.values())} grandfathered "
          f"call sites in {len(counts)} files; "
          f"{len(scan_metric_names(repo_root))} dqn_* families "
          f"documented)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
