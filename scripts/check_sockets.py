#!/usr/bin/env python
"""Lint: every socket acquisition site in dist_dqn_tpu/ must bound its
blocking behavior — set a timeout nearby or carry a rationale comment.

ISSUE 8: the chaos harness's whole disconnect/partition fault class
turns into a silent process wedge the moment one socket blocks forever
(the round-1 tunnel incident was exactly an unbounded wait nobody knew
existed). This lint makes the policy mechanical: wherever a socket is
CREATED or ACCEPTED (``socket.socket(``, ``socket.create_connection(``,
``.accept()``), one of the following must hold within
``CONTEXT_LINES`` lines of the call:

  * a ``settimeout(`` / ``timeout=`` appears (the socket is bounded), or
  * a ``# socket:`` rationale comment explains why unbounded blocking
    is safe here (e.g. a daemon thread whose close() path shuts the fd
    down out from under it).

Stdlib ``http.server``/``socketserver`` internals are out of scope —
the lint covers this repo's own call sites: every package under
``dist_dqn_tpu/`` including the zero-copy ingest subsystem
(``dist_dqn_tpu/ingest/``, ISSUE 9 — its shm slot ring is socket-free
by design, and this lint is what keeps a future wire helper there
honest). REQUIRED_SUBPACKAGES makes the coverage explicit: the lint
FAILS if a listed tree goes missing rather than silently scanning
nothing. Run from the repo root: ``python scripts/check_sockets.py``.
Wired into tier-1 via tests/test_sockets_lint.py.
"""
from __future__ import annotations

import re
import sys
from pathlib import Path

#: How far (in lines, both directions) evidence may sit from the call.
CONTEXT_LINES = 6

ACQUIRE = re.compile(
    r"socket\.socket\(|socket\.create_connection\(|\.accept\(\)")
EVIDENCE = re.compile(r"settimeout\(|timeout\s*=|#\s*socket:")


#: Subtrees the scan must actually see (guards against a refactor
#: moving socket code out from under the rglob): the transport-bearing
#: packages today.
REQUIRED_SUBPACKAGES = ("actors", "ingest", "serving", "telemetry")


def scan(repo_root: Path):
    failures = []
    pkg = repo_root / "dist_dqn_tpu"
    # Coverage guard only for the real repo (the lint tests scan
    # synthetic single-file trees, which legitimately lack subpackages).
    if (repo_root / "scripts" / "check_sockets.py").exists():
        for sub in REQUIRED_SUBPACKAGES:
            if pkg.is_dir() and not (pkg / sub).is_dir():
                failures.append(
                    f"dist_dqn_tpu/{sub}/: expected subpackage missing "
                    f"— update REQUIRED_SUBPACKAGES if it moved")
    for f in sorted(pkg.rglob("*.py")):
        lines = f.read_text().splitlines()
        for i, line in enumerate(lines):
            if not ACQUIRE.search(line):
                continue
            lo = max(0, i - CONTEXT_LINES)
            hi = min(len(lines), i + CONTEXT_LINES + 1)
            window = "\n".join(lines[lo:hi])
            if not EVIDENCE.search(window):
                rel = f.relative_to(repo_root).as_posix()
                failures.append(
                    f"{rel}:{i + 1}: socket acquired without a nearby "
                    f"timeout or '# socket:' rationale comment: "
                    f"{line.strip()}")
    return failures


def main() -> int:
    repo_root = Path(__file__).resolve().parent.parent
    failures = scan(repo_root)
    if failures:
        print("check_sockets: FAIL", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        print("  Bound the socket (settimeout) or add a '# socket: "
              "<why unbounded blocking is safe>' comment within "
              f"{CONTEXT_LINES} lines.", file=sys.stderr)
        return 1
    print("check_sockets: OK")
    return 0


if __name__ == "__main__":
    sys.exit(main())
