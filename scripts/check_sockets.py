#!/usr/bin/env python
"""Compatibility shim (ISSUE 13): the socket-hygiene lint now lives in
``dist_dqn_tpu/analysis/plugins/sockets.py``, registered with
``scripts/dqnlint.py`` as the ``sockets`` check. This entry point keeps
the original verdict contract — ``python scripts/check_sockets.py``
prints ``check_sockets: OK``/``FAIL`` with the same exit code — and
re-exports the historical module surface for external references.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dist_dqn_tpu.analysis.plugins.sockets import (ACQUIRE,  # noqa: F401,E402
                                                   CONTEXT_LINES,
                                                   EVIDENCE,
                                                   REQUIRED_SUBPACKAGES,
                                                   scan)
from dist_dqn_tpu.analysis.runner import legacy_main  # noqa: E402


def main() -> int:
    """The historical module-level entry point."""
    return legacy_main("sockets", "check_sockets")


if __name__ == "__main__":
    sys.exit(main())
