#!/usr/bin/env python
"""Game-day runner (ISSUE 8): execute REAL CartPole runs of the apex,
host-replay and serving stacks under a seeded fault schedule and assert
the survival invariants the chaos harness exists to prove.

Scenarios (each armed with a FaultPlan derived from ``--seed``; the
same seed derives the same schedule — ``--print-plan`` emits it without
running, so replayability is checkable byte-for-byte):

  apex_fleet      actor kill -9 (every actor dies and is restarted by
                  supervision, repeatedly), transport bit-flip (the
                  corrupt frame is CRC-dropped + counted server-side
                  and the actor NACK-reconnects) and transport
                  disconnect — training must reach its target anyway.
  pipeline_wedge  evac + prefetch worker stalls past a short watchdog
                  deadline — each stall must produce exactly one
                  forensics bundle and a /healthz 503 -> 200 round
                  trip, and the run must finish with correct numerics.
  ckpt_crash      commit-without-stamp checkpoint crash, torn LATEST
                  pointer, then a hard kill at chunk k — the resumed
                  run must be BIT-IDENTICAL to an uninterrupted,
                  never-checkpointed reference, with every injected
                  trip recovered.
  sharded_ckpt_crash  the data-parallel twin (ISSUE 12): a dp=2
                  host-replay run takes a commit-without-stamp crash,
                  a TORN PER-SHARD SIDECAR (truncated npz at the final
                  path while the orbax step commits) and a hard kill
                  at chunk k — resume must delete the unusable step,
                  fall back to the previous intact one, and still end
                  BIT-IDENTICAL to an uninterrupted dp=2 reference,
                  all trips recovered.
  fleet_pane      fleet observability game day (ISSUE 16): a real
                  apex learner + two real remote-actor CLI processes
                  registered in one fleet dir; SIGKILL one worker —
                  within one registry sweep /fleet/status must name it
                  dead and trip ingest_degraded, a restarted worker
                  must flip the fleet back to healthy, and the run
                  still reaches its step target.
  serving_reload  hot-reload under live load with a slowed restore and
                  a slowed + failed dispatch — every request answers
                  (the one injected failure as a structured error),
                  versions never tear or regress per client, and the
                  SIGTERM drain completes with admissions refused.

Every scenario also reports its injector's ``open_trips()`` — the
runner exits non-zero when ANY scenario ends with an unrecovered trip,
so game days are CI-gateable on the recovery evidence itself, not only
on each scenario's bespoke invariants.

Run from the repo root (CPU is fine)::

    JAX_PLATFORMS=cpu python scripts/chaos_run.py --seed 7
    python scripts/chaos_run.py --seed 7 --print-plan   # schedule only
    python scripts/chaos_run.py --scenario ckpt_crash

Exit 0 = every invariant held. Each scenario prints one JSON line of
evidence; the failure-mode matrix in docs/fault_tolerance.md says
which invariant pins which fault.
"""
from __future__ import annotations

import argparse
import dataclasses
import json
import os
import random
import sys
import tempfile
import threading
import time

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)

# The sharded scenario runs a dp=2 mesh; on a CPU-only box that needs
# the virtual-device flag BEFORE the jax backend initializes (the
# scenarios import jax lazily, so setting it here covers them all —
# same bootstrap as conftest.py).
_flags = os.environ.get("XLA_FLAGS", "")
if "xla_force_host_platform_device_count" not in _flags:
    os.environ["XLA_FLAGS"] = (
        _flags + " --xla_force_host_platform_device_count=2").strip()

from dist_dqn_tpu import chaos  # noqa: E402
from dist_dqn_tpu.chaos.plan import FaultEvent, FaultPlan  # noqa: E402


class InvariantError(AssertionError):
    pass


def _check(cond, msg):
    if not cond:
        raise InvariantError(msg)


def _counter_total(name, **labels):
    """Sum a family's counters matching the given labels."""
    from dist_dqn_tpu.telemetry import get_registry

    total = 0.0
    for inst in get_registry().collect().get(name, []):
        if all(inst.labels.get(k) == v for k, v in labels.items()):
            total += inst.value
    return total


# ---------------------------------------------------------------------------
# Seeded schedules: same seed -> same plan, per scenario
# ---------------------------------------------------------------------------

def plan_apex_fleet(seed: int) -> FaultPlan:
    rng = random.Random(f"{seed}:apex_fleet")
    return FaultPlan(seed=seed, events=(
        # Every actor process arms this slice: each dies (SIGKILL
        # semantics) once per ~this many step passes and supervision
        # must restart it — repeated fleet churn, not a one-off.
        FaultEvent("actor.step", "crash", at_hit=100 + rng.randrange(40)),
        # Remote actors only (the seam sits on the TCP client): one
        # frame's payload corrupted on the wire, one hard disconnect.
        FaultEvent("transport.send", "bit_flip",
                   at_hit=40 + rng.randrange(20),
                   args={"bit": 200 + rng.randrange(4000)}),
        FaultEvent("transport.send", "disconnect",
                   at_hit=70 + rng.randrange(20)),
    ))


def plan_pipeline_wedge(seed: int, stall_s: float) -> FaultPlan:
    rng = random.Random(f"{seed}:pipeline_wedge")
    return FaultPlan(seed=seed, events=(
        FaultEvent("evac.drain", "stall", at_hit=2 + rng.randrange(2),
                   args={"delay_s": stall_s}),
        # Far later in the batch stream than the evac stall so the two
        # wedges are distinct episodes (=> one bundle EACH).
        FaultEvent("prefetch.sample", "stall",
                   at_hit=30 + rng.randrange(8),
                   args={"delay_s": stall_s}),
    ))


def plan_ckpt_crash(seed: int) -> FaultPlan:
    rng = random.Random(f"{seed}:ckpt_crash")
    return FaultPlan(seed=seed, events=(
        # Save 2 commits its orbax step but dies before stamping LATEST.
        FaultEvent("checkpoint.save", "crash_before_stamp", at_hit=2),
        # Save 3's stamp lands torn (crash mid-write without rename).
        FaultEvent("latest.write", "torn", at_hit=3),
        # Then the run itself is killed right after chunk k's save.
        FaultEvent("host_replay.chunk", "crash",
                   at_hit=4 + rng.randrange(2)),
    ))


def plan_sharded_ckpt_crash(seed: int) -> FaultPlan:
    rng = random.Random(f"{seed}:sharded_ckpt_crash")
    # The torn sidecar and the kill share one chunk (one save per
    # chunk at this scenario's cadence): the NEWEST step at kill time
    # is the unusable one, so resume must exercise the fallback. A
    # later kill would leave a newer intact step and the torn one
    # would never be read.
    k = 4 + rng.randrange(2)
    return FaultPlan(seed=seed, events=(
        # Save 2 commits its orbax step but dies before stamping LATEST.
        FaultEvent("checkpoint.save", "crash_before_stamp", at_hit=2),
        # Save k's per-shard sidecar lands TORN at the final path while
        # the orbax step still commits (crash mid-write on a
        # non-atomic-rename filesystem) — the newest step is unusable.
        FaultEvent("sidecar.write", "torn", at_hit=k),
        # And the run is killed right after that save.
        FaultEvent("host_replay.chunk", "crash", at_hit=k),
    ))


def plan_serving_reload(seed: int) -> FaultPlan:
    rng = random.Random(f"{seed}:serving_reload")
    return FaultPlan(seed=seed, events=(
        # Hit 1 is the startup restore; the slowed one is the watcher's
        # reload-under-load.
        FaultEvent("serving.reload", "slow_reload", at_hit=2,
                   args={"delay_s": 0.5}),
        FaultEvent("serving.dispatch", "slow_model",
                   at_hit=3 + rng.randrange(3),
                   args={"delay_s": 0.3}),
        FaultEvent("serving.dispatch", "exception",
                   at_hit=10 + rng.randrange(5)),
    ))


# ---------------------------------------------------------------------------
# Scenarios
# ---------------------------------------------------------------------------

def scenario_apex_fleet(seed: int, workdir: str) -> dict:
    from dist_dqn_tpu.actors.service import ApexRuntimeConfig, run_apex
    from dist_dqn_tpu.config import CONFIGS

    cfg = CONFIGS["apex"]
    cfg = dataclasses.replace(
        cfg,
        network=dataclasses.replace(cfg.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    dueling=False,
                                    compute_dtype="float32"),
        replay=dataclasses.replace(cfg.replay, capacity=4096, min_fill=150),
        learner=dataclasses.replace(cfg.learner, batch_size=16, n_step=2))
    rt = ApexRuntimeConfig(host_env="CartPole-v1", num_actors=1,
                           envs_per_actor=4, total_env_steps=4000,
                           inserts_per_grad_step=32,
                           num_remote_actors=2, log_every_s=2.0)
    plan = plan_apex_fleet(seed)
    corrupt_before = _counter_total("dqn_transport_corrupt_frames_total",
                                    reason="crc", side="server")
    # export_env: spawned actor processes arm their own copy of the
    # plan (hit counters are per process — every actor lives the same
    # schedule, which is what decimates the fleet).
    inj = chaos.install(plan, export_env=True, log_fn=None)
    try:
        out = run_apex(cfg, rt, log_fn=lambda s: None)
        open_trips = inj.open_trips()
    finally:
        chaos.uninstall()
        os.environ.pop(chaos.CHAOS_PLAN_ENV, None)
    corrupt = _counter_total("dqn_transport_corrupt_frames_total",
                             reason="crc", side="server") - corrupt_before
    # Survival: progress to target with actors dying under us.
    _check(out["env_steps"] >= rt.total_env_steps,
           f"apex run stalled at {out['env_steps']} env steps")
    _check(out["grad_steps"] > 0, "no training happened")
    _check(out["actor_restarts"] >= 1,
           "no actor was killed+restarted — the crash seam never fired")
    # The flipped bit was dropped at the CRC gate, counted, and the
    # run STILL finished: it never reached the codec or the learner.
    _check(corrupt >= 1, "no corrupt frame was counted server-side")
    _check(out["bad_records"] == 0,
           "a corrupt frame leaked past the integrity gate")
    return {"scenario": "apex_fleet", "plan": plan.to_dict(),
            "env_steps": out["env_steps"],
            "grad_steps": out["grad_steps"],
            "actor_restarts": out["actor_restarts"],
            "corrupt_frames_dropped": int(corrupt),
            "parent_injections": inj.injected,
            "open_trips": open_trips}


def scenario_pipeline_wedge(seed: int, workdir: str) -> dict:
    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.host_replay_loop import run_host_replay
    from dist_dqn_tpu.telemetry import watchdog as tm_watchdog

    cfg = CONFIGS["cartpole"]
    cfg = dataclasses.replace(
        cfg,
        actor=dataclasses.replace(cfg.actor, num_envs=8),
        network=dataclasses.replace(cfg.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    compute_dtype="float32"),
        replay=dataclasses.replace(cfg.replay, capacity=4096, min_fill=64,
                                   prioritized=False),
        learner=dataclasses.replace(cfg.learner, batch_size=16))
    stall_s, deadline_s = 4.0, 1.5
    plan = plan_pipeline_wedge(seed, stall_s)
    forensics = os.path.join(workdir, "forensics")
    bundles_before = len(os.listdir(forensics)) \
        if os.path.isdir(forensics) else 0
    tm_watchdog.install_watchdog(forensics_dir=forensics,
                                 deadline_s=deadline_s, poll_s=0.25,
                                 log_fn=None)

    # /healthz sampler: the wedge must flip health to 503 and the
    # recovery back to 200 — sampled while the run executes.
    health_samples, stop = [], threading.Event()

    def poll_health():
        while not stop.is_set():
            ok, detail = tm_watchdog.health_state()
            health_samples.append(bool(ok))
            time.sleep(0.1)

    poller = threading.Thread(target=poll_health,
                              name="chaos-health-poller", daemon=True)
    poller.start()
    try:
        with chaos.installed(plan, log_fn=None) as inj:
            out = run_host_replay(cfg, total_env_steps=6400,
                                  chunk_iters=50, log_fn=lambda s: None)
            injected = list(inj.injected)
            open_trips = inj.open_trips()
    finally:
        stop.set()
        poller.join(5)
        # Relax the deadline so later scenarios / idle time can't trip.
        tm_watchdog.install_watchdog(forensics_dir=None, deadline_s=600.0,
                                     log_fn=None)
    bundles = sorted(os.listdir(forensics)) if os.path.isdir(forensics) \
        else []
    n_bundles = len(bundles) - bundles_before
    stalls = [e for e in injected if e["fault"] == "stall"]
    _check(len(stalls) == 2, f"expected 2 stall injections, got {stalls}")
    _check(out["env_steps"] >= 6400, "wedged run did not finish")
    # Exactly one bundle per injected stall: the watchdog latches a
    # stale stage until it recovers, so each wedged WORKER stage shows
    # up newly-stale in exactly one bundle — no bundle storm. (A wedge
    # can additionally stall the main-loop stages blocked on its fence;
    # those cascade bundles name OTHER stages, never the same wedge
    # twice.)
    named = []
    for b in bundles:
        with open(os.path.join(forensics, b, "reason.json")) as fh:
            named.append(json.load(fh)["detail"]["newly_stale"])
    for stage in ("evac.host_replay", "prefetch.host_replay"):
        hits = sum(1 for stages in named if stage in stages)
        _check(hits == 1,
               f"wedged stage {stage} appears newly-stale in {hits} "
               f"bundles (want exactly 1): {named}")
        _check(_counter_total("dqn_watchdog_stalls_total",
                              stage=stage) == 1,
               f"stall episodes for {stage} != 1")
    _check(n_bundles >= 2, f"missing bundles: {named}")
    _check(not all(health_samples),
           "healthz never went 503 during a 4s wedge")
    _check(health_samples and health_samples[-1],
           "healthz did not recover to 200 after the wedges")
    _check(open_trips == [],
           f"stall trips never marked recovered: {open_trips}")
    return {"scenario": "pipeline_wedge", "plan": plan.to_dict(),
            "env_steps": out["env_steps"], "bundles": n_bundles,
            "healthz_ever_503": not all(health_samples),
            "healthz_final_200": bool(health_samples[-1]),
            "injections": injected, "open_trips": open_trips}


def scenario_ckpt_crash(seed: int, workdir: str) -> dict:
    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.host_replay_loop import run_host_replay

    cfg = CONFIGS["cartpole"]
    cfg = dataclasses.replace(
        cfg,
        actor=dataclasses.replace(cfg.actor, num_envs=8),
        network=dataclasses.replace(cfg.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    compute_dtype="float32"),
        replay=dataclasses.replace(cfg.replay, capacity=4096, min_fill=64,
                                   prioritized=False),
        learner=dataclasses.replace(cfg.learner, batch_size=16))
    kw = dict(total_env_steps=3200, chunk_iters=50,
              log_fn=lambda s: None)
    ref = run_host_replay(cfg, **kw)

    plan = plan_ckpt_crash(seed)
    ckpt_dir = os.path.join(workdir, "ckpt_crash")
    killed = False
    with chaos.installed(plan, log_fn=None) as inj:
        try:
            run_host_replay(cfg, **kw, checkpoint_dir=ckpt_dir,
                            save_every_frames=400)
        except chaos.ChaosInjectedError:
            killed = True
        _check(killed, "the injected chunk crash never fired")
        # Resume under the SAME armed injector: resuming IS the
        # recovery proof for the crash, and the resumed run's first
        # completed save+stamp proves the checkpoint seams recovered.
        out = run_host_replay(cfg, **kw, checkpoint_dir=ckpt_dir,
                              save_every_frames=400)
        injected = sorted((e["seam"], e["fault"], e["hit"])
                          for e in inj.injected)
        open_trips = inj.open_trips()
    expected = sorted((e.seam, e.fault, e.at_hit) for e in plan.events)
    _check(injected == expected,
           f"injection sequence diverged from the plan: {injected} != "
           f"{expected}")
    _check(out["param_checksum"] == ref["param_checksum"],
           "resumed run is NOT bit-identical to the uninterrupted one: "
           f"{out['param_checksum']} != {ref['param_checksum']}")
    _check(out["grad_steps"] == ref["grad_steps"],
           "resumed run trained a different number of steps")
    _check(open_trips == [],
           f"unrecovered trips after resume: {open_trips}")
    return {"scenario": "ckpt_crash", "plan": plan.to_dict(),
            "param_checksum": out["param_checksum"],
            "reference_checksum": ref["param_checksum"],
            "bit_identical": True, "injections": injected,
            "open_trips": open_trips}


def scenario_sharded_ckpt_crash(seed: int, workdir: str) -> dict:
    """The ISSUE 12 game day: dp=2 host-replay under checkpoint chaos.
    Invariants: the injected sequence equals the plan; the torn sidecar
    forces a LOGGED fallback to the previous step; the resumed run is
    bit-identical (param_checksum + grad steps) to an uninterrupted
    never-checkpointed dp=2 reference; every trip recovered."""
    import jax

    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.host_replay_loop import run_host_replay

    if len(jax.devices()) < 2:
        raise InvariantError(
            "sharded_ckpt_crash needs >= 2 devices (the runner forces "
            "2 virtual CPU devices; a site hook overrode it?)")
    cfg = CONFIGS["cartpole"]
    cfg = dataclasses.replace(
        cfg,
        actor=dataclasses.replace(cfg.actor, num_envs=8),
        network=dataclasses.replace(cfg.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    compute_dtype="float32"),
        replay=dataclasses.replace(cfg.replay, capacity=4096, min_fill=64,
                                   prioritized=False),
        learner=dataclasses.replace(cfg.learner, batch_size=16))
    kw = dict(total_env_steps=3200, chunk_iters=50, mesh_devices=2,
              log_fn=lambda s: None)
    ref = run_host_replay(cfg, **kw)
    _check(ref["dp_size"] == 2, "reference run was not data-parallel")

    plan = plan_sharded_ckpt_crash(seed)
    ckpt_dir = os.path.join(workdir, "sharded_ckpt_crash")
    killed = False
    logs = []
    with chaos.installed(plan, log_fn=None) as inj:
        try:
            run_host_replay(cfg, total_env_steps=3200, chunk_iters=50,
                            mesh_devices=2, log_fn=lambda s: None,
                            checkpoint_dir=ckpt_dir,
                            save_every_frames=400)
        except chaos.ChaosInjectedError:
            killed = True
        _check(killed, "the injected chunk crash never fired")
        out = run_host_replay(cfg, total_env_steps=3200, chunk_iters=50,
                              mesh_devices=2,
                              log_fn=lambda s: logs.append(s),
                              checkpoint_dir=ckpt_dir,
                              save_every_frames=400)
        injected = sorted((e["seam"], e["fault"], e["hit"])
                          for e in inj.injected)
        open_trips = inj.open_trips()
    expected = sorted((e.seam, e.fault, e.at_hit) for e in plan.events)
    _check(injected == expected,
           f"injection sequence diverged from the plan: {injected} != "
           f"{expected}")
    fallback = [s for s in logs if "sidecar unreadable" in s]
    _check(fallback, "the torn sidecar never forced a logged fallback")
    resumed = [json.loads(s) for s in logs if "resumed_at_frames" in s]
    _check(resumed and resumed[0].get("resumed_dp") == 2,
           f"resume evidence missing/wrong: {resumed}")
    _check(out["param_checksum"] == ref["param_checksum"],
           "resumed dp=2 run is NOT bit-identical to the uninterrupted "
           f"one: {out['param_checksum']} != {ref['param_checksum']}")
    _check(out["grad_steps"] == ref["grad_steps"],
           "resumed run trained a different number of steps")
    _check(open_trips == [],
           f"unrecovered trips after resume: {open_trips}")
    return {"scenario": "sharded_ckpt_crash", "plan": plan.to_dict(),
            "dp_size": 2, "param_checksum": out["param_checksum"],
            "reference_checksum": ref["param_checksum"],
            "bit_identical": True,
            "resumed_at_frames": resumed[0]["resumed_at_frames"],
            "torn_sidecar_fallbacks": len(fallback),
            "injections": injected, "open_trips": open_trips}


def plan_fleet_pane(seed: int) -> FaultPlan:
    # No seam events: the fault here is PROCESS-LEVEL (a SIGKILL the
    # runner itself delivers to a worker the seed picks), because the
    # invariant under test is the fleet pane's VIEW of a death, not a
    # seam's recovery path. The plan still derives from the seed so
    # --print-plan shows the (empty) schedule and the victim choice
    # replays byte-for-byte.
    return FaultPlan(seed=seed, events=())


def scenario_fleet_pane(seed: int, workdir: str) -> dict:
    """Fleet observability game day (ISSUE 16): a real apex learner +
    two REAL remote-actor CLI processes, all registered in one fleet
    dir. SIGKILL one worker mid-run: within ONE registry sweep the
    /fleet/status rollup must name it ``dead`` and trip
    ``ingest_degraded`` (half the actor quorum gone); a restarted
    worker must flip the fleet back to healthy; and the run itself must
    still reach its step target — the pane observes the death, the
    stateless-worker protocol absorbs it."""
    import signal
    import subprocess

    from dist_dqn_tpu.actors.service import (ApexLearnerService,
                                             ApexRuntimeConfig)
    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.telemetry import fleet

    fleet_dir = os.path.join(workdir, "fleet")
    os.makedirs(fleet_dir, exist_ok=True)
    os.environ[fleet.FLEET_ENV] = fleet_dir
    stop_file = os.path.join(workdir, "fleet_pane_stop")

    cfg = CONFIGS["apex"]
    cfg = dataclasses.replace(
        cfg,
        network=dataclasses.replace(cfg.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    dueling=False,
                                    compute_dtype="float32"),
        replay=dataclasses.replace(cfg.replay, capacity=4096, min_fill=150),
        learner=dataclasses.replace(cfg.learner, batch_size=16, n_step=2))
    rt = ApexRuntimeConfig(host_env="CartPole-v1", num_actors=1,
                           envs_per_actor=4, total_env_steps=4000,
                           inserts_per_grad_step=32,
                           num_remote_actors=2,
                           spawn_remote_actors=False,  # real CLI workers
                           telemetry_port=0, log_every_s=5.0)
    service = ApexLearnerService(cfg, rt, log_fn=lambda s: None)
    host, port = service.tcp_address

    def _spawn_worker(actor_id: int):
        return subprocess.Popen(
            [sys.executable, "-m", "dist_dqn_tpu.actors.remote",
             "--address", f"127.0.0.1:{port}", "--actor-id",
             str(actor_id), "--env", "CartPole-v1", "--num-envs", "4",
             "--telemetry-port", "0", "--fleet-dir", fleet_dir,
             "--stop-file", stop_file],
            stdout=subprocess.DEVNULL, stderr=subprocess.DEVNULL,
            cwd=REPO)

    workers = {1: _spawn_worker(1), 2: _spawn_worker(2)}
    agg = fleet.FleetAggregator(fleet_dir, sweep_interval_s=0.5,
                                scrape_timeout_s=2.0)
    out = {}
    runner = threading.Thread(
        target=lambda: out.update(service.run()), daemon=True)
    runner.start()
    try:
        # Quorum up: learner + both workers on the pane.
        deadline = time.time() + 120.0
        while time.time() < deadline:
            agg.sweep_once()
            st = agg.status()
            if st["counts"]["live"] >= 3:
                break
            time.sleep(0.3)
        _check(st["counts"]["live"] >= 3,
               f"fleet never converged to 3 live members: {st['counts']}")
        _check(not st["ingest_degraded"],
               "degraded with the whole quorum live")

        victim_id = random.Random(f"{seed}:fleet_pane").choice([1, 2])
        victim = workers[victim_id]
        victim.send_signal(signal.SIGKILL)
        victim.wait(timeout=30.0)
        # ONE sweep later the pane must tell the truth: the descriptor
        # is still on disk (SIGKILL skips the exit lifecycle), the pid
        # is gone, so the member is dead — and one of two actors dead
        # trips the quorum gauge.
        agg.sweep_once()
        st = agg.status()
        dead_name = f"actor-{victim.pid}"
        _check(st["members"][dead_name]["state"] == "dead",
               f"killed worker not dead on the pane: "
               f"{st['members'].get(dead_name)}")
        _check(st["ingest_degraded"],
               "half the actor fleet is dead but ingest_degraded is 0")
        _check(any(dead_name in a for a in st["alerts"]),
               f"no alert names the dead member: {st['alerts']}")

        # Restart (new pid, same actor id): the stateless worker
        # re-introduces itself and the fleet flips back to healthy.
        workers[victim_id] = _spawn_worker(victim_id)
        deadline = time.time() + 120.0
        while time.time() < deadline:
            agg.sweep_once()
            st = agg.status()
            back = st["members"].get(
                f"actor-{workers[victim_id].pid}", {})
            if back.get("state") == "live" and not st["ingest_degraded"]:
                break
            time.sleep(0.3)
        _check(back.get("state") == "live",
               f"restarted worker never went live: {back}")
        _check(not st["ingest_degraded"],
               "fleet still degraded after the restart")
        # The merged pane carries the workers' own families under
        # process/role labels — one scrape for the whole fleet.
        merged = agg.render_metrics()
        _check('dqn_actor_env_steps_total' in merged
               and 'role="actor"' in merged,
               "worker families missing from the federated exposition")

        runner.join(timeout=300.0)
        _check(not runner.is_alive(), "apex run did not finish")
        _check(out.get("env_steps", 0) >= rt.total_env_steps,
               f"run stalled at {out.get('env_steps')} env steps")
    finally:
        with open(stop_file, "w") as f:
            f.write("stop\n")
        for w in workers.values():
            try:
                w.wait(timeout=30.0)
            except subprocess.TimeoutExpired:
                w.kill()
        os.environ.pop(fleet.FLEET_ENV, None)
        try:
            os.unlink(stop_file)
        except OSError:
            pass
    return {"scenario": "fleet_pane",
            "plan": plan_fleet_pane(seed).to_dict(),
            "victim_actor_id": victim_id,
            "env_steps": out.get("env_steps"),
            "grad_steps": out.get("grad_steps"),
            "fleet_counts": st["counts"],
            "open_trips": []}


def scenario_serving_reload(seed: int, workdir: str) -> dict:
    import jax
    import jax.numpy as jnp
    import numpy as np

    from dist_dqn_tpu.agents.dqn import make_learner
    from dist_dqn_tpu.config import CONFIGS
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.models import build_network
    from dist_dqn_tpu.serving import ServerClosedError, build_server
    from dist_dqn_tpu.utils.checkpoint import TrainCheckpointer

    cfg = CONFIGS["cartpole"]
    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    init, _ = make_learner(net, cfg.learner)
    obs0 = jnp.zeros(env.observation_shape, env.observation_dtype)
    directory = os.path.join(workdir, "serving_ckpt")
    ckpt = TrainCheckpointer(directory, save_every_frames=1)
    ckpt.save(100, init(jax.random.PRNGKey(0), obs0))
    ckpt.wait()

    plan = plan_serving_reload(seed)
    reloads_before = _counter_total("dqn_serving_reloads_total")
    srv = build_server(cfg, {"default": directory}, max_rows=8,
                       max_wait_ms=1.0, queue_limit=64,
                       poll_interval_s=0.2, log_fn=lambda *_: None)
    results, errors = [], []
    try:
        with chaos.installed(plan, log_fn=None) as inj:
            def client(tid):
                rng = np.random.default_rng(tid)
                for _ in range(20):
                    obs = rng.standard_normal((2, 4)).astype(np.float32)
                    try:
                        r = srv.batcher.submit(obs, greedy=True)
                        results.append((tid, r.version, r.step))
                    except chaos.ChaosInjectedError as e:
                        errors.append((tid, repr(e)))
                    time.sleep(0.01)

            threads = [threading.Thread(target=client, args=(t,),
                                        name=f"chaos-client-{t}",
                                        daemon=True) for t in range(4)]
            for t in threads:
                t.start()
            # Reload under load: two version bumps while clients hammer
            # and the injected slow_reload holds a restore mid-flight.
            time.sleep(0.2)
            ckpt.save(200, init(jax.random.PRNGKey(1), obs0))
            ckpt.wait()
            time.sleep(0.4)
            ckpt.save(300, init(jax.random.PRNGKey(2), obs0))
            ckpt.wait()
            for t in threads:
                t.join(60)
                _check(not t.is_alive(), "a serving client hung")
            # Keep serving until the second reload demonstrably landed:
            # the act path must pick up step 300 while never tearing.
            deadline = time.monotonic() + 30
            while time.monotonic() < deadline:
                r = srv.batcher.submit(
                    np.zeros((1, 4), np.float32), greedy=True)
                results.append((0, r.version, r.step))
                if r.step == 300:
                    break
                time.sleep(0.05)
            injected = list(inj.injected)
            open_trips = inj.open_trips()
        # Every request answered: results + the structured errors of
        # the ONE injected dispatch failure (each rider coalesced into
        # that batch gets it).
        _check(len(results) + len(errors) >= 80,
               f"lost requests: {len(results)} ok + {len(errors)} err")
        _check(1 <= len(errors) <= 4,
               f"expected 1 failed dispatch (<=4 riders), got {errors}")
        # No version tears or regressions per client: a later request
        # rides the same or a newer snapshot, never an older one.
        for tid in range(4):
            seq = [v for t, v, _ in results if t == tid]
            _check(seq == sorted(seq),
                   f"client {tid} saw a version regression: {seq}")
        steps_seen = {s for _, _, s in results}
        _check(max(steps_seen) == 300,
               f"hot reload never landed while serving: {steps_seen}")
        reloads = _counter_total("dqn_serving_reloads_total") \
            - reloads_before
        _check(reloads >= 2, f"expected >=2 reloads, got {reloads}")
        _check(open_trips == [],
               f"unrecovered serving trips: {open_trips}")
        # Graceful drain: admitted work completes, new work is refused,
        # the server closes clean — the SIGTERM path minus the signal.
        drained = srv.drain(5.0)
        _check(drained, "drain timed out with requests in flight")
        refused = False
        try:
            srv.batcher.submit(np.zeros((1, 4), np.float32), greedy=True)
        except ServerClosedError:
            refused = True
        _check(refused, "a post-drain admission was not refused")
    finally:
        srv.close()
        ckpt.close()
    return {"scenario": "serving_reload", "plan": plan.to_dict(),
            "answered": len(results), "injected_failures": len(errors),
            "steps_seen": sorted(steps_seen), "reloads": int(reloads),
            "drained": True, "injections": injected,
            "open_trips": open_trips}


SCENARIOS = {
    "apex_fleet": scenario_apex_fleet,
    "fleet_pane": scenario_fleet_pane,
    "pipeline_wedge": scenario_pipeline_wedge,
    "ckpt_crash": scenario_ckpt_crash,
    "sharded_ckpt_crash": scenario_sharded_ckpt_crash,
    "serving_reload": scenario_serving_reload,
}

PLANS = {
    "apex_fleet": plan_apex_fleet,
    "fleet_pane": plan_fleet_pane,
    "pipeline_wedge": lambda seed: plan_pipeline_wedge(seed, 4.0),
    "ckpt_crash": plan_ckpt_crash,
    "sharded_ckpt_crash": plan_sharded_ckpt_crash,
    "serving_reload": plan_serving_reload,
}


def main() -> int:
    parser = argparse.ArgumentParser(description=__doc__)
    parser.add_argument("--seed", type=int, default=0,
                        help="schedule seed: the same seed derives the "
                             "same fault plan for every scenario")
    parser.add_argument("--scenario", action="append", default=[],
                        choices=sorted(SCENARIOS),
                        help="run only these (repeatable; default all)")
    parser.add_argument("--print-plan", action="store_true",
                        help="emit every scenario's derived schedule "
                             "as JSON and exit — diff two invocations "
                             "to verify seed replayability")
    parser.add_argument("--workdir", default=None,
                        help="scratch directory (default: a fresh "
                             "tempdir)")
    args = parser.parse_args()

    names = args.scenario or sorted(SCENARIOS)
    if args.print_plan:
        print(json.dumps({name: PLANS[name](args.seed).to_dict()
                          for name in names}, sort_keys=True, indent=2))
        return 0

    workdir = args.workdir or tempfile.mkdtemp(prefix="chaos_run_")
    failures = []
    t_all = time.perf_counter()
    for name in names:
        t0 = time.perf_counter()
        try:
            result = SCENARIOS[name](args.seed, workdir)
            result["wall_s"] = round(time.perf_counter() - t0, 1)
            # CI gate (ISSUE 12 satellite): an unrecovered injection is
            # a game-day failure even when every scenario-specific
            # invariant held — a seam whose recovery proof never fired
            # must fail the run, not pass silently.
            if result.get("open_trips"):
                failures.append(name)
                result["ok"] = False
                result["invariant_failed"] = (
                    "open trips (injections without a recovery proof): "
                    f"{result['open_trips']}")
            else:
                result["ok"] = True
        except InvariantError as e:
            failures.append(name)
            result = {"scenario": name, "ok": False,
                      "invariant_failed": str(e),
                      "wall_s": round(time.perf_counter() - t0, 1)}
        print(json.dumps(result), flush=True)
    print(json.dumps({
        "chaos_run": {"seed": args.seed, "scenarios": names,
                      "failures": failures,
                      "wall_s": round(time.perf_counter() - t_all, 1)}}),
        flush=True)
    return 1 if failures else 0


if __name__ == "__main__":
    sys.exit(main())
