#!/usr/bin/env python
"""Compatibility shim (ISSUE 13): the buffer-donation lint now lives in
``dist_dqn_tpu/analysis/plugins/donation.py``, registered with
``scripts/dqnlint.py`` as the ``donation`` check. This entry point
keeps the original verdict contract — ``python scripts/check_donation.py``
prints ``check_donation: OK``/``FAIL`` with the same exit code — and
re-exports the historical module surface for external references.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dist_dqn_tpu.analysis.plugins.donation import (RATIONALE,  # noqa: F401,E402
                                                    SCAN_ROOTS, TARGET,
                                                    _is_jit_call,
                                                    _jitted_expr_text,
                                                    scan)
from dist_dqn_tpu.analysis.runner import legacy_main  # noqa: E402


def main() -> int:
    """The historical module-level entry point."""
    return legacy_main("donation", "check_donation")


if __name__ == "__main__":
    sys.exit(main())
