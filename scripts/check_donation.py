#!/usr/bin/env python
"""Lint: every jitted train/collect entry point must declare explicit
``donate_argnums`` — or carry a ``donation:`` rationale comment.

ISSUE 6's aliasing audit (utils/donation.py) verified the chunk
programs donate their GB-sized carries completely (alias_bytes ==
argument_bytes on the fused chunk); what the runtime audit cannot do is
stop the NEXT train/collect jit from silently omitting the donation —
the failure mode is an HBM working set doubled on a chip that used to
fit, discovered as an OOM months later. This is the static half of the
guard, the sibling of scripts/check_metrics.py / check_threads.py.

AST-based: any ``jax.jit(...)`` call (or ``partial(jax.jit, ...)``)
whose jitted expression mentions ``train``/``collect``/``chunk`` is a
learner/collector entry point and must either

* pass ``donate_argnums=`` explicitly, or
* be preceded (within two lines, or on the same line) by a comment
  containing ``donation:`` stating why nothing is donated (e.g. a
  pure-function cast whose inputs are reused by the caller).

Functions named act/eval/sample are out of scope by construction (their
params ARE reused across calls — donating would be the bug).

Run from the repo root: ``python scripts/check_donation.py``. Wired
into tier-1 via tests/test_donation_lint.py.
"""
from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

SCAN_ROOTS = ("dist_dqn_tpu", "benchmarks", "bench.py")

#: What makes a jitted expression a train/collect entry point.
#: ``shard`` joined in ISSUE 10: the data-parallel learners wrap their
#: train steps in closures named ``sharded`` (parallel/learner.py
#: make_sharded_train_step), which the train/collect/chunk patterns
#: would silently stop seeing.
TARGET = re.compile(r"train|collect|chunk|shard")
#: Rationale escape hatch: a nearby comment owning the decision.
RATIONALE = re.compile(r"#.*donation:")


def _is_jit_call(node: ast.Call) -> bool:
    """True for ``jax.jit(...)`` / ``jit(...)`` and the
    ``partial(jax.jit, ...)`` spelling."""
    f = node.func
    if isinstance(f, ast.Attribute) and f.attr == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "jit":
        return True
    if isinstance(f, ast.Name) and f.id == "partial" and node.args:
        inner = node.args[0]
        return (isinstance(inner, ast.Attribute) and inner.attr == "jit") \
            or (isinstance(inner, ast.Name) and inner.id == "jit")
    return False


def _jitted_expr_text(node: ast.Call) -> str:
    """Source text of what is being jitted (first non-jax.jit arg)."""
    args = node.args
    if args and isinstance(args[0], (ast.Attribute, ast.Name)) \
            and getattr(args[0], "attr", getattr(args[0], "id", "")) \
            == "jit":
        args = args[1:]  # partial(jax.jit, ...) positional tail
    try:
        return " ".join(ast.unparse(a) for a in args)
    except Exception:
        return ""


def _has_rationale(lines, lineno: int) -> bool:
    """A ``donation:`` comment on the call line or the two above it."""
    lo = max(lineno - 3, 0)
    return any(RATIONALE.search(ln) for ln in lines[lo:lineno])


def scan(repo_root: Path):
    """[(relpath, lineno, jitted expr), ...] for violating sites."""
    failures = []
    for root in SCAN_ROOTS:
        base = repo_root / root
        files = ([base] if base.is_file()
                 else sorted(base.rglob("*.py")) if base.is_dir() else [])
        for f in files:
            rel = f.relative_to(repo_root).as_posix()
            src = f.read_text()
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                failures.append((rel, e.lineno or 0, "<unparseable>"))
                continue
            lines = src.splitlines()
            decorator_calls = set()
            # Decorator spellings: @jax.jit / @partial(jax.jit, ...) on
            # a def — the jitted expression is the function's own name.
            for node in ast.walk(tree):
                if not isinstance(node, (ast.FunctionDef,
                                         ast.AsyncFunctionDef)):
                    continue
                for dec in node.decorator_list:
                    is_call = isinstance(dec, ast.Call)
                    if is_call and _is_jit_call(dec):
                        decorator_calls.add(id(dec))
                        kw = {k.arg for k in dec.keywords}
                    elif isinstance(dec, ast.Attribute) \
                            and dec.attr == "jit":
                        kw = set()
                    else:
                        continue
                    if not TARGET.search(node.name):
                        continue
                    if "donate_argnums" in kw:
                        continue
                    if _has_rationale(lines, dec.lineno):
                        continue
                    failures.append((rel, dec.lineno, node.name))
            for node in ast.walk(tree):
                if not (isinstance(node, ast.Call)
                        and _is_jit_call(node)) \
                        or id(node) in decorator_calls:
                    continue
                expr = _jitted_expr_text(node)
                if not TARGET.search(expr):
                    continue
                kw = {k.arg for k in node.keywords}
                if "donate_argnums" in kw:
                    continue
                if _has_rationale(lines, node.lineno):
                    continue
                failures.append((rel, node.lineno, expr.split("\n")[0]))
    return failures


def main() -> int:
    repo_root = Path(__file__).resolve().parent.parent
    failures = scan(repo_root)
    if failures:
        print("check_donation: FAIL", file=sys.stderr)
        for rel, lineno, expr in failures:
            print(f"  {rel}:{lineno}: jax.jit({expr!r}) is a train/"
                  "collect entry point without explicit donate_argnums "
                  "— donate the carry/state (in-place HBM update) or "
                  "add a '# donation: <why not>' rationale comment "
                  "(docs/performance.md, learner utilization)",
                  file=sys.stderr)
        return 1
    print("check_donation: OK (every jitted train/collect entry point "
          "declares its donation or a rationale)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
