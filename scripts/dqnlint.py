#!/usr/bin/env python
"""dqnlint: the one runner for every static check (ISSUE 13).

Replaces seven disconnected ``scripts/check_*.py`` invocations with one
in-process run over a shared file/AST cache::

    python scripts/dqnlint.py --all              # human report
    python scripts/dqnlint.py --all --json       # CI findings artifact
    python scripts/dqnlint.py --check threads --check lock-discipline
    python scripts/dqnlint.py --list             # registered checks

Exit code 0 iff no unsuppressed findings (baselined findings and their
reasons are reported, never silently dropped; a STALE baseline entry is
itself a failure). Suppression surfaces, in triage order: fix the code;
own it with the check's rationale comment at the site (``# lock:`` /
``# donation:`` / ``# socket:`` / ``# mesh-axis:``); or add a reasoned
entry to scripts/dqnlint_baseline.json. Catalog + plugin how-to:
docs/static_analysis.md. The legacy ``scripts/check_*.py`` entry points
remain as shims with their original verdicts.
"""
from __future__ import annotations

import argparse
import json
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent
sys.path.insert(0, str(REPO))


def main(argv=None) -> int:
    from dist_dqn_tpu.analysis import (BaselineError, check_names,
                                       get_checks, render_json,
                                       render_text, run_checks)

    parser = argparse.ArgumentParser(
        prog="dqnlint", description="unified static analysis runner")
    parser.add_argument("--all", action="store_true",
                        help="run every registered check (default when "
                             "no --check is given)")
    parser.add_argument("--check", action="append", default=[],
                        metavar="NAME",
                        help="run one named check (repeatable)")
    parser.add_argument("--json", action="store_true",
                        help="emit the machine-readable findings "
                             "artifact on stdout instead of the human "
                             "report")
    parser.add_argument("--list", action="store_true",
                        help="list registered checks and exit")
    parser.add_argument("--baseline", metavar="PATH", default=None,
                        help="baseline file (default: "
                             "scripts/dqnlint_baseline.json)")
    parser.add_argument("--root", metavar="DIR", default=str(REPO),
                        help="repo root to analyze (default: this repo)")
    parser.add_argument("--verbose", action="store_true",
                        help="also report baselined findings with their "
                             "reasons")
    args = parser.parse_args(argv)

    if args.list:
        for check in get_checks():
            tag = f"  [suppress: '# {check.rationale_tag} <reason>']" \
                if check.rationale_tag else ""
            print(f"{check.name}: {check.description}{tag}")
        return 0

    names = args.check or None
    if args.all:
        names = None
    try:
        results = run_checks(
            Path(args.root), names=names,
            baseline_path=Path(args.baseline) if args.baseline else None)
    except BaselineError as e:
        print(f"dqnlint: invalid baseline — {e}", file=sys.stderr)
        return 2
    except KeyError as e:
        print(f"dqnlint: {e.args[0]}", file=sys.stderr)
        return 2

    ok = all(r.ok for r in results)
    if args.json:
        print(json.dumps(render_json(results), indent=1, sort_keys=True))
    else:
        out = render_text(results, verbose=args.verbose)
        print(out, file=sys.stdout if ok else sys.stderr)
    return 0 if ok else 1


if __name__ == "__main__":
    sys.exit(main())
