#!/usr/bin/env python
"""Lint: the checkpoint-sidecar schema is pinned to its version.

ISSUE 12: host-replay's whole-state resume deserializes an npz sidecar
by FIELD NAME — a renamed/dropped/added field without a version bump
would surface at restore time (3am, on the production fleet) as a
silently-wrong or crashing resume, not in CI. This lint makes the
schema change mechanical, mirroring the wire codec's check_wire.py:

  * it fingerprints the sidecar field registry of
    ``dist_dqn_tpu/utils/ckpt_schema.py`` (scalars, conditionals and
    per-shard/per-entry patterns);
  * the digest must equal ``SIDECAR_HISTORY[SIDECAR_VERSION]``;
  * history is append-only: every version maps to a distinct digest,
    and the live version leads the history.

Editing any sidecar field without adding a NEW (version, digest) pair
fails CI with the expected digest printed; the resume path refuses a
mismatched on-disk version loudly at restore. Run from the repo root:
``python scripts/check_ckpt_schema.py``. Wired into tier-1 via
tests/test_ckpt_schema_lint.py.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def check() -> list:
    from dist_dqn_tpu.utils import ckpt_schema as cs

    failures = []
    digest = cs.sidecar_digest()
    if cs.SIDECAR_VERSION not in cs.SIDECAR_HISTORY:
        failures.append(
            f"SIDECAR_VERSION {cs.SIDECAR_VERSION} has no SIDECAR_HISTORY "
            f"entry — record it as {cs.SIDECAR_VERSION}: \"{digest}\"")
    elif cs.SIDECAR_HISTORY[cs.SIDECAR_VERSION] != digest:
        failures.append(
            f"sidecar-schema fingerprint {digest} does not match "
            f"SIDECAR_HISTORY[{cs.SIDECAR_VERSION}] = "
            f"{cs.SIDECAR_HISTORY[cs.SIDECAR_VERSION]!r}: the field set "
            f"changed — bump SIDECAR_VERSION "
            f"(dist_dqn_tpu/utils/ckpt_schema.py) and append the new "
            f"(version, digest) pair to SIDECAR_HISTORY; resumes then "
            f"refuse a mismatched sidecar loudly at restore instead of "
            f"deserializing silence")
    if cs.SIDECAR_HISTORY and max(cs.SIDECAR_HISTORY) != cs.SIDECAR_VERSION:
        failures.append(
            f"SIDECAR_HISTORY records version {max(cs.SIDECAR_HISTORY)} "
            f"but SIDECAR_VERSION is {cs.SIDECAR_VERSION} — history is "
            "append-only and the constant must lead it")
    digests = list(cs.SIDECAR_HISTORY.values())
    if len(set(digests)) != len(digests):
        failures.append(
            "SIDECAR_HISTORY maps two versions to the same digest — a "
            "version bump without a schema change (or a rewritten entry)")
    # The validator itself must accept a canonical minimal sidecar —
    # a schema whose own patterns reject its scalar fields would pass
    # the digest check while failing every real save.
    try:
        cs.validate_sidecar(list(cs.SIDECAR_SCALAR_FIELDS))
    except ValueError as e:
        failures.append(f"validate_sidecar rejects the schema's own "
                        f"scalar field set: {e}")
    return failures


def main() -> int:
    failures = check()
    if failures:
        print("check_ckpt_schema: FAIL", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    from dist_dqn_tpu.utils import ckpt_schema as cs

    print(f"check_ckpt_schema: OK (sidecar v{cs.SIDECAR_VERSION}, "
          f"digest {cs.sidecar_digest()})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
