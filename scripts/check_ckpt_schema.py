#!/usr/bin/env python
"""Compatibility shim (ISSUE 13): the checkpoint-sidecar schema lint
now lives in ``dist_dqn_tpu/analysis/plugins/ckpt_schema.py``,
registered with ``scripts/dqnlint.py`` as the ``ckpt-schema`` check.
This entry point keeps the original verdict contract —
``python scripts/check_ckpt_schema.py`` prints ``check_ckpt_schema:
OK``/``FAIL`` with the same exit code — and re-exports the historical
module surface for external references.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dist_dqn_tpu.analysis.plugins.ckpt_schema import check  # noqa: F401,E402
from dist_dqn_tpu.analysis.runner import legacy_main  # noqa: E402


def main() -> int:
    """The historical module-level entry point."""
    return legacy_main("ckpt-schema", "check_ckpt_schema")


if __name__ == "__main__":
    sys.exit(main())
