#!/usr/bin/env python
"""Compatibility shim (ISSUE 13): the mesh-axis lint now lives in
``dist_dqn_tpu/analysis/plugins/mesh_axis.py``, registered with
``scripts/dqnlint.py`` as the ``mesh-axis`` check. This entry point
keeps the original verdict contract — ``python scripts/check_mesh_axis.py``
prints ``check_mesh_axis: OK``/``FAIL`` with the same exit code — and
re-exports the historical module surface for external references.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dist_dqn_tpu.analysis.plugins.mesh_axis import (AXIS_IN_CALL,  # noqa: F401,E402
                                                     COMPAT_MODULE,
                                                     DIRECT, RATIONALE,
                                                     SCAN_ROOTS, scan)
from dist_dqn_tpu.analysis.runner import legacy_main  # noqa: E402


def main() -> int:
    """The historical module-level entry point."""
    return legacy_main("mesh-axis", "check_mesh_axis")


if __name__ == "__main__":
    sys.exit(main())
