#!/usr/bin/env python
"""Lint: mesh-parallel call sites must resolve through utils/compat.py
and must name their mesh axis (or carry a rationale comment).

Two rules, both born from the ISSUE 10 scale-out:

1. **No direct ``jax.shard_map`` / ``jax.experimental.shard_map``
   outside ``dist_dqn_tpu/utils/compat.py``.** JAX moved the API
   between 0.4.x and 0.5 (and renamed ``check_rep`` to ``check_vma``),
   and a direct spelling import-errors on the other side — exactly the
   failure that carried 13 tier-1 tests on the 0.4.37 dev box. The
   compat resolver is the one place allowed to touch either spelling.

2. **Every ``shard_map``/``pjit`` call site names its axis.** The call
   text must contain a literal axis (a ``P("dp")``-style spec or an
   ``axis``/``axis_name`` keyword), or a ``# mesh-axis:`` comment
   within three lines above stating where the axis lives (e.g. "the
   specs are built by train_step_specs") — so a reader at the call
   site can always answer "which leaves live on which axis" without
   spelunking. docs/architecture.md's scale-out table is the prose
   twin of this rule.

Run from the repo root: ``python scripts/check_mesh_axis.py``. Wired
into tier-1 via tests/test_mesh_lint.py, the sibling of
check_donation.py / check_metrics.py.
"""
from __future__ import annotations

import ast
import re
import sys
from pathlib import Path

SCAN_ROOTS = ("dist_dqn_tpu", "benchmarks", "bench.py", "__graft_entry__.py")
COMPAT_MODULE = "dist_dqn_tpu/utils/compat.py"

#: Direct spellings rule 1 forbids outside the compat module.
DIRECT = re.compile(
    r"jax\.shard_map|jax\.experimental\.shard_map|"
    r"from\s+jax\.experimental\.shard_map\s+import")
#: What satisfies rule 2 inside the call text.
AXIS_IN_CALL = re.compile(r"""P\(\s*['"]|axis_name|axis\s*=""")
#: Rationale escape hatch for spec-variable call sites.
RATIONALE = re.compile(r"#.*mesh-axis:")


def _call_name(node: ast.Call) -> str:
    f = node.func
    if isinstance(f, ast.Attribute):
        return f.attr
    if isinstance(f, ast.Name):
        return f.id
    return ""


def _has_rationale(lines, lineno: int) -> bool:
    lo = max(lineno - 4, 0)
    return any(RATIONALE.search(ln) for ln in lines[lo:lineno])


def scan(repo_root: Path):
    """[(relpath, lineno, message), ...] for violating sites."""
    failures = []
    for root in SCAN_ROOTS:
        base = repo_root / root
        files = ([base] if base.is_file()
                 else sorted(base.rglob("*.py")) if base.is_dir() else [])
        for f in files:
            rel = f.relative_to(repo_root).as_posix()
            src = f.read_text()
            lines = src.splitlines()
            if rel != COMPAT_MODULE:
                for i, ln in enumerate(lines, 1):
                    if DIRECT.search(ln):
                        failures.append(
                            (rel, i,
                             "direct jax.shard_map spelling — resolve "
                             "through dist_dqn_tpu.utils.compat."
                             "shard_map (version-adaptive)"))
            else:
                # The resolver itself forwards to whichever spelling
                # exists; its axis comes from the caller's specs —
                # rule 2 applies at call sites, not here.
                continue
            try:
                tree = ast.parse(src)
            except SyntaxError as e:
                failures.append((rel, e.lineno or 0, "<unparseable>"))
                continue
            for node in ast.walk(tree):
                if not isinstance(node, ast.Call):
                    continue
                if _call_name(node) not in ("shard_map", "pjit"):
                    continue
                try:
                    call_text = ast.get_source_segment(src, node) or ""
                except Exception:
                    call_text = ""
                if AXIS_IN_CALL.search(call_text):
                    continue
                if _has_rationale(lines, node.lineno):
                    continue
                failures.append(
                    (rel, node.lineno,
                     f"{_call_name(node)}(...) names no mesh axis — "
                     "put a literal axis spec in the call or a "
                     "'# mesh-axis: <where the specs name it>' comment "
                     "above it"))
    return failures


def main() -> int:
    repo_root = Path(__file__).resolve().parent.parent
    failures = scan(repo_root)
    if failures:
        print("check_mesh_axis: FAIL", file=sys.stderr)
        for rel, lineno, msg in failures:
            print(f"  {rel}:{lineno}: {msg}", file=sys.stderr)
        return 1
    print("check_mesh_axis: OK (shard_map resolves through compat and "
          "every mesh call site names its axis)")
    return 0


if __name__ == "__main__":
    sys.exit(main())
