#!/usr/bin/env python
"""Compatibility shim (ISSUE 13): the wire-format lint now lives in
``dist_dqn_tpu/analysis/plugins/wire.py``, registered with
``scripts/dqnlint.py`` as the ``wire`` check. This entry point keeps
the original verdict contract — ``python scripts/check_wire.py`` prints
``check_wire: OK``/``FAIL`` with the same exit code — and re-exports
the historical module surface for external references.
"""
from __future__ import annotations

import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))

from dist_dqn_tpu.analysis.plugins.wire import check, wire_digest  # noqa: F401,E402
from dist_dqn_tpu.analysis.runner import legacy_main  # noqa: E402


def main() -> int:
    """The historical module-level entry point."""
    return legacy_main("wire", "check_wire")


if __name__ == "__main__":
    sys.exit(main())
