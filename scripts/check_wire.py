#!/usr/bin/env python
"""Lint: the zero-copy wire format is pinned to its protocol version.

ISSUE 9: before the explicit version field existed, a codec change
surfaced as CRC/desync noise mid-stream. The version handshake makes a
mismatch fail at connect — but only if every header change actually
BUMPS the constant. This lint makes that mechanical:

  * it fingerprints the frame-header layout (``WIRE_HEADER_FIELDS`` —
    names + struct formats), the record-kind registry and the flag
    registry of ``dist_dqn_tpu/ingest/codec.py``;
  * the digest must equal ``WIRE_HISTORY[PROTOCOL_VERSION]``;
  * history is append-only: every version maps to a distinct digest.

So editing any frame-header field without adding a NEW
``(PROTOCOL_VERSION, digest)`` pair — i.e. without bumping the
version — fails CI with the expected digest printed. Run from the repo
root: ``python scripts/check_wire.py``. Wired into tier-1 via
tests/test_wire_lint.py.
"""
from __future__ import annotations

import hashlib
import json
import sys
from pathlib import Path

sys.path.insert(0, str(Path(__file__).resolve().parent.parent))


def wire_digest() -> str:
    """Canonical fingerprint of everything a peer must agree on to
    parse a frame header."""
    from dist_dqn_tpu.ingest import codec

    spec = {
        "struct": codec._HDR.format,
        "fields": [list(f) for f in codec.WIRE_HEADER_FIELDS],
        "kinds": dict(codec.WIRE_KINDS),
        "flags": dict(codec.WIRE_FLAGS),
    }
    return hashlib.sha256(
        json.dumps(spec, sort_keys=True).encode()).hexdigest()[:16]


def check() -> list:
    from dist_dqn_tpu.ingest import codec
    from dist_dqn_tpu.ingest.schema import PROTOCOL_VERSION

    failures = []
    digest = wire_digest()
    if PROTOCOL_VERSION not in codec.WIRE_HISTORY:
        failures.append(
            f"PROTOCOL_VERSION {PROTOCOL_VERSION} has no WIRE_HISTORY "
            f"entry — record it as {PROTOCOL_VERSION}: \"{digest}\"")
    elif codec.WIRE_HISTORY[PROTOCOL_VERSION] != digest:
        failures.append(
            f"wire-format fingerprint {digest} does not match "
            f"WIRE_HISTORY[{PROTOCOL_VERSION}] = "
            f"{codec.WIRE_HISTORY[PROTOCOL_VERSION]!r}: the frame "
            f"header changed — bump PROTOCOL_VERSION "
            f"(dist_dqn_tpu/ingest/schema.py) and append the new "
            f"(version, digest) pair to WIRE_HISTORY; peers then fail "
            f"loudly at connect instead of desyncing mid-stream")
    if codec.WIRE_HISTORY and max(codec.WIRE_HISTORY) != PROTOCOL_VERSION:
        failures.append(
            f"WIRE_HISTORY records version {max(codec.WIRE_HISTORY)} "
            f"but PROTOCOL_VERSION is {PROTOCOL_VERSION} — history is "
            f"append-only and the constant must lead it")
    digests = list(codec.WIRE_HISTORY.values())
    if len(set(digests)) != len(digests):
        failures.append(
            "WIRE_HISTORY maps two versions to the same digest — a "
            "version bump without a wire change (or a rewritten entry)")
    return failures


def main() -> int:
    failures = check()
    if failures:
        print("check_wire: FAIL", file=sys.stderr)
        for f in failures:
            print("  " + f, file=sys.stderr)
        return 1
    print(f"check_wire: OK (protocol "
          f"{__import__('dist_dqn_tpu.ingest.schema', fromlist=['x']).PROTOCOL_VERSION}, "
          f"digest {wire_digest()})")
    return 0


if __name__ == "__main__":
    sys.exit(main())
