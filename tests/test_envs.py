"""Env tests: CartPole dynamics vs gymnasium; PixelPong contract checks."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_dqn_tpu.envs.cartpole import CartPole, CartPoleState
from dist_dqn_tpu.envs.pixel_pong import PixelPong


def test_cartpole_matches_gymnasium():
    gymnasium = pytest.importorskip("gymnasium")
    ref = gymnasium.make("CartPole-v1").unwrapped
    ref.reset(seed=0)
    env = CartPole()
    # Force identical physical state.
    phys = np.array([0.01, -0.02, 0.03, 0.04], np.float32)
    ref.state = tuple(phys)
    state = CartPoleState(phys=jnp.asarray(phys), t=jnp.int32(0),
                          rng=jax.random.PRNGKey(0))
    actions = [0, 1, 1, 0, 1, 0, 0, 1, 1, 1]
    for a in actions:
        ref_obs, ref_r, ref_term, _, _ = ref.step(a)
        state, obs, r, term, trunc = env.env_step(state, jnp.int32(a))
        np.testing.assert_allclose(np.asarray(obs), ref_obs, rtol=1e-5,
                                   atol=1e-6)
        assert float(r) == ref_r
        assert bool(term) == ref_term
        if ref_term:
            break


def test_cartpole_truncates_at_500():
    env = CartPole()
    state, obs = env.reset(jax.random.PRNGKey(0))
    state = state._replace(t=jnp.int32(499),
                           phys=jnp.zeros(4))  # balanced: won't terminate
    state, _, _, term, trunc = env.env_step(state, jnp.int32(0))
    assert not bool(term)
    assert bool(trunc)


def test_cartpole_autoreset_vector_step():
    env = CartPole()
    step = jax.jit(env.v_step)
    state, obs = env.v_reset(jax.random.PRNGKey(0), 4)
    for _ in range(600):  # long enough that every env resets at least once
        state, out = step(state, jnp.zeros((4,), jnp.int32))
    assert out.obs.shape == (4, 4)
    # All envs keep valid (non-terminal) current obs thanks to auto-reset.
    assert np.all(np.abs(np.asarray(out.obs)[:, 0]) <= 2.4)


def test_pixel_pong_contract():
    env = PixelPong()
    state, obs = env.reset(jax.random.PRNGKey(0))
    assert obs.shape == (84, 84, 4)
    assert obs.dtype == jnp.uint8
    assert np.asarray(obs).max() == 255  # ball rendered
    total_r = []
    step = jax.jit(env.step)
    for i in range(500):
        state, out = step(state, jnp.int32(i % 6))
        total_r.append(float(out.reward))
    rs = set(np.unique(np.asarray(total_r)))
    assert rs <= {-1.0, 0.0, 1.0}
    assert -1.0 in rs or 1.0 in rs  # someone scored within 500 steps


def test_pixel_pong_episode_ends():
    env = PixelPong(max_steps=300)
    state, _ = env.reset(jax.random.PRNGKey(1))
    step = jax.jit(env.env_step)
    done = False
    for _ in range(301):
        state, _, _, term, trunc = step(state, jnp.int32(0))
        if bool(term) or bool(trunc):
            done = True
            break
    assert done


def test_pixel_catch_contract_and_tracking_policy_wins():
    """Contract checks + semantic sanity: a scripted track-the-ball policy
    must catch (reward +1) every episode — if it can't, the learning test
    in test_pixel_learning.py would be measuring a broken env."""
    from dist_dqn_tpu.envs.pixel_catch import PixelCatch

    env = PixelCatch()
    state, obs = env.reset(jax.random.PRNGKey(0))
    assert obs.shape == (84, 84, 4) and obs.dtype == jnp.uint8
    assert np.asarray(obs).max() == 255
    step = jax.jit(env.step)
    caught = missed = 0
    for _ in range(200):
        # Track: move toward the ball column (state is visible to the
        # script; the LEARNER only ever sees pixels).
        a = jnp.where(state.ball_x < state.pad_x - 1.0, 1,
                      jnp.where(state.ball_x > state.pad_x + 1.0, 2, 0))
        state, out = step(state, a)
        if float(out.reward) > 0:
            caught += 1
        elif float(out.reward) < 0:
            missed += 1
    assert caught >= 5 and missed == 0, (caught, missed)
    # And a always-NOOP policy must miss sometimes (the task is not free).
    state, _ = env.reset(jax.random.PRNGKey(3))
    rewards = []
    for _ in range(300):
        state, out = step(state, jnp.int32(0))
        rewards.append(float(out.reward))
    assert -1.0 in rewards


def test_pixel_pong_framestack_shifts():
    env = PixelPong()
    state, obs = env.reset(jax.random.PRNGKey(2))
    state2, out = env.step(state, jnp.int32(2))
    # New stack's first 3 frames == old stack's last 3.
    np.testing.assert_array_equal(np.asarray(out.obs)[:, :, :3],
                                  np.asarray(obs)[:, :, 1:])
