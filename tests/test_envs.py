"""Env tests: CartPole dynamics vs gymnasium; PixelPong contract checks."""
import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_dqn_tpu.envs.cartpole import CartPole, CartPoleState
from dist_dqn_tpu.envs.pixel_pong import PixelPong


def test_cartpole_matches_gymnasium():
    gymnasium = pytest.importorskip("gymnasium")
    ref = gymnasium.make("CartPole-v1").unwrapped
    ref.reset(seed=0)
    env = CartPole()
    # Force identical physical state.
    phys = np.array([0.01, -0.02, 0.03, 0.04], np.float32)
    ref.state = tuple(phys)
    state = CartPoleState(phys=jnp.asarray(phys), t=jnp.int32(0),
                          rng=jax.random.PRNGKey(0))
    actions = [0, 1, 1, 0, 1, 0, 0, 1, 1, 1]
    for a in actions:
        ref_obs, ref_r, ref_term, _, _ = ref.step(a)
        state, obs, r, term, trunc = env.env_step(state, jnp.int32(a))
        np.testing.assert_allclose(np.asarray(obs), ref_obs, rtol=1e-5,
                                   atol=1e-6)
        assert float(r) == ref_r
        assert bool(term) == ref_term
        if ref_term:
            break


def test_cartpole_truncates_at_500():
    env = CartPole()
    state, obs = env.reset(jax.random.PRNGKey(0))
    state = state._replace(t=jnp.int32(499),
                           phys=jnp.zeros(4))  # balanced: won't terminate
    state, _, _, term, trunc = env.env_step(state, jnp.int32(0))
    assert not bool(term)
    assert bool(trunc)


def test_cartpole_autoreset_vector_step():
    env = CartPole()
    step = jax.jit(env.v_step)
    state, obs = env.v_reset(jax.random.PRNGKey(0), 4)
    for _ in range(600):  # long enough that every env resets at least once
        state, out = step(state, jnp.zeros((4,), jnp.int32))
    assert out.obs.shape == (4, 4)
    # All envs keep valid (non-terminal) current obs thanks to auto-reset.
    assert np.all(np.abs(np.asarray(out.obs)[:, 0]) <= 2.4)


def test_pixel_pong_contract():
    env = PixelPong()
    state, obs = env.reset(jax.random.PRNGKey(0))
    assert obs.shape == (84, 84, 4)
    assert obs.dtype == jnp.uint8
    assert np.asarray(obs).max() == 255  # ball rendered
    total_r = []
    step = jax.jit(env.step)
    for i in range(500):
        state, out = step(state, jnp.int32(i % 6))
        total_r.append(float(out.reward))
    rs = set(np.unique(np.asarray(total_r)))
    assert rs <= {-1.0, 0.0, 1.0}
    assert -1.0 in rs or 1.0 in rs  # someone scored within 500 steps


def test_pixel_pong_episode_ends():
    env = PixelPong(max_steps=300)
    state, _ = env.reset(jax.random.PRNGKey(1))
    step = jax.jit(env.env_step)
    done = False
    for _ in range(301):
        state, _, _, term, trunc = step(state, jnp.int32(0))
        if bool(term) or bool(trunc):
            done = True
            break
    assert done


def test_pixel_catch_contract_and_tracking_policy_wins():
    """Contract checks + semantic sanity: a scripted track-the-ball policy
    must catch (reward +1) every episode — if it can't, the learning test
    in test_pixel_learning.py would be measuring a broken env."""
    from dist_dqn_tpu.envs.pixel_catch import PixelCatch

    env = PixelCatch()
    state, obs = env.reset(jax.random.PRNGKey(0))
    assert obs.shape == (84, 84, 4) and obs.dtype == jnp.uint8
    assert np.asarray(obs).max() == 255
    step = jax.jit(env.step)
    caught = missed = 0
    for _ in range(200):
        # Track: move toward the ball column (state is visible to the
        # script; the LEARNER only ever sees pixels).
        a = jnp.where(state.ball_x < state.pad_x - 1.0, 1,
                      jnp.where(state.ball_x > state.pad_x + 1.0, 2, 0))
        state, out = step(state, a)
        if float(out.reward) > 0:
            caught += 1
        elif float(out.reward) < 0:
            missed += 1
    assert caught >= 5 and missed == 0, (caught, missed)
    # And a always-NOOP policy must miss sometimes (the task is not free).
    state, _ = env.reset(jax.random.PRNGKey(3))
    rewards = []
    for _ in range(300):
        state, out = step(state, jnp.int32(0))
        rewards.append(float(out.reward))
    assert -1.0 in rewards


def test_pixel_pong_framestack_shifts():
    env = PixelPong()
    state, obs = env.reset(jax.random.PRNGKey(2))
    state2, out = env.step(state, jnp.int32(2))
    # New stack's first 3 frames == old stack's last 3.
    np.testing.assert_array_equal(np.asarray(out.obs)[:, :, :3],
                                  np.asarray(obs)[:, :, 1:])


def test_pixel_breakout_contract_and_tracking_policy_scores():
    """Contract + semantic sanity for the second device-native game
    (envs/pixel_breakout.py): FIRE-to-serve gates play, a scripted
    track-the-ball policy scores many bricks without losing a life, and
    a random policy scores little and burns out its 5 lives."""
    from dist_dqn_tpu.envs.pixel_breakout import PixelBreakout

    env = PixelBreakout()
    state, obs = env.reset(jax.random.PRNGKey(0))
    assert obs.shape == (84, 84, 4) and obs.dtype == jnp.uint8
    frame0 = np.asarray(obs)
    assert frame0.max() == 200          # paddle drawn, ball NOT in play
    assert (frame0 == 120).any()        # brick wall drawn
    step = jax.jit(env.env_step)

    # NOOP never serves: no ball, no rewards.
    s = state
    for _ in range(20):
        s, _, r, term, trunc = step(s, jnp.int32(0))
        assert float(r) == 0.0 and not bool(term)
    assert not bool(s.in_play)

    # FIRE serves; the ball renders at 255.
    s, f, _, _, _ = step(s, jnp.int32(1))
    assert bool(s.in_play)
    assert np.asarray(f).max() == 255

    # Scripted tracker: fire when dead, else chase the ball column.
    s = state
    ret = 0.0
    for _ in range(1200):
        if not bool(s.in_play):
            a = 1
        else:
            bx, px = float(s.ball[0]), float(s.pad_x)
            a = 2 if bx > px + 1.0 else (3 if bx < px - 1.0 else 0)
        s, _, r, term, trunc = step(s, jnp.int32(a))
        ret += float(r)
        if bool(term) or bool(trunc):
            break
    assert ret >= 20.0, ret             # measured: 38 bricks by 1500 steps
    assert int(s.lives) == 5            # perfect tracking never loses one

    # Random play: few bricks, loses all lives, episode terminates.
    rng = np.random.RandomState(0)
    s, _ = env.reset(jax.random.PRNGKey(1))
    ret_rand, done = 0.0, False
    for _ in range(1500):
        s, _, r, term, _ = step(s, jnp.int32(int(rng.randint(4))))
        ret_rand += float(r)
        if bool(term):
            done = True
            break
    assert done and int(s.lives) == 0
    assert ret_rand < ret / 2


def test_pixel_breakout_brick_depletes_and_registry():
    from dist_dqn_tpu.envs import make_jax_env
    from dist_dqn_tpu.envs.pixel_breakout import PixelBreakout

    env = make_jax_env("pixel_breakout")
    assert isinstance(env, PixelBreakout)
    # A brick hit removes exactly one brick and bounces the ball.
    state, _ = env.reset(jax.random.PRNGKey(2))
    import dataclasses  # noqa: F401 (parity with file style)
    ball = jnp.asarray([40.0, 37.0, 0.0, -2.0])  # heading into the wall
    state = state._replace(ball=ball, in_play=jnp.bool_(True))
    state2, _, r, _, _ = env.env_step(state, jnp.int32(0))
    assert float(r) == 1.0
    assert float(state.bricks.sum()) - float(state2.bricks.sum()) == 1.0
    assert float(state2.ball[3]) > 0    # vy flipped downward
