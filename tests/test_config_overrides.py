"""--set dotted-path config overrides (config.apply_overrides): typed
coercion, nesting, section/unknown-field errors, and the train CLI
honoring the flag end-to-end."""
import pytest

from dist_dqn_tpu.config import CONFIGS, apply_overrides


def test_typed_coercion_across_field_kinds():
    cfg = apply_overrides(CONFIGS["atari"], [
        "network.dueling=true",
        "network.torso=small",
        "learner.batch_size=64",
        "learner.learning_rate=3e-4",
        "network.mlp_features=128,64",
        "replay.capacity=0x1000",
        "train_every=2",
    ])
    assert cfg.network.dueling is True
    assert cfg.network.torso == "small"
    assert cfg.learner.batch_size == 64
    assert cfg.learner.learning_rate == pytest.approx(3e-4)
    assert cfg.network.mlp_features == (128, 64)
    assert cfg.replay.capacity == 4096
    assert cfg.train_every == 2
    # The source preset is untouched (frozen dataclasses, pure replace).
    assert CONFIGS["atari"].network.dueling is False


def test_int_fields_accept_unambiguous_shorthand():
    """1e6 / 2.5e5 / 200_000 spellings have exactly one integer meaning;
    the coercion takes them. Non-integral floats stay errors (ADVICE
    round 3)."""
    cfg = apply_overrides(CONFIGS["atari"], [
        "replay.capacity=1e6",
        "replay.min_fill=2.5e4",
        "total_env_steps=200_000",
    ])
    assert cfg.replay.capacity == 1_000_000
    assert cfg.replay.min_fill == 25_000
    assert cfg.total_env_steps == 200_000
    with pytest.raises(ValueError, match="batch_size: expected an int"):
        apply_overrides(CONFIGS["atari"], ["learner.batch_size=1.5"])


def test_optional_field_accepts_none_and_bool():
    cfg = apply_overrides(CONFIGS["atari"],
                          ["replay.store_final_obs=true"])
    assert cfg.replay.store_final_obs is True
    cfg = apply_overrides(cfg, ["replay.store_final_obs=none"])
    # Round-trips back to the auto default.
    assert cfg.replay.store_final_obs is None


@pytest.mark.parametrize("bad, hint", [
    ("network.duelling=true", "unknown field"),
    ("network=big", "config section"),
    ("learner.batch_size", "dotted.path=value"),
    ("network.dueling=maybe", "expected a bool"),
    ("network.dueling.x=1", "past a leaf"),
    ("learner.batch_size=abc", "batch_size: expected an int"),
    ("learner.learning_rate=fast", "learning_rate: expected a float"),
])
def test_errors_name_the_problem(bad, hint):
    with pytest.raises(ValueError, match=hint):
        apply_overrides(CONFIGS["atari"], [bad])


def test_train_cli_honors_set(tmp_path, capsys):
    """End-to-end through the real CLI surface: --set reshapes the run."""
    import json
    import sys
    from unittest import mock

    from dist_dqn_tpu.train import main

    argv = ["train", "--config", "cartpole", "--platform", "cpu",
            "--total-env-steps", "600", "--chunk-iters", "150",
            "--set", "actor.num_envs=4",
            "--set", "network.mlp_features=16",
            "--set", "replay.capacity=512",
            "--set", "replay.min_fill=64",
            "--set", "learner.batch_size=16"]
    with mock.patch.object(sys, "argv", argv):
        main()
    rows = [json.loads(line) for line in
            capsys.readouterr().out.splitlines()
            if line.startswith("{")]
    # The CLI's first JSON line is the run manifest (ISSUE 4) — and it
    # must fingerprint the OVERRIDDEN config, not the preset.
    assert rows and rows[0]["manifest"]["config"]["actor"][
        "num_envs"] == 4
    # 4 env lanes (not the preset's 16): 150-iter chunks advance 600
    # frames each.
    metric_rows = [r for r in rows if "env_frames" in r]
    assert metric_rows and metric_rows[0]["env_frames"] == 600


def test_train_cli_eval_zero_disables_without_save_churn(tmp_path, capsys):
    """An explicit --eval-every-steps 0 DISABLES eval (it used to fall
    through a truthiness test to the config period), and the checkpoint
    cadence must not collapse to save-every-chunk when it does."""
    import json
    import os
    import sys
    from unittest import mock

    from dist_dqn_tpu.train import main

    ckpt_dir = str(tmp_path / "ck")
    argv = ["train", "--config", "cartpole", "--platform", "cpu",
            "--total-env-steps", "1200", "--chunk-iters", "100",
            "--eval-every-steps", "0",
            "--checkpoint-dir", ckpt_dir,
            "--set", "actor.num_envs=4",
            "--set", "network.mlp_features=16",
            "--set", "replay.capacity=512",
            "--set", "replay.min_fill=64",
            "--set", "learner.batch_size=16"]
    with mock.patch.object(sys, "argv", argv):
        main()
    rows = [json.loads(line) for line in
            capsys.readouterr().out.splitlines()
            if line.startswith("{") and "env_frames" in line]
    assert rows and all("eval_return" not in r for r in rows)
    # 3 chunks ran; the save cadence fell back to a sane default —
    # first boundary crossing (400) plus the end-of-run save (1200),
    # NOT one per chunk (800 would appear if the cadence collapsed).
    steps = {d for d in os.listdir(ckpt_dir) if d.isdigit()}
    assert steps == {"400", "1200"}


def test_train_cli_reports_bad_set_cleanly(capsys):
    """A bad --set exits via parser.error (clean usage message naming the
    failing path), not a traceback."""
    import sys
    from unittest import mock

    from dist_dqn_tpu.train import main

    argv = ["train", "--config", "cartpole", "--platform", "cpu",
            "--set", "learner.batch_size=abc"]
    with mock.patch.object(sys, "argv", argv):
        with pytest.raises(SystemExit) as exc:
            main()
    assert exc.value.code == 2
    assert "learner.batch_size: expected an int" in capsys.readouterr().err
