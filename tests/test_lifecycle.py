"""Direct coverage for the shared flush lifecycle
(telemetry/lifecycle.py) — previously only exercised implicitly through
SpanTracer/snapshot tests (ISSUE 4 satellite): the run-once latch
(double-flush idempotency), callback ordering, and the atexit-after-
SIGTERM leg that must NOT flush a second time.
"""
import signal
import subprocess
import sys
import time

from dist_dqn_tpu.telemetry import lifecycle


def test_run_callbacks_is_once_only_and_ordered():
    """The latch: a SIGTERM flush followed by the atexit leg (or two
    racing flush paths) runs every callback exactly once, in
    registration order."""
    lifecycle._reset_for_tests()
    try:
        calls = []
        lifecycle.on_exit(lambda: calls.append("a"))
        lifecycle.on_exit(lambda: calls.append("b"))
        lifecycle._run_callbacks()
        assert calls == ["a", "b"]
        lifecycle._run_callbacks()  # second leg: latched, no double flush
        assert calls == ["a", "b"]
    finally:
        lifecycle._reset_for_tests()


def test_late_registration_after_flush_does_not_retrigger():
    """A callback registered AFTER the once-latch fired stays unrun (the
    process is already exiting; surprising late side effects are worse
    than a lost flush) — pins the current contract."""
    lifecycle._reset_for_tests()
    try:
        calls = []
        lifecycle._run_callbacks()
        lifecycle.on_exit(lambda: calls.append("late"))
        lifecycle._run_callbacks()
        assert calls == []
    finally:
        lifecycle._reset_for_tests()


def test_off_exit_deregisters():
    lifecycle._reset_for_tests()
    try:
        calls = []
        fn = lambda: calls.append("x")  # noqa: E731
        lifecycle.on_exit(fn)
        lifecycle.off_exit(fn)
        lifecycle._run_callbacks()
        assert calls == []
        lifecycle.off_exit(fn)  # absent: no-op, no raise
    finally:
        lifecycle._reset_for_tests()


def _run_child(code: str, sig=None, timeout=30):
    """Run a child that writes `ready` when set up; optionally signal it;
    return the completed process."""
    proc = subprocess.Popen([sys.executable, "-c", code],
                            stdout=subprocess.PIPE, text=True)
    if sig is None:
        proc.wait(timeout=timeout)
        return proc
    deadline = time.time() + timeout
    line = proc.stdout.readline()
    assert line.strip() == "ready", f"child never became ready: {line!r}"
    assert time.time() < deadline
    proc.send_signal(sig)
    proc.wait(timeout=timeout)
    return proc


def test_sigterm_flushes_once_then_exits_128_plus_signum(tmp_path):
    """SIGTERM ordering: the handler runs the callbacks, the chained
    atexit leg must not run them again, and with no pre-existing handler
    the process exits 128+SIGTERM."""
    out = tmp_path / "flushes.txt"
    code = (
        "import sys\n"
        "from dist_dqn_tpu.telemetry import lifecycle\n"
        "lifecycle.on_exit(lambda: open(%r, 'a').write('flush\\n'))\n"
        "print('ready', flush=True)\n"
        "import time; time.sleep(60)\n" % str(out))
    proc = _run_child(code, sig=signal.SIGTERM)
    assert proc.returncode == 128 + signal.SIGTERM
    assert out.read_text() == "flush\n"  # exactly once


def test_sigterm_chains_preexisting_handler_after_flush(tmp_path):
    """A SIGTERM handler installed BEFORE the lifecycle (device_cleanup
    does this in accelerator entry points) still runs — after the flush
    callbacks, and the flush still happens exactly once."""
    out = tmp_path / "order.txt"
    code = (
        "import os, signal, sys, time\n"
        "def prev(signum, frame):\n"
        "    open(%r, 'a').write('prev\\n')\n"
        "    os._exit(7)\n"
        "signal.signal(signal.SIGTERM, prev)\n"
        "from dist_dqn_tpu.telemetry import lifecycle\n"
        "lifecycle.on_exit(lambda: open(%r, 'a').write('flush\\n'))\n"
        "print('ready', flush=True)\n"
        "time.sleep(60)\n" % (str(out), str(out)))
    proc = _run_child(code, sig=signal.SIGTERM)
    assert proc.returncode == 7  # the chained handler decided the exit
    assert out.read_text() == "flush\nprev\n"


def test_normal_exit_flushes_via_atexit(tmp_path):
    out = tmp_path / "flushes.txt"
    code = (
        "from dist_dqn_tpu.telemetry import lifecycle\n"
        "lifecycle.on_exit(lambda: open(%r, 'a').write('flush\\n'))\n"
        % str(out))
    proc = _run_child(code)
    assert proc.returncode == 0
    assert out.read_text() == "flush\n"
