"""Unit tests for ops/losses.py against brute-force numpy references."""
import jax
import jax.numpy as jnp
import numpy as np

from dist_dqn_tpu.ops import losses


def test_huber_values():
    x = jnp.array([-2.0, -0.5, 0.0, 0.5, 2.0])
    out = losses.huber(x, delta=1.0)
    expected = np.array([1.5, 0.125, 0.0, 0.125, 1.5])
    np.testing.assert_allclose(out, expected, rtol=1e-6)


def test_n_step_from_rollout_matches_bruteforce():
    rng = np.random.default_rng(0)
    T, n = 12, 4
    rewards = rng.normal(size=(T,)).astype(np.float32)
    discounts = (0.9 * rng.integers(0, 2, size=(T,))).astype(np.float32)
    got_r, got_d = losses.n_step_from_rollout(
        jnp.asarray(rewards), jnp.asarray(discounts), n)
    for t in range(T - n + 1):
        acc, d = 0.0, 1.0
        for k in range(n):
            acc += d * rewards[t + k]
            d *= discounts[t + k]
        np.testing.assert_allclose(got_r[t], acc, rtol=1e-5)
        np.testing.assert_allclose(got_d[t], d, rtol=1e-5)


def test_value_rescale_roundtrip():
    x = jnp.linspace(-300.0, 300.0, 101)
    y = losses.inv_value_rescale(losses.value_rescale(x))
    np.testing.assert_allclose(y, x, rtol=1e-4, atol=1e-3)


def test_double_q_bootstrap_picks_online_argmax():
    q_online = jnp.array([[1.0, 5.0, 2.0], [9.0, 0.0, 1.0]])
    q_target = jnp.array([[10.0, 20.0, 30.0], [40.0, 50.0, 60.0]])
    out = losses.double_q_bootstrap(q_online, q_target)
    np.testing.assert_allclose(out, [20.0, 40.0])


def _naive_projection(atoms, probs, rewards, discounts):
    """Scalar-loop reference for the C51 categorical projection."""
    m = len(atoms)
    v_min, v_max = atoms[0], atoms[-1]
    dz = (v_max - v_min) / (m - 1)
    out = np.zeros_like(probs)
    for i in range(probs.shape[0]):
        for j in range(m):
            tz = np.clip(rewards[i] + discounts[i] * atoms[j], v_min, v_max)
            b = (tz - v_min) / dz
            low, high = int(np.floor(b)), int(np.ceil(b))
            if low == high:
                out[i, low] += probs[i, j]
            else:
                out[i, low] += probs[i, j] * (high - b)
                out[i, high] += probs[i, j] * (b - low)
    return out


def test_categorical_projection_matches_naive():
    rng = np.random.default_rng(1)
    m, batch = 21, 16
    atoms = np.linspace(-5.0, 5.0, m).astype(np.float32)
    logits = rng.normal(size=(batch, m)).astype(np.float32)
    probs = np.exp(logits) / np.exp(logits).sum(-1, keepdims=True)
    rewards = rng.uniform(-3, 3, size=(batch,)).astype(np.float32)
    discounts = rng.choice([0.0, 0.97], size=(batch,)).astype(np.float32)
    got = losses.categorical_projection(
        jnp.asarray(atoms), jnp.asarray(probs), jnp.asarray(rewards),
        jnp.asarray(discounts))
    want = _naive_projection(atoms, probs, rewards, discounts)
    np.testing.assert_allclose(got, want, rtol=1e-4, atol=1e-5)
    np.testing.assert_allclose(np.asarray(got).sum(-1), 1.0, rtol=1e-5)


def test_categorical_td_loss_gradient_direction():
    """Cross-entropy loss should pull predicted dist toward the target."""
    m = 11
    atoms = jnp.linspace(-1.0, 1.0, m)
    target = jax.nn.one_hot(7, m)
    logits = jnp.zeros((1, 2, m))
    actions = jnp.array([0])

    def f(lg):
        return losses.categorical_td_loss(lg, actions, target[None]).sum()

    g = jax.grad(f)(logits)
    # Gradient wrt the chosen action's logit at the target atom is negative
    # (increasing it lowers the loss); untouched action has zero grad.
    assert g[0, 0, 7] < 0
    np.testing.assert_allclose(g[0, 1], 0.0, atol=1e-7)
