"""Tier-1 wiring for the metric-emission lint (scripts/check_metrics.py):
new code must record through the telemetry registry, not grow ad-hoc
``print(json.dumps(...))`` metric call sites."""
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_no_new_direct_metric_emission():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_metrics.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr or proc.stdout


def test_lint_catches_a_new_call_site(tmp_path):
    """The lint must actually bite: a synthetic tree with an unlisted
    emission site fails."""
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_metrics", REPO / "scripts" / "check_metrics.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    pkg = tmp_path / "dist_dqn_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text("print(json.dumps({'m': 1}))\n")
    counts = mod.scan(tmp_path)
    assert counts == {"dist_dqn_tpu/rogue.py": 1}
    assert counts["dist_dqn_tpu/rogue.py"] > mod.ALLOWLIST.get(
        "dist_dqn_tpu/rogue.py", 0)
