"""Tier-1 wiring for the metric-emission lint (scripts/check_metrics.py):
new code must record through the telemetry registry, not grow ad-hoc
``print(json.dumps(...))`` metric call sites."""
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_no_new_direct_metric_emission():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_metrics.py")],
        capture_output=True, text=True, timeout=60)
    assert proc.returncode == 0, proc.stderr or proc.stdout


def _load_lint():
    import importlib.util

    spec = importlib.util.spec_from_file_location(
        "check_metrics", REPO / "scripts" / "check_metrics.py")
    mod = importlib.util.module_from_spec(spec)
    spec.loader.exec_module(mod)
    return mod


def test_lint_catches_a_new_call_site(tmp_path):
    """The lint must actually bite: a synthetic tree with an unlisted
    emission site fails."""
    mod = _load_lint()
    pkg = tmp_path / "dist_dqn_tpu"
    pkg.mkdir()
    (pkg / "rogue.py").write_text("print(json.dumps({'m': 1}))\n")
    counts = mod.scan(tmp_path)
    assert counts == {"dist_dqn_tpu/rogue.py": 1}
    assert counts["dist_dqn_tpu/rogue.py"] > mod.ALLOWLIST.get(
        "dist_dqn_tpu/rogue.py", 0)


def test_docs_drift_check_catches_undocumented_family(tmp_path):
    """ISSUE 5 satellite: a dqn_* family registered in code but absent
    from docs/observability.md must fail the lint — including the
    multi-line constant spelling collectors.py uses."""
    mod = _load_lint()
    pkg = tmp_path / "dist_dqn_tpu"
    tele = pkg / "telemetry"
    tele.mkdir(parents=True)
    (tele / "collectors.py").write_text(
        'DOCUMENTED = "dqn_documented_total"\n'
        'WRAPPED = \\\n    "dqn_wrapped_but_undocumented_total"\n')
    (pkg / "loopy.py").write_text(
        'c = reg.counter(\n    "dqn_registered_elsewhere_total",\n'
        '    "help text")\n'
        'g = reg.gauge("dqn_documented", "a PREFIX of the doc name")\n')
    docs = tmp_path / "docs"
    docs.mkdir()
    (docs / "observability.md").write_text(
        "only `dqn_documented_total` is in the table\n")
    names = mod.scan_metric_names(tmp_path)
    assert names == {"dqn_documented", "dqn_documented_total",
                     "dqn_wrapped_but_undocumented_total",
                     "dqn_registered_elsewhere_total"}
    # dqn_documented is a substring of the documented dqn_documented_
    # total but is NOT itself documented — whole-name matching must
    # still flag it.
    missing = mod.check_docs(tmp_path)
    assert missing == ["dqn_documented",
                       "dqn_registered_elsewhere_total",
                       "dqn_wrapped_but_undocumented_total"]


def test_docs_allowlist_entries_are_real():
    """Every DOCS_ALLOWLIST entry must still be registered somewhere —
    a stale entry means the family was removed or documented and the
    allowlist should shrink."""
    mod = _load_lint()
    names = mod.scan_metric_names(REPO)
    for allowed in mod.DOCS_ALLOWLIST:
        assert allowed in names, (
            f"{allowed} is allowlisted but no longer registered — "
            "drop it from DOCS_ALLOWLIST")
