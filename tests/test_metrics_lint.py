"""Thin compatibility shim (ISSUE 13, one release): the metric-emission
lint migrated into ``dist_dqn_tpu/analysis/plugins/metrics.py`` and its
bite tests into tests/test_dqnlint.py. This file keeps the historical
test name + the legacy entry point's verdict pinned so external
references (CI configs, docs) don't break."""
import subprocess
import sys
from pathlib import Path

REPO = Path(__file__).resolve().parent.parent


def test_no_new_direct_metric_emission():
    proc = subprocess.run(
        [sys.executable, str(REPO / "scripts" / "check_metrics.py")],
        capture_output=True, text=True, timeout=120)
    assert proc.returncode == 0, proc.stderr or proc.stdout
