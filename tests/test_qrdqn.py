"""QR-DQN: quantile-regression distributional head + loss (Dabney 2018).

The second distributional family next to C51 — checked against a numpy
reference for the loss op, against known quantile-regression behavior for
the estimator (quantiles of a fixed target distribution), and end-to-end
through the fused loop.
"""
import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_dqn_tpu.config import CONFIGS
from dist_dqn_tpu.models import build_network
from dist_dqn_tpu.ops import losses


def _np_quantile_huber(theta, target, kappa=1.0):
    B, N = theta.shape
    M = target.shape[1]
    tau = (np.arange(N) + 0.5) / N
    out = np.zeros(B)
    for b in range(B):
        acc = 0.0
        for i in range(N):
            for j in range(M):
                u = target[b, j] - theta[b, i]
                au = abs(u)
                hub = 0.5 * u * u if au <= kappa else \
                    kappa * (au - 0.5 * kappa)
                acc += abs(tau[i] - (u < 0)) * hub / kappa / M
        out[b] = acc
    return out


def test_quantile_huber_matches_numpy_reference():
    r = np.random.default_rng(0)
    theta = r.normal(size=(4, 5)).astype(np.float32)
    target = r.normal(size=(4, 7)).astype(np.float32)
    got = losses.quantile_huber_td(jnp.asarray(theta), jnp.asarray(target),
                                   kappa=1.0)
    np.testing.assert_allclose(np.asarray(got),
                               _np_quantile_huber(theta, target),
                               rtol=1e-5, atol=1e-5)


def test_quantile_regression_recovers_distribution_quantiles():
    """Gradient descent on the loss drives N=3 predicted quantiles to the
    quantile midpoints of a discrete uniform target {0, 10}: tau-hats
    (1/6, 3/6, 5/6) -> quantiles (0, ~anything in the atom gap, 10); the
    outer two must converge to the atoms."""
    target = jnp.asarray(np.array([[0.0, 10.0]] * 1, np.float32))
    theta = jnp.zeros((1, 3)) + 5.0

    @jax.jit
    def step(theta):
        g = jax.grad(
            lambda t: jnp.sum(losses.quantile_huber_td(t, target)))(theta)
        return theta - 0.05 * g

    for _ in range(3000):
        theta = step(theta)
    vals = np.sort(np.asarray(theta)[0])
    assert abs(vals[0] - 0.0) < 0.3, vals
    assert abs(vals[2] - 10.0) < 0.3, vals


def test_double_q_select_uses_mean_over_quantiles():
    theta_sel = jnp.asarray(
        np.array([[[0.0, 10.0], [4.0, 4.1]]], np.float32))  # means: 5, 4.05
    theta_tgt = jnp.asarray(
        np.array([[[1.0, 2.0], [7.0, 8.0]]], np.float32))
    out = losses.quantile_double_q_select(theta_sel, theta_tgt)
    np.testing.assert_allclose(np.asarray(out), [[1.0, 2.0]])  # action 0


def test_qr_network_shapes_and_q_values():
    cfg = CONFIGS["qrdqn"]
    net_cfg = dataclasses.replace(cfg.network, torso="mlp",
                                  mlp_features=(16,), hidden=0, num_atoms=8,
                                  compute_dtype="float32")
    net = build_network(net_cfg, 4)
    obs = jnp.zeros((3, 6))
    params = net.init(jax.random.PRNGKey(0), obs)
    theta = net.apply(params, obs)
    assert theta.shape == (3, 4, 8)
    q = net.apply(params, obs, method=net.q_values)
    assert q.shape == (3, 4)
    np.testing.assert_allclose(np.asarray(q),
                               np.asarray(theta).mean(-1), rtol=1e-6)


def test_qr_learner_step_runs_and_reports_priorities():
    from benchmarks.learner_bench import _feedforward_case

    cfg = CONFIGS["qrdqn"]
    cfg = dataclasses.replace(
        cfg,
        network=dataclasses.replace(cfg.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    num_atoms=16, compute_dtype="float32"),
        learner=dataclasses.replace(cfg.learner, batch_size=8))
    import benchmarks.learner_bench as lb
    old = lb.OBS_SHAPE
    lb.OBS_SHAPE = (12,)
    try:
        state, step, args = _feedforward_case(cfg)
    finally:
        lb.OBS_SHAPE = old
    state, metrics = step(state, *args)
    assert metrics["priorities"].shape == (8,)
    assert np.isfinite(float(metrics["loss"]))
    assert (np.asarray(metrics["priorities"]) >= 0).all()


@pytest.mark.slow
def test_qrdqn_fused_loop_learns_cartpole():
    """The full combination learns: QR head + PER + double-Q through the
    fused on-device loop clears a clearly-better-than-random return."""
    from fused_cartpole import run_scaled_cartpole

    ret, metrics = run_scaled_cartpole(CONFIGS["qrdqn"],
                                       dict(num_atoms=11))
    assert ret >= 150.0, (ret, metrics)
