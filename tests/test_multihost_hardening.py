"""Adversarial multi-host coverage (VERDICT round 1, weak #6 / next #7).

Round 1's multi-host evidence was 2 processes x 1 device with no faults.
These tests scale the REAL collective apex learner to a 4-process group
and a 2-process x 4-device group, and inject the failure modes the
lockstep design argues about in comments:

  * one DELAYED host (joins its first agreement seconds late — peers must
    block and then proceed, not desync),
  * one actor KILLED mid-run (supervision must respawn it and the host
    must stay in lockstep),
  * a PEER DEATH between agreements (survivors must fail fast via the
    agree() timeout instead of wedging forever — the advisor's round-1
    medium finding).

All workers assert the lockstep invariant at exit: every host executed
the SAME number of collective train steps.
"""
import os
import socket
import subprocess
import sys
import textwrap
from pathlib import Path

import pytest

REPO = Path(__file__).resolve().parent.parent

pytestmark = pytest.mark.slow  # real multi-process runs, minutes on 1 core

_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = (
        "--xla_force_host_platform_device_count=" + sys.argv[4])
    os.environ["JAX_PLATFORMS"] = "cpu"
    sys.path.insert(0, {repo!r})

    def _start_actor_killer():
        # Kill one of THIS host's actor processes a few seconds into the
        # run; supervision must respawn it (actor_restarts >= 1) without
        # breaking the collective cadence.
        import multiprocessing as mp
        import signal, threading, time

        def killer():
            deadline = time.time() + 60
            while time.time() < deadline:
                kids = mp.active_children()
                if kids:
                    time.sleep(4.0)  # let it stream some records first
                    os.kill(kids[0].pid, signal.SIGKILL)
                    return
                time.sleep(0.2)

        threading.Thread(target=killer, daemon=True).start()

    def main():
        import jax
        jax.config.update("jax_platforms", "cpu")
        port, pid = int(sys.argv[1]), int(sys.argv[2])
        nprocs, devs = int(sys.argv[3]), int(sys.argv[4])
        from dist_dqn_tpu.parallel.distributed import initialize
        initialize(f"localhost:{{port}}", nprocs, pid)
        assert jax.device_count() == nprocs * devs
        assert jax.local_device_count() == devs
        import time
        if pid == 1 and nprocs >= 4:
            # Delayed host: peers reach their first agreement and must
            # BLOCK until this host joins, then continue in lockstep.
            # (Only injected in the 4-host test; in the 2-host x 4-device
            # run the delay plus per-host min_fill gating can eat the whole
            # short training window.)
            time.sleep(2.0)
        if pid == nprocs - 1 and nprocs >= 4:
            _start_actor_killer()
        import dataclasses
        from dist_dqn_tpu.actors.service import ApexRuntimeConfig, run_apex
        from dist_dqn_tpu.config import CONFIGS
        cfg = CONFIGS["apex"]
        cfg = dataclasses.replace(
            cfg,
            network=dataclasses.replace(cfg.network, torso="mlp",
                                        mlp_features=(32,), hidden=0,
                                        dueling=False,
                                        compute_dtype="float32"),
            replay=dataclasses.replace(cfg.replay, capacity=4096,
                                       min_fill=128),
            # GLOBAL batch: divides nprocs * devs devices in both configs.
            learner=dataclasses.replace(cfg.learner, batch_size=32,
                                        n_step=2),
        )
        total = 1600 if nprocs >= 4 else 2400
        rt = ApexRuntimeConfig(host_env="CartPole-v1", num_actors=1,
                               envs_per_actor=4, total_env_steps=total,
                               inserts_per_grad_step=32,
                               sync_every_s=0.02,
                               eval_every_steps=total // 2, eval_episodes=2)
        result = run_apex(cfg, rt, log_fn=print)
        assert result["global_env_steps"] >= total, result
        assert result["env_steps"] > 0
        assert result["grad_steps"] >= 5, result
        assert result["ring_dropped"] == 0 and result["bad_records"] == 0
        if pid == nprocs - 1 and nprocs >= 4:
            assert result["actor_restarts"] >= 1, result
        print("MH_OK", pid, result["grad_steps"], flush=True)

    if __name__ == "__main__":
        main()
""")

_AGREE_WORKER = textwrap.dedent("""
    import os, sys
    os.environ["XLA_FLAGS"] = "--xla_force_host_platform_device_count=1"
    os.environ["JAX_PLATFORMS"] = "cpu"
    # Round 1 budget is generous: the first agree() pays jit compile +
    # gloo init, which can exceed 12s when the box is contended (the
    # full suite runs everything on 1 core — this raced and flaked in
    # round 4). The 12s fail-fast budget under test is set just before
    # round 2; agree() reads the env var per call.
    os.environ["DQN_AGREE_TIMEOUT_S"] = "180"
    sys.path.insert(0, {repo!r})

    def main():
        import numpy as np
        import jax
        jax.config.update("jax_platforms", "cpu")
        port, pid = int(sys.argv[1]), int(sys.argv[2])
        from dist_dqn_tpu.parallel.distributed import initialize
        initialize(f"localhost:{{port}}", 2, pid)
        from dist_dqn_tpu.actors.multihost import MultihostLearner
        mh = MultihostLearner()
        out = mh.agree(np.array([pid + 1]))
        assert int(out[0]) == 3, out  # both joined round 1
        os.environ["DQN_AGREE_TIMEOUT_S"] = "12"  # the budget under test
        if pid == 0:
            # Die between agreements (uncaught-error stand-in). The
            # surviving peer must NOT hang in round 2.
            print("P0_EXITING", flush=True)
            os._exit(17)
        try:
            mh.agree(np.array([5]))
            print("AGREE_COMPLETED_UNEXPECTEDLY", flush=True)
        except Exception as e:
            # RuntimeError from the watchdog timeout, or a collective
            # error surfaced by the dead peer — either is fail-fast.
            print("AGREE_FAILFAST_OK", type(e).__name__, flush=True)
            if "incomplete after" in str(e):
                # Timeout path: the learner must now be POISONED — the
                # worker thread is still parked in the psum, so a second
                # collective must be refused, not issued (ADVICE round 2).
                try:
                    mh.agree(np.array([5]))
                    print("POISON_MISSING", flush=True)
                except RuntimeError as e2:
                    marker = ("POISON_OK" if "poisoned" in str(e2)
                              else "POISON_MISSING")
                    print(marker, flush=True)
        # NOTE: jax's coordination service may also detect the peer death
        # and fatally terminate this process right after the marker prints
        # (absl FATAL in client.h) — that too is fail-fast, so the parent
        # test checks the marker, not the exit code.
        sys.exit(0)

    if __name__ == "__main__":
        main()
""")


def _free_port() -> int:
    s = socket.socket()
    s.bind(("localhost", 0))
    port = s.getsockname()[1]
    s.close()
    return port


def _launch(script_text, tmp_path, args_per_proc, timeout):
    script = tmp_path / "mh_worker.py"
    script.write_text(script_text.format(repo=str(REPO)))
    env = {k: v for k, v in os.environ.items()
           if k not in ("XLA_FLAGS", "JAX_PLATFORMS")}
    procs = [
        subprocess.Popen([sys.executable, str(script)] + [str(a) for a in
                                                          args],
                         stdout=subprocess.PIPE, stderr=subprocess.STDOUT,
                         env=env, cwd=str(REPO), text=True)
        for args in args_per_proc
    ]
    outs = []
    try:
        for p in procs:
            out, _ = p.communicate(timeout=timeout)
            outs.append(out)
    finally:
        for p in procs:
            p.kill()
    return procs, outs


def test_four_host_apex_with_churn(tmp_path):
    """4 processes x 1 device: delayed host + SIGKILLed actor, lockstep
    grad counts agree, async eval logs on host 0."""
    port = _free_port()
    procs, outs = _launch(
        _WORKER, tmp_path,
        [(port, pid, 4, 1) for pid in range(4)], timeout=560)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"MH_OK {pid}" in out, out[-2000:]
    grads = {out.split("MH_OK")[1].split()[1] for out in outs}
    assert len(grads) == 1, grads  # identical collective step count
    assert "eval_return" in outs[0]
    assert all("eval_return" not in o for o in outs[1:])


def test_two_host_four_device_slices(tmp_path):
    """2 processes x 4 devices: the global mesh has multi-device host
    slices, so the collective batch shards WITHIN hosts as well as across
    them (ICI + DCN axes of the real pod layout)."""
    port = _free_port()
    procs, outs = _launch(
        _WORKER, tmp_path,
        [(port, pid, 2, 4) for pid in range(2)], timeout=560)
    for pid, (p, out) in enumerate(zip(procs, outs)):
        assert p.returncode == 0, f"proc {pid} failed:\n{out[-4000:]}"
        assert f"MH_OK {pid}" in out, out[-2000:]
    grads = {out.split("MH_OK")[1].split()[1] for out in outs}
    assert len(grads) == 1, grads


def test_agree_fails_fast_when_peer_dies(tmp_path):
    """The advisor's medium finding: a dead peer must not wedge the fleet.
    Process 0 exits between agreements; process 1's next agree() must
    raise within the DQN_AGREE_TIMEOUT_S budget, not block forever."""
    port = _free_port()
    procs, outs = _launch(
        _AGREE_WORKER, tmp_path,
        [(port, pid) for pid in range(2)], timeout=240)
    assert procs[0].returncode == 17, outs[0][-2000:]
    assert "P0_EXITING" in outs[0]
    # The survivor must terminate promptly (the 240s communicate() above
    # bounds it) without HANGING in round 2. Two legitimate fail-fast
    # outcomes race: (a) agree() returns control with an exception — the
    # marker proves it; (b) jax's coordination service notices the dead
    # peer first and fatally terminates the survivor (absl FATAL in
    # client.h) BEFORE the marker can print — also fail-fast. Only a
    # hang (no marker, no coordination-death signature, killed by the
    # 240s bound) fails.
    survivor = outs[1]
    # Tight death signature: absl FATAL aborts (negative rc from the
    # signal, or the FATAL/Check-failure log line). Routine jax
    # "coordination" INFO lines must NOT qualify — an AssertionError
    # exit (rc=1, no FATAL text) has to keep failing this test.
    coord_death = (procs[1].returncode < 0
                   or "FATAL" in survivor or "Check failure" in survivor)
    assert "AGREE_FAILFAST_OK" in survivor or coord_death, survivor[-2000:]
    # If the fail-fast came from the watchdog timeout, the follow-up
    # agree() must have been refused by the poison guard.
    assert "POISON_MISSING" not in outs[1], outs[1][-2000:]
