"""Driver-contract checks for __graft_entry__ (VERDICT round 1, item #1).

``dryrun_multichip`` must finish well inside the driver's capture timeout
even when the calling process cannot provide a sane backend (wedged TPU
tunnel, no env forcing) — the subprocess design makes the caller's backend
state irrelevant, which is exactly what these tests exercise by calling it
from the CPU-forced pytest process.
"""
import os
import subprocess
import sys
import time

import pytest

REPO = os.path.dirname(os.path.dirname(os.path.abspath(__file__)))
sys.path.insert(0, REPO)


def test_entry_compiles():
    import jax

    import __graft_entry__ as g

    fn, args = g.entry()
    out = jax.jit(fn).lower(*args).compile()(*args)
    assert out.shape[0] == 8


def test_dryrun_multichip_inside_driver_budget():
    """The judge's acceptance check: timeout 120 ... dryrun_multichip(8)."""
    import __graft_entry__ as g

    t0 = time.monotonic()
    g.dryrun_multichip(8)
    assert time.monotonic() - t0 < 120.0


@pytest.mark.slow
def test_dryrun_multichip_survives_hostile_env():
    """Caller env pointing at a nonexistent platform must not matter."""
    env = dict(os.environ)
    env["JAX_PLATFORMS"] = "tpu"  # would hang/fail if inherited verbatim
    code = "import __graft_entry__ as g; g.dryrun_multichip(4)"
    proc = subprocess.run([sys.executable, "-c", code], env=env, cwd=REPO,
                          timeout=120)
    assert proc.returncode == 0
