"""On-device replay-ratio engine (ISSUE 6): more grad steps per
collected chunk must change HOW MANY updates run, never WHAT each one
computes.

The load-bearing assertions:

* the FUSED EQUIVALENCE pin: ``replay.updates_per_chunk=N`` draws the
  same N batches — and lands the same params, bit for bit — as the
  pre-existing ``updates_per_train=N`` serial scan (same key stream:
  the ratio multiplies the scan length, it does not re-derive keys);
  the mirror of PR 5's uniform prefetch pin;
* the RATIO-1 pin: the default config runs the exact pre-knob program
  (param checksums equal with the knobs at their defaults, explicit
  ratio 1, and an explicit float32 actor dtype);
* the PER WRITE-BACK pin: N sub-steps' priority updates collapse to ONE
  flush with deterministic chronological last-wins on slots several
  sub-steps sampled (replay/prioritized_device.py
  prioritized_ring_update_batched over device.last_write_wins_scatter);
* the APEX SCAN pin: ``make_scan_train`` over N stacked batches ==
  N jitted serial train steps, bit for bit, priorities concatenated in
  sub-step order;
* the DONATION AUDIT: the compiled fused chunk aliases its donated
  carry completely (alias_bytes == argument bytes on this backend) at
  every ratio — the "no unintended device copies" check from the
  jax.stages evidence (utils/donation.py).
"""
from __future__ import annotations

import dataclasses

import jax
import jax.numpy as jnp
import numpy as np
import pytest

from dist_dqn_tpu.config import CONFIGS
from dist_dqn_tpu.envs import make_jax_env
from dist_dqn_tpu.models import build_network
from dist_dqn_tpu.train_loop import make_fused_train


def _tiny_cfg(ratio=1, upt=1, prioritized=False, actor_dtype="float32",
              train_batch=0):
    cfg = CONFIGS["cartpole"]
    return dataclasses.replace(
        cfg,
        actor=dataclasses.replace(cfg.actor, num_envs=8),
        network=dataclasses.replace(cfg.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    compute_dtype="float32",
                                    actor_dtype=actor_dtype),
        replay=dataclasses.replace(cfg.replay, capacity=2048, min_fill=64,
                                   prioritized=prioritized,
                                   updates_per_chunk=ratio,
                                   train_batch=train_batch),
        learner=dataclasses.replace(cfg.learner, batch_size=16),
        updates_per_train=upt,
    )


def _run_fused(cfg, chunks=3, iters=40):
    env = make_jax_env(cfg.env_name)
    net = build_network(cfg.network, env.num_actions)
    init, run_chunk = make_fused_train(cfg, env, net)
    run = jax.jit(run_chunk, static_argnums=1, donate_argnums=0)
    carry = init(jax.random.PRNGKey(0))
    metrics = None
    for _ in range(chunks):
        carry, metrics = run(carry, iters)
    checksum = float(sum(
        np.float64(np.sum(np.asarray(leaf, np.float64)))
        for leaf in jax.tree.leaves(jax.device_get(carry.learner.params))))
    return carry, jax.device_get(metrics), checksum


def test_fused_ratio_equals_serial_updates():
    """THE equivalence pin: ratio N == updates_per_train N, bit for bit
    (same scan length, same key stream), with N x the grad steps."""
    _, m1, ck1 = _run_fused(_tiny_cfg(ratio=1))
    _, m4, ck4 = _run_fused(_tiny_cfg(ratio=4))
    _, mu, cku = _run_fused(_tiny_cfg(ratio=1, upt=4))
    assert float(m4["grad_steps_in_chunk"]) == \
        4 * float(m1["grad_steps_in_chunk"]) > 0
    assert ck4 == cku
    assert np.isfinite(ck4)


def test_fused_ratio1_default_program_unchanged():
    """Ratio 1 + float32 actor dtype + train_batch 0 IS the pre-knob
    program: explicit defaults and implicit defaults land identical
    params (the param_checksum A/B pin guarding the dtype split)."""
    _, _, ck_default = _run_fused(_tiny_cfg())
    _, _, ck_explicit = _run_fused(
        _tiny_cfg(ratio=1, actor_dtype="float32", train_batch=0))
    assert ck_default == ck_explicit


def test_fused_per_ratio_runs_and_scales():
    """PER + ratio: the deferred last-wins flush path compiles, trains,
    scales the grad count, and stays finite."""
    _, m1, _ = _run_fused(_tiny_cfg(ratio=1, prioritized=True))
    carry, m4, ck = _run_fused(_tiny_cfg(ratio=4, prioritized=True))
    assert float(m4["grad_steps_in_chunk"]) == \
        4 * float(m1["grad_steps_in_chunk"]) > 0
    assert np.isfinite(ck)
    # The flush really landed: the priority plane moved off its
    # max-priority seeding for sampled slots.
    prios = np.asarray(carry.replay.priorities)
    assert (prios[prios > 0] != float(carry.replay.max_priority)).any()


def test_actor_dtype_split_keeps_fp32_masters():
    """bf16 acting must never touch the learner's master params: every
    float leaf stays float32 and the run stays finite."""
    carry, metrics, ck = _run_fused(_tiny_cfg(ratio=2,
                                              actor_dtype="bfloat16"))
    for leaf in jax.tree.leaves(carry.learner.params):
        if jnp.issubdtype(leaf.dtype, jnp.floating):
            assert leaf.dtype == jnp.float32
    assert np.isfinite(ck)
    assert float(metrics["grad_steps_in_chunk"]) > 0


def test_train_batch_pow2_bucketing():
    """replay.train_batch widens the train event batch to the next
    power of two; 0 keeps learner.batch_size exactly."""
    from dist_dqn_tpu import loop_common

    assert loop_common.resolve_train_batch(_tiny_cfg()) == 16
    assert loop_common.resolve_train_batch(
        _tiny_cfg(train_batch=24)) == 32
    assert loop_common.resolve_train_batch(
        _tiny_cfg(train_batch=32)) == 32
    with pytest.raises(ValueError):
        loop_common.resolve_replay_ratio(_tiny_cfg(ratio=0))
    with pytest.raises(ValueError):
        loop_common.make_actor_param_cast("float16")
    # And the fused loop actually trains at the widened width.
    _, m, ck = _run_fused(_tiny_cfg(train_batch=24))
    assert np.isfinite(ck) and float(m["grad_steps_in_chunk"]) > 0


def test_per_batched_writeback_last_wins():
    """N sub-steps' updates collapse to one flush; a slot sampled by
    several sub-steps ends at the LAST sub-step's |TD| (+eps),
    deterministically — not whichever XLA's scatter applied last."""
    from dist_dqn_tpu.replay import prioritized_device as pring

    state = pring.prioritized_ring_init(8, 4, jnp.zeros((2,), jnp.float32))
    # Three "sub-steps" of two rows each; slot (1, 2) written by sub-
    # steps 0 and 2, slot (3, 1) by sub-steps 1 and 2.
    t_idx = jnp.array([[1, 3], [3, 5], [1, 3]], jnp.int32)
    b_idx = jnp.array([[2, 1], [1, 0], [2, 1]], jnp.int32)
    prios = jnp.array([[10.0, 20.0], [30.0, 40.0], [1.0, 2.0]])
    out = pring.prioritized_ring_update_batched(state, t_idx, b_idx,
                                                prios, eps=0.5)
    got = np.asarray(out.priorities)
    assert got[1, 2] == pytest.approx(1.0 + 0.5)    # last writer: step 2
    assert got[3, 1] == pytest.approx(2.0 + 0.5)    # last writer: step 2
    assert got[5, 0] == pytest.approx(40.0 + 0.5)   # single writer
    assert float(out.max_priority) == pytest.approx(40.5)
    # Jitted path (how the chunk program runs it) agrees.
    out_j = jax.jit(pring.prioritized_ring_update_batched,
                    static_argnames=("eps",))(state, t_idx, b_idx, prios,
                                              eps=0.5)
    np.testing.assert_array_equal(got, np.asarray(out_j.priorities))


def test_last_write_wins_scatter_matches_serial_loop():
    """Property check against the obvious serial reference on random
    collision-heavy index streams."""
    from dist_dqn_tpu.replay.device import last_write_wins_scatter

    rng = np.random.default_rng(0)
    for _ in range(5):
        plane = rng.normal(size=32).astype(np.float32)
        idx = rng.integers(0, 32, size=64).astype(np.int32)
        vals = rng.normal(size=64).astype(np.float32)
        ref = plane.copy()
        for i, v in zip(idx, vals):   # chronological: later wins
            ref[i] = v
        got = np.asarray(last_write_wins_scatter(
            jnp.asarray(plane), jnp.asarray(idx), jnp.asarray(vals)))
        np.testing.assert_array_equal(got, ref)


def test_scan_train_matches_serial_steps():
    """make_scan_train over N stacked batches == N jitted serial steps,
    bit for bit — the apex service's replay-ratio dispatch."""
    from dist_dqn_tpu.agents.dqn import make_learner, make_scan_train
    from dist_dqn_tpu.config import LearnerConfig, NetworkConfig
    from dist_dqn_tpu.types import Transition

    net = build_network(NetworkConfig(torso="mlp", mlp_features=(32,),
                                      hidden=0), 2)
    init, step = make_learner(net, LearnerConfig(batch_size=8))
    state = init(jax.random.PRNGKey(0), jnp.zeros((4,), jnp.float32))
    jit_step = jax.jit(step)
    r = np.random.default_rng(0)
    N, B = 3, 8

    def mk():
        return Transition(
            obs=jnp.asarray(r.normal(size=(B, 4)).astype(np.float32)),
            action=jnp.asarray(r.integers(0, 2, B, np.int32)),
            reward=jnp.asarray(r.normal(size=B).astype(np.float32)),
            discount=jnp.full(B, 0.99, jnp.float32),
            next_obs=jnp.asarray(r.normal(size=(B, 4)).astype(np.float32)))

    batches = [mk() for _ in range(N)]
    s_serial, prios = state, []
    for b in batches:
        s_serial, m = jit_step(s_serial, b, jnp.ones(B))
        prios.append(np.asarray(m["priorities"]))
    stacked = Transition(*(jnp.stack([getattr(b, f) for b in batches])
                           for f in Transition._fields))
    scan = jax.jit(make_scan_train(step))
    s_scan, m_scan = scan(state, stacked, jnp.ones((N, B), jnp.float32))
    for a, b in zip(jax.tree.leaves(s_serial.params),
                    jax.tree.leaves(s_scan.params)):
        np.testing.assert_array_equal(np.asarray(a), np.asarray(b))
    np.testing.assert_array_equal(np.concatenate(prios),
                                  np.asarray(m_scan["priorities"]))
    assert np.asarray(m_scan["priorities"]).shape == (N * B,)


def test_host_replay_ratio_prefetch_pin():
    """Host-replay at ratio 2: the prefetcher draws the event's batches
    from the same per-index RNG streams as the serial path — identical
    params (PR 5's pin extended over the ratio), and 2x the grad steps
    of ratio 1."""
    from dist_dqn_tpu.host_replay_loop import run_host_replay

    def hr_cfg(ratio):
        cfg = _tiny_cfg(ratio=ratio)
        return dataclasses.replace(
            cfg, replay=dataclasses.replace(cfg.replay, capacity=4096))

    out1 = run_host_replay(hr_cfg(1), total_env_steps=1600, chunk_iters=50,
                           log_fn=lambda s: None)
    out2 = run_host_replay(hr_cfg(2), total_env_steps=1600, chunk_iters=50,
                           log_fn=lambda s: None)
    out2s = run_host_replay(hr_cfg(2), total_env_steps=1600, chunk_iters=50,
                            log_fn=lambda s: None, prefetch=False)
    assert out2["grad_steps"] == 2 * out1["grad_steps"] > 0
    assert out2["param_checksum"] == out2s["param_checksum"]
    assert out2["replay_ratio"] == 2
    assert out2["train_batch"] == 16
    assert out2["actor_dtype"] == "float32"
    assert out2["grad_steps_per_sec"] > 0


def test_fused_chunk_donation_audit():
    """The jax.stages evidence: the donated fused-chunk carry aliases
    completely — argument bytes == alias bytes (no unintended device
    copy of the replay ring or learner state), at ratio 1 and 4."""
    from dist_dqn_tpu.utils import donation

    for ratio in (1, 4):
        cfg = _tiny_cfg(ratio=ratio, prioritized=True)
        env = make_jax_env(cfg.env_name)
        net = build_network(cfg.network, env.num_actions)
        init, run_chunk = make_fused_train(cfg, env, net)
        carry = init(jax.random.PRNGKey(0))
        ring_bytes = sum(np.asarray(leaf).nbytes
                         for leaf in jax.tree.leaves(carry.replay))
        compiled = jax.jit(run_chunk, static_argnums=1,
                           donate_argnums=0).lower(carry, 20).compile()
        rep = donation.assert_donation(
            compiled, min_aliased_pairs=10, min_alias_bytes=ring_bytes,
            what=f"fused chunk (ratio {ratio})")
        if rep.get("alias_bytes") is not None \
                and rep.get("argument_bytes") is not None:
            assert rep["alias_bytes"] == rep["argument_bytes"]


def test_apex_service_scan_path_trains():
    """The apex service's replay-ratio wiring: the scanned dispatch
    trains in strides of N, priorities come back [N*B] and flush
    through the batched write-back without error."""
    from dist_dqn_tpu.actors.service import (ApexLearnerService,
                                             ApexRuntimeConfig)
    from dist_dqn_tpu.actors.transport import ShmRing, encode_arrays

    base = CONFIGS["cartpole"]
    cfg = dataclasses.replace(
        base,
        network=dataclasses.replace(base.network, torso="mlp",
                                    mlp_features=(32,), hidden=0,
                                    compute_dtype="float32"),
        replay=dataclasses.replace(base.replay, capacity=4096,
                                   prioritized=True, min_fill=64,
                                   updates_per_chunk=4),
        learner=dataclasses.replace(base.learner, batch_size=16,
                                    n_step=1))
    rt = ApexRuntimeConfig(num_actors=2, envs_per_actor=8,
                           total_env_steps=10 ** 9, ring_mb=8,
                           stall_warn_s=0.0, log_every_s=10 ** 9,
                           train_steps_per_pass=8)
    service = ApexLearnerService(cfg, rt, log_fn=lambda *a: None)
    try:
        assert service.replay_ratio == 4
        assert service._train_scan is not None
        ring = ShmRing(f"req_{service.run_id}")
        r = np.random.default_rng(3)

        def obs():
            return r.normal(size=(8, 4)).astype(np.float32)

        for a in range(2):
            assert ring.push(encode_arrays(
                {"obs": obs()}, {"kind": "hello", "actor": a, "t": 0}))
        service._drain_transports()
        service._flush_act_queue()
        for t in range(1, 25):
            for a in range(2):
                done = r.random(8) < 0.05
                assert ring.push(encode_arrays(
                    {"obs": obs(),
                     "reward": r.normal(size=8).astype(np.float32),
                     "terminated": done.astype(np.uint8),
                     "truncated": np.zeros(8, np.uint8),
                     "next_obs": obs()},
                    {"kind": "step", "actor": a, "t": t}))
            service._drain_transports()
            service._flush_act_queue()
            service._flush_pending(force=True)
        assert len(service.replay) >= 64
        service._maybe_train()
        assert service.grad_steps > 0
        assert service.grad_steps % 4 == 0
        service._finalize_all_train()
        assert np.isfinite(service._last_loss)
    finally:
        service.shutdown()


def test_train_cli_flag_routing(monkeypatch, capsys):
    """ISSUE 6 satellite: --replay-ratio / --actor-dtype apply where
    supported and emit the standard ignored-flag warning where not —
    apex warns (and strips) the dtype split but takes the ratio; the
    recurrent fused loop warns both."""
    import sys

    import dist_dqn_tpu.actors.service as svc_mod
    from dist_dqn_tpu import train as train_mod

    seen = {}

    def fake_run_apex(cfg, rt, log_fn=print):
        seen["cfg"] = cfg
        return {}

    monkeypatch.setattr(svc_mod, "run_apex", fake_run_apex)
    monkeypatch.setattr(train_mod, "train",
                        lambda cfg, **kw: seen.setdefault("fused", cfg)
                        or (None, []))
    monkeypatch.setattr(sys, "argv", [
        "train", "--config", "cartpole", "--runtime", "apex",
        "--replay-ratio", "2", "--actor-dtype", "bfloat16"])
    train_mod.main()
    out = capsys.readouterr().out
    assert "--actor-dtype" in out and "ignored" in out
    assert seen["cfg"].replay.updates_per_chunk == 2      # ratio applied
    assert seen["cfg"].network.actor_dtype == "float32"   # dtype stripped

    monkeypatch.setattr(sys, "argv", [
        "train", "--config", "r2d2", "--replay-ratio", "2",
        "--actor-dtype", "bfloat16"])
    train_mod.main()
    out = capsys.readouterr().out
    assert "--replay-ratio" in out and "--actor-dtype" in out
    cfg = seen["fused"]
    assert cfg.replay.updates_per_chunk == 1              # both ignored
    assert cfg.network.actor_dtype == "float32"


def test_replay_ratio_sweep_smoke():
    """The learner_bench sweep harness cannot bit-rot: two tiny points,
    rows carry the acceptance fields, grad counts scale with the
    ratio."""
    import sys
    from pathlib import Path
    sys.path.insert(0, str(Path(__file__).resolve().parent.parent
                           / "benchmarks"))
    import json

    from learner_bench import replay_ratio_sweep

    rows = []
    replay_ratio_sweep(2, ratios=(1, 2), chunk_iters=30,
                       emit=lambda s: rows.append(json.loads(s)))
    assert [r["replay_ratio"] for r in rows] == [1, 2]
    for r in rows:
        for key in ("grad_steps_per_sec", "train_batch", "actor_dtype",
                    "scaling_vs_ratio1", "aliased_pairs"):
            assert key in r
    assert rows[1]["grad_steps_per_chunk"] == \
        2 * rows[0]["grad_steps_per_chunk"] > 0
